#!/usr/bin/env python
"""Fleet chaos gate: seeded replica-level failures against the router
with asserted fleet-healing invariants — the fleet twin of
experiments/serving_chaos.py (one engine) and chaos_soak.py (training).

Each scenario spawns a real in-process fleet (N
:class:`~.serving_http.PredictServer` replicas over ONE tiny seeded
paged export behind one :class:`~.serving_router.ReplicaRouter`),
injects one replica-level failure class — the
:mod:`~.runtime.faults` fleet seams (``router.probe`` /
``router.forward`` / ``replica.crash``) or the fleet's own control
surface (kill/wedge/drain/hedge) — and asserts the round-15 contract:

- ``kill_replica_mid_decode``   — a seeded ``replica.crash`` hard-kills
                                  one replica while the request wave is
                                  in flight: ZERO client-visible
                                  failures, every response byte-matches
                                  an undisturbed single-replica run,
                                  the router retried/failed-over, and
                                  exactly one replica ends dead.
- ``wedge_one_replica_watchdog``— one replica's decode dispatch wedges:
                                  its /healthz flips stalled, the
                                  prober demotes it to degraded, the
                                  wave lands entirely on the survivors
                                  to byte parity, and the released
                                  replica is re-admitted.
- ``breaker_trip_and_recover``  — a crashed replica's breaker OPENS off
                                  the probe cadence (no client request
                                  eaten), traffic heals on the
                                  survivor, and after a restart the
                                  half-open probe CLOSES the breaker —
                                  the replica serves again.
- ``drain_one_replica_under_load`` — SIGTERM-equivalent drain on one
                                  replica mid-wave: its in-flight
                                  requests finish, new admissions route
                                  around the 503-pushback without
                                  charging the retry budget, zero
                                  drops, bytes to parity, the drained
                                  replica ends dead.
- ``hedge_cancels_loser``       — a wedged primary forces the hedged
                                  second attempt to win; the losing
                                  attempt is CANCELLED through
                                  POST /cancel/<rid> so the victim
                                  replica's ``blocks_free`` provably
                                  returns to baseline (no leaked slot
                                  or cache blocks).

Usage::

    JAX_PLATFORMS=cpu python experiments/fleet_chaos.py \
        [--scenario all] [--seed 0] [--smoke]

One JSON line per scenario plus a summary line; nonzero exit on any
failed invariant. tests/test_fleet_chaos.py runs every scenario in
tier-1 against one shared export; the CLI soak is the slow-lane twin.
"""

import argparse
import json
import os
import sys
import threading
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from serving_chaos import (MAX_NEW, _wait, build_chaos_export,
                           reference_run, seeded_prompts)

from distributed_tensorflow_example_tpu.runtime import faults


def _post(port: int, name: str, prompt, *, max_new: int, rid=None,
          timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}:generate",
        data=json.dumps({"inputs": {"input_ids": [prompt.tolist()]},
                         "max_new": max_new}).encode(),
        headers={"Content-Type": "application/json",
                 **({"X-Request-Id": rid} if rid else {})})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def make_fleet(d: str, n: int, *, server_kw=None, **router_kw):
    """A started fleet with chaos-friendly cadences: fast probes, fast
    dead-marking, prefix cache off (the scenarios assert EXACT
    ``blocks_free`` recovery, and cached prefixes legitimately retain
    block references)."""
    from distributed_tensorflow_example_tpu.serving_router import \
        InProcessFleet
    router_kw.setdefault("probe_interval_s", 0.05)
    router_kw.setdefault("dead_after_probes", 2)
    router_kw.setdefault("retry_budget", 3)
    skw = dict(server_kw or {})
    skw.setdefault("prefix_cache", False)
    return InProcessFleet(d, n, server_kw=skw, **router_kw)


def router_post(fleet, prompt, *, max_new: int, rid=None, timeout=120):
    return _post(fleet.port, fleet.name, prompt, max_new=max_new,
                 rid=rid, timeout=timeout)


def replica_stats(fleet, i: int) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{fleet.servers[i].port}/stats",
            timeout=30) as r:
        return json.loads(r.read())["generate"]


def router_counters(fleet) -> dict:
    snap = fleet.router.registry.snapshot()
    return {k: rec["value"] for k, rec in snap.items()
            if rec["type"] in ("counter", "gauge")}


def _drive_wave(fleet, prompts, max_new: int):
    """Concurrent client wave via the router; returns (generations,
    served_by, errors) index-aligned with ``prompts``."""
    outs: list = [None] * len(prompts)
    served: list = [None] * len(prompts)
    errors: list = []

    def client(i):
        try:
            resp = router_post(fleet, prompts[i], max_new=max_new,
                               rid=f"wave-{i}")
            outs[i] = resp["generations"][0]
            served[i] = resp.get("served_by")
        except Exception as e:     # noqa: BLE001 — the invariant IS
            errors.append(f"request {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return outs, served, errors


# ---------------------------------------------------------------------------
# scenarios — each returns (detail, metrics)
# ---------------------------------------------------------------------------

def scenario_kill_replica_mid_decode(d: str, seed: int, vocab: int):
    prompts = seeded_prompts(6, seed + 10, vocab)
    ref = reference_run(d, prompts, max_new=8)
    # one-shot: the 3rd forwarded request's target replica is KILLED
    # (listener torn down, engine failed fast) while the rest of the
    # wave is in flight on it
    faults.install(faults.parse_spec("replica.crash:step=3", seed=seed))
    try:
        fleet = make_fleet(d, 3)
        try:
            outs, served, errors = _drive_wave(fleet, prompts,
                                               max_new=8)
            assert not errors, f"client-visible failures: {errors}"
            assert outs == ref, \
                "failover changed greedy bytes vs the undisturbed run"
            met = router_counters(fleet)
            assert met["router_retries_total"] >= 1, met
            _wait(lambda: list(
                fleet.router.replica_states().values()).count("dead")
                == 1, what="exactly one replica marked dead")
            dead = [n for n, s in
                    fleet.router.replica_states().items()
                    if s == "dead"]
            return (f"replica {dead[0]} killed mid-wave; 6/6 requests "
                    f"served to byte parity with "
                    f"{met['router_retries_total']} retry(ies), "
                    f"{met['router_failovers_total']} failover(s)",
                    met)
        finally:
            fleet.close()
    finally:
        faults.install(None)


def scenario_wedge_one_replica_watchdog(d: str, seed: int, vocab: int):
    prompts = seeded_prompts(4, seed + 11, vocab)
    ref = reference_run(d, prompts, max_new=6)
    # stall_after_s small so the wedge is detectable fast; the round-15
    # idle-wait fix keeps an IDLE engine's heartbeat well inside it
    fleet = make_fleet(d, 3, server_kw={"stall_after_s": 0.2,
                                        "prefix_cache": False})
    # warm every replica first: the FIRST prefill/decode dispatch pays
    # XLA compilation (hundreds of ms), which a 0.2 s watchdog would
    # misread as a stall — the scenario is about a WEDGED dispatch,
    # not about compile cost
    for srv in fleet.servers:
        _post(srv.port, srv.name, prompts[0], max_new=2)
    wedged, release = threading.Event(), threading.Event()
    srv0 = fleet.servers[0]
    orig = srv0.engine.sw.decode

    def wedge(feats):
        wedged.set()
        release.wait(timeout=60)
        return orig(feats)

    srv0.engine.sw.decode = wedge
    try:
        # wedge replica0 with a DIRECT request (an external actor —
        # the router never saw it), then prove the fleet routes around
        # the stalled watchdog
        direct: dict = {}

        def direct_post():
            try:
                direct["out"] = _post(srv0.port, srv0.name,
                                      prompts[0], max_new=6)
            except Exception as e:   # noqa: BLE001 — recorded
                direct["err"] = f"{type(e).__name__}: {e}"

        th = threading.Thread(target=direct_post)
        th.start()
        assert wedged.wait(timeout=30), "decode never dispatched"
        _wait(lambda: fleet.router.replica_states()["replica0"]
              == "degraded",
              what="prober demoting the wedged replica")
        outs, served, errors = _drive_wave(fleet, prompts, max_new=6)
        assert not errors, f"client-visible failures: {errors}"
        assert outs == ref, "survivor routing changed greedy bytes"
        assert set(filter(None, served)) <= {"replica1", "replica2"}, \
            f"a request landed on the wedged replica: {served}"
        _wait(lambda: router_counters(fleet)["router_replica_healthy"]
              == 2, what="gauge settling at 2 healthy survivors")
        met = router_counters(fleet)
        release.set()
        th.join(timeout=60)
        assert direct.get("out") is not None, direct
        _wait(lambda: fleet.router.replica_states()["replica0"]
              == "healthy", what="released replica re-admitted")
        return (f"wedged replica0 demoted to degraded in-probe; 4/4 "
                f"requests served by survivors to byte parity; "
                "released replica re-admitted as healthy", met)
    finally:
        release.set()
        fleet.close()


def scenario_breaker_trip_and_recover(d: str, seed: int, vocab: int):
    prompts = seeded_prompts(3, seed + 12, vocab)
    ref = reference_run(d, prompts, max_new=4)
    fleet = make_fleet(d, 2, breaker_threshold=2,
                       breaker_cooldown_s=0.2)
    try:
        warm = router_post(fleet, prompts[0], max_new=4)
        assert warm["generations"][0] == ref[0]
        fleet.crash(0)
        rep0 = fleet.router.replicas[0]
        _wait(lambda: rep0.breaker.state == "open",
              what="breaker opening off the probe cadence")
        _wait(lambda: fleet.router.replica_states()["replica0"]
              == "dead", what="crashed replica marked dead")
        met = router_counters(fleet)
        assert met["router_breaker_open_total"] >= 1, met
        outs, served, errors = _drive_wave(fleet, prompts, max_new=4)
        assert not errors, f"failures while breaker open: {errors}"
        assert outs == ref, "survivor bytes diverged"
        assert set(filter(None, served)) == {"replica1"}, served
        fleet.restart(0)
        _wait(lambda: fleet.router.replica_states()["replica0"]
              == "healthy" and rep0.breaker.state == "closed",
              what="half-open probe closing the breaker")
        outs2, served2, errors2 = _drive_wave(fleet, prompts,
                                              max_new=4)
        assert not errors2 and outs2 == ref, (errors2, "parity")
        assert "replica0" in set(served2), \
            f"recovered replica took no traffic: {served2}"
        met = router_counters(fleet)
        return (f"crash opened replica0's breaker via probes "
                f"(opens={met['router_breaker_open_total']}); "
                "survivor served the wave to parity; restart + "
                "half-open probe closed the breaker and replica0 "
                "serves again", met)
    finally:
        fleet.close()


def scenario_drain_one_replica_under_load(d: str, seed: int,
                                          vocab: int):
    prompts = seeded_prompts(9, seed + 13, vocab)
    ref = reference_run(d, prompts, max_new=4)
    fleet = make_fleet(d, 3,
                       server_kw={"drain_timeout_s": 60.0,
                                  "prefix_cache": False})
    try:
        outs: list = [None] * len(prompts)
        errors: list = []

        def client(i):
            try:
                outs[i] = router_post(fleet, prompts[i],
                                      max_new=4)["generations"][0]
            except Exception as e:   # noqa: BLE001 — recorded
                errors.append(f"request {i}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads[:4]:
            t.start()
        # SIGTERM-equivalent mid-wave: replica0 drains gracefully
        # (listener up answering 503 while its in-flight work finishes)
        drainer = threading.Thread(
            target=lambda: fleet.servers[0].stop(drain=True))
        drainer.start()
        for t in threads[4:]:
            t.start()
        for t in threads:
            t.join()
        drainer.join(timeout=120)
        assert not errors, f"dropped requests under drain: {errors}"
        assert outs == ref, "drain changed greedy bytes"
        _wait(lambda: fleet.router.replica_states()["replica0"]
              == "dead", what="drained replica leaving the fleet")
        met = router_counters(fleet)
        assert met["router_replica_healthy"] == 2, met
        return ("9/9 requests to byte parity across a mid-wave "
                "graceful drain; drained replica excluded then dead; "
                "2 replicas left healthy", met)
    finally:
        fleet.close()


def scenario_hedge_cancels_loser(d: str, seed: int, vocab: int):
    prompts = seeded_prompts(1, seed + 14, vocab)
    ref = reference_run(d, prompts, max_new=4)
    fleet = make_fleet(d, 2, hedge_after_ms=60)
    wedged, release = threading.Event(), threading.Event()
    srv0 = fleet.servers[0]
    orig = srv0.engine.sw.decode

    def wedge(feats):
        wedged.set()
        release.wait(timeout=60)
        return orig(feats)

    try:
        free0 = replica_stats(fleet, 0)["blocks_free"]
        srv0.engine.sw.decode = wedge
        # both replicas idle -> the tie-break picks replica0, which
        # wedges; the hedge fires at 60 ms and replica1 wins
        resp = router_post(fleet, prompts[0], max_new=4,
                           rid="hedge-rid")
        assert wedged.is_set(), "primary never reached replica0"
        assert resp["generations"][0] == ref[0], \
            "hedged response diverged from the undisturbed run"
        assert resp["served_by"] == "replica1", resp["served_by"]
        assert resp["request_ids"] == ["hedge-rid"], \
            resp["request_ids"]
        met = router_counters(fleet)
        assert met["router_hedges_total"] == 1, met
        release.set()
        # the loser was cancelled through POST /cancel/<rid>: its slot
        # and cache blocks must come back — NOT decode to max_new
        _wait(lambda: replica_stats(fleet, 0)["blocks_free"] == free0,
              what="loser replica's blocks_free returning to baseline")
        s0 = replica_stats(fleet, 0)
        assert s0["cancelled"] == 1, s0
        assert s0["requests_done"] == 0, s0
        return (f"hedge won on replica1 (bytes to parity, same "
                f"request id end-to-end); loser cancelled on "
                f"replica0 — blocks_free back to {free0}, "
                f"cancelled=1, requests_done=0", met)
    finally:
        release.set()
        fleet.close()


SCENARIOS = {
    "kill_replica_mid_decode": scenario_kill_replica_mid_decode,
    "wedge_one_replica_watchdog": scenario_wedge_one_replica_watchdog,
    "breaker_trip_and_recover": scenario_breaker_trip_and_recover,
    "drain_one_replica_under_load": scenario_drain_one_replica_under_load,
    "hedge_cancels_loser": scenario_hedge_cancels_loser,
}


def run_scenarios(names, *, seed: int, export_dir: str | None = None,
                  vocab: int | None = None) -> list[dict]:
    """Build the shared ample-pool export (unless the caller passes a
    pre-built one — the tier-1 tests amortize ONE export), run
    ``names``, return one result dict per scenario."""
    import tempfile
    results = []
    with tempfile.TemporaryDirectory() as scratch:
        d = export_dir
        if d is None:
            d = os.path.join(scratch, "fleet")
            vocab = build_chaos_export(d, seed=seed)
        assert vocab is not None, \
            "pass vocab= alongside a pre-built export dir"
        for name in names:
            try:
                detail, met = SCENARIOS[name](d, seed, vocab)
                results.append({"scenario": name, "ok": True,
                                "detail": detail, "metrics": met})
            except Exception as e:   # a failed invariant is the signal
                results.append({"scenario": name, "ok": False,
                                "detail": f"{type(e).__name__}: {e}",
                                "metrics": {}})
            finally:
                faults.install(None)
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", default="all",
                    help="comma-separated scenario names, or 'all': "
                         + ", ".join(SCENARIOS))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="alias kept for symmetry with serving_chaos "
                    "(the fleets are already CPU-tiny; --smoke changes "
                    "nothing today)")
    args = ap.parse_args(argv)
    names = (list(SCENARIOS) if args.scenario == "all"
             else [s.strip() for s in args.scenario.split(",")
                   if s.strip()])
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        ap.error(f"unknown scenario(s) {unknown}; have {list(SCENARIOS)}")
    results = run_scenarios(names, seed=args.seed)
    for r in results:
        print(json.dumps(r), flush=True)
    failed = sum(1 for r in results if not r["ok"])
    print(json.dumps({"summary": True, "scenarios": len(results),
                      "failed": failed, "max_new_cap": MAX_NEW,
                      "smoke": bool(args.smoke)}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
