#!/usr/bin/env python
"""Fleet chaos gate: seeded replica-level failures against the router
with asserted fleet-healing invariants — the fleet twin of
experiments/serving_chaos.py (one engine) and chaos_soak.py (training).

Each scenario spawns a real in-process fleet (N
:class:`~.serving_http.PredictServer` replicas over ONE tiny seeded
paged export behind one :class:`~.serving_router.ReplicaRouter`),
injects one replica-level failure class — the
:mod:`~.runtime.faults` fleet seams (``router.probe`` /
``router.forward`` / ``replica.crash``) or the fleet's own control
surface (kill/wedge/drain/hedge) — and asserts the round-15 contract:

- ``kill_replica_mid_decode``   — a seeded ``replica.crash`` hard-kills
                                  one replica while the request wave is
                                  in flight: ZERO client-visible
                                  failures, every response byte-matches
                                  an undisturbed single-replica run,
                                  the router retried/failed-over, and
                                  exactly one replica ends dead.
- ``wedge_one_replica_watchdog``— one replica's decode dispatch wedges:
                                  its /healthz flips stalled, the
                                  prober demotes it to degraded, the
                                  wave lands entirely on the survivors
                                  to byte parity, and the released
                                  replica is re-admitted.
- ``breaker_trip_and_recover``  — a crashed replica's breaker OPENS off
                                  the probe cadence (no client request
                                  eaten), traffic heals on the
                                  survivor, and after a restart the
                                  half-open probe CLOSES the breaker —
                                  the replica serves again.
- ``drain_one_replica_under_load`` — SIGTERM-equivalent drain on one
                                  replica mid-wave: its in-flight
                                  requests finish, new admissions route
                                  around the 503-pushback without
                                  charging the retry budget, zero
                                  drops, bytes to parity, the drained
                                  replica ends dead.
- ``hedge_cancels_loser``       — a wedged primary forces the hedged
                                  second attempt to win; the losing
                                  attempt is CANCELLED through
                                  POST /cancel/<rid> so the victim
                                  replica's ``blocks_free`` provably
                                  returns to baseline (no leaked slot
                                  or cache blocks). Round 17: the
                                  router's ``GET /trace/fleet`` must
                                  yield ONE stitched Perfetto timeline
                                  for the request — the hedge span
                                  parenting BOTH replica attempts,
                                  each replica's engine spans in its
                                  own process group (clock-corrected
                                  into the router's window), and the
                                  loser's "cancel" span carrying the
                                  same request id.

Round 17 also arms the wedge scenario's flight recorder: the stalled
watchdog must AUTO-write exactly one incident bundle
(cause=watchdog_stall) — nobody POSTs /trace/start — whose registry
snapshot matches the wedged replica's live /metrics page.

Usage::

    JAX_PLATFORMS=cpu python experiments/fleet_chaos.py \
        [--scenario all] [--seed 0] [--smoke]

One JSON line per scenario plus a summary line; nonzero exit on any
failed invariant. tests/test_fleet_chaos.py runs every scenario in
tier-1 against one shared export; the CLI soak is the slow-lane twin.
"""

import argparse
import glob
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from serving_chaos import (MAX_NEW, _wait, build_chaos_export,
                           reference_run, seeded_prompts)

from distributed_tensorflow_example_tpu.runtime import faults


def _post(port: int, name: str, prompt, *, max_new: int, rid=None,
          timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}:generate",
        data=json.dumps({"inputs": {"input_ids": [prompt.tolist()]},
                         "max_new": max_new}).encode(),
        headers={"Content-Type": "application/json",
                 **({"X-Request-Id": rid} if rid else {})})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def make_fleet(d: str, n: int, *, server_kw=None, **router_kw):
    """A started fleet with chaos-friendly cadences: fast probes, fast
    dead-marking, prefix cache off (the scenarios assert EXACT
    ``blocks_free`` recovery, and cached prefixes legitimately retain
    block references)."""
    from distributed_tensorflow_example_tpu.serving_router import \
        InProcessFleet
    router_kw.setdefault("probe_interval_s", 0.05)
    router_kw.setdefault("dead_after_probes", 2)
    router_kw.setdefault("retry_budget", 3)
    skw = dict(server_kw or {})
    skw.setdefault("prefix_cache", False)
    return InProcessFleet(d, n, server_kw=skw, **router_kw)


def router_post(fleet, prompt, *, max_new: int, rid=None, timeout=120):
    return _post(fleet.port, fleet.name, prompt, max_new=max_new,
                 rid=rid, timeout=timeout)


def replica_stats(fleet, i: int) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{fleet.servers[i].port}/stats",
            timeout=30) as r:
        return json.loads(r.read())["generate"]


def router_counters(fleet) -> dict:
    snap = fleet.router.registry.snapshot()
    return {k: rec["value"] for k, rec in snap.items()
            if rec["type"] in ("counter", "gauge")}


def _drive_wave(fleet, prompts, max_new: int):
    """Concurrent client wave via the router; returns (generations,
    served_by, errors) index-aligned with ``prompts``."""
    outs: list = [None] * len(prompts)
    served: list = [None] * len(prompts)
    errors: list = []

    def client(i):
        try:
            resp = router_post(fleet, prompts[i], max_new=max_new,
                               rid=f"wave-{i}")
            outs[i] = resp["generations"][0]
            served[i] = resp.get("served_by")
        except Exception as e:     # noqa: BLE001 — the invariant IS
            errors.append(f"request {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return outs, served, errors


# ---------------------------------------------------------------------------
# scenarios — each returns (detail, metrics)
# ---------------------------------------------------------------------------

def scenario_kill_replica_mid_decode(d: str, seed: int, vocab: int):
    prompts = seeded_prompts(6, seed + 10, vocab)
    ref = reference_run(d, prompts, max_new=8)
    # one-shot: the 3rd forwarded request's target replica is KILLED
    # (listener torn down, engine failed fast) while the rest of the
    # wave is in flight on it
    faults.install(faults.parse_spec("replica.crash:step=3", seed=seed))
    try:
        fleet = make_fleet(d, 3)
        try:
            outs, served, errors = _drive_wave(fleet, prompts,
                                               max_new=8)
            assert not errors, f"client-visible failures: {errors}"
            assert outs == ref, \
                "failover changed greedy bytes vs the undisturbed run"
            met = router_counters(fleet)
            assert met["router_retries_total"] >= 1, met
            _wait(lambda: list(
                fleet.router.replica_states().values()).count("dead")
                == 1, what="exactly one replica marked dead")
            dead = [n for n, s in
                    fleet.router.replica_states().items()
                    if s == "dead"]
            return (f"replica {dead[0]} killed mid-wave; 6/6 requests "
                    f"served to byte parity with "
                    f"{met['router_retries_total']} retry(ies), "
                    f"{met['router_failovers_total']} failover(s)",
                    met)
        finally:
            fleet.close()
    finally:
        faults.install(None)


def scenario_wedge_one_replica_watchdog(d: str, seed: int, vocab: int):
    prompts = seeded_prompts(4, seed + 11, vocab)
    ref = reference_run(d, prompts, max_new=6)
    # Round 17: incident_dir arms the flight recorder's bundle writer —
    # the stalled watchdog must auto-dump exactly one bundle without
    # anyone POSTing /trace/start
    incident_dir = tempfile.mkdtemp(prefix="fleet-incidents-")
    fleet = make_fleet(d, 3, server_kw={"prefix_cache": False,
                                        "incident_dir": incident_dir})
    # warm every replica first: the FIRST prefill/decode dispatch pays
    # XLA compilation (hundreds of ms), which a tight watchdog would
    # misread as a stall (and the flight recorder would dutifully
    # bundle) — so the fleet warms under the default 10 s threshold,
    # THEN the watchdog tightens to 0.2 s so the wedge below is
    # detected fast (set_stall_after re-parks the idle wait and
    # settles the heartbeat before the tighter threshold applies)
    for srv in fleet.servers:
        _post(srv.port, srv.name, prompts[0], max_new=2)
    for srv in fleet.servers:
        srv.engine.set_stall_after(0.2)
    wedged, release = threading.Event(), threading.Event()
    srv0 = fleet.servers[0]
    orig = srv0.engine.sw.decode

    def wedge(feats):
        wedged.set()
        release.wait(timeout=60)
        return orig(feats)

    srv0.engine.sw.decode = wedge
    try:
        # wedge replica0 with a DIRECT request (an external actor —
        # the router never saw it), then prove the fleet routes around
        # the stalled watchdog
        direct: dict = {}

        def direct_post():
            try:
                direct["out"] = _post(srv0.port, srv0.name,
                                      prompts[0], max_new=6)
            except Exception as e:   # noqa: BLE001 — recorded
                direct["err"] = f"{type(e).__name__}: {e}"

        th = threading.Thread(target=direct_post)
        th.start()
        assert wedged.wait(timeout=30), "decode never dispatched"
        _wait(lambda: fleet.router.replica_states()["replica0"]
              == "degraded",
              what="prober demoting the wedged replica")
        outs, served, errors = _drive_wave(fleet, prompts, max_new=6)
        assert not errors, f"client-visible failures: {errors}"
        assert outs == ref, "survivor routing changed greedy bytes"
        assert set(filter(None, served)) <= {"replica1", "replica2"}, \
            f"a request landed on the wedged replica: {served}"
        _wait(lambda: router_counters(fleet)["router_replica_healthy"]
              == 2, what="gauge settling at 2 healthy survivors")
        # ---- flight recorder (round 17): the stalled watchdog must
        # have AUTO-written exactly one incident bundle for replica0
        # (cause=watchdog_stall, rate-limited past the probe cadence),
        # nobody having armed tracing via /trace/start
        _wait(lambda: glob.glob(os.path.join(incident_dir,
                                             "incident-*.json")),
              what="the watchdog-stall incident bundle appearing")
        bundles = sorted(glob.glob(os.path.join(incident_dir,
                                                "incident-*.json")))
        assert len(bundles) == 1, \
            f"expected exactly one bundle, got {bundles}"
        with open(bundles[0]) as f:
            bundle = json.load(f)
        assert bundle["cause"] == "watchdog_stall", bundle["cause"]
        assert bundle["process"] == "replica0", bundle["process"]
        assert bundle["spans"], "bundle carries no span history"
        assert bundle["health"]["status"] == "stalled", bundle["health"]
        # the bundle's registry snapshot must MATCH the wedged
        # replica's live /metrics page: the engine is frozen
        # mid-dispatch, so every serving_* counter/gauge is stable
        # between the bundle write and this scrape
        from distributed_tensorflow_example_tpu.obs import prom
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv0.port}/metrics",
                timeout=30) as r:
            page = prom.parse(r.read().decode())
        snap = bundle["registry"]
        compared = 0
        for name, rec in snap.items():
            if not name.startswith("serving_") \
                    or rec["type"] not in ("counter", "gauge"):
                continue
            if name.startswith("serving_incidents"):
                # the rate-limit suppression counter keeps moving with
                # every later probe of the still-stalled replica — the
                # one legitimately-live metric between bundle and scrape
                continue
            assert page.get(name) == rec["value"], \
                (name, rec["value"], page.get(name))
            compared += 1
        assert compared >= 10, f"only {compared} metrics compared"
        met = router_counters(fleet)
        release.set()
        th.join(timeout=60)
        assert direct.get("out") is not None, direct
        _wait(lambda: fleet.router.replica_states()["replica0"]
              == "healthy", what="released replica re-admitted")
        return (f"wedged replica0 demoted to degraded in-probe; 4/4 "
                f"requests served by survivors to byte parity; "
                "released replica re-admitted as healthy; watchdog "
                "stall auto-wrote one incident bundle whose registry "
                f"snapshot matches /metrics ({compared} metrics)", met)
    finally:
        release.set()
        fleet.close()
        shutil.rmtree(incident_dir, ignore_errors=True)


def scenario_breaker_trip_and_recover(d: str, seed: int, vocab: int):
    prompts = seeded_prompts(3, seed + 12, vocab)
    ref = reference_run(d, prompts, max_new=4)
    # round 17: the ROUTER's flight recorder rides this scenario — a
    # breaker opening and a replica death are incident causes, so the
    # crash below must auto-write router-side bundles
    incident_dir = tempfile.mkdtemp(prefix="router-incidents-")
    fleet = make_fleet(d, 2, breaker_threshold=2,
                       breaker_cooldown_s=0.2,
                       incident_dir=incident_dir)
    try:
        warm = router_post(fleet, prompts[0], max_new=4)
        assert warm["generations"][0] == ref[0]
        fleet.crash(0)
        rep0 = fleet.router.replicas[0]
        _wait(lambda: rep0.breaker.state == "open",
              what="breaker opening off the probe cadence")
        _wait(lambda: fleet.router.replica_states()["replica0"]
              == "dead", what="crashed replica marked dead")
        met = router_counters(fleet)
        assert met["router_breaker_open_total"] >= 1, met
        outs, served, errors = _drive_wave(fleet, prompts, max_new=4)
        assert not errors, f"failures while breaker open: {errors}"
        assert outs == ref, "survivor bytes diverged"
        assert set(filter(None, served)) == {"replica1"}, served
        fleet.restart(0)
        _wait(lambda: fleet.router.replica_states()["replica0"]
              == "healthy" and rep0.breaker.state == "closed",
              what="half-open probe closing the breaker")
        outs2, served2, errors2 = _drive_wave(fleet, prompts,
                                              max_new=4)
        assert not errors2 and outs2 == ref, (errors2, "parity")
        assert "replica0" in set(served2), \
            f"recovered replica took no traffic: {served2}"
        met = router_counters(fleet)
        # router flight recorder: the breaker open and the replica
        # death each wrote one bundle (distinct causes), counted in
        # the router registry
        bundles = sorted(os.path.basename(p) for p in glob.glob(
            os.path.join(incident_dir, "incident-router-*.json")))
        causes = {b.split("-")[2] for b in bundles}
        assert {"breaker_open", "replica_death"} <= causes, bundles
        assert met["router_incidents_total"] == len(bundles) >= 2, \
            (met, bundles)
        return (f"crash opened replica0's breaker via probes "
                f"(opens={met['router_breaker_open_total']}); "
                "survivor served the wave to parity; restart + "
                "half-open probe closed the breaker and replica0 "
                "serves again; router flight recorder bundled "
                f"{sorted(causes)}", met)
    finally:
        fleet.close()
        shutil.rmtree(incident_dir, ignore_errors=True)


def scenario_drain_one_replica_under_load(d: str, seed: int,
                                          vocab: int):
    prompts = seeded_prompts(9, seed + 13, vocab)
    ref = reference_run(d, prompts, max_new=4)
    fleet = make_fleet(d, 3,
                       server_kw={"drain_timeout_s": 60.0,
                                  "prefix_cache": False})
    try:
        outs: list = [None] * len(prompts)
        errors: list = []

        def client(i):
            try:
                outs[i] = router_post(fleet, prompts[i],
                                      max_new=4)["generations"][0]
            except Exception as e:   # noqa: BLE001 — recorded
                errors.append(f"request {i}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads[:4]:
            t.start()
        # SIGTERM-equivalent mid-wave: replica0 drains gracefully
        # (listener up answering 503 while its in-flight work finishes)
        drainer = threading.Thread(
            target=lambda: fleet.servers[0].stop(drain=True))
        drainer.start()
        for t in threads[4:]:
            t.start()
        for t in threads:
            t.join()
        drainer.join(timeout=120)
        assert not errors, f"dropped requests under drain: {errors}"
        assert outs == ref, "drain changed greedy bytes"
        _wait(lambda: fleet.router.replica_states()["replica0"]
              == "dead", what="drained replica leaving the fleet")
        met = router_counters(fleet)
        assert met["router_replica_healthy"] == 2, met
        return ("9/9 requests to byte parity across a mid-wave "
                "graceful drain; drained replica excluded then dead; "
                "2 replicas left healthy", met)
    finally:
        fleet.close()


def scenario_hedge_cancels_loser(d: str, seed: int, vocab: int):
    prompts = seeded_prompts(1, seed + 14, vocab)
    ref = reference_run(d, prompts, max_new=4)
    fleet = make_fleet(d, 2, hedge_after_ms=60)
    wedged, release = threading.Event(), threading.Event()
    srv0 = fleet.servers[0]
    orig = srv0.engine.sw.decode

    def wedge(feats):
        wedged.set()
        release.wait(timeout=60)
        return orig(feats)

    try:
        free0 = replica_stats(fleet, 0)["blocks_free"]
        srv0.engine.sw.decode = wedge
        # both replicas idle -> the tie-break picks replica0, which
        # wedges; the hedge fires at 60 ms and replica1 wins
        resp = router_post(fleet, prompts[0], max_new=4,
                           rid="hedge-rid")
        assert wedged.is_set(), "primary never reached replica0"
        assert resp["generations"][0] == ref[0], \
            "hedged response diverged from the undisturbed run"
        assert resp["served_by"] == "replica1", resp["served_by"]
        assert resp["request_ids"] == ["hedge-rid"], \
            resp["request_ids"]
        met = router_counters(fleet)
        assert met["router_hedges_total"] == 1, met
        assert met["router_hedge_wins_total"] == 1, met
        # ---- the stitched fleet timeline (round 17): ONE Perfetto
        # trace in which the hedge span parents BOTH replica attempts,
        # each replica renders as its own process group with the
        # request's engine spans clock-corrected into the router's
        # window, and the loser's cancellation span carries the same
        # request id
        trace_id = resp["trace_id"]
        # the loser's "cancel" span is recorded by the router's
        # fire-and-forget cancel thread AFTER its POST resolves —
        # /trace/fleet DRAINS, so wait (non-destructively, via the
        # in-process ring) for the span to land before the one fetch
        from distributed_tensorflow_example_tpu.obs import \
            trace as obs_trace
        _wait(lambda: any(
            s[2] == "cancel" for s in
            obs_trace.recorder().tail(256, process="router")),
            what="the loser's cancel span landing in the ring")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fleet.port}/trace/fleet",
                timeout=30) as r:
            stitched = json.loads(r.read())
        trace_detail = _assert_stitched_hedge(stitched, trace_id,
                                              "hedge-rid")
        release.set()
        # the loser was cancelled through POST /cancel/<rid>: its slot
        # and cache blocks must come back — NOT decode to max_new
        _wait(lambda: replica_stats(fleet, 0)["blocks_free"] == free0,
              what="loser replica's blocks_free returning to baseline")
        s0 = replica_stats(fleet, 0)
        assert s0["cancelled"] == 1, s0
        assert s0["requests_done"] == 0, s0
        return (f"hedge won on replica1 (bytes to parity, same "
                f"request id end-to-end); loser cancelled on "
                f"replica0 — blocks_free back to {free0}, "
                f"cancelled=1, requests_done=0; stitched fleet trace: "
                f"{trace_detail}", met)
    finally:
        release.set()
        fleet.close()


def _assert_stitched_hedge(stitched: dict, trace_id: str,
                           rid: str) -> str:
    """Structural contract of the hedge scenario's stitched timeline
    (the round-17 acceptance core); returns a one-line description."""
    events = stitched["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    procs = {e["pid"]: e["args"]["name"] for e in events
             if e.get("name") == "process_name"}
    by_name = {name: pid for pid, name in procs.items()}
    assert {"router", "replica0", "replica1"} <= set(by_name), procs
    # router lane on top: the anchor export claims the first pid
    assert by_name["router"] < by_name["replica0"] \
        and by_name["router"] < by_name["replica1"], procs
    mine = [e for e in xs
            if (e.get("args") or {}).get("trace_id") == trace_id]
    assert mine, f"no spans for trace {trace_id}"

    def named(n):
        return [e for e in mine if e["name"] == n]

    root = named("request")
    assert len(root) == 1 and root[0]["pid"] == by_name["router"], root
    hedge = named("hedge")
    assert len(hedge) == 1, hedge
    hedge_sid = hedge[0]["args"]["span_id"]
    assert hedge[0]["args"]["parent_id"] == root[0]["args"]["span_id"]
    # the hedge span parents BOTH replica attempts: the launch markers
    # are the guaranteed-visible half (the wedged loser's completed
    # "forward" span only lands once its cancellation resolves — after
    # this fetch), and the winner's completed span must be there too
    launches = [e for e in named("forward_launch")
                if e["args"].get("parent_id") == hedge_sid]
    assert len(launches) == 2, launches
    assert {e["args"]["replica"] for e in launches} \
        == {"replica0", "replica1"}, launches
    done = [e for e in named("forward")
            if e["args"].get("parent_id") == hedge_sid]
    assert [e["args"]["replica"] for e in done] == ["replica1"], done
    assert done[0]["args"]["status"] == 200, done
    fwd_sids = {e["args"]["replica"]: e["args"]["span_id"]
                for e in launches}
    # each replica's engine spans land in ITS process group, parented
    # under that replica's forward attempt (the propagated traceparent)
    for rep in ("replica0", "replica1"):
        rep_spans = [e for e in mine if e["pid"] == by_name[rep]]
        assert rep_spans, f"no {rep} spans under trace {trace_id}"
        assert all(e["args"].get("parent_id") == fwd_sids[rep]
                   for e in rep_spans), (rep, rep_spans)
        assert all(e["args"].get("request_id") == rid
                   for e in rep_spans), (rep, rep_spans)
        # clock correction put the replica's spans inside the router's
        # request window (generous slack: the in-process offset
        # estimate is bounded by probe RTT)
        lo = root[0]["ts"] - 50_000            # µs
        hi = root[0]["ts"] + root[0]["dur"] + 50_000
        for e in rep_spans:
            assert lo <= e["ts"] and e["ts"] + e["dur"] <= hi, \
                (rep, e, root[0])
    # the winner retired; the loser (cancelled mid-decode) did not
    winner_names = {e["name"] for e in mine
                    if e["pid"] == by_name["replica1"]}
    assert "retire" in winner_names, winner_names
    # the loser's cancellation is visible with the SAME request id
    cancels = [e for e in named("cancel")
               if e["args"].get("request_id") == rid]
    assert cancels and cancels[0]["args"]["parent_id"] == hedge_sid \
        and cancels[0]["args"]["replica"] == "replica0", cancels
    offs = stitched["metadata"]["clock_offsets_s"]
    assert {"replica0", "replica1"} <= set(offs), offs
    assert all(abs(v) < 0.1 for v in offs.values()), offs
    return (f"{len(mine)} spans across {len(procs)} process groups, "
            f"hedge parents both attempts, cancel visible "
            f"(offsets {offs})")


SCENARIOS = {
    "kill_replica_mid_decode": scenario_kill_replica_mid_decode,
    "wedge_one_replica_watchdog": scenario_wedge_one_replica_watchdog,
    "breaker_trip_and_recover": scenario_breaker_trip_and_recover,
    "drain_one_replica_under_load": scenario_drain_one_replica_under_load,
    "hedge_cancels_loser": scenario_hedge_cancels_loser,
}


def run_scenarios(names, *, seed: int, export_dir: str | None = None,
                  vocab: int | None = None) -> list[dict]:
    """Build the shared ample-pool export (unless the caller passes a
    pre-built one — the tier-1 tests amortize ONE export), run
    ``names``, return one result dict per scenario."""
    import tempfile
    results = []
    with tempfile.TemporaryDirectory() as scratch:
        d = export_dir
        if d is None:
            d = os.path.join(scratch, "fleet")
            vocab = build_chaos_export(d, seed=seed)
        assert vocab is not None, \
            "pass vocab= alongside a pre-built export dir"
        for name in names:
            try:
                detail, met = SCENARIOS[name](d, seed, vocab)
                results.append({"scenario": name, "ok": True,
                                "detail": detail, "metrics": met})
            except Exception as e:   # a failed invariant is the signal
                results.append({"scenario": name, "ok": False,
                                "detail": f"{type(e).__name__}: {e}",
                                "metrics": {}})
            finally:
                faults.install(None)
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", default="all",
                    help="comma-separated scenario names, or 'all': "
                         + ", ".join(SCENARIOS))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="alias kept for symmetry with serving_chaos "
                    "(the fleets are already CPU-tiny; --smoke changes "
                    "nothing today)")
    args = ap.parse_args(argv)
    names = (list(SCENARIOS) if args.scenario == "all"
             else [s.strip() for s in args.scenario.split(",")
                   if s.strip()])
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        ap.error(f"unknown scenario(s) {unknown}; have {list(SCENARIOS)}")
    results = run_scenarios(names, seed=args.seed)
    for r in results:
        print(json.dumps(r), flush=True)
    failed = sum(1 for r in results if not r["ok"])
    print(json.dumps({"summary": True, "scenarios": len(results),
                      "failed": failed, "max_new_cap": MAX_NEW,
                      "smoke": bool(args.smoke)}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
