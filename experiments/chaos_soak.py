#!/usr/bin/env python
"""Chaos soak: seeded kill/corrupt/NaN/flaky-IO scenarios with asserted
recovery invariants — the repo's systematic robustness gate.

Each scenario runs real Trainers (CPU mesh works: ``JAX_PLATFORMS=cpu``
+ ``XLA_FLAGS=--xla_force_host_platform_device_count=4``) through a
deterministic failure and asserts the self-healing contract:

- ``kill_resume``     — clean kill at step K, restart, run to N: final
                        params BIT-match an uninterrupted run (the
                        pre-existing exact-resume guarantee, kept honest
                        under the new verified-restore path).
- ``corrupt_latest``  — the newest checkpoint file is truncated on disk
                        (and, separately, zero-filled): restart restores
                        the previous VALID step and still converges.
- ``nan_skip``        — an injected NaN batch under --on_anomaly=skip:
                        same final step as the clean run, loss stream
                        finite throughout, anomaly_count == 1.
- ``nan_rollback``    — an injected divergence under
                        --on_anomaly=rollback: the run restores the last
                        clean checkpoint, replays, and its FINAL PARAMS
                        match the uninterrupted run (divergence
                        repaired, not merely survived).
- ``flaky_io``        — probabilistic loader faults under the bounded
                        retry+backoff policy: the run completes with
                        zero anomalies.
- ``budget_halt``     — more injected NaN steps than --max_anomalies:
                        the run halts early instead of limping on.
- ``torn_write``      — fault-injected torn checkpoint writes
                        (corrupt=truncate): a restart falls back past
                        every damaged file to the newest valid one.

Usage::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python experiments/chaos_soak.py [--scenario all] [--seed 0] \
        [--steps 20]

Prints one JSON line per scenario: {"scenario", "ok", "detail"}. Exits
nonzero if any scenario fails. tests/test_chaos_soak.py runs the full
soak as a ``slow`` test; tests/test_self_healing.py keeps a fast smoke
of the same invariants in tier-1.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from distributed_tensorflow_example_tpu.config import (CheckpointConfig,
                                                       DataConfig, MeshShape,
                                                       ObservabilityConfig,
                                                       OptimizerConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.data.mnist import synthetic_mnist
from distributed_tensorflow_example_tpu.models import get_model
from distributed_tensorflow_example_tpu.parallel.mesh import local_mesh
from distributed_tensorflow_example_tpu.train import hooks as hooks_lib
from distributed_tensorflow_example_tpu.train.trainer import Trainer

MESH = 4


def make_config(*, steps: int, seed: int, ckpt_dir: str | None = None,
                save_steps: int = 0, on_anomaly: str = "halt",
                max_anomalies: int = 10, fault_spec: str = "",
                log_every: int = 5) -> TrainConfig:
    return TrainConfig(
        model="mlp", train_steps=steps, mesh=MeshShape(data=MESH),
        data=DataConfig(batch_size=64, seed=seed + 1),
        optimizer=OptimizerConfig(name="momentum", learning_rate=0.1),
        checkpoint=CheckpointConfig(directory=ckpt_dir,
                                    save_steps=save_steps),
        obs=ObservabilityConfig(log_every_steps=log_every),
        on_anomaly=on_anomaly, max_anomalies=max_anomalies,
        fault_spec=fault_spec, seed=seed)


class LossStream(hooks_lib.Hook):
    """Collect every step's materialized loss (forces per-step metrics —
    a test instrument, not a production pattern)."""

    every_steps = 1

    def __init__(self):
        self.losses: list[float] = []

    def after_step(self, trainer, step, metrics):
        if metrics is not None:
            self.losses.append(float(metrics["loss"]))


def run_trainer(cfg: TrainConfig, data, hooks=None):
    model = get_model("mlp", cfg)
    trainer = Trainer(model, cfg,
                      {"x": data["train_x"], "y": data["train_y"]},
                      mesh=local_mesh(MESH), process_index=0,
                      num_processes=1, hooks=hooks)
    with trainer:
        state, summary = trainer.train()
    return state, summary


def host_params(state):
    return jax.tree_util.tree_map(np.asarray, jax.device_get(state.params))


def assert_params_equal(a, b, what: str, rtol=1e-6, atol=1e-7):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(x, y, rtol=rtol, atol=atol,
                                                err_msg=what),
        host_params(a), host_params(b))


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------

def scenario_kill_resume(data, seed: int, steps: int) -> str:
    ref_state, _ = run_trainer(make_config(steps=steps, seed=seed), data)
    d = tempfile.mkdtemp(prefix="chaos_kill_")
    run_trainer(make_config(steps=steps // 2, seed=seed, ckpt_dir=d,
                            save_steps=5), data)        # the "killed" run
    state, summary = run_trainer(
        make_config(steps=steps, seed=seed, ckpt_dir=d, save_steps=5),
        data)
    assert summary["final_step"] == steps, summary["final_step"]
    assert_params_equal(state, ref_state, "kill/resume parity")
    return f"resumed at {steps // 2}, parity at {steps}"


def _damage(path: str, mode: str) -> None:
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        if mode == "truncate":
            f.truncate(max(1, size // 2))
        else:
            f.seek(size // 3)
            f.write(b"\0" * max(1, size // 3))


def scenario_corrupt_latest(data, seed: int, steps: int) -> str:
    details = []
    for mode in ("truncate", "zero"):
        d = tempfile.mkdtemp(prefix=f"chaos_corrupt_{mode}_")
        cfg = make_config(steps=steps, seed=seed, ckpt_dir=d, save_steps=5)
        run_trainer(cfg, data)
        from distributed_tensorflow_example_tpu.ckpt.checkpoint import \
            CheckpointManager
        mgr = CheckpointManager(d)
        latest = mgr.latest_step()
        _damage(mgr.checkpoint_path(latest), mode)
        # restart: must fall back to the previous valid step, not crash
        model = get_model("mlp", cfg)
        trainer = Trainer(model, cfg,
                          {"x": data["train_x"], "y": data["train_y"]},
                          mesh=local_mesh(MESH), process_index=0,
                          num_processes=1)
        with trainer:
            trainer.initialize()
            start = trainer.start_step
        assert 0 < start < latest, (start, latest)
        details.append(f"{mode}: {latest}->{start}")
    return "; ".join(details)


def scenario_nan_skip(data, seed: int, steps: int) -> str:
    bad_step = steps // 2 + 1
    stream = LossStream()
    _, ref = run_trainer(make_config(steps=steps, seed=seed), data)
    state, summary = run_trainer(
        make_config(steps=steps, seed=seed, on_anomaly="skip",
                    fault_spec=f"step.nan:step={bad_step}"),
        data, hooks=[stream])
    assert summary["final_step"] == ref["final_step"], summary["final_step"]
    assert all(np.isfinite(l) for l in stream.losses), stream.losses
    count = int(summary["final_metrics"]["anomaly_count"])
    assert count == 1, count
    return (f"NaN at step {bad_step} skipped; {len(stream.losses)} finite "
            "losses")


def scenario_nan_rollback(data, seed: int, steps: int) -> str:
    bad_step = steps // 2 + 1
    ref_state, _ = run_trainer(make_config(steps=steps, seed=seed), data)
    d = tempfile.mkdtemp(prefix="chaos_rollback_")
    state, summary = run_trainer(
        make_config(steps=steps, seed=seed, ckpt_dir=d, save_steps=5,
                    on_anomaly="rollback",
                    fault_spec=f"step.nan:step={bad_step}"), data)
    assert summary["final_step"] == steps, summary["final_step"]
    assert int(summary["final_metrics"]["anomaly_count"]) == 1
    # the strong contract: replaying the repaired window converges to the
    # SAME final params as a run that never saw the fault
    assert_params_equal(state, ref_state, "rollback divergence repair")
    return f"NaN at {bad_step} rolled back + replayed to parity"


def scenario_flaky_io(data, seed: int, steps: int) -> str:
    state, summary = run_trainer(
        make_config(steps=steps, seed=seed, on_anomaly="skip",
                    fault_spec="loader.next:p=0.2"), data)
    assert summary["final_step"] == steps, summary["final_step"]
    assert int(summary["final_metrics"]["anomaly_count"]) == 0
    return f"{steps} steps through p=0.2 loader faults (retried)"


def scenario_budget_halt(data, seed: int, steps: int) -> str:
    spec = ";".join(f"step.nan:step={s}" for s in range(2, steps, 2))
    state, summary = run_trainer(
        make_config(steps=steps, seed=seed, on_anomaly="skip",
                    max_anomalies=2, log_every=2, fault_spec=spec), data)
    assert summary["final_step"] < steps, \
        f"budget never halted ({summary['final_step']})"
    count = int(summary["final_metrics"]["anomaly_count"])
    assert count > 2, count
    return (f"halted at step {summary['final_step']} after {count} "
            "anomalies (budget 2)")


def scenario_torn_write(data, seed: int, steps: int) -> str:
    d = tempfile.mkdtemp(prefix="chaos_torn_")
    # the LAST ring write lands torn; earlier ones are whole (no extra
    # end-of-run save happens: the cadence already saved the final step)
    n_saves = steps // 5
    cfg = make_config(steps=steps, seed=seed, ckpt_dir=d, save_steps=5,
                      fault_spec=f"ckpt.write:step={n_saves}:"
                                 "corrupt=truncate")
    run_trainer(cfg, data)
    clean = make_config(steps=steps, seed=seed, ckpt_dir=d, save_steps=5)
    model = get_model("mlp", clean)
    trainer = Trainer(model, clean,
                      {"x": data["train_x"], "y": data["train_y"]},
                      mesh=local_mesh(MESH), process_index=0,
                      num_processes=1)
    with trainer:
        trainer.initialize()
        start = trainer.start_step
    assert 0 < start < steps, (start, steps)
    return f"torn final write; restart fell back to step {start}"


SCENARIOS = {
    "kill_resume": scenario_kill_resume,
    "corrupt_latest": scenario_corrupt_latest,
    "nan_skip": scenario_nan_skip,
    "nan_rollback": scenario_nan_rollback,
    "flaky_io": scenario_flaky_io,
    "budget_halt": scenario_budget_halt,
    "torn_write": scenario_torn_write,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", default="all",
                    help="comma-separated scenario names, or 'all': "
                         + ", ".join(SCENARIOS))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steps", type=int, default=20,
                    help="training steps per scenario run (>= 10)")
    args = ap.parse_args(argv)
    names = (list(SCENARIOS) if args.scenario == "all"
             else [s.strip() for s in args.scenario.split(",") if s.strip()])
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        ap.error(f"unknown scenario(s) {unknown}; have {list(SCENARIOS)}")
    if args.steps < 10:
        ap.error("--steps must be >= 10 (scenarios inject mid-run)")

    data = synthetic_mnist(num_train=640, num_test=64, seed=args.seed)
    failed = 0
    for name in names:
        try:
            detail = SCENARIOS[name](data, args.seed, args.steps)
            print(json.dumps({"scenario": name, "ok": True,
                              "detail": detail}), flush=True)
        except Exception as e:      # a failed invariant is the signal
            failed += 1
            print(json.dumps({"scenario": name, "ok": False,
                              "detail": f"{type(e).__name__}: {e}"}),
                  flush=True)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
