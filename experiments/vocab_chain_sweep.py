#!/usr/bin/env python
"""Vocab-chain sweep: the [B, S, V] LM-head logits chain, impl x block x
shape (ISSUE 3 tentpole; the flash_sweep.py discipline applied to the
loss end of the model).

The GPT-small round-5 profile attributes ~21 ms of the 170 ms step to
the 30,522-vocab logits chain (logits fwd 4.0 + backward recompute 4.0
+ tied-embedding grad 4.4 + softmax reductions 5.3 + accuracy argmax
3.2 — BASELINE.md "Vocab chain"), and the [B, S, V] tensor is the
causal-LM memory wall (b64 s512 OOMs without chunking). The fused
blockwise cross-entropy (``--lm_loss_impl fused``,
ops/losses.py lm_head_xent) removes the tensor from BOTH passes; this
script makes the choice reproducible: an analytic bytes/flops model of
the chain per impl, and measured fresh-process train-step cells over
impl x vocab block x the gate shapes.

Modes (one JSON line per cell; fresh process per cell via --all/--smoke
— the round-4 lesson: long-lived processes through the axon tunnel
accumulate timing artifacts):

  --roofline        analytic model, runs anywhere: matmul FLOPs and HBM
                    bytes of the logits chain per impl (full / chunked /
                    fused at each block), the peak logits residency, and
                    the implied MXU/HBM floors per (B, S) gate shape.
  cell MODEL B S IMPL [SIZE]
                    one measured train-step cell on the current backend:
                    step ms, eps/chip, temp/peak MiB. IMPL: full |
                    chunked | fused; SIZE is the seq chunk (chunked) or
                    vocab block (fused). Records OOM as an error line —
                    the full-vs-fused crossover table needs the OOM rows
                    (full at b64 s512 is EXPECTED to be one on the v5e).
  --all             the committed TPU grid: gpt x {b32 s512, b64 s512,
                    b4 s4096} x (full + chunked 512 + fused blocks
                    {1024, 2048, 4096, 8192}).
  --smoke           tiny CPU grid (gpt_tiny, b4 s64, fused blocks incl.
                    a non-divisible one) — the CI end-to-end path; the
                    numbers are meaningless off-TPU, the exercise is
                    that every impl runs and the JSON contract holds.

Measured cells are TPU cells (off-TPU timings are meaningless):
--all/--cell refuse to run off-TPU unless VOCAB_SWEEP_CPU=1 (--smoke
sets it for its subprocesses). --roofline is platform-independent.

Pre-committed decision rule (BASELINE.md "Vocab chain"): at the next
TPU window, if fused beats the incumbent (full at gpt_small b32,
chunked 512 at gpt_long) at the gate shapes, the gate configs stay
fused and re-base with a methodology note; if not, the losing cells get
committed and full/chunked return as the gate configs — either way the
winning vocab block becomes the configs' --lm_loss_vocab_block.
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the gate-adjacent (batch, seq) cells: the gpt_small bench shape, the
#: shape that OOMs the full path on the v5e, and the gpt_long shape
SHAPES = ((32, 512), (64, 512), (4, 4096))
BLOCKS = (1024, 2048, 4096, 8192)
CHUNK = 512                    # the incumbent gpt_long chunk
HIDDEN, LAYERS, VOCAB = 768, 12, 30522
PEAK_FLOPS = 197e12            # v5e bf16
HBM_BPS = 819e9                # v5e


# ---------------------------------------------------------------------------
# analytic model — pass counts mirror what each impl executes
# ---------------------------------------------------------------------------

def chain_flops(impl: str, n: int, *, h: int = HIDDEN,
                v: int = VOCAB) -> float:
    """Matmul FLOPs of the logits chain for one train step over n
    tokens. full: fwd (2nhv) + bwd dh (2nhv) + bwd tied-embed grad
    (2nhv) = 6nhv. chunked AND fused both regenerate the logits once in
    backward (jax.checkpoint / the custom VJP) = 8nhv — the fused win
    is bytes and residency, not flops."""
    return (6.0 if impl == "full" else 8.0) * n * h * v


def chain_bytes(impl: str, n: int, *, h: int = HIDDEN, v: int = VOCAB,
                block: int = 0, chunk: int = 0, batch: int = 0,
                seq: int = 0, op_bytes: int = 2) -> dict:
    """HBM byte model of the chain (f32 logits tiles, ``op_bytes``
    matmul operands). Documented pass counts:

    - full: the [n, v] f32 tensor is written (fwd), read by the
      logsumexp/softmax reductions, read by the accuracy argmax, and in
      backward d_logits is written then read by BOTH grad matmuls —
      ~6 full-tensor passes. Table read twice (fwd + dE), dE written.
    - chunked: same logits traffic PLUS one more pass (the recompute),
      and the table is re-streamed per seq chunk in each of the 4
      matmul passes (fwd, recompute, dh, dE) — the chunked tax that
      grows with S/chunk. Peak residency: one [b, chunk, v] tile.
    - fused: per vocab block the [n, block] tile is produced and
      consumed in-scan (write+read, fwd and bwd = ~4 passes of n·v
      f32 in BLOCK tiles — never resident at once), h is re-streamed
      once per block per pass (3 passes: fwd, bwd-regen+dE, dh), the
      table twice, dE written once. Peak residency: one [n, block]
      tile + the dh accumulator.
    """
    logits_f32 = 4.0 * n * v
    table = v * h * op_bytes
    h_stream = n * h * op_bytes
    if impl == "full":
        return {
            "logits_GB": 6.0 * logits_f32 / 1e9,
            "table_GB": (2 * table + v * h * 4) / 1e9,
            "h_GB": 3.0 * h_stream / 1e9,
            "peak_logits_MiB": logits_f32 / 2**20,
        }
    if impl == "chunked":
        n_chunks = max(1, seq // max(chunk, 1))
        return {
            "logits_GB": 7.0 * logits_f32 / 1e9,
            "table_GB": (4 * n_chunks * table + v * h * 4) / 1e9,
            "h_GB": 4.0 * h_stream / 1e9,
            "peak_logits_MiB": 4.0 * batch * chunk * v / 2**20,
        }
    nb = max(1, -(-v // max(block, 1)))
    return {
        "logits_GB": 4.0 * logits_f32 / 1e9,
        "table_GB": (2 * table + v * h * 4) / 1e9,
        "h_GB": 3.0 * nb * h_stream / 1e9,
        "peak_logits_MiB": (4.0 * n * block + 4.0 * n * h) / 2**20,
    }


def roofline_row(impl: str, b: int, s: int, size: int) -> dict:
    n = b * s
    chunk = size if impl == "chunked" else 0
    block = size if impl == "fused" else 0
    flops = chain_flops(impl, n)
    by = chain_bytes(impl, n, block=block, chunk=chunk, batch=b, seq=s)
    total_gb = by["logits_GB"] + by["table_GB"] + by["h_GB"]
    return {
        "impl": impl, "batch": b, "seq": s,
        "size": size or None,
        "chain_TF": round(flops / 1e12, 2),
        **{k: round(x, 2) for k, x in by.items()},
        "chain_GB": round(total_gb, 2),
        "mxu_floor_ms": round(flops / PEAK_FLOPS * 1e3, 2),
        "hbm_floor_ms": round(total_gb * 1e9 / HBM_BPS * 1e3, 2),
    }


def roofline() -> None:
    print("# Analytic vocab-chain roofline (v5e: 197 TFLOP/s bf16, "
          "819 GB/s HBM); per TRAIN STEP, logits chain only")
    print("# chain_TF = matmul FLOPs of the chain; chain_GB = modeled "
          "HBM traffic; peak_logits_MiB = largest resident logits tile")
    for b, s in SHAPES:
        for impl, sizes in (("full", (0,)), ("chunked", (CHUNK,)),
                            ("fused", BLOCKS)):
            for size in sizes:
                print(json.dumps(roofline_row(impl, b, s, size)))


# ---------------------------------------------------------------------------
# measured cells
# ---------------------------------------------------------------------------

def measure(model_name: str, b: int, s: int, impl: str, size: int,
            *, steps: int = 6, warmup: int = 2) -> dict:
    import time

    import jax
    import numpy as np

    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           OptimizerConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
    from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
        SyncReplicas)
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)

    # shared with the gate: ONE batch-builder and ONE timing
    # implementation (flash_sweep.py:206 principle) — sweep cells must
    # measure exactly what the bench rows measure
    from bench import _gpt_batch_at, robust_time

    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu and not os.environ.get("VOCAB_SWEEP_CPU"):
        raise SystemExit("measured cells are TPU cells (CPU timings are "
                         "meaningless); set VOCAB_SWEEP_CPU=1 for a CI "
                         "smoke run, or use --smoke")
    if impl not in ("full", "chunked", "fused"):
        raise SystemExit(f"IMPL must be full/chunked/fused, got {impl!r}")
    cfg = TrainConfig(
        model=model_name, dtype="bfloat16",
        data=DataConfig(batch_size=b, seq_len=s),
        optimizer=OptimizerConfig(name="adamw", learning_rate=1e-4),
        remat="none",
        # long-S cells ride the tuned gate attention config; short-S
        # cells keep xla attention like the gpt_small gate row
        attention_impl="flash" if s >= 4096 else "xla",
        lm_loss_impl=impl if impl != "chunked" else None,
        lm_loss_chunk=size if impl == "chunked" else None,
        lm_loss_vocab_block=(size or None) if impl == "fused" else None)
    model = get_model(model_name, cfg)
    mesh = build_mesh()
    sync = SyncReplicas(model.loss, make_optimizer(cfg.optimizer), mesh)
    state = sync.init(model.init, seed=0,
                      prng_impl="rbg" if on_tpu else None)
    placed = sync.shard_batch(_gpt_batch_at(s)(model, b, 0))
    compiled = sync.step.lower(state, placed).compile()
    ma = compiled.memory_analysis()
    if isinstance(ma, (list, tuple)):
        ma = ma[0]

    state, m_ = compiled(state, placed)      # prime (binds metrics too)
    for _ in range(max(0, warmup - 1)):
        state, m_ = compiled(state, placed)
    jax.block_until_ready(state.params)

    def timed():
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m_ = compiled(state, placed)
        jax.block_until_ready(state.params)
        return time.perf_counter() - t0

    # robust_time rejects the tunnel's corrupt-fast readings; a suspect
    # cell must never pick the winning impl/block for the gate re-base
    dt, suspect = robust_time(timed, steps=steps)
    step_ms = dt / steps * 1e3
    return {
        "model": model_name, "batch": b, "seq": s, "impl": impl,
        "size": size or None,
        "step_ms": round(step_ms, 1),
        "eps_chip": round(b / (dt / steps), 2),
        # CPU jax builds lack the peak stat; 0 = unavailable, not "fits"
        "temp_MiB": round(getattr(ma, "temp_size_in_bytes", 0) / 2**20),
        "peak_MiB": round(getattr(ma, "peak_memory_in_bytes", 0) / 2**20),
        "loss_finite": bool(np.isfinite(float(jax.device_get(
            m_["loss"])))),
        "suspect": bool(suspect),
    }


def _run_cells(cells, env_extra=None) -> None:
    """One fresh subprocess per cell (timing-artifact hygiene); OOM or
    any other per-cell failure becomes an error JSON line, not a dead
    sweep — the crossover table needs the OOM rows. A cell whose
    process dies before its own error handler (host OOM-killer SIGKILL
    at compile is the realistic case for the b64 s512 full cell) is
    recorded from the returncode here, so a row can never silently
    vanish from the table."""
    env = dict(os.environ,
               DTX_JAX_CACHE=os.environ.get("DTX_JAX_CACHE",
                                            "/tmp/dtx_jax_cache"),
               **(env_extra or {}))
    me = os.path.abspath(__file__)
    for mn, b, s, impl, size in cells:
        proc = subprocess.run(
            [sys.executable, me, "cell", mn, str(b), str(s), impl,
             str(size)], env=env, check=False)
        if proc.returncode != 0:
            print(json.dumps({"model": mn, "batch": b, "seq": s,
                              "impl": impl, "size": size or None,
                              "error": f"cell process exited "
                                       f"{proc.returncode} (killed "
                                       "before its error handler — "
                                       "host OOM is the usual cause)"}),
                  flush=True)


def main() -> None:
    if sys.argv[1:2] == ["--roofline"]:
        roofline()
        return
    if sys.argv[1:2] == ["--all"]:
        cells = []
        for b, s in SHAPES:
            cells.append(("gpt", b, s, "full", 0))
            cells.append(("gpt", b, s, "chunked", CHUNK))
            cells += [("gpt", b, s, "fused", blk) for blk in BLOCKS]
        _run_cells(cells)
        return
    if sys.argv[1:2] == ["--smoke"]:
        # tiny CPU end-to-end pass: every impl executes and emits the
        # JSON contract; block 200 exercises the vocab-not-divisible
        # padding (gpt_tiny vocab = 1000). b8 so the batch still shards
        # when the host is a virtual 8-device CPU mesh (the test rig)
        cells = [("gpt_tiny", 8, 64, "full", 0),
                 ("gpt_tiny", 8, 64, "chunked", 32),
                 ("gpt_tiny", 8, 64, "fused", 128),
                 ("gpt_tiny", 8, 64, "fused", 200)]
        _run_cells(cells, env_extra={"VOCAB_SWEEP_CPU": "1",
                                     "JAX_PLATFORMS": "cpu"})
        return
    if sys.argv[1:2] != ["cell"]:
        raise SystemExit(__doc__)
    mn, b, s, impl = (sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
                      sys.argv[5])
    size = int(sys.argv[6]) if len(sys.argv) > 6 else 0
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DTX_JAX_CACHE", "/tmp/dtx_jax_cache"))
    try:
        print(json.dumps(measure(mn, b, s, impl, size)), flush=True)
    except Exception as e:  # noqa: BLE001 — OOM at compile is a finding
        print(json.dumps({"model": mn, "batch": b, "seq": s,
                          "impl": impl, "size": size or None,
                          "error": f"{type(e).__name__}: {str(e)[:200]}"}),
              flush=True)


if __name__ == "__main__":
    main()
