#!/usr/bin/env python
"""Long-context gate-config sweep (VERDICT r4 task #4 / weak #1).

The bert_long gate workload (S=4096 b4 flash) was configured with
``remat=full`` when the knob was built at S=1024 b8 — but at the gate
shape the step uses ~12% of HBM, which suggests a cheaper checkpoint
policy (or none) fits and is faster: the gate may be measuring an
over-conservative config. This sweep measures remat x {none, dots,
full} for BOTH long-context programs — bert_long (non-causal MLM) and
gpt_long (causal + chunked LM loss) — at the gate shape: step time,
XLA temp memory, examples/sec.

One fresh process per cell (round-4 lesson: long-lived processes
through the axon tunnel accumulate timing artifacts); one JSON line
per cell; the decision table lives in BASELINE.md.

Usage: python experiments/long_context_sweep.py MODEL REMAT   # one cell
       python experiments/long_context_sweep.py --all         # loop
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODELS = ("bert", "gpt")
REMATS = ("none", "dots", "full")


def measure(model_name: str, remat: str, *, batch=4, seq=4096,
            steps=6, warmup=2) -> dict:
    import jax
    import numpy as np

    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           OptimizerConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
    from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
        SyncReplicas)
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)

    cfg = TrainConfig(model=model_name, dtype="bfloat16",
                      data=DataConfig(batch_size=batch, seq_len=seq),
                      optimizer=OptimizerConfig(name="adamw",
                                                learning_rate=1e-4),
                      attention_impl="flash", remat=remat,
                      lm_loss_chunk=512 if model_name == "gpt" else None)
    model = get_model(model_name, cfg)
    mesh = build_mesh()
    sync = SyncReplicas(model.loss, make_optimizer(cfg.optimizer), mesh)
    state = sync.init(model.init, seed=0, prng_impl="rbg")
    rs = np.random.RandomState(0)
    if model_name == "gpt":
        batch_np = {
            "input_ids": rs.randint(0, cfg.data.vocab_size, (batch, seq),
                                    dtype=np.int32),
            "attention_mask": np.ones((batch, seq), np.int32),
        }
    else:
        c = model.cfg
        m = c.max_predictions
        batch_np = {
            "input_ids": rs.randint(0, c.vocab_size, (batch, seq),
                                    dtype=np.int32),
            "token_type_ids": np.zeros((batch, seq), np.int32),
            "attention_mask": np.ones((batch, seq), np.int32),
            "masked_positions": np.tile(np.arange(m, dtype=np.int32),
                                        (batch, 1)),
            "masked_labels": rs.randint(0, c.vocab_size, (batch, m),
                                        dtype=np.int32),
            "masked_weights": np.ones((batch, m), np.float32),
        }
    placed = sync.shard_batch(batch_np)
    compiled = sync.step.lower(state, placed).compile()
    ma = compiled.memory_analysis()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]

    for _ in range(warmup):
        state, m_ = compiled(state, placed)
    jax.block_until_ready(state.params)

    def timed():
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m_ = compiled(state, placed)
        jax.block_until_ready(state.params)
        return time.perf_counter() - t0

    dt = max(timed(), timed())
    step_ms = dt / steps * 1e3
    peak = 197e12
    flops = float(ca.get("flops", 0.0))
    return {
        "model": model_name, "remat": remat,
        "step_ms": round(step_ms, 1),
        "eps_chip": round(batch / (dt / steps), 2),
        "temp_MiB": round(ma.temp_size_in_bytes / 2**20),
        "peak_MiB": round(ma.peak_memory_in_bytes / 2**20),
        "mfu": round(flops / (dt / steps) / peak, 4) if flops else None,
        "loss_finite": bool(np.isfinite(float(jax.device_get(m_["loss"])))),
    }


def main() -> None:
    if sys.argv[1:2] == ["--all"]:
        env = dict(os.environ,
                   DTX_JAX_CACHE=os.environ.get("DTX_JAX_CACHE",
                                                "/tmp/dtx_jax_cache"))
        for mn in MODELS:
            for r in REMATS:
                subprocess.run([sys.executable, os.path.abspath(__file__),
                                mn, r], env=env, check=False)
        return
    mn, r = sys.argv[1], sys.argv[2]
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DTX_JAX_CACHE", "/tmp/dtx_jax_cache"))
    try:
        print(json.dumps(measure(mn, r)), flush=True)
    except Exception as e:  # noqa: BLE001 — OOM at compile is a finding
        print(json.dumps({"model": mn, "remat": r,
                          "error": f"{type(e).__name__}: {str(e)[:200]}"}),
              flush=True)


if __name__ == "__main__":
    main()
