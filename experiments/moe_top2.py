#!/usr/bin/env python
"""MoE top-2 routing cost on the real chip (VERDICT r4 task #8/weak #6).

The gate measures MoE-BERT at top-1 (Switch) routing only; top-2 — the
GShard/ST-MoE default — is implemented and oracle-tested but has no
measured cost story. This sweep measures the moe_bert bench config
(b64, seq 128, adamw, rbg, bf16) at top-2 across the standard capacity
factors, recording step time AND the routing-health metrics the round-4
visibility work exposed (dropped_fraction — top-2 doubles assignments,
so capacity pressure is the central trade).

One fresh process per cell; one JSON line per cell; the BASELINE.md
table holds the verdicts.

Usage: python experiments/moe_top2.py TOPK CAPACITY
       python experiments/moe_top2.py --all
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CELLS = [(1, 1.25), (2, 1.0), (2, 1.25), (2, 2.0)]


def measure(top_k: int, capacity: float, *, batch=64, steps=20,
            warmup=5) -> dict:
    import jax
    import numpy as np

    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           OptimizerConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
    from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
        SyncReplicas)
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)

    cfg = TrainConfig(model="moe_bert", dtype="bfloat16",
                      data=DataConfig(batch_size=batch),
                      optimizer=OptimizerConfig(name="adamw",
                                                learning_rate=1e-4),
                      moe_top_k=top_k, moe_capacity_factor=capacity)
    model = get_model("moe_bert", cfg)
    mesh = build_mesh()
    sync = SyncReplicas(model.loss, make_optimizer(cfg.optimizer), mesh)
    state = sync.init(model.init, seed=0, prng_impl="rbg")
    placed = sync.shard_batch(model.dummy_batch(batch))
    compiled = sync.step.lower(state, placed).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]

    for _ in range(warmup):
        state, m = compiled(state, placed)
    jax.block_until_ready(state.params)

    def timed():
        nonlocal state, m
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = compiled(state, placed)
        jax.block_until_ready(state.params)
        return time.perf_counter() - t0

    dt = max(timed(), timed())
    step_ms = dt / steps * 1e3
    host = {k: float(np.mean(np.asarray(jax.device_get(v))))
            for k, v in m.items()
            if k in ("loss", "dropped_token_fraction", "aux_loss",
                     "expert_load_min", "expert_load_max")}
    return {
        "top_k": top_k, "capacity_factor": capacity,
        "step_ms": round(step_ms, 1),
        "eps_chip": round(batch / (dt / steps), 1),
        "flops_T": round(float(ca.get("flops", 0.0)) / 1e12, 3),
        **{k: round(v, 4) for k, v in host.items()},
    }


def main() -> None:
    if sys.argv[1:2] == ["--all"]:
        env = dict(os.environ,
                   DTX_JAX_CACHE=os.environ.get("DTX_JAX_CACHE",
                                                "/tmp/dtx_jax_cache"))
        for k, c in CELLS:
            subprocess.run([sys.executable, os.path.abspath(__file__),
                            str(k), str(c)], env=env, check=False)
        return
    k, c = int(sys.argv[1]), float(sys.argv[2])
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DTX_JAX_CACHE", "/tmp/dtx_jax_cache"))
    try:
        print(json.dumps(measure(k, c)), flush=True)
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"top_k": k, "capacity_factor": c,
                          "error": f"{type(e).__name__}: {str(e)[:200]}"}),
              flush=True)


if __name__ == "__main__":
    main()
