#!/usr/bin/env python
"""Serving chaos soak: seeded dirty-failure scenarios against the
continuous-batching engine with asserted self-healing invariants — the
serving twin of experiments/chaos_soak.py (training).

Each scenario builds a real engine over a tiny seeded paged GPT export
(CPU works: ``JAX_PLATFORMS=cpu``), injects one failure class through
the :mod:`~.runtime.faults` seams (``engine.prefill`` /
``engine.decode_step`` / ``engine.admit`` / ``pool.alloc``) or the
engine's own control surface (cancel/drain/deadlines), and asserts the
round-14 contract:

- ``deadline_storm``   — a wave of 1 ms-deadline requests races a wave
                         with no deadline: every tight request fails
                         with DeadlineExceededError, every loose
                         request's greedy bytes MATCH an undisturbed
                         run, and ``blocks_free`` recovers exactly.
- ``poison_step``      — a shared decode step fails twice at the same
                         invocation: the newest-admitted request is
                         evicted (PoisonedRequestError), survivors'
                         bytes match an undisturbed run ("repaired,
                         not survived"), ``redispatches >= 2``.
- ``blocks_cancel``    — a tight block pool over-committed by design:
                         mid-decode exhaustion fails exactly the
                         starved request loudly; cancelling a live
                         neighbor frees its blocks IMMEDIATELY (not at
                         retirement), the survivor finishes to parity,
                         and the pool recovers to the exact free count.
- ``drain_under_load`` — drain() with the queue still full: zero
                         dropped requests (all bytes to parity), new
                         admissions refused with DrainingError,
                         ``serving_drain_ms`` within budget, engine
                         dead after.
- ``flaky_dispatch``   — a one-shot transient decode fault: the
                         bounded re-dispatch heals it invisibly (zero
                         failed requests, bytes to parity, exactly one
                         extra dispatch counted).
- ``watchdog_trip``    — a wedged decode dispatch: /healthz flips
                         live -> stalled, close() raises
                         EngineStalledError naming the heartbeat age
                         instead of silently leaking the thread, and a
                         released engine still tears down clean.
- ``queue_full_retry`` — clients hammering a 2-deep admission queue
                         honor 429/Retry-After semantics in a retry
                         loop: every request eventually lands, bytes
                         to parity.
- ``overload_storm``   — ~2x sustainable offered load (round 18):
                         every admitted interactive request finishes
                         within its deadline to byte parity with zero
                         failures, best_effort is shed 429-class with
                         a measured Retry-After once the pressure
                         ladder leaves healthy, shed accounting is
                         exact, and pressure/blocks recover after.
- ``long_prompt_storm``— chunked prefill (round 18): full-length
                         prompts admit chunk by chunk while a live
                         short decoder keeps stepping — the dispatch
                         order proves decode steps interleave between
                         one prompt's chunks, bytes match the
                         chunk-off engine exactly, chunk accounting
                         is exact, blocks recover.
- ``spec_verify_fault``— a seeded ``engine.decode_step`` fault lands
                         DURING a K-token speculative verify dispatch
                         (round 16): the transient heals via the same
                         bounded re-dispatch protocol (byte parity,
                         exactly one extra dispatch, zero failures);
                         a repeat failure at the same dispatch evicts
                         the newest-admitted request with survivors
                         byte-identical and every per-row ``pos``
                         rewound exactly (pinned by byte parity plus
                         exact ``blocks_free`` recovery).

Usage::

    JAX_PLATFORMS=cpu python experiments/serving_chaos.py \
        [--scenario all] [--seed 0] [--smoke]

Prints one JSON line per scenario ({"scenario", "ok", "detail",
"metrics"}) — ``metrics`` carries the engine-registry counters the
scenario advanced (``serving_requests_failed_total`` /
``serving_cancelled_total`` / ``serving_deadline_expired_total`` /
``serving_redispatches_total`` / ``serving_drain_ms``) — plus a final
summary line. Exits nonzero if any scenario fails.
tests/test_serving_chaos.py runs the full soak as a ``slow`` test and
keeps a fast smoke of every scenario in tier-1.
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from distributed_tensorflow_example_tpu.runtime import faults

# one tiny seeded export shared by all scenarios (built once in main);
# per-request max_new stays well under the exported depth so scenarios
# pick short runs for speed and long runs where they need a live window
PROMPT_LEN = 8
MAX_NEW = 16
SLOTS = 4
BLOCK = 4


def _bps() -> int:
    """Blocks per full-depth slot at the shared export shapes."""
    return -(-(PROMPT_LEN + MAX_NEW) // BLOCK)


def build_chaos_export(out_dir: str, *, seed: int,
                       num_blocks: int | None = None) -> int:
    """The scenario artifact: paged stepwise export at the module
    shapes (paged so block accounting is observable; ``num_blocks``
    lets the exhaustion scenario under-provision deliberately)."""
    from serving_load import build_export
    return build_export(
        out_dir, prompt_len=PROMPT_LEN, max_new=MAX_NEW, slots=SLOTS,
        seed=seed, paged=True, block_size=BLOCK,
        num_blocks=(1 + 4 * SLOTS * _bps()
                    if num_blocks is None else num_blocks))


def fresh_engine(export_dir: str, **kw):
    """A started engine over the shared artifact. Prefix cache OFF by
    default: every scenario asserts EXACT ``blocks_free`` recovery,
    and cached prefixes legitimately retain block references."""
    from distributed_tensorflow_example_tpu.serving import load_stepwise
    from distributed_tensorflow_example_tpu.serving_batch import \
        GenerationEngine
    kw.setdefault("prefix_cache", False)
    return GenerationEngine(load_stepwise(export_dir), **kw).start()


def seeded_prompts(n: int, seed: int, vocab: int):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, vocab, (int(rs.randint(1, PROMPT_LEN + 1)),))
            .astype(np.int32) for _ in range(n)]


def reference_run(export_dir: str, prompts, max_new: int,
                  sequential: bool = False) -> list:
    """The undisturbed oracle: the same prompts through a clean engine
    (greedy rows are computationally independent, so any surviving
    subset of a chaos run must byte-match its rows here).
    ``sequential`` serves one request at a time — the oracle for the
    deliberately under-provisioned pool, where a concurrent reference
    would hit the very exhaustion the scenario injects."""
    eng = fresh_engine(export_dir)
    try:
        if sequential:
            return [eng.submit(p, max_new=max_new).result(timeout=120)
                    for p in prompts]
        handles = [eng.submit(p, max_new=max_new) for p in prompts]
        return [h.result(timeout=120) for h in handles]
    finally:
        eng.close()


def counters(eng) -> dict:
    """The scenario's published-metrics view: the self-healing counters
    this PR added, straight from the engine registry snapshot."""
    snap = eng.registry.snapshot()

    def v(name):
        m = snap.get(name)
        return (m.get("value", 0) if isinstance(m, dict) else m) or 0

    return {k: v(k) for k in (
        "serving_requests_failed_total", "serving_cancelled_total",
        "serving_deadline_expired_total", "serving_redispatches_total",
        "serving_drain_ms", "serving_shed_total",
        "serving_shed_infeasible_total", "serving_prefill_chunks_total",
        "serving_pressure_transitions_total")}


def _wait(pred, timeout=30.0, what="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# scenarios — each returns (detail, metrics)
# ---------------------------------------------------------------------------

def scenario_deadline_storm(d: str, seed: int, vocab: int):
    """Round-18 note: a 1 ms deadline can now be SHED (429-class
    ShedError, deadline infeasible at the measured rate) instead of
    expiring into the 504-class DeadlineExceededError once the
    engine's decode EMA has a signal — both are the fail-fast-and-
    return-blocks outcome this storm pins, so either counts; the
    accounting assertion covers their sum."""
    from distributed_tensorflow_example_tpu.serving_batch import (
        DeadlineExceededError, ShedError)
    prompts = seeded_prompts(2 * SLOTS, seed, vocab)
    tight, loose = prompts[::2], prompts[1::2]
    ref = reference_run(d, loose, max_new=6)
    eng = fresh_engine(d)
    try:
        free0 = eng.stats()["blocks_free"]
        handles = []
        for i in range(len(prompts)):
            if i % 2 == 0:          # the storm: 1 ms — expires queued
                handles.append(eng.submit(prompts[i], max_new=MAX_NEW,
                                          deadline_ms=1))
            else:
                handles.append(eng.submit(prompts[i], max_new=6))
        expired = shed = survived = 0
        for i, h in enumerate(handles):
            if i % 2 == 0:
                try:
                    h.result(timeout=120)
                    raise AssertionError(
                        f"1 ms-deadline request {h.request_id} was "
                        "never expired or shed")
                except ShedError:
                    shed += 1
                except DeadlineExceededError:
                    expired += 1
            else:
                toks = h.result(timeout=120)
                assert toks == ref[i // 2], \
                    f"survivor {i} diverged from the undisturbed run"
                survived += 1
        _wait(lambda: eng.stats()["blocks_free"] == free0,
              what="exact blocks_free recovery")
        met = counters(eng)
        assert met["serving_deadline_expired_total"] == expired, met
        assert met["serving_shed_infeasible_total"] == shed, met
        return (f"{expired} expired (504-class) + {shed} shed "
                f"(429-class feasibility), {survived} survivors to "
                f"byte parity, blocks_free recovered to {free0}",
                met)
    finally:
        eng.close()


def scenario_poison_step(d: str, seed: int, vocab: int):
    from distributed_tensorflow_example_tpu.serving_batch import \
        PoisonedRequestError
    prompts = seeded_prompts(3, seed + 1, vocab)
    ref = reference_run(d, prompts, max_new=8)
    # two rules on the SAME invocation: the retry (attempt 1) re-probes
    # index 2 and hits the second rule — the repeat failure that
    # triggers the newest-admitted eviction
    faults.install(faults.parse_spec(
        "engine.decode_step:step=2;engine.decode_step:step=2",
        seed=seed))
    try:
        eng = fresh_engine(d)
        try:
            handles = [eng.submit(p, max_new=8) for p in prompts]
            outs, poisoned = [], []
            for i, h in enumerate(handles):
                try:
                    outs.append((i, h.result(timeout=120)))
                except PoisonedRequestError:
                    poisoned.append(i)
            assert poisoned == [2], \
                f"expected exactly the newest admission evicted, got " \
                f"{poisoned}"
            for i, toks in outs:
                assert toks == ref[i], \
                    f"survivor {i} diverged after the re-dispatch"
            met = counters(eng)
            assert met["serving_redispatches_total"] >= 2, met
            assert met["serving_requests_failed_total"] == 1, met
            return (f"decode step 2 failed twice; request {poisoned[0]} "
                    f"evicted, {len(outs)} survivors to byte parity, "
                    f"{met['serving_redispatches_total']} re-dispatches",
                    met)
        finally:
            eng.close()
    finally:
        faults.install(None)


def scenario_blocks_cancel(d_tight: str, seed: int, vocab: int):
    from distributed_tensorflow_example_tpu.serving_batch import (
        BlocksExhaustedError, RequestCancelledError)
    rs = np.random.RandomState(seed + 2)
    # full-length prompts: 2 blocks each at admission, growing to
    # _bps() at full depth — three full-depth requests need 3*_bps()
    # blocks against a pool of 2*_bps(): one MUST starve mid-decode
    prompts = [rs.randint(0, vocab, (PROMPT_LEN,)).astype(np.int32)
               for _ in range(3)]
    ref = reference_run(d_tight, prompts, max_new=MAX_NEW,
                        sequential=True)
    eng = fresh_engine(d_tight)
    try:
        free0 = eng.stats()["blocks_free"]
        handles = [eng.submit(p, max_new=MAX_NEW) for p in prompts]
        _wait(lambda: eng.stats()["live_slots"] >= 2,
              what="two live slots")
        # cancel the FIRST live request mid-decode: its blocks must
        # come back at the next step boundary, not at retirement
        free_before = eng.stats()["blocks_free"]
        assert handles[0].cancel(), "cancel() found nothing to cancel"
        _wait(lambda: eng.stats()["blocks_free"] > free_before,
              what="cancelled request's blocks returning to the pool")
        outcomes = {"done": 0, "exhausted": 0, "cancelled": 0}
        for i, h in enumerate(handles):
            try:
                toks = h.result(timeout=120)
                assert toks == ref[i], \
                    f"survivor {i} diverged from the undisturbed run"
                outcomes["done"] += 1
            except RequestCancelledError:
                outcomes["cancelled"] += 1
            except BlocksExhaustedError:
                outcomes["exhausted"] += 1
        assert outcomes["cancelled"] == 1, outcomes
        assert outcomes["done"] >= 1, outcomes
        _wait(lambda: eng.stats()["blocks_free"] == free0,
              what="exact blocks_free recovery")
        # the pool must still SERVE after recovery, not just count right
        probe = eng.submit(prompts[0], max_new=2).result(timeout=120)
        assert probe == ref[0][:2], "post-recovery probe diverged"
        met = counters(eng)
        return (f"{outcomes} against a {free0}-block pool; recovery "
                "exact; post-recovery probe served to parity", met)
    finally:
        eng.close()


def scenario_drain_under_load(d: str, seed: int, vocab: int):
    from distributed_tensorflow_example_tpu.serving_batch import \
        DrainingError
    prompts = seeded_prompts(2 * SLOTS, seed + 3, vocab)
    ref = reference_run(d, prompts, max_new=4)
    eng = fresh_engine(d, drain_timeout_s=60.0)
    try:
        handles = [eng.submit(p, max_new=4) for p in prompts]

        # drain in the background so THIS thread can probe the
        # draining window deterministically (the flag flips at
        # drain() entry; the 2*SLOTS-deep backlog keeps the window
        # open for hundreds of CPU decode steps)
        result: dict = {}
        th = threading.Thread(
            target=lambda: result.setdefault("ms", eng.drain()))
        th.start()
        _wait(lambda: eng.health()["draining"], what="drain flag")
        try:
            eng.submit(prompts[0], max_new=2)
            raise AssertionError("admission accepted during drain")
        except DrainingError as e:
            assert e.retry_after > 0, e
        th.join(timeout=120)
        drain_ms = result["ms"]
        for i, h in enumerate(handles):
            toks = h.result(timeout=1)       # drained = already done
            assert toks == ref[i], f"drained request {i} diverged"
        assert drain_ms <= 60_000, drain_ms
        assert eng.health()["status"] == "dead", eng.health()
        met = counters(eng)
        assert met["serving_drain_ms"] == drain_ms, met
        return (f"{len(handles)} in-flight requests finished to parity "
                f"under drain ({drain_ms:.0f} ms); late admission "
                "refused 503-class; engine dead after", met)
    finally:
        try:
            eng.close()
        except RuntimeError:
            pass
    return None


def scenario_flaky_dispatch(d: str, seed: int, vocab: int):
    prompts = seeded_prompts(3, seed + 4, vocab)
    ref = reference_run(d, prompts, max_new=6)
    # ONE one-shot rule: attempt 0 raises, the retry re-probes the same
    # spent rule and heals — the transient class
    faults.install(faults.parse_spec("engine.decode_step:step=2",
                                     seed=seed))
    try:
        eng = fresh_engine(d)
        try:
            handles = [eng.submit(p, max_new=6) for p in prompts]
            outs = [h.result(timeout=120) for h in handles]
            assert outs == ref, "transient retry changed greedy bytes"
            met = counters(eng)
            assert met["serving_redispatches_total"] == 1, met
            assert met["serving_requests_failed_total"] == 0, met
            return ("one-shot decode fault healed by a single "
                    "re-dispatch; all bytes to parity", met)
        finally:
            eng.close()
    finally:
        faults.install(None)


def scenario_watchdog_trip(d: str, seed: int, vocab: int):
    from distributed_tensorflow_example_tpu.serving_batch import \
        EngineStalledError
    eng = fresh_engine(d, stall_after_s=0.05)
    wedged, release = threading.Event(), threading.Event()
    orig = eng.sw.decode

    def wedge(feats):
        wedged.set()
        release.wait(timeout=60)
        return orig(feats)

    eng.sw.decode = wedge
    try:
        prompt = seeded_prompts(1, seed + 5, vocab)[0]
        h = eng.submit(prompt, max_new=4)
        assert wedged.wait(timeout=30), "decode never dispatched"
        assert eng.health()["status"] in ("live", "stalled")
        _wait(lambda: eng.health()["status"] == "stalled",
              what="watchdog flipping to stalled")
        age = eng.health()["heartbeat_age_s"]
        try:
            eng.close(timeout=0.2)
            raise AssertionError(
                "close() returned with the scheduler thread wedged")
        except EngineStalledError as e:
            assert "heartbeat" in str(e), e
        release.set()
        eng.close(timeout=30)               # parks clean once released
        assert eng.health()["status"] == "dead"
        try:
            h.result(timeout=1)
        except RuntimeError:
            pass                            # failed loudly by close()
        met = counters(eng)
        return (f"watchdog saw heartbeat_age {age:.2f}s > 0.05s; "
                "close() raised EngineStalledError while wedged; "
                "released engine parked clean", met)
    finally:
        release.set()
        try:
            eng.close()
        except RuntimeError:
            pass


def scenario_queue_full_retry(d: str, seed: int, vocab: int):
    from distributed_tensorflow_example_tpu.serving_batch import \
        QueueFullError
    n = 8
    prompts = seeded_prompts(n, seed + 6, vocab)
    ref = reference_run(d, prompts, max_new=4)
    eng = fresh_engine(d, max_queue=2)
    try:
        outs: list = [None] * n
        rejections = [0] * n                 # per-thread, no sharing

        def client(i):
            while True:
                try:
                    h = eng.submit(prompts[i], max_new=4)
                    break
                except QueueFullError as e:
                    rejections[i] += 1
                    time.sleep(min(e.retry_after, 0.02))
            outs[i] = h.result(timeout=120)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert outs == ref, "retried requests diverged from parity"
        assert sum(rejections) > 0, \
            "a 2-deep queue never refused an 8-request hammer"
        met = counters(eng)
        assert met["serving_requests_failed_total"] == 0, met
        return (f"{n} requests through a 2-deep queue with "
                f"{sum(rejections)} 429-class refusals, all to parity",
                met)
    finally:
        eng.close()


def scenario_spec_verify_fault(d: str, seed: int, vocab: int):
    """Round-16 coverage: the decode-step fault seam fires DURING a
    speculative verify dispatch. Builds its own verify-program export
    (the shared scenario artifact carries none) over a repetitive
    workload so verify dispatches genuinely happen, locates the first
    one via a seeded instrumented run (everything is deterministic, so
    the same global dispatch index is a verify dispatch in every
    re-run), then asserts the PR-10 protocol end-to-end on that exact
    dispatch."""
    from serving_load import build_export
    from distributed_tensorflow_example_tpu.serving_batch import \
        PoisonedRequestError
    rs = np.random.RandomState(seed + 7)
    pattern = rs.randint(0, vocab, (3,)).astype(np.int32)
    prompts = [np.tile(pattern, 3)[:int(rs.randint(4, PROMPT_LEN + 1))]
               .astype(np.int32) for _ in range(3)]
    with tempfile.TemporaryDirectory() as ds:
        # max_new=10 (not the module MAX_NEW): the scenario's requests
        # cap at 10 tokens, and the smaller monolithic scan keeps this
        # tier-1 smoke's export cheap
        build_export(ds, prompt_len=PROMPT_LEN, max_new=10,
                     slots=SLOTS, seed=seed, paged=True,
                     block_size=BLOCK,
                     num_blocks=1 + 4 * SLOTS * _bps(), spec_tokens=4)

        def run(spec: int, wrap: bool = False):
            eng = fresh_engine(ds, spec_tokens=spec)
            order: list[str] = []
            if wrap:
                od, ov = eng.sw.decode, eng.sw.verify
                eng.sw.decode = \
                    lambda f: (order.append("decode"), od(f))[1]
                eng.sw.verify = \
                    lambda f: (order.append("verify"), ov(f))[1]
            try:
                free0 = eng.stats()["blocks_free"]
                handles = [eng.submit(p, max_new=10) for p in prompts]
                outs: list = []
                poisoned: list[str] = []
                for h in handles:
                    try:
                        outs.append(h.result(timeout=120))
                    except PoisonedRequestError:
                        outs.append(None)
                        poisoned.append(h.request_id)
                _wait(lambda: eng.stats()["blocks_free"] == free0,
                      what="exact blocks_free recovery")
                return outs, poisoned, counters(eng), eng.stats(), order
            finally:
                eng.close()

        ref, p0, _, _, _ = run(0)
        und, p1, _, s1, order = run(4, wrap=True)
        assert not p0 and not p1
        assert und == ref, \
            "undisturbed spec run diverged from the spec-off oracle"
        assert s1["spec_accepted"] > 0, s1
        assert "verify" in order, \
            "the repetitive workload never dispatched a verify step"
        v_idx = order.index("verify") + 1     # 1-based seam index
        # transient: one fault at exactly that verify dispatch — the
        # bounded re-dispatch heals it invisibly
        faults.install(faults.parse_spec(
            f"engine.decode_step:step={v_idx}", seed=seed))
        try:
            outs_t, pois_t, met_t, st_t, _ = run(4)
        finally:
            faults.install(None)
        assert not pois_t and outs_t == ref, \
            "transient verify fault was not healed to byte parity"
        assert met_t["serving_redispatches_total"] == 1, met_t
        assert met_t["serving_requests_failed_total"] == 0, met_t
        assert st_t["verify_steps"] > 0, st_t
        # repeat failure at the SAME verify dispatch: newest-admitted
        # evicted, survivors byte-identical, per-row pos rewound
        # exactly (byte parity + the exact blocks_free recovery inside
        # run() are the rewind's observables)
        faults.install(faults.parse_spec(
            f"engine.decode_step:step={v_idx};"
            f"engine.decode_step:step={v_idx}", seed=seed))
        try:
            outs_p, pois_p, met_p, _, _ = run(4)
        finally:
            faults.install(None)
        assert len(pois_p) == 1, \
            f"expected exactly one eviction, got {pois_p}"
        survivors = [(i, o) for i, o in enumerate(outs_p)
                     if o is not None]
        assert all(o == ref[i] for i, o in survivors), \
            "a survivor diverged after the verify-dispatch eviction"
        assert met_p["serving_requests_failed_total"] == 1, met_p
        assert met_p["serving_redispatches_total"] >= 2, met_p
    return (f"verify dispatch {v_idx}: transient healed to byte parity "
            f"(1 re-dispatch, 0 failures); repeat fault evicted "
            f"{pois_p[0]} with {len(survivors)} survivors to parity "
            "and exact pos/blocks recovery", met_p)


def scenario_overload_storm(d: str, seed: int, vocab: int):
    """Round-18 overload gate: ~2x sustainable offered load against a
    small admission queue. Every ADMITTED interactive request finishes
    within its (generous) deadline with zero client-visible failures
    and byte parity; once the pressure ladder leaves healthy,
    best_effort submissions are shed with 429-class ShedError carrying
    a measured Retry-After (never a timeout); the shed accounting is
    exact per class; and pressure returns to healthy with blocks_free
    recovered exactly once the storm drains."""
    from distributed_tensorflow_example_tpu.serving_batch import (
        QueueFullError, ShedError)
    prompts = seeded_prompts(3 * SLOTS, seed + 8, vocab)
    ref = reference_run(d, prompts, max_new=8)
    eng = fresh_engine(d, max_queue=3 * SLOTS)
    try:
        free0 = eng.stats()["blocks_free"]
        # the interactive base load: 3x the slot count, generous
        # deadlines — the class the ladder protects
        handles = [eng.submit(p, max_new=8, deadline_ms=120_000)
                   for p in prompts]
        _wait(lambda: eng._pressure_level >= 1,
              what="pressure ladder leaving healthy under backlog")
        shed = 0
        retry_afters = []
        probe = seeded_prompts(1, seed + 9, vocab)[0]
        probe_handles = []
        for _ in range(200):
            try:
                probe_handles.append(
                    eng.submit(probe, max_new=2,
                               priority="best_effort"))
            except ShedError as e:
                shed += 1
                retry_afters.append(e.retry_after)
                if shed >= 3:
                    break
            except QueueFullError:
                pass        # full below the ladder: plain pushback
            time.sleep(0.002)
        assert shed > 0, "the ladder never shed best_effort traffic"
        assert all(ra >= 0.0 for ra in retry_afters), retry_afters
        for i, h in enumerate(handles):
            toks = h.result(timeout=120)
            assert toks == ref[i], \
                f"interactive request {i} diverged under overload"
        for h in probe_handles:     # admitted below the ladder: fine
            try:
                h.result(timeout=120)
            except ShedError:
                # admitted at healthy, then swept by a later
                # interactive_only rung while still queued — the same
                # 429-class outcome, counted in the same ledger
                shed += 1
        _wait(lambda: eng.stats()["blocks_free"] == free0,
              what="exact blocks_free recovery")
        _wait(lambda: eng.stats()["pressure"] == "healthy",
              what="pressure returning to healthy after the storm")
        met = counters(eng)
        st = eng.stats()
        assert met["serving_shed_total"] == shed, (met, shed)
        assert st["shed_best_effort"] == shed, st
        assert met["serving_deadline_expired_total"] == 0, met
        assert met["serving_requests_failed_total"] == 0, met
        assert met["serving_pressure_transitions_total"] >= 2, met
        return (f"{len(handles)} interactive requests to byte parity "
                f"with zero failures under 2x load; {shed} "
                f"best_effort shed 429-class with measured "
                f"Retry-After; pressure healthy again, blocks "
                f"recovered to {free0}", met)
    finally:
        eng.close()


def scenario_long_prompt_storm(d_unused: str, seed: int,
                               vocab_unused: int):
    """Round-18 chunked-prefill gate: a live short decoder keeps
    decoding WHILE a wave of full-length prompts admits chunk by chunk
    — the dispatch order proves decode steps interleave between a
    single prompt's chunks (impossible with the monolithic prefill),
    greedy bytes stay byte-identical to the chunk-off engine over the
    same export, the chunk accounting is exact, and blocks_free
    recovers."""
    from serving_load import build_export
    rs = np.random.RandomState(seed + 10)
    with tempfile.TemporaryDirectory() as ds:
        # its own export: the shared scenario artifact carries no
        # chunk program; 16-token prompts over 4-token blocks = 4
        # chunks per long admission
        pl = 16
        vocab = build_export(ds, prompt_len=pl, max_new=MAX_NEW,
                             slots=SLOTS, seed=seed, paged=True,
                             block_size=BLOCK, prefill_chunk=BLOCK,
                             num_blocks=1 + 4 * SLOTS
                             * -(-(pl + MAX_NEW) // BLOCK))
        long_prompts = [rs.randint(0, vocab, (pl,)).astype(np.int32)
                        for _ in range(2)]
        short = rs.randint(0, vocab, (3,)).astype(np.int32)

        def run(chunk, wrap=False):
            eng = fresh_engine(ds, prefill_chunk_tokens=chunk)
            order: list[str] = []
            if wrap:
                od, oc = eng.sw.decode, eng.sw.prefill_chunk
                eng.sw.decode = \
                    lambda f: (order.append("decode"), od(f))[1]
                eng.sw.prefill_chunk = \
                    lambda f: (order.append("chunk"), oc(f))[1]
            try:
                free0 = eng.stats()["blocks_free"]
                h0 = eng.submit(short, max_new=MAX_NEW)
                _wait(lambda: eng.stats()["live_slots"] >= 1,
                      what="the short decoder going live")
                hs = [eng.submit(p, max_new=4) for p in long_prompts]
                outs = [h.result(timeout=120) for h in [h0, *hs]]
                _wait(lambda: eng.stats()["blocks_free"] == free0,
                      what="exact blocks_free recovery")
                return outs, counters(eng), order
            finally:
                eng.close()

        ref, met0, _ = run(0)
        outs, met1, order = run(BLOCK, wrap=True)
    assert outs == ref, \
        "chunked admission diverged from the monolithic prefill"
    assert met0["serving_prefill_chunks_total"] == 0, met0
    # short prompt: 1 chunk; each long prompt: pl/BLOCK chunks
    want = 1 + 2 * (pl // BLOCK)
    assert met1["serving_prefill_chunks_total"] == want, (met1, want)
    first, last = order.index("chunk"), len(order) - 1 - \
        order[::-1].index("chunk")
    interleaved = "decode" in order[first:last]
    assert interleaved, \
        f"no decode step ever ran between prefill chunks: {order}"
    assert met1["serving_requests_failed_total"] == 0, met1
    return (f"{want} chunk dispatches interleaved with shared decode "
            f"steps (order window {order[first:last + 1][:12]}...), "
            "all bytes to chunk-off parity, blocks recovered", met1)


SCENARIOS = {
    "deadline_storm": scenario_deadline_storm,
    "poison_step": scenario_poison_step,
    "blocks_cancel": scenario_blocks_cancel,
    "drain_under_load": scenario_drain_under_load,
    "flaky_dispatch": scenario_flaky_dispatch,
    "watchdog_trip": scenario_watchdog_trip,
    "queue_full_retry": scenario_queue_full_retry,
    "spec_verify_fault": scenario_spec_verify_fault,
    "overload_storm": scenario_overload_storm,
    "long_prompt_storm": scenario_long_prompt_storm,
}

#: scenarios that need the deliberately under-provisioned block pool
TIGHT_POOL = {"blocks_cancel"}


#: the tight-pool export's block count: 2 full-depth slots' worth
#: MINUS two blocks, so even after one of the exhaustion scenario's
#: three requests is cancelled the remaining two cannot BOTH reach
#: full depth — mid-decode exhaustion is guaranteed, not
#: timing-dependent
def tight_pool_blocks() -> int:
    return 1 + 2 * _bps() - 2


def run_scenarios(names, *, seed: int, export_dir: str | None = None,
                  tight_dir: str | None = None,
                  vocab: int | None = None) -> list[dict]:
    """Build the shared exports (unless the caller passes pre-built
    ones — the tier-1 smoke amortizes ONE export across tests), run
    ``names`` against them, and return one result dict per scenario
    (the test harness entry)."""
    results = []
    with tempfile.TemporaryDirectory() as scratch:
        d, d_tight = export_dir, tight_dir
        if d is None and any(n not in TIGHT_POOL for n in names):
            d = os.path.join(scratch, "ample")
            vocab = build_chaos_export(d, seed=seed)
        if d_tight is None and any(n in TIGHT_POOL for n in names):
            d_tight = os.path.join(scratch, "tight")
            v = build_chaos_export(d_tight, seed=seed,
                                   num_blocks=tight_pool_blocks())
            vocab = vocab if vocab is not None else v
        assert vocab is not None, \
            "pass vocab= alongside pre-built export dirs"
        for name in names:
            export = d_tight if name in TIGHT_POOL else d
            try:
                detail, met = SCENARIOS[name](export, seed, vocab)
                results.append({"scenario": name, "ok": True,
                                "detail": detail, "metrics": met})
            except Exception as e:   # a failed invariant is the signal
                results.append({"scenario": name, "ok": False,
                                "detail": f"{type(e).__name__}: {e}",
                                "metrics": {}})
            finally:
                faults.install(None)   # never leak a registry forward
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", default="all",
                    help="comma-separated scenario names, or 'all': "
                         + ", ".join(SCENARIOS))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="alias kept for symmetry with serving_load "
                    "(the scenarios are already CPU-tiny; --smoke "
                    "changes nothing today)")
    args = ap.parse_args(argv)
    names = (list(SCENARIOS) if args.scenario == "all"
             else [s.strip() for s in args.scenario.split(",")
                   if s.strip()])
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        ap.error(f"unknown scenario(s) {unknown}; have {list(SCENARIOS)}")
    results = run_scenarios(names, seed=args.seed)
    for r in results:
        print(json.dumps(r), flush=True)
    failed = sum(1 for r in results if not r["ok"])
    print(json.dumps({"summary": True, "scenarios": len(results),
                      "failed": failed}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
