#!/usr/bin/env python
"""GPT long-context queued-dispatch failure bisection (VERDICT r4 task #1).

Round 4 left ONE open reliability defect (BASELINE.md GPT row, commit
b450165): the composed GPT long-context training step (S=4096, b=4,
causal flash + remat + sequence-chunked LM loss) trains reliably when
every dispatch is host-blocked, but intermittently dies with a
tunnel-reported INVALID_ARGUMENT when several ~1.35 s steps are queued
back-to-back — while the same-shape non-causal bert_long program queues
8 steps reliably in every bench run and the raw causal flash kernel is
clean standalone. This script bisects the program delta:

  repro          — the failing config: b4 S4096 causal flash, remat=full,
                   loss_chunk=512 (queue 8, expect intermittent failure)
  noncausal      — identical program with causal=False in the flash call
                   (the bert_long-like control inside the GPT body)
  nochunk_b1     — causal flash + remat, chunk=0 at b1 (full logits fit):
                   removes the chunked-loss lax.scan from the program
  chunk256/1024  — chunk-size sensitivity
  remat_dots     — checkpoint policy sensitivity (dots vs full)
  remat_none_b2  — no remat at b2 (memory-safe): removes the
                   rematerialized causal flash bwd entirely
  inflight{1,2,4}— the candidate MITIGATION on the repro config: cap the
                   number of un-blocked dispatches in flight

Each variant runs T trials of queue-N-steps-then-block in a FRESH
process (round-4 lesson: long-lived processes through the axon tunnel
accumulate artifacts); one JSON line per variant with per-trial
outcomes. Intermittency means a clean single trial proves nothing —
only fail COUNTS across trials discriminate.

Usage: python experiments/gpt_long_dispatch.py VARIANT [trials] [queue]
       python experiments/gpt_long_dispatch.py --all   # subprocess loop
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VARIANTS = ("repro", "noncausal", "nochunk_b1", "chunk256", "chunk1024",
            "remat_dots", "remat_none_b2", "inflight1", "inflight2",
            "inflight4")


def measure(variant: str, trials: int, queue: int) -> dict:
    import jax

    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           OptimizerConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.ops.attention import (
        multi_head_attention)
    from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
    from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
        SyncReplicas)
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)
    import numpy as np

    batch, chunk, remat, inflight = 4, 512, "full", 0
    causal = True
    if variant == "noncausal":
        causal = False
    elif variant == "nochunk_b1":
        batch, chunk = 1, 0
    elif variant == "chunk256":
        chunk = 256
    elif variant == "chunk1024":
        chunk = 1024
    elif variant == "remat_dots":
        remat = "dots"
    elif variant == "remat_none_b2":
        batch, remat = 2, "none"
    elif variant.startswith("inflight"):
        inflight = int(variant[len("inflight"):])

    cfg = TrainConfig(model="gpt", dtype="bfloat16",
                      data=DataConfig(batch_size=batch, seq_len=4096),
                      optimizer=OptimizerConfig(name="adamw",
                                                learning_rate=1e-4),
                      attention_impl="flash", remat=remat,
                      lm_loss_chunk=chunk)
    model = get_model("gpt", cfg)
    if not causal:
        # same program shape, causal=False in the flash kernel — the
        # one-bit delta between this body and the reliable bert_long one
        model.attention_fn = lambda q, k, v, mask, causal: \
            multi_head_attention(q, k, v, mask=mask[:, None, None, :],
                                 causal=False, impl="flash")
    mesh = build_mesh()
    sync = SyncReplicas(model.loss, make_optimizer(cfg.optimizer), mesh)
    state = sync.init(model.init, seed=0, prng_impl="rbg")
    rs = np.random.RandomState(0)
    placed = sync.shard_batch({
        "input_ids": rs.randint(0, cfg.data.vocab_size, (batch, 4096),
                                dtype=np.int32),
        "attention_mask": np.ones((batch, 4096), np.int32),
    })
    compiled = sync.step.lower(state, placed).compile()

    # blocked warmup (known-reliable regime)
    for _ in range(2):
        state, m = compiled(state, placed)
        jax.block_until_ready(state.params)

    outcomes, step_ms = [], None
    for t in range(trials):
        t0 = time.perf_counter()
        try:
            for i in range(queue):
                state, m = compiled(state, placed)
                if inflight and (i + 1) % inflight == 0:
                    jax.block_until_ready(state.params)
            jax.block_until_ready(state.params)
            dt = time.perf_counter() - t0
            step_ms = dt / queue * 1e3
            loss = float(jax.device_get(m["loss"]))
            outcomes.append("ok" if np.isfinite(loss) else "nonfinite")
        except Exception as e:  # noqa: BLE001 — record and continue
            outcomes.append(f"FAIL:{type(e).__name__}")
            err = f"{type(e).__name__}: {str(e)[:200]}"
            # the device may be wedged for this process; report what we
            # have rather than cascade misattributed failures
            return {"variant": variant, "outcomes": outcomes,
                    "error": err, "step_ms": step_ms,
                    "aborted_at_trial": t}
    return {"variant": variant, "outcomes": outcomes,
            "fails": sum(o != "ok" for o in outcomes),
            "step_ms": round(step_ms, 1) if step_ms else None}


def main() -> None:
    if sys.argv[1:2] == ["--all"]:
        variants = sys.argv[2:] or list(VARIANTS)
        env = dict(os.environ,
                   DTX_JAX_CACHE=os.environ.get("DTX_JAX_CACHE",
                                                "/tmp/dtx_jax_cache"))
        for v in variants:
            # fresh process per variant; repeat the repro twice as the
            # intermittency control
            subprocess.run([sys.executable, os.path.abspath(__file__), v],
                           env=env, check=False)
        return
    variant, trials, queue = (sys.argv[1],
                              int(sys.argv[2]) if len(sys.argv) > 2 else 5,
                              int(sys.argv[3]) if len(sys.argv) > 3 else 8)
    if variant not in VARIANTS:
        raise SystemExit(f"unknown variant {variant!r} (have {VARIANTS})")
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DTX_JAX_CACHE", "/tmp/dtx_jax_cache"))
    try:
        out = measure(variant, trials, queue)
    except Exception as e:  # noqa: BLE001 — compile/init failure
        out = {"variant": variant,
               "error": f"{type(e).__name__}: {str(e)[:300]}"}
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
