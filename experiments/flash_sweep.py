#!/usr/bin/env python
"""Flash-attention kernel lever sweep + long-context analytic roofline
(VERDICT r5 missing #1 / weak #2, ISSUE 2 tentpole).

The long-context gate family (bert_long/gpt_long, S=4096 b4) is the one
family with no profile and no analytic bound: the gate numbers imply
~10% of peak with nothing explaining the other 90%, and the prime
suspect is the Pallas flash kernel's hardwired DEFAULT_BLOCK=128 grid —
~2 MFLOP per grid-step matmul, ~1.8M grid steps per train step at the
gate shape (see ``--roofline``), small enough that Mosaic per-step
overhead plausibly dominates. ``block_q``/``block_k``/``bwd_block``/
``bwd_variant`` existed as parameters no caller ever varied; they are
now plumbed through config/CLI (``--attention_block_q`` etc.) and this
script sweeps them.

Modes (one JSON line per measured cell; fresh process per cell via
``--all`` — the round-4 lesson: long-lived processes through the axon
tunnel accumulate timing artifacts):

  --roofline         analytic model, runs anywhere: dense + attention
                     FLOPs, kernel HBM streaming bytes AS A FUNCTION OF
                     BLOCK SIZE, grid-step counts, and the implied
                     MXU/HBM/overhead floors per (S, block, variant).
                     The committed PROFILE_r06_bert_long.txt is this
                     output plus the measured-gap discussion.
  cell MODEL S IMPL [BLOCK] [VARIANT] [BWD_BLOCK]
                     one measured cell on the current backend: step
                     time, eps/chip, temp/peak MiB, MFU (analytic basis
                     when the kernel engages). IMPL: xla | flash.
                     Records OOM as an error line — the flash-vs-XLA
                     crossover table needs the OOM rows too.
  --all              the committed grid: MODEL x S in {512, 1024, 2048,
                     4096} x (xla + flash blocks {128, 256, 512} x
                     bwd {split, fused}); b=4 long-context batch.
  --trace DIR MODEL  5-step profiler capture of the S=4096 b4 gate
                     step (reduce with utils.trace_summary into
                     PROFILE_r06_<model>_long.txt).

The measured columns are TPU columns: off-TPU the kernels run in Pallas
interpret mode (orders of magnitude slow, numbers meaningless), so
--all/--cell refuse to print a table row off-TPU unless FLASH_SWEEP_CPU=1
(CI smoke only). --roofline is platform-independent.
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODELS = ("bert", "gpt")
SEQS = (512, 1024, 2048, 4096)
BLOCKS = (128, 256, 512)
VARIANTS = ("split", "fused")
BATCH = 4                      # the long-context gate batch
PEAK_FLOPS = 197e12            # v5e bf16
HBM_BPS = 819e9                # v5e
#: Mosaic per-grid-step overhead bracket (µs) for the predicted-floor
#: column: TPU kernel-dispatch folklore puts sequential-grid step cost
#: at a few hundred ns to ~1 µs; the sweep MEASURES where reality sits.
OVERHEAD_US = (0.3, 1.0)


# ---------------------------------------------------------------------------
# analytic model — every formula mirrors the kernel/model structure
# ---------------------------------------------------------------------------

def model_shapes(model: str) -> dict:
    # bert-base / gpt-small bodies are the same trunk shape
    return dict(hidden=768, layers=12, heads=12, head_dim=64,
                intermediate=3072, vocab=30522,
                max_predictions=20 if model == "bert" else None)


def dense_train_flops(model: str, b: int, s: int) -> float:
    """Exact matmul FLOPs of the non-attention trunk for one train step
    (fwd x3: backward costs 2x forward for matmuls). Embedding gathers
    and elementwise chains are excluded — they are byte-, not
    FLOP-bound."""
    m = model_shapes(model)
    h, i, L, v = m["hidden"], m["intermediate"], m["layers"], m["vocab"]
    per_layer_fwd = (4 * 2 * b * s * h * h        # QKV + O projections
                     + 2 * 2 * b * s * h * i)     # FFN in/out
    trunk = 3.0 * per_layer_fwd * L
    if model == "bert":
        t = b * m["max_predictions"]              # masked positions only
        head = 3.0 * (2 * t * h * h + 2 * t * h * v)
    else:
        # full-vocab logits chain; the gate config chunks the loss at
        # S=4096 (lm_loss_chunk=512): logits fwd + checkpoint recompute
        # + bwd = 4x one pass
        head = 4.0 * 2 * b * s * h * v
    return trunk + head


def attn_stream_bytes(b: int, s: int, heads: int, d: int, blk_q: int,
                      blk_k: int, bwd_block: int, variant: str,
                      *, op_bytes: int = 2) -> float:
    """HBM bytes the flash kernels move per train step PER LAYER — the
    block-size-controlled term. K/V do not fit VMEM at long S, so the
    fwd grid re-fetches them once per Q block (nq times); the split
    backward re-streams K/V again (dq kernel) AND Q/dO nk times (dkv
    kernel); the fused backward drops the K/V re-stream. Row/output
    traffic (Q, O, lse, dq/dk/dv writes) is streamed once and included.
    """
    bq, bk = (bwd_block or blk_q), (bwd_block or blk_k)
    bh = b * heads
    sd = bh * s * d * op_bytes                    # one full Q/K/V/O pass
    nq, nk = s // blk_q, s // blk_k
    nq_b, nk_b = s // bq, s // bk
    fwd = sd * (1 + 1) + sd * 2 * nq + bh * s * 4          # Q,O + K,V + lse
    if variant == "split":
        dq = sd * (2 + 1) + sd * 2 * nq_b                  # Q,dO,dq + K,V
        dkv = sd * (2 + 2) + sd * 2 * nk_b                 # K,V,dk,dv + Q,dO
        bwd = dq + dkv
    else:
        bwd = sd * (2 + 3) + sd * 2 * nk_b     # K,V once; dq,dk,dv; Q,dO
    return fwd + bwd


def grid_steps(b: int, s: int, heads: int, blk_q: int, blk_k: int,
               bwd_block: int, variant: str) -> int:
    """Grid steps per train step per layer. NOTE: causal saves ~half the
    FLOPs but none of these steps — dead blocks still pay the per-step
    cost (the @pl.when guard skips compute, not the step)."""
    bq, bk = (bwd_block or blk_q), (bwd_block or blk_k)
    bh = b * heads
    fwd = bh * (s // blk_q) * (s // blk_k)
    bwd = bh * (s // bq) * (s // bk)
    return fwd + bwd * (2 if variant == "split" else 1)


def roofline_row(model: str, b: int, s: int, blk: int, variant: str,
                 bwd_block: int = 0) -> dict:
    from distributed_tensorflow_example_tpu.ops.pallas.flash_attention \
        import attention_train_flops

    m = model_shapes(model)
    causal = model == "gpt"
    dense = dense_train_flops(model, b, s)
    attn = attention_train_flops(b, s, m["hidden"], m["layers"],
                                 causal=causal, bwd_variant=variant)
    stream = m["layers"] * attn_stream_bytes(
        b, s, m["heads"], m["head_dim"], blk, blk, bwd_block, variant)
    steps = m["layers"] * grid_steps(b, s, m["heads"], blk, blk,
                                     bwd_block, variant)
    mxu_ms = (dense + attn) / PEAK_FLOPS * 1e3
    hbm_ms = stream / HBM_BPS * 1e3
    ovh_ms = tuple(round(steps * us / 1e3, 1) for us in OVERHEAD_US)
    return {
        "model": model, "seq": s, "batch": b, "block": blk,
        "bwd_variant": variant,
        "dense_TF": round(dense / 1e12, 2),
        "attn_TF": round(attn / 1e12, 2),
        "attn_stream_GB": round(stream / 1e9, 1),
        "grid_steps_k": round(steps / 1e3),
        "mxu_floor_ms": round(mxu_ms, 1),
        "attn_hbm_floor_ms": round(hbm_ms, 1),
        "overhead_ms_at_0.3_1.0us": ovh_ms,
    }


def roofline() -> None:
    print("# Analytic long-context roofline (v5e: 197 TFLOP/s bf16, "
          "819 GB/s HBM)")
    print("# dense/attn TF = executed TFLOP per train step; "
          "attn_stream_GB = kernel HBM bytes (block-controlled); "
          "grid_steps_k = Pallas grid steps (overhead-controlled)")
    for model in MODELS:
        for s in SEQS:
            for blk in BLOCKS:
                for variant in VARIANTS:
                    print(json.dumps(roofline_row(model, BATCH, s, blk,
                                                  variant)))


# ---------------------------------------------------------------------------
# measured cells
# ---------------------------------------------------------------------------

def measure(model_name: str, seq: int, impl: str, block: int,
            variant: str, bwd_block: int, *, batch=BATCH, steps=6,
            warmup=2) -> dict:
    import time

    import jax
    import numpy as np

    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           OptimizerConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.ops.pallas.flash_attention \
        import (attention_train_flops, effective_bwd_variant,
                kernel_engages)
    from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
    from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
        SyncReplicas)
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)

    # shared with the gate: ONE batch-builder and ONE timing
    # implementation (decode_roofline.py:88 principle) — sweep cells
    # must measure exactly what the bench rows measure
    from bench import _gpt_batch_at, _long_batch, robust_time

    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu and not os.environ.get("FLASH_SWEEP_CPU"):
        raise SystemExit("measured cells are TPU cells (interpret-mode "
                         "Pallas timings are meaningless); set "
                         "FLASH_SWEEP_CPU=1 for a CI smoke run")
    cfg = TrainConfig(model=model_name, dtype="bfloat16",
                      data=DataConfig(batch_size=batch, seq_len=seq),
                      optimizer=OptimizerConfig(name="adamw",
                                                learning_rate=1e-4),
                      attention_impl=impl, remat="none",
                      attention_block_q=block if impl == "flash" else 0,
                      attention_block_k=block if impl == "flash" else 0,
                      attention_bwd_block=bwd_block,
                      attention_bwd=variant if impl == "flash" else "split",
                      lm_loss_chunk=512 if model_name == "gpt" else None)
    model = get_model(model_name, cfg)
    mesh = build_mesh()
    sync = SyncReplicas(model.loss, make_optimizer(cfg.optimizer), mesh)
    state = sync.init(model.init, seed=0,
                      prng_impl="rbg" if on_tpu else None)
    make_batch = _gpt_batch_at(seq) if model_name == "gpt" else _long_batch
    placed = sync.shard_batch(make_batch(model, batch, 0))
    compiled = sync.step.lower(state, placed).compile()
    ma = compiled.memory_analysis()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    flops = float(ca.get("flops", 0.0))
    basis = "cost_analysis"
    ms = model_shapes(model_name)
    # add the in-kernel FLOPs only when the kernel ENGAGES — on the XLA
    # fallback (non-tileable shape) cost_analysis already counts the
    # attention einsums and adding the analytic number would double-count
    # (and over-raise robust_time's impossibility floor)
    if impl == "flash" and kernel_engages(
            seq, ms["head_dim"], block_q=block, block_k=block,
            bwd_block=bwd_block):
        flops += attention_train_flops(
            batch, seq, ms["hidden"], ms["layers"],
            causal=model_name == "gpt",
            # count what EXECUTES: fused degrades to split past the
            # VMEM slab limit
            bwd_variant=effective_bwd_variant(seq, ms["head_dim"],
                                              variant))
        basis = "analytic"

    # one untimed priming step binds the metrics for loss_finite even at
    # warmup=0 (the --trace window), then the remaining warmup
    state, m_ = compiled(state, placed)
    for _ in range(max(0, warmup - 1)):
        state, m_ = compiled(state, placed)
    jax.block_until_ready(state.params)

    def timed():
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m_ = compiled(state, placed)
        jax.block_until_ready(state.params)
        return time.perf_counter() - t0

    # bench.robust_time rejects the tunnel's corrupt-fast readings via
    # the analytic-FLOP impossibility check and flags what it cannot fix
    # — a suspect cell must never pick the winning block for the gate
    # re-base (PROFILE_r06 §5 decision rule)
    dt, suspect = robust_time(timed, steps=steps, flops=flops or None,
                              peak=PEAK_FLOPS if on_tpu else None)
    step_ms = dt / steps * 1e3
    return {
        "model": model_name, "seq": seq, "impl": impl,
        "block": block if impl == "flash" else None,
        "bwd_variant": variant if impl == "flash" else None,
        "bwd_block": bwd_block or None,
        "step_ms": round(step_ms, 1),
        "eps_chip": round(batch / (dt / steps), 2),
        # CPU jax builds lack the peak stat; 0 = unavailable, not "fits"
        "temp_MiB": round(getattr(ma, "temp_size_in_bytes", 0) / 2**20),
        "peak_MiB": round(getattr(ma, "peak_memory_in_bytes", 0) / 2**20),
        "mfu": round(flops / (dt / steps) / PEAK_FLOPS, 4) if flops
        else None,
        "mfu_basis": basis,
        "loss_finite": bool(np.isfinite(float(jax.device_get(
            m_["loss"])))),
        "suspect": bool(suspect),
    }


def trace(outdir: str, model_name: str) -> dict:
    """5-step xplane capture of the S=4096 b4 gate config (block/variant
    defaults) — reduce with utils.trace_summary for the PROFILE
    artifact."""
    import jax

    # warm the compilation cache with one measured pass, then capture a
    # fresh 5-step window (the second call re-uses the persistent cache)
    out = measure(model_name, 4096, "flash", 128, "split", 0,
                  steps=5, warmup=3)
    jax.profiler.start_trace(outdir)
    try:
        measure(model_name, 4096, "flash", 128, "split", 0, steps=5,
                warmup=0)
    finally:
        jax.profiler.stop_trace()   # never leave the profiler running
    return {"trace": outdir, "model": model_name, "warm_cell": out}


def main() -> None:
    if sys.argv[1:2] == ["--roofline"]:
        roofline()
        return
    if sys.argv[1:2] == ["--all"]:
        env = dict(os.environ,
                   DTX_JAX_CACHE=os.environ.get("DTX_JAX_CACHE",
                                                "/tmp/dtx_jax_cache"))
        me = os.path.abspath(__file__)
        for mn in MODELS:
            for s in SEQS:
                cells = [("xla", 0, "split", 0)]
                cells += [("flash", blk, var, 0) for blk in BLOCKS
                          for var in VARIANTS]
                # the wider-block split-dkv probe at the gate shape
                if s == 4096:
                    cells.append(("flash", 128, "split", 512))
                for impl, blk, var, bb in cells:
                    subprocess.run(
                        [sys.executable, me, "cell", mn, str(s), impl,
                         str(blk), var, str(bb)], env=env, check=False)
        return
    if sys.argv[1:2] == ["--trace"]:
        outdir, mn = sys.argv[2], sys.argv[3]
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("DTX_JAX_CACHE",
                                         "/tmp/dtx_jax_cache"))
        print(json.dumps(trace(outdir, mn)), flush=True)
        return
    if sys.argv[1:2] != ["cell"]:
        raise SystemExit(__doc__)
    mn, s, impl = sys.argv[2], int(sys.argv[3]), sys.argv[4]
    blk = int(sys.argv[5]) if len(sys.argv) > 5 else 128
    var = sys.argv[6] if len(sys.argv) > 6 else "split"
    bb = int(sys.argv[7]) if len(sys.argv) > 7 else 0
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DTX_JAX_CACHE", "/tmp/dtx_jax_cache"))
    try:
        print(json.dumps(measure(mn, s, impl, blk, var, bb)), flush=True)
    except Exception as e:  # noqa: BLE001 — OOM at compile is a finding
        print(json.dumps({"model": mn, "seq": s, "impl": impl,
                          "block": blk, "bwd_variant": var,
                          "error": f"{type(e).__name__}: {str(e)[:200]}"}),
              flush=True)


if __name__ == "__main__":
    main()
