#!/usr/bin/env python
"""Autoregressive decode roofline + lever sweep (VERDICT r4 task #3).

Round 4 measured KV-cache greedy decode at 1.55 ms/token-step (b8,
prompt 128 + 128 new, GPT-small) — ~5x above the naive weight-traffic
bound (~0.25 GB of bf16 params re-read per token-step / 819 GB/s ~=
0.31 ms). This script measures the decode step against that bound and
runs the candidate levers:

  batch   — b in {1, 8, 16, 32, 64}: weight reads amortize over rows,
            so tokens/s/chip should scale until something else binds
  newlen  — max_new in {32, 128, 256} at b8: cache length T = prompt +
            new grows attention/DUS traffic; measures its slope
  trace   — jax.profiler capture of one generation dispatch, reduced
            with utils.trace_summary (committed as PROFILE_r05_decode)

Each cell is a fresh process (axon-tunnel timing lesson, round 4);
prints one JSON line per cell. Numbers + verdicts live in BASELINE.md.

Usage: python experiments/decode_roofline.py batch 8
       python experiments/decode_roofline.py newlen 256
       python experiments/decode_roofline.py trace /tmp/decode_trace
       python experiments/decode_roofline.py --all
"""

import functools
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PROMPT = 128


def _build(batch: int):
    import jax
    import numpy as np

    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.models.base import cast_floating
    import jax.numpy as jnp

    cfg = TrainConfig(model="gpt", dtype="bfloat16",
                      param_dtype="bfloat16",
                      data=DataConfig(batch_size=batch))
    model = get_model("gpt", cfg)
    params = cast_floating(model.init(jax.random.key(0)), jnp.bfloat16)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.data.vocab_size, (batch, PROMPT),
                                 dtype=np.int32))
    return model, params, ids


def measure(batch: int, max_new: int, *, reps=8, warmup=2) -> dict:
    # ONE decode-measurement implementation: bench.py's _run_decode
    # (device_get timing + weight-floor retry + suspect flag) — the
    # experiment and the gate must never measure two different ways
    # (that divergence is how the round-4 1.55 ms and the artifacted
    # 0.001 ms readings coexisted)
    from bench import _run_decode

    tps, token_step_ms, bound_ms, suspect = _run_decode(
        batch=batch, prompt=PROMPT, max_new=max_new, reps=reps,
        warmup=warmup, tiny=False)
    return {
        "batch": batch, "prompt": PROMPT, "max_new": max_new,
        "gen_ms": round(token_step_ms * max_new, 1),
        "token_step_ms": round(token_step_ms, 3),
        "tokens_per_s_chip": round(tps),
        # naive bound: every param (bf16) read once per token-step
        "weight_bound_ms": round(bound_ms, 3),
        "suspect": suspect,
    }


def trace(outdir: str) -> dict:
    import jax

    model, params, ids = _build(8)
    gen = jax.jit(functools.partial(model.generate, max_new_tokens=128))
    jax.block_until_ready(gen(params, ids))      # compile outside trace
    jax.profiler.start_trace(outdir)
    jax.block_until_ready(gen(params, ids))
    jax.profiler.stop_trace()
    return {"trace": outdir}


def main() -> None:
    if sys.argv[1:2] == ["--all"]:
        env = dict(os.environ,
                   DTX_JAX_CACHE=os.environ.get("DTX_JAX_CACHE",
                                                "/tmp/dtx_jax_cache"))
        me = os.path.abspath(__file__)
        for b in (1, 8, 16, 32, 64):
            subprocess.run([sys.executable, me, "batch", str(b)],
                           env=env, check=False)
        for n in (32, 256):
            subprocess.run([sys.executable, me, "newlen", str(n)],
                           env=env, check=False)
        return
    mode, arg = sys.argv[1], sys.argv[2]
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DTX_JAX_CACHE", "/tmp/dtx_jax_cache"))
    try:
        if mode == "batch":
            out = measure(int(arg), 128)
        elif mode == "newlen":
            out = measure(8, int(arg))
        elif mode == "trace":
            out = trace(arg)
        else:
            raise SystemExit(f"unknown mode {mode!r}")
        print(json.dumps(out), flush=True)
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"mode": mode, "arg": arg,
                          "error": f"{type(e).__name__}: {str(e)[:200]}"}),
              flush=True)


if __name__ == "__main__":
    main()
