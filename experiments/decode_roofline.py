#!/usr/bin/env python
"""Autoregressive decode roofline + lever table (VERDICT r4 task #3,
r5 task #3 — the per-token kernel-floor attack).

Round 5 measured KV-cache greedy decode at 0.67 ms/token-step of device
time (b8, prompt 128 + 128 new, GPT-small) — 2.5x the weight-traffic
bound (0.267 ms), root-caused to a per-op latency floor (~100 small
kernels/token — PROFILE_r05_decode). This script measures the decode
step against that bound and runs the levers:

  batch   — b in {1, 8, 16, 32, 64}: weight reads amortize over rows,
            so tokens/s/chip should scale until something else binds
  newlen  — max_new in {32, 128, 256} at b8: cache length T = prompt +
            new grows attention/DUS traffic; measures its slope
  trace   — jax.profiler capture of one generation dispatch, reduced
            with utils.trace_summary (committed as PROFILE_r05_decode)
  lever   — the round-6 fast-path lever table, one row per config:
              loop     the pre-fast-path reference (per-layer Python
                       loop, 3 QKV matmuls, XLA 1-query attention)
              stacked  lax.scan over restacked layer params + fused
                       QKV, XLA attention (isolates the scan/fusion
                       win from the kernel win)
              pallas   stacked + the single-query Pallas cache-slab
                       attention kernel (decode_attention="auto":
                       engages on TPU; off-TPU the row equals stacked)
              ktoken   pallas + tokens_per_dispatch=4 (K token steps
                       unrolled per loop body)
              int8     ktoken + int8-quantized stacked layer weights
                       (LOSSY — the weight-traffic comparison row)

Each cell is a fresh process (axon-tunnel timing lesson, round 4);
prints one JSON line per cell. Numbers + verdicts live in BASELINE.md
("Decode fast path").

Usage: python experiments/decode_roofline.py batch 8
       python experiments/decode_roofline.py newlen 256
       python experiments/decode_roofline.py lever stacked
       python experiments/decode_roofline.py trace /tmp/decode_trace
       python experiments/decode_roofline.py --all
       python experiments/decode_roofline.py --levers
"""

import functools
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PROMPT = 128

#: the lever table rows: cumulative fast-path configs (generate kwargs)
LEVERS = {
    "loop": {"decode_impl": "loop"},
    "stacked": {"decode_impl": "stacked", "decode_attention": "xla"},
    "pallas": {"decode_impl": "stacked", "decode_attention": "auto"},
    "ktoken": {"decode_impl": "stacked", "decode_attention": "auto",
               "tokens_per_dispatch": 4},
    "int8": {"decode_impl": "stacked", "decode_attention": "auto",
             "tokens_per_dispatch": 4, "weight_quant": "int8"},
}


def _build(batch: int):
    import jax
    import numpy as np

    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.models.base import cast_floating
    import jax.numpy as jnp

    cfg = TrainConfig(model="gpt", dtype="bfloat16",
                      param_dtype="bfloat16",
                      data=DataConfig(batch_size=batch))
    model = get_model("gpt", cfg)
    params = cast_floating(model.init(jax.random.key(0)), jnp.bfloat16)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.data.vocab_size, (batch, PROMPT),
                                 dtype=np.int32))
    return model, params, ids


def measure(batch: int, max_new: int, *, reps=7, warmup=2,
            lever: str | None = None, tiny: bool = False) -> dict:
    # ONE decode-measurement implementation: bench.py's _run_decode
    # (device_get timing + median-of-repeats + weight-floor retry +
    # suspect flag) — the experiment and the gate must never measure
    # two different ways (that divergence is how the round-4 1.55 ms
    # and the artifacted 0.001 ms readings coexisted)
    from bench import _run_decode

    gen_kwargs = LEVERS[lever] if lever else None
    row = _run_decode(
        batch=batch, prompt=PROMPT if not tiny else 16,
        max_new=max_new, reps=reps, warmup=warmup, tiny=tiny,
        gen_kwargs=gen_kwargs)
    out = {
        "batch": batch, "prompt": PROMPT if not tiny else 16,
        "max_new": max_new,
        "gen_ms": round(row["token_step_ms"] * max_new, 1),
        "token_step_ms": round(row["token_step_ms"], 3),
        "tokens_per_s_chip": round(row["tokens_s_chip"]),
        # naive bound: every param (bf16) read once per token-step
        "weight_bound_ms": round(row["weight_bound_ms"], 3),
        "spread": round(row["spread"], 4),
        "suspect": row["suspect"],
    }
    if lever:
        import jax
        out["lever"] = lever
        out["platform"] = jax.devices()[0].platform
        out["tiny"] = tiny
    return out


def trace(outdir: str) -> dict:
    import jax

    model, params, ids = _build(8)
    gen = jax.jit(functools.partial(model.generate, max_new_tokens=128))
    jax.block_until_ready(gen(params, ids))      # compile outside trace
    jax.profiler.start_trace(outdir)
    jax.block_until_ready(gen(params, ids))
    jax.profiler.stop_trace()
    return {"trace": outdir}


def _subprocess_cells(cells) -> None:
    env = dict(os.environ,
               DTX_JAX_CACHE=os.environ.get("DTX_JAX_CACHE",
                                            "/tmp/dtx_jax_cache"))
    me = os.path.abspath(__file__)
    for mode, arg in cells:
        subprocess.run([sys.executable, me, mode, str(arg)],
                       env=env, check=False)


def main() -> None:
    if sys.argv[1:2] == ["--all"]:
        _subprocess_cells([("batch", b) for b in (1, 8, 16, 32, 64)]
                          + [("newlen", n) for n in (32, 256)])
        return
    if sys.argv[1:2] == ["--levers"]:
        # the round-6 lever table: one fresh process per row
        _subprocess_cells([("lever", name) for name in LEVERS])
        return
    mode, arg = sys.argv[1], sys.argv[2]
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DTX_JAX_CACHE", "/tmp/dtx_jax_cache"))
    try:
        if mode == "batch":
            out = measure(int(arg), 128)
        elif mode == "newlen":
            out = measure(8, int(arg))
        elif mode == "lever":
            if arg not in LEVERS:
                raise SystemExit(f"unknown lever {arg!r}; have "
                                 f"{sorted(LEVERS)}")
            # off-TPU the GPT-small decode is minutes per row: fall back
            # to the tiny model (relative ordering only, labeled)
            on_tpu = jax.devices()[0].platform == "tpu"
            out = measure(8, 128 if on_tpu else 32, lever=arg,
                          reps=7 if on_tpu else 3,
                          warmup=2 if on_tpu else 1, tiny=not on_tpu)
        elif mode == "trace":
            out = trace(arg)
        else:
            raise SystemExit(f"unknown mode {mode!r}")
        print(json.dumps(out), flush=True)
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"mode": mode, "arg": arg,
                          "error": f"{type(e).__name__}: {str(e)[:200]}"}),
              flush=True)


if __name__ == "__main__":
    main()
