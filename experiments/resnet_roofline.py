#!/usr/bin/env python
"""ResNet-50 byte-roofline attack experiments (VERDICT r3 task #4).

Round 3 established (BASELINE.md roofline, judge-verified) that the
ResNet-50 sync step is bandwidth-bound: 44.65 GB of program bytes at
819 GB/s ≈ 54.5 ms vs 50.3 ms measured, so MFU ~30% is a byte ceiling,
not an MXU ceiling. This script runs the committed levers that try to
CUT those bytes, one measured step time each:

  base            — the bench.py config (b128, bf16, momentum)
  bn_stats_bf16   — --bn_stats_dtype bfloat16: batch-statistic
                    reductions read/accumulate bf16 (the profile's top
                    ops are BN-stat multiply_reduce fusions re-reading
                    ~50 MB activation tensors)
  rwb_off         — xla_tpu_rwb_fusion=false (reduce+broadcast fusion
                    strategy toggle; BN is exactly reduce→broadcast)
  vmem_64m        — xla_tpu_scoped_vmem_limit_kib=65536 (more VMEM per
                    fusion → deeper fusions → fewer HBM round trips)
  latency_sched   — xla_tpu_enable_latency_hiding_scheduler=true

XLA_FLAGS cannot carry xla_tpu_* flags through the axon tunnel (the
client-side parser rejects backend flags — verified), so levers ride
``lowered.compile(compiler_options=...)``, which ships them to the TPU
compiler via PJRT (bogus names are rejected, so accepted == applied).

Usage: python experiments/resnet_roofline.py [lever ...]
Each lever prints one JSON line {"lever", "step_ms", "eps_chip", "mfu",
"cost_GB"}; the results table + verdicts live in BASELINE.md.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: lever -> compiler options (None = in-process knob, no options)
LEVERS: "dict[str, dict | None]" = {
    "base": None,
    "bn_stats_bf16": None,
    "rwb_off": {"xla_tpu_rwb_fusion": "false"},
    "vmem_64m": {"xla_tpu_scoped_vmem_limit_kib": "65536"},
    "latency_sched": {"xla_tpu_enable_latency_hiding_scheduler": "true"},
}


def measure(bn_stats_dtype: str = "float32",
            compiler_options: "dict | None" = None) -> dict:
    import jax
    import numpy as np

    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           OptimizerConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
    from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
        SyncReplicas)
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)

    batch, steps, warmup = 128, 30, 5
    cfg = TrainConfig(model="resnet50", dtype="bfloat16",
                      bn_stats_dtype=bn_stats_dtype,
                      data=DataConfig(batch_size=batch),
                      optimizer=OptimizerConfig(name="momentum",
                                                learning_rate=0.1))
    model = get_model("resnet50", cfg)
    mesh = build_mesh()
    sync = SyncReplicas(model.loss, make_optimizer(cfg.optimizer), mesh)
    state = sync.init(model.init, seed=0)
    placed = sync.shard_batch(model.dummy_batch(batch))
    lowered = sync.step.lower(state, placed)
    compiled = (lowered.compile(compiler_options=compiler_options)
                if compiler_options else lowered.compile())
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    flops = float(ca.get("flops", 0.0))
    byts = float(ca.get("bytes accessed", 0.0))

    for _ in range(warmup):
        state, m = compiled(state, placed)
    jax.block_until_ready(state.params)

    def timed():
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = compiled(state, placed)
        jax.block_until_ready(state.params)
        return time.perf_counter() - t0

    dt = max(timed(), timed())          # robust_time discipline
    step_ms = dt / steps * 1e3
    peak = 197e12 if "v5 lite" in jax.devices()[0].device_kind.lower() \
        else None
    return {
        "step_ms": round(step_ms, 2),
        "eps_chip": round(batch / (dt / steps), 1),
        "mfu": round(flops / (dt / steps) / peak, 4) if peak else None,
        "cost_GB": round(byts / 1e9, 2),
        "loss_finite": bool(np.isfinite(float(jax.device_get(m["loss"])))),
    }


def main() -> None:
    levers = sys.argv[1:] or list(LEVERS)
    for lever in levers:
        if lever not in LEVERS:
            raise SystemExit(f"unknown lever {lever!r} "
                             f"(have {sorted(LEVERS)})")
        bn = "bfloat16" if lever == "bn_stats_bf16" else "float32"
        try:
            out = measure(bn_stats_dtype=bn,
                          compiler_options=LEVERS[lever])
            print(json.dumps({"lever": lever, **out}), flush=True)
        except Exception as e:
            print(json.dumps({"lever": lever,
                              "error": f"{type(e).__name__}: "
                                       f"{str(e)[:300]}"}), flush=True)


if __name__ == "__main__":
    main()
