#!/usr/bin/env python
"""Closed-loop load generator for the serving path: continuous
batching (scheduler on) vs one-request-one-program (scheduler off).

Builds a seeded GPT, exports a stepwise generator artifact, starts the
REST server in-process, and drives it with N closed-loop clients × M
``:generate`` requests each (every client posts, waits, posts again —
the classic closed-loop model, so offered load tracks service rate).
Prompt and ``max_new`` lengths are drawn per request from a seeded RNG
(mixed lengths — the ragged-admission case the scheduler exists for).
Each mode's row reports:

- ``tokens_per_s`` / ``requests_per_s`` — wall-clock throughput over
  the whole matrix;
- ``latency_p50/p95/p99_ms`` — client-observed per-request latency;
- ``decode_steps`` / ``prefills`` / ``steps_shared`` — the
  dispatch-count story from ``/stats`` (scheduler on): K concurrent
  requests should cost ~max(max_new) shared decode dispatches per
  wave, NOT the per-request sum. Scheduler off reports
  ``decode_steps = requests`` (one monolithic decode program each).

The greedy outputs of the two modes are asserted byte-identical per
request (the parity contract) unless ``--no_parity``.

Round 10 adds the block-paged legs: ``--paged`` (+ ``--block_size`` /
``--num_blocks``) serves the block-paged stepwise artifacts, and
``--prefix_mode shared|cold`` shapes the WORKLOAD — ``shared``
prepends one seeded system prefix to every prompt (the prefix-cache
case at the millions-of-users north star), ``cold`` keeps fully
random prompts. Paged rows additionally report
``prefix_cache_hits`` / ``prefill_tokens_saved`` / ``cow_copies``.

Usage::

    JAX_PLATFORMS=cpu python experiments/serving_load.py --smoke
    python experiments/serving_load.py --clients 8 --requests 8 \
        --slots 8 --prompt_len 64 --max_new 64
    python experiments/serving_load.py --paged --block_size 16 \
        --prompt_len 64 --prefix_mode shared

Round 11 — telemetry: scheduler-on rows additionally carry a
``breakdown_ms`` block (queue-wait vs prefill vs decode p50/p95/p99,
from the per-request ``timings`` field every scheduled ``:generate``
response now returns) and a ``registry`` block (the ``GET /metrics``
Prometheus exposition parsed back — the SAME atomic snapshot ``/stats``
renders; ``run_mode`` asserts the two agree exactly once the matrix is
quiesced, and ``bench.py`` sources its serving counters from it). The
``--smoke`` paged-shared leg runs under ``POST /trace/start``/``stop``
and validates the captured Perfetto timeline (per-slot prefill/decode
spans, request-id correlation).

Prints one JSON line per mode plus a ``summary`` line. ``--smoke`` is
the tier-1 CPU configuration (2 clients, tiny model) and ALSO runs the
paged cold/shared legs, asserting paged-vs-slab byte parity,
shared-vs-cold admission byte parity, shared-mode prefill dispatches
strictly below cold-mode, and the scheduler-trace capture; the full
matrix is registered as a ``slow`` test (tests/test_serving_load.py).

Round 13 — thread-ownership sanitizer: ``--thread_sanitizer`` arms the
engine's THR01 runtime checks (every scheduler-owned attribute access
asserts the owning thread) on the scheduler-on legs, and ``--smoke``
always runs a ``tsan_on`` leg asserting the ARMED engine stays byte-
and dispatch-identical to the plain leg (the disabled default provably
adds zero dispatches) plus a seeded cross-thread violation probe
(:func:`thread_sanitizer_check`) proving the sanitizer names the
offending field and thread.

Round 14 — self-healing: ``--smoke`` also runs a ``chaos_on`` leg —
the same matrix with a one-shot transient ``engine.decode_step`` fault
armed through the :mod:`~.runtime.faults` seams. The engine's bounded
re-dispatch protocol must heal it INVISIBLY: byte parity with the
fault-disabled leg, identical dispatch counts, exactly one
``serving_redispatches_total``, zero failed requests (the serving twin
of the training chaos gate; the full scenario soak lives in
``experiments/serving_chaos.py``).
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from distributed_tensorflow_example_tpu.obs import prom as prom_mod

#: documented greedy-drift gate for the int8 legs: token-level
#: agreement with the bf16 oracle over the seeded prompt matrix must
#: stay at or above this bound (measured 1.0 on the tiny CPU config;
#: the bound leaves headroom for the gpt-small TPU re-base — see
#: DESIGN.md §15 "drift gate")
INT8_MIN_AGREEMENT = 0.75


def token_agreement(gens_a, gens_b) -> float:
    """Token-level agreement between two [client][request][tokens]
    generation matrices of identical shape: matching positions /
    compared positions (1.0 when empty)."""
    agree = total = 0
    for row_a, row_b in zip(gens_a, gens_b):
        for ga, gb in zip(row_a, row_b):
            total += len(ga)
            agree += sum(int(a == b) for a, b in zip(ga, gb))
    return agree / total if total else 1.0


def _post(port, name, verb, payload, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}:{verb}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _stats(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/stats",
                                timeout=30) as r:
        return json.loads(r.read())


def _prom(port):
    """GET /metrics parsed into {sample_name: value} — the registry
    snapshot in Prometheus clothing; the bench row sources its
    counters from THIS instead of re-deriving them."""
    from distributed_tensorflow_example_tpu.obs import prom
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=30) as r:
        return prom.parse(r.read().decode())


def _get_json(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return json.loads(r.read())


def _get_text(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return r.read().decode()


def _trace(port, verb):
    req = urllib.request.Request(f"http://127.0.0.1:{port}/trace/{verb}",
                                 data=b"{}")
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _validate_trace(tr, want_request_ids):
    """A captured scheduler trace must be loadable chrome trace-event
    JSON: complete events carry ts/dur/pid/tid/name, per-slot lanes
    exist with prefill/decode spans, and every served request's id
    appears in span args. Returns the X-event count."""
    xs = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
    assert xs, "trace captured no spans"
    for e in xs:
        for k in ("ts", "dur", "pid", "tid", "name"):
            assert k in e, f"X event missing {k}: {e}"
    lanes = {e["args"]["name"] for e in tr["traceEvents"]
             if e.get("name") == "thread_name"}
    assert any(ln.startswith("slot") for ln in lanes), lanes
    names = {e["name"] for e in xs}
    assert {"prefill", "decode_step", "queue_wait", "retire"} <= names, \
        sorted(names)
    span_rids = {e["args"]["request_id"] for e in xs
                 if e.get("args", {}).get("request_id")}
    missing = set(want_request_ids) - span_rids
    assert not missing, f"request ids absent from trace: {missing}"
    return len(xs)


def saturated_histograms(parsed: dict) -> list[str]:
    """Histogram names whose top FINITE bucket is saturated: more than
    1% of observations overflowed into +Inf (i.e. p99 lives above the
    largest finite bound, where percentile queries degenerate). The
    round-17 bucket-audit gate: no default-registered histogram may
    saturate in the --smoke run."""
    names = {k.split("_bucket{le=", 1)[0] for k in parsed
             if "_bucket{le=" in k}
    bad = []
    for h in sorted(names):
        count = parsed.get(f"{h}_count", 0)
        if not count:
            continue
        finite = [v for k, v in parsed.items()
                  if k.startswith(f'{h}_bucket{{le="')
                  and not k.endswith('le="+Inf"}')]
        top_finite_cum = max(finite) if finite else 0
        if (count - top_finite_cum) / count > 0.01:
            bad.append(h)
    return bad


def _pctls(samples_ms):
    """{p50,p95,p99} of a millisecond sample list (zeros when empty) —
    the same nearest-rank rule /stats uses."""
    from distributed_tensorflow_example_tpu.serving_batch import \
        percentile
    return {f"p{q}": round(percentile(samples_ms, q), 2)
            for q in (50, 95, 99)}


def build_export(out_dir: str, *, prompt_len: int, max_new: int,
                 slots: int, seed: int = 0, model_name: str = "gpt_tiny",
                 platforms=("cpu",), paged: bool = False,
                 block_size: int = 16, num_blocks=None,
                 weight_quant: str = "off",
                 kv_cache_dtype: str = "auto", pool_bytes=None,
                 spec_tokens: int = 0, prefill_chunk: int = 0):
    """Seeded GPT stepwise export (ragged monolithic artifact too, so
    the off path serves the same mixed prompt lengths). ``platforms``
    includes "tpu" when bench.py runs the serving row on chip;
    ``paged=True`` exports the block-paged stepwise pair instead of
    the slab pool. ``weight_quant``/``kv_cache_dtype``/``pool_bytes``
    pass straight through to ``export_generator`` (the int8 legs)."""
    import jax
    from distributed_tensorflow_example_tpu.config import TrainConfig
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.serving import export_generator

    model = get_model(model_name, TrainConfig(model=model_name))
    params = model.init(jax.random.key(seed))
    export_generator(model, params, out_dir, prompt_len=prompt_len,
                     max_new_tokens=max_new, batch_size=1, ragged=True,
                     stepwise=True, slots=slots, paged=paged,
                     block_size=block_size, num_blocks=num_blocks,
                     weight_quant=weight_quant,
                     kv_cache_dtype=kv_cache_dtype,
                     pool_bytes=pool_bytes, spec_tokens=spec_tokens,
                     prefill_chunk=prefill_chunk,
                     platforms=tuple(platforms))
    return model.cfg.vocab_size


def make_requests(clients: int, requests: int, *, prompt_len: int,
                  max_new: int, vocab: int, seed: int,
                  prefix_mode: str = "cold", block_size: int = 16):
    """The seeded request matrix: [client][request] -> (prompt ids,
    max_new). Mixed lengths, identical across modes (same seed).

    ``prefix_mode="shared"`` models the millions-of-users shape: every
    prompt starts with ONE seeded system prefix (length = the largest
    ``block_size`` multiple that leaves suffix room, at least
    ``block_size``) followed by a short random user suffix — the
    workload the paged engine's prefix cache exists for.
    ``"cold"`` keeps fully random prompts (every admission misses)."""
    if prefix_mode not in ("cold", "shared"):
        raise ValueError(f"prefix_mode must be cold/shared, got "
                         f"{prefix_mode!r}")
    rs = np.random.RandomState(seed)
    sys_prefix = None
    if prefix_mode == "shared":
        sys_len = max(block_size,
                      (prompt_len - 1) // block_size * block_size)
        if sys_len >= prompt_len:
            raise ValueError(
                f"prompt_len {prompt_len} leaves no suffix room after a "
                f"{sys_len}-token shared prefix (block_size "
                f"{block_size}) — raise prompt_len or shrink block_size")
        sys_prefix = rs.randint(0, vocab, (sys_len,)).astype(np.int32)
    matrix = []
    for _ in range(clients):
        rows = []
        for _ in range(requests):
            if sys_prefix is None:
                p = int(rs.randint(1, prompt_len + 1))
                prompt = rs.randint(0, vocab, (p,)).astype(np.int32)
            else:
                s = int(rs.randint(1, prompt_len - sys_prefix.size + 1))
                prompt = np.concatenate(
                    [sys_prefix,
                     rs.randint(0, vocab, (s,)).astype(np.int32)])
            m = int(rs.randint(1, max_new + 1))
            rows.append((prompt, m))
        matrix.append(rows)
    return matrix


def make_repetitive_requests(clients: int, requests: int, *,
                             prompt_len: int, max_new: int, vocab: int,
                             seed: int, period: int = 3):
    """The speculative-decoding workload: every prompt is one seeded
    ``period``-token pattern tiled to a seeded length, so the
    prompt-lookup drafter's suffix n-grams recur from token one — and
    greedy decode of a fixed model drifts into its own repetitive
    fixed points, which the drafter then mines from the GENERATED
    context too. Same [client][request] -> (prompt, max_new) shape as
    :func:`make_requests`."""
    rs = np.random.RandomState(seed)
    pattern = rs.randint(0, vocab, (period,)).astype(np.int32)
    matrix = []
    for _ in range(clients):
        rows = []
        for _ in range(requests):
            p = int(rs.randint(max(2, period), prompt_len + 1))
            prompt = np.tile(pattern, -(-p // period))[:p]
            rows.append((prompt, max_new))
        matrix.append(rows)
    return matrix


def run_mode(export_dir: str, matrix, *, scheduler: str,
             prompt_len: int, mode_name: str | None = None,
             prefix_cache: bool = True, trace: bool = False,
             thread_sanitizer: bool = False,
             spec_tokens: int = 0,
             server_kw: dict | None = None) -> dict:
    """Drive one server mode with the closed-loop client matrix;
    returns the result row (and stashes per-request generations under
    ``_gens`` for the parity check). ``thread_sanitizer=True`` arms the
    engine's THR01 runtime ownership checks for the whole leg — a
    cross-thread touch of a scheduler-owned field fails the run
    loudly, and the row must stay byte- and dispatch-identical to the
    unarmed leg (asserted by the --smoke checks)."""
    from distributed_tensorflow_example_tpu.serving_http import PredictServer

    clients = len(matrix)
    lat: list[list[float]] = [[] for _ in range(clients)]
    gens: list[list[list[int]]] = [[] for _ in range(clients)]
    timings: list[dict] = []             # scheduler on: one per request
    request_ids: list[str] = []
    errors: list[str] = []
    with PredictServer(export_dir, scheduler=scheduler,
                       prefix_cache=prefix_cache,
                       thread_sanitizer=thread_sanitizer,
                       spec_tokens=spec_tokens,
                       **(server_kw or {})) as srv:
        def client(ci):
            for prompt, m in matrix[ci]:
                if scheduler == "on":
                    payload = {"inputs": {"input_ids": [prompt.tolist()]},
                               "max_new": m}
                else:
                    # the monolithic artifact is static-shape: pad to
                    # the exported prompt + mask; it always generates
                    # its exported max_new — truncate client-side so
                    # both modes compare the same m tokens
                    ids = np.zeros((prompt_len,), np.int32)
                    ids[:prompt.size] = prompt
                    mask = np.zeros((prompt_len,), np.int32)
                    mask[:prompt.size] = 1
                    payload = {"inputs": {"input_ids": [ids.tolist()],
                                          "prompt_mask": [mask.tolist()]}}
                t0 = time.perf_counter()
                try:
                    out = _post(srv.port, srv.name, "generate", payload)
                except Exception as e:          # noqa: BLE001 — recorded
                    errors.append(f"client {ci}: {type(e).__name__}: {e}")
                    return
                lat[ci].append(time.perf_counter() - t0)
                gens[ci].append(out["generations"][0][:m])
                if "timings" in out:
                    timings.extend(out["timings"])
                    request_ids.extend(out["request_ids"])

        if trace:
            _trace(srv.port, "start")
        t_start = time.perf_counter()
        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start
        stats = _stats(srv.port)
        registry = _prom(srv.port) if scheduler == "on" else {}
        trace_events = None
        if trace:
            trace_events = _validate_trace(_trace(srv.port, "stop"),
                                           request_ids)

    flat_lat = sorted(x for row in lat for x in row)
    n_req = len(flat_lat)
    n_tok = sum(len(g) for row in gens for g in row)

    def pctl(q):
        if not flat_lat:
            return 0.0
        i = min(n_req - 1, int(round(q / 100 * (n_req - 1))))
        return flat_lat[i] * 1e3

    g = stats.get("generate", {})
    if registry:
        # /stats is a view of the registry snapshot /metrics renders —
        # with the server quiesced (all closed-loop clients joined) the
        # two must agree EXACTLY; a mismatch means the one-source-of-
        # truth contract broke
        for stat_key, prom_key in (
                ("decode_steps", "serving_decode_steps_total"),
                ("prefills", "serving_prefills_total"),
                ("requests_done", "serving_requests_done_total"),
                ("tokens_out", "serving_tokens_out_total")):
            if g.get(stat_key) != registry.get(prom_key):
                errors.append(
                    f"/stats {stat_key}={g.get(stat_key)} disagrees "
                    f"with /metrics {prom_key}={registry.get(prom_key)}")
    row = {
        "mode": mode_name or f"scheduler_{scheduler}",
        "clients": clients,
        "requests": n_req,
        "errors": errors,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(n_tok / wall, 2) if wall else 0.0,
        "requests_per_s": round(n_req / wall, 3) if wall else 0.0,
        "latency_p50_ms": round(pctl(50), 2),
        "latency_p95_ms": round(pctl(95), 2),
        "latency_p99_ms": round(pctl(99), 2),
        # off path: every request is one monolithic decode dispatch
        "decode_steps": g.get("decode_steps", n_req),
        "prefills": g.get("prefills", n_req),
        "steps_shared": g.get("steps_shared", 1.0),
        "_gens": gens,
    }
    if timings:
        # per-request latency breakdown from the engine's `timings`
        # field: WHERE the time went (admission queue vs prefill vs
        # shared decode), not just how much there was
        row["breakdown_ms"] = {
            "queue": _pctls([t["queue_ms"] for t in timings]),
            "prefill": _pctls([t["prefill_ms"] for t in timings]),
            "decode": _pctls([t["decode_ms"] for t in timings]),
        }
    if registry:
        # the registry snapshot itself (counters/gauges only — bucket
        # series stay on /metrics): bench.py sources its serving
        # counters from here instead of re-deriving them
        row["registry"] = {k: v for k, v in sorted(registry.items())
                           if "_bucket{" not in k}
        # the round-17 bucket-audit observable: histograms whose top
        # finite bucket saturated (p99 above the largest bound) —
        # --smoke gates this list empty
        row["saturated_histograms"] = saturated_histograms(registry)
    if trace_events is not None:
        row["trace_events"] = trace_events
    if g.get("paged"):
        row.update({
            "prefix_cache_hits": g["prefix_cache_hits"],
            "prefix_cache_misses": g["prefix_cache_misses"],
            "prefill_tokens_saved": g["prefill_tokens_saved"],
            "blocks_total": g["blocks_total"],
            "cow_copies": g["cow_copies"],
        })
    if g.get("spec_tokens"):
        # speculative-decoding observability: the accept-rate story
        # and the dispatch-count win live on the row itself
        row.update({
            "spec_tokens": g["spec_tokens"],
            "verify_steps": g["verify_steps"],
            "spec_proposed": g["spec_proposed"],
            "spec_accepted": g["spec_accepted"],
            "spec_emitted": g["spec_emitted"],
            "accept_rate": g["accept_rate"],
        })
    return row


def run_router_mode(export_dir: str, matrix, *, replicas: int = 2,
                    mode_name: str = "router_on",
                    **router_kw) -> dict:
    """Drive the closed-loop client matrix through a replica ROUTER
    fronting ``replicas`` in-process servers over the same export —
    the fleet leg: tps/p95 next to the single-replica rows, fleet
    counters from the merged ``/metrics`` page, and the same ``_gens``
    stash for the byte-parity check (greedy output must not depend on
    which replica serves)."""
    from distributed_tensorflow_example_tpu.serving_router import \
        InProcessFleet

    clients = len(matrix)
    lat: list[list[float]] = [[] for _ in range(clients)]
    gens: list[list[list[int]]] = [[] for _ in range(clients)]
    # per-client rows, aggregated after join — a shared dict's
    # read-modify-write would race across client threads
    served_rows: list[list[str]] = [[] for _ in range(clients)]
    errors: list[str] = []
    fleet = InProcessFleet(export_dir, replicas, **router_kw)
    try:
        def client(ci):
            for prompt, m in matrix[ci]:
                payload = {"inputs": {"input_ids": [prompt.tolist()]},
                           "max_new": m}
                t0 = time.perf_counter()
                try:
                    out = _post(fleet.port, fleet.name, "generate",
                                payload)
                except Exception as e:      # noqa: BLE001 — recorded
                    errors.append(f"client {ci}: {type(e).__name__}: "
                                  f"{e}")
                    return
                lat[ci].append(time.perf_counter() - t0)
                gens[ci].append(out["generations"][0][:m])
                served_rows[ci].append(out.get("served_by", "?"))

        t_start = time.perf_counter()
        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start
        registry = _prom(fleet.port)        # fleet-merged /metrics
    finally:
        fleet.close()

    served: dict[str, int] = {}
    for row in served_rows:
        for by in row:
            served[by] = served.get(by, 0) + 1
    flat_lat = sorted(x for row in lat for x in row)
    n_req = len(flat_lat)
    n_tok = sum(len(g) for row in gens for g in row)

    def pctl(q):
        if not flat_lat:
            return 0.0
        i = min(n_req - 1, int(round(q / 100 * (n_req - 1))))
        return flat_lat[i] * 1e3

    return {
        "mode": mode_name,
        "replicas": replicas,
        "clients": clients,
        "requests": n_req,
        "errors": errors,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(n_tok / wall, 2) if wall else 0.0,
        "requests_per_s": round(n_req / wall, 3) if wall else 0.0,
        "latency_p50_ms": round(pctl(50), 2),
        "latency_p95_ms": round(pctl(95), 2),
        "latency_p99_ms": round(pctl(99), 2),
        "served_by": dict(sorted(served.items())),
        # fleet-level counters: replica registries + the router's own,
        # merged by the /metrics page itself
        "decode_steps": int(registry.get("serving_decode_steps_total",
                                         0)),
        "prefills": int(registry.get("serving_prefills_total", 0)),
        "router_requests": int(registry.get("router_requests_total",
                                            0)),
        "router_retries": int(registry.get("router_retries_total", 0)),
        "router_hedges": int(registry.get("router_hedges_total", 0)),
        "router_hedge_wins": int(registry.get(
            "router_hedge_wins_total", 0)),
        "router_failovers": int(registry.get(
            "router_failovers_total", 0)),
        # percentile sourced from the MERGED registry's
        # router_request_seconds histogram (not a client stopwatch) —
        # the trajectory bench.py publishes as {key}_router_p95_ms
        "fleet_registry_p95_ms": round(
            prom_mod.quantile_from_parsed(
                registry, "router_request_seconds", 0.95) * 1e3, 2),
        "saturated_histograms": saturated_histograms(registry),
        "_gens": gens,
    }


def int8_capacity_check(*, prompt_len: int, max_new: int, seed: int,
                        block_size: int) -> tuple[int, int]:
    """THE equal-bytes capacity probe: export a bf16 and an int8 paged
    artifact at the SAME K/V pool byte budget, offer each engine a wave
    of distinct short prompts, and count concurrent admissions. int8
    halves the per-block payload, so its pool holds 2x the blocks and
    must admit strictly more requests. Returns ``(bf16_admitted,
    int8_admitted)``."""
    import tempfile

    from distributed_tensorflow_example_tpu.serving import load_stepwise
    from distributed_tensorflow_example_tpu.serving_batch import \
        GenerationEngine

    total = prompt_len + max_new
    bps = -(-total // block_size)
    slots = 16
    rs = np.random.RandomState(seed + 999)
    # distinct 2-token prompts (1 block each) — prefix cache off, so
    # admission counts are pure block-capacity observations
    prompts = [np.array([i, int(rs.randint(0, 1000))], np.int32)
               for i in range(slots)]
    counts = {}
    pool_bytes = None
    for dtype in ("bf16", "int8"):
        with tempfile.TemporaryDirectory() as d:
            build_export(d, prompt_len=prompt_len, max_new=max_new,
                         slots=slots, seed=seed, paged=True,
                         block_size=block_size,
                         kv_cache_dtype=dtype,
                         num_blocks=(1 + 2 * bps) if pool_bytes is None
                         else None,
                         pool_bytes=pool_bytes)
            sw = load_stepwise(d)
            if pool_bytes is None:
                # the bf16 pool's K/V byte budget = the int8 export's
                # pool_bytes (block_bytes is pure payload for bf16)
                m = sw.step_meta
                pool_bytes = (int(m["num_blocks"]) - 1) \
                    * int(m["block_bytes"])
            eng = GenerationEngine(sw, prefix_cache=False)
            for p in prompts:
                # max_new=2: a slot stays LIVE after its admission
                # prefill (max_new=1 retires on the prefill logits),
                # so len(_live) counts concurrent residency
                eng.submit(p, max_new=2)
            eng._admit()
            counts[dtype] = len(eng._live)
            eng.close()
    return counts["bf16"], counts["int8"]


def chunk_stall_probe(*, seed: int = 0, prompt_len: int = 512,
                      block_size: int = 32, max_new: int = 16,
                      storms: int = 2) -> dict:
    """THE decode-stall-under-long-prompt probe (round 18): a
    long-context GPT (the tiny smoke model's prefill is too cheap to
    stall anything) serves a live short decoder while full-length
    prompts admit mid-stream, chunked OFF vs ON over the same export.
    Measures the gap between consecutive shared decode dispatches as
    the live decoder experiences it (direct engine drive, warmed
    first — compile time must not masquerade as stall) and returns
    both modes' p95/max-stall plus wall time. The gated figure is the
    WORST-CASE stall: monolithic admission stalls the decoder for one
    whole prompt forward, chunked for at most one chunk dispatch —
    the structural bound chunked prefill exists for. ``storms`` runs
    per mode take the min-of-max (OS jitter must not fail the gate).
    Byte parity between the modes is asserted inside."""
    import tempfile as _tf

    import jax
    from distributed_tensorflow_example_tpu.models.gpt import (GPT,
                                                               GPTConfig)
    from distributed_tensorflow_example_tpu.serving import (
        export_generator, load_stepwise)
    from distributed_tensorflow_example_tpu.serving_batch import \
        GenerationEngine

    cfg = GPTConfig(vocab_size=512, hidden=128, layers=2, heads=4,
                    intermediate=256,
                    max_len=prompt_len + max(max_new, 192))
    model = GPT(cfg)
    params = model.init(jax.random.key(seed))
    rs = np.random.RandomState(seed)
    long_prompts = [rs.randint(0, cfg.vocab_size,
                               (prompt_len,)).astype(np.int32)
                    for _ in range(3)]
    short = rs.randint(0, cfg.vocab_size, (4,)).astype(np.int32)
    with _tf.TemporaryDirectory() as d:
        export_generator(model, params, d, prompt_len=prompt_len,
                         max_new_tokens=192, batch_size=1,
                         ragged=True, stepwise=True, slots=4,
                         paged=True, block_size=block_size,
                         prefill_chunk=block_size, platforms=("cpu",))

        def run(chunk):
            # prefix cache OFF: the warmup request would otherwise
            # cache the long prompt and turn the storm's admissions
            # into prefill-free cache hits — the A/B must measure the
            # PREFILL stall it exists to compare
            eng = GenerationEngine(load_stepwise(d),
                                   prefix_cache=False,
                                   prefill_chunk_tokens=chunk).start()
            od = eng.sw.decode
            gaps: list[float] = []
            last = [0.0]

            def wrapped(feats):
                t = time.perf_counter()
                if last[0]:
                    gaps.append(t - last[0])
                out = od(feats)
                last[0] = time.perf_counter()
                return out

            try:
                # warm every program (prefill or chunks + decode)
                eng.submit(long_prompts[0],
                           max_new=2).result(timeout=600)
                # the witness decoder: deep enough max_new to stay
                # live through every storm — its inter-dispatch gaps
                # ARE the stall measurement
                witness = eng.submit(short, max_new=192)
                t_w = time.monotonic()
                while eng.stats()["live_slots"] < 1 \
                        and time.monotonic() - t_w < 60:
                    time.sleep(0.002)
                eng.sw.decode = wrapped
                # gaps accumulate across every storm: the witness
                # keeps decoding between storms, so inter-storm gaps
                # are ordinary ~ms decode cadence, not idle time
                gaps.clear()
                last[0] = 0.0
                outs, wall, lived = [], 0.0, True
                t_all = time.perf_counter()
                for _ in range(storms):
                    hs = [eng.submit(p, max_new=2)
                          for p in long_prompts]
                    outs = [h.result(timeout=600) for h in hs]
                    lived = lived and not witness.done()
                wall = time.perf_counter() - t_all
                from distributed_tensorflow_example_tpu.serving_batch \
                    import percentile
                witness.cancel()
                return {"outs": outs,
                        "witness_lived": lived,
                        "stall_p95_ms": round(
                            percentile(gaps, 95) * 1e3, 2),
                        "stall_max_ms": round(
                            (max(gaps) if gaps else 0.0) * 1e3, 2),
                        "wall_s": round(wall, 3)}
            finally:
                eng.close()

        off, on = run(0), run(block_size)
    parity = (off.pop("outs") == on.pop("outs")
              and off.pop("witness_lived") and on.pop("witness_lived"))
    return {"off": off, "on": on, "parity": parity,
            "prompt_len": prompt_len, "chunk": block_size}


def run_overload(export_dir: str, *, vocab: int, seed: int,
                 prompt_len: int, max_new: int = 4,
                 max_queue: int = 3,
                 interactive_clients: int = 4, requests: int = 3,
                 deadline_ms: int = 60_000) -> dict:
    """The overload leg (round 18): ~2x sustainable offered load — a
    closed-loop INTERACTIVE base load that keeps the small admission
    queue deep, plus a best_effort poster hammering beside it. The
    brownout ladder must shed the best_effort traffic with 429 + a
    Retry-After header while EVERY admitted interactive request
    finishes inside its (generous) deadline with zero client-visible
    failures — shed requests are told when to come back, never left
    to time out."""
    from distributed_tensorflow_example_tpu.serving_http import \
        PredictServer

    rs = np.random.RandomState(seed)
    lat: list[float] = []
    errors: list[str] = []
    shed_429: list[str] = []          # Retry-After header per SHED 429
    queue_full_429 = [0]              # blunt-bound 429s (not sheds)
    missing_retry_after = [0]
    with PredictServer(export_dir, max_queue=max_queue) as srv:
        stop = threading.Event()

        def interactive(ci):
            for _ in range(requests):
                prompt = rs.randint(0, vocab,
                                    (prompt_len,)).astype(np.int32)
                t0 = time.perf_counter()
                for _attempt in range(100):
                    try:
                        _post(srv.port, srv.name, "generate",
                              {"inputs": {"input_ids":
                                          [prompt.tolist()]},
                               "max_new": max_new,
                               "deadline_ms": deadline_ms,
                               "priority": "interactive"})
                        lat.append(time.perf_counter() - t0)
                        break
                    except urllib.error.HTTPError as e:
                        if e.code == 429:
                            # queue-full pushback: a closed-loop
                            # client honors Retry-After and retries —
                            # interactive is never CLASS-shed, so
                            # this is the blunt bound, not the ladder
                            try:
                                ra = float(e.headers.get(
                                    "Retry-After", 0) or 0)
                            except ValueError:
                                ra = 0.0
                            e.read()
                            time.sleep(min(max(ra, 0.005), 0.05))
                            continue
                        errors.append(f"interactive {ci}: http "
                                      f"{e.code}")
                        return
                    except Exception as e:  # noqa: BLE001 — recorded
                        errors.append(f"interactive {ci}: "
                                      f"{type(e).__name__}: {e}")
                        return
                else:
                    errors.append(f"interactive {ci}: retry budget "
                                  "exhausted on 429s")
                    return

        def best_effort():
            # hammer until the ladder sheds (bounded): a 429 carrying
            # Retry-After is the success condition here
            for _ in range(200):
                if stop.is_set():
                    return
                try:
                    _post(srv.port, srv.name, "generate",
                          {"inputs": {"input_ids": [[1, 2]]},
                           "max_new": 2, "priority": "best_effort"})
                except urllib.error.HTTPError as e:
                    if e.code == 429:
                        ra = e.headers.get("Retry-After")
                        body = e.read().decode(errors="replace")
                        # a class SHED names itself ("shedding ... /
                        # shed while queued"); a blunt queue-full 429
                        # is the pre-round-18 bound, not a shed — the
                        # registry's serving_shed_total only counts
                        # the former, so the client ledger must too
                        if "shed" not in body:
                            queue_full_429[0] += 1
                        elif ra is None:
                            missing_retry_after[0] += 1
                        else:
                            shed_429.append(ra)
                    else:
                        errors.append(f"best_effort: http {e.code}")
                except Exception as e:      # noqa: BLE001 — recorded
                    errors.append(f"best_effort: {type(e).__name__}: "
                                  f"{e}")
                time.sleep(0.002)

        threads = [threading.Thread(target=interactive, args=(ci,))
                   for ci in range(interactive_clients)]
        be = threading.Thread(target=best_effort)
        for t in threads:
            t.start()
        be.start()
        for t in threads:
            t.join()
        stop.set()
        be.join()
        stats = _stats(srv.port)["generate"]
        registry = _prom(srv.port)
    n = len(lat)
    lat.sort()

    def pctl(q):
        if not lat:
            return 0.0
        return lat[min(n - 1, int(round(q / 100 * (n - 1))))] * 1e3

    return {
        "mode": "overload",
        "interactive_requests": n,
        "interactive_expected": interactive_clients * requests,
        "errors": errors,
        "latency_p95_ms": round(pctl(95), 2),
        "deadline_ms": deadline_ms,
        "shed_429": len(shed_429),
        "queue_full_429": queue_full_429[0],
        "missing_retry_after": missing_retry_after[0],
        "shed_total": int(registry.get("serving_shed_total", 0)),
        "shed_best_effort": int(registry.get(
            "serving_shed_best_effort_total", 0)),
        "deadline_expired": int(registry.get(
            "serving_deadline_expired_total", 0)),
        "pressure_transitions": int(registry.get(
            "serving_pressure_transitions_total", 0)),
        "pressure_final": stats["pressure"],
    }


def run_slo_report(export_dir: str, *, vocab: int, seed: int,
                   prompt_len: int, max_new: int = 4,
                   max_queue: int = 3,
                   interactive_clients: int = 4, requests: int = 3,
                   deadline_ms: int = 60_000) -> dict:
    """The ``slo_report`` leg (round 19): the overload-shaped
    mixed-class workload against a server with the history sampler +
    SLO objectives armed, reconciled THREE ways — the registry-derived
    attainment/goodput (what ``servetop`` computes from
    ``GET /stats/history``) must EXACTLY equal the harness's own
    per-request outcome ledger AND a replay of the ``--request_log``
    JSONL events. The induced best_effort burn must produce exactly
    ONE rate-limited ``slo_burn`` incident bundle whose registry
    snapshot agrees with the live ``/metrics`` page.

    Determinism without sleeps: ``history_interval_s`` is set far
    beyond the leg's lifetime, so the ring holds exactly the samples
    this harness forces — the zero baseline ``start()`` captures and
    one forced sample per ``GET /stats/history`` poll. No sample
    lands mid-traffic, so the breach evaluates exactly twice, both
    after quiesce: the first poll writes THE bundle (quiesced
    snapshot == live page), the second is suppressed by the per-cause
    rate limit."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools import servetop

    from distributed_tensorflow_example_tpu.serving_http import \
        PredictServer

    rs = np.random.RandomState(seed)
    errors: list[str] = []
    # the harness ledger: per-class terminal outcomes as the CLIENT
    # saw them (the ground truth the registry and the request log
    # must reconcile against)
    ledger = {cls: {"ok": 0, "good": 0, "shed": 0, "tokens": 0,
                    "goodput_tokens": 0}
              for cls in ("interactive", "batch", "best_effort")}
    ledger_lock = threading.Lock()
    with tempfile.TemporaryDirectory() as d:
        req_log = os.path.join(d, "requests.jsonl")
        inc_dir = os.path.join(d, "incidents")
        srv = PredictServer(
            export_dir, max_queue=max_queue, request_log=req_log,
            incident_dir=inc_dir,
            history_interval_s=3600.0, history_samples=64,
            slo_spec=("interactive:hit_rate=0.9;"
                      "interactive:p95_ms=60000@0.9;"
                      "best_effort:hit_rate=0.9"),
            slo_fast_window_s=7200.0, slo_slow_window_s=7200.0,
            slo_burn_threshold=1.0)
        srv.start()
        try:
            stop = threading.Event()

            def record(cls: str, out: dict) -> None:
                t = out["timings"][0]
                with ledger_lock:
                    ledger[cls]["ok"] += 1
                    ledger[cls]["tokens"] += t["tokens"]
                    if t["slo_good"]:
                        ledger[cls]["good"] += 1
                        ledger[cls]["goodput_tokens"] += t["tokens"]

            def interactive(ci):
                for _ in range(requests):
                    prompt = rs.randint(0, vocab,
                                        (prompt_len,)).astype(np.int32)
                    for _attempt in range(100):
                        try:
                            out = _post(
                                srv.port, srv.name, "generate",
                                {"inputs": {"input_ids":
                                            [prompt.tolist()]},
                                 "max_new": max_new,
                                 "deadline_ms": deadline_ms,
                                 "priority": "interactive"})
                            record("interactive", out)
                            break
                        except urllib.error.HTTPError as e:
                            if e.code == 429:
                                # interactive is never ladder-shed:
                                # this is the blunt queue-full bound,
                                # which the SLO counters exclude — the
                                # closed-loop client retries it
                                try:
                                    ra = float(e.headers.get(
                                        "Retry-After", 0) or 0)
                                except ValueError:
                                    ra = 0.0
                                e.read()
                                time.sleep(min(max(ra, 0.005), 0.05))
                                continue
                            errors.append(f"interactive {ci}: http "
                                          f"{e.code}")
                            return
                        except Exception as e:  # noqa: BLE001
                            errors.append(f"interactive {ci}: "
                                          f"{type(e).__name__}: {e}")
                            return
                    else:
                        errors.append(f"interactive {ci}: retry "
                                      "budget exhausted on 429s")
                        return

            def best_effort():
                for _ in range(200):
                    if stop.is_set():
                        return
                    try:
                        out = _post(srv.port, srv.name, "generate",
                                    {"inputs": {"input_ids": [[1, 2]]},
                                     "max_new": 2,
                                     "priority": "best_effort"})
                        record("best_effort", out)
                    except urllib.error.HTTPError as e:
                        if e.code == 429:
                            body = e.read().decode(errors="replace")
                            # only a class SHED enters the SLO served
                            # counters; the blunt queue-full 429 is a
                            # pre-admission refusal the client retries
                            if "shed" in body:
                                with ledger_lock:
                                    ledger["best_effort"]["shed"] += 1
                        else:
                            errors.append(f"best_effort: http "
                                          f"{e.code}")
                    except Exception as e:      # noqa: BLE001
                        errors.append(f"best_effort: "
                                      f"{type(e).__name__}: {e}")
                    time.sleep(0.002)

            threads = [threading.Thread(target=interactive, args=(ci,))
                       for ci in range(interactive_clients)]
            be = threading.Thread(target=best_effort)
            for t in threads:
                t.start()
            be.start()
            for t in threads:
                t.join()
            stop.set()
            be.join()
            # ---- quiesced: poll #1 forces the breach evaluation ----
            hist1 = _get_json(srv.port, "/stats/history")
            bundles = sorted(os.listdir(inc_dir))
            burn_bundles = [b for b in bundles if "-slo_burn-" in b]
            bundle_matches = False
            if burn_bundles:
                with open(os.path.join(inc_dir, burn_bundles[0])) as f:
                    bundle = json.load(f)
                # the bundle snapshot must agree with the live page —
                # rendered through the same exposition path; only the
                # http_* counters may differ (each poll advances them
                # at response time, after the incident landed)
                from distributed_tensorflow_example_tpu.obs import \
                    prom as obs_prom

                def page(text):
                    return "\n".join(
                        ln for ln in text.splitlines()
                        if "http_requests_total" not in ln
                        and "http_errors_total" not in ln)

                live = _get_text(srv.port, "/metrics")
                bundle_matches = (
                    page(obs_prom.render(bundle["registry"]))
                    == page(live))
            # poll #2: still breaching, must be rate-limit suppressed
            # (the re-count AFTER it is what proves suppression — the
            # first count alone could not see a second bundle land)
            hist2 = _get_json(srv.port, "/stats/history")
            burn_bundles = [b for b in sorted(os.listdir(inc_dir))
                            if "-slo_burn-" in b]
            registry = _prom(srv.port)
            healthz = _get_json(srv.port, "/healthz")
        finally:
            srv.stop()
        # ---- the three-way reconciliation ---------------------------
        summary = servetop.compute_summary(hist2)
        replay = {cls: {"ok": 0, "good": 0, "shed": 0,
                        "goodput_tokens": 0}
                  for cls in ("interactive", "batch", "best_effort")}
        with open(req_log) as f:
            for line in f:
                ev = json.loads(line)
                if ev.get("event") != "generate":
                    continue
                cls = ev["priority"]
                if ev["outcome"] == "ok":
                    replay[cls]["ok"] += 1
                    if ev["slo_good"]:
                        replay[cls]["good"] += 1
                        replay[cls]["goodput_tokens"] += ev["tokens"]
                elif ev["outcome"] == "shed":
                    replay[cls]["shed"] += 1
        diffs: list[str] = []

        def must_eq(what, *vals):
            if len({json.dumps(v, sort_keys=True)
                    for v in vals}) != 1:
                diffs.append(f"{what}: {vals}")

        for cls in ("interactive", "best_effort"):
            led, rep = ledger[cls], replay[cls]
            stc = summary["classes"][cls]
            must_eq(f"{cls} served", led["ok"] + led["shed"],
                    rep["ok"] + rep["shed"], stc["served"],
                    int(registry.get(
                        f"serving_slo_served_{cls}_total", 0)))
            must_eq(f"{cls} good", led["good"], rep["good"],
                    stc["good"],
                    int(registry.get(
                        f"serving_slo_good_{cls}_total", 0)))
            must_eq(f"{cls} shed", led["shed"], rep["shed"],
                    stc["shed"],
                    int(registry.get(f"serving_shed_{cls}_total", 0)))
        total_goodput = sum(c["goodput_tokens"]
                            for c in ledger.values())
        must_eq("goodput tokens", total_goodput,
                sum(c["goodput_tokens"] for c in replay.values()),
                summary["goodput_tokens"],
                int(registry.get("serving_goodput_tokens_total", 0)))
        slo_block = (healthz.get("slo") or {})
        return {
            "mode": "slo_report",
            "errors": errors,
            "interactive_ok": ledger["interactive"]["ok"],
            "interactive_expected": interactive_clients * requests,
            "best_effort_shed": ledger["best_effort"]["shed"],
            "goodput_tokens": total_goodput,
            "tokens": int(registry.get("serving_tokens_out_total",
                                       0)),
            "goodput_tps": summary["goodput_tps"],
            "throughput_tps": summary["throughput_tps"],
            "attainment_interactive":
                summary["classes"]["interactive"]["attainment"],
            "attainment_best_effort":
                summary["classes"]["best_effort"]["attainment"],
            "reconciled": not diffs,
            "reconcile_diff": diffs,
            "burn_bundles": len(burn_bundles),
            "bundle_matches_metrics": bundle_matches,
            "burn_suppressed": int(registry.get(
                "serving_incidents_suppressed_total", 0)),
            "healthz_breaching": slo_block.get("breaching", []),
            "history_samples": len(hist2.get("samples", ())),
            "history_samples_first_poll":
                len(hist1.get("samples", ())),
        }


def thread_sanitizer_check(export_dir: str, prompt) -> tuple[bool, str]:
    """The seeded THR01 violation probe: arm an engine's runtime
    thread sanitizer, let the scheduler thread take ownership (one
    request through the fully legal path first), then touch a
    scheduler-owned field from THIS thread — the exact cross-thread
    mutation class the single-flight design forbids. Returns
    ``(caught, message)``: ``caught`` is True only when the sanitizer
    raised :class:`ThreadOwnershipError` naming both the field and the
    offending thread."""
    from distributed_tensorflow_example_tpu.serving import load_stepwise
    from distributed_tensorflow_example_tpu.serving_batch import (
        GenerationEngine, ThreadOwnershipError)

    eng = GenerationEngine(load_stepwise(export_dir),
                           thread_sanitizer=True).start()
    try:
        # legal traffic first: the armed engine must serve it clean
        eng.submit(prompt, max_new=2).result(timeout=120)
        try:
            eng._live            # noqa: B018 — the seeded violation
        except ThreadOwnershipError as e:
            msg = str(e)
            named = ("_live" in msg
                     and threading.current_thread().name in msg)
            return named, msg
        return False, "cross-thread read of _live went unchallenged"
    finally:
        eng.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--requests", type=int, default=8,
                    help="requests per client (closed loop)")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prompt_len", type=int, default=16)
    ap.add_argument("--max_new", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--paged", action="store_true",
                    help="export/serve the block-paged stepwise "
                    "artifacts (block pool + prefix cache) instead of "
                    "the slab pool")
    ap.add_argument("--block_size", type=int, default=16,
                    help="paged: tokens per physical cache block")
    ap.add_argument("--num_blocks", type=int, default=None,
                    help="paged: physical blocks in the pool (default: "
                    "slab-equivalent capacity + the null block)")
    ap.add_argument("--pool_bytes", type=int, default=None,
                    help="paged: size the block pool in BYTES instead "
                    "of blocks (int8 then holds 2x the bf16 block "
                    "count at the same budget)")
    ap.add_argument("--weight_quant", choices=("off", "int8"),
                    default="off",
                    help="decode weights: 'int8' bakes per-output-"
                    "channel int8 + scales into every decode program "
                    "(LOSSY — gated by the drift bound, not byte "
                    "parity)")
    ap.add_argument("--kv_cache_dtype", choices=("auto", "bf16", "int8"),
                    default="auto",
                    help="KV-cache pool storage: 'auto' keeps the "
                    "model dtype (the bitwise no-op), 'int8' stores "
                    "quantized blocks + per-row scales (requires "
                    "--paged)")
    ap.add_argument("--spec_tokens", type=int, default=0,
                    help="speculative decoding: export the K-token "
                    "verify program and serve the scheduler-on leg "
                    "with --spec_tokens K (greedy byte parity vs the "
                    "off leg still asserted — speculation is exact); "
                    "needs --paged. --smoke runs its own spec legs")
    ap.add_argument("--prefix_mode", choices=("cold", "shared"),
                    default="cold",
                    help="workload shape: 'shared' prepends one seeded "
                    "system prefix to every prompt (the prefix-cache "
                    "case); 'cold' keeps fully random prompts")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 CPU config: 2 clients x 2 requests, "
                    "tiny shapes; runs the slab on/off pair PLUS the "
                    "paged cold/shared legs, an int8 leg (drift "
                    "bound + equal-bytes capacity), a THR01 "
                    "thread-sanitizer leg (armed byte/dispatch parity "
                    "+ seeded cross-thread violation probe), a "
                    "chaos_on leg (one-shot transient decode fault "
                    "healed to byte/dispatch parity), and a router_on "
                    "leg (2-replica fleet behind serving_router, byte "
                    "parity with the single-replica row), asserting "
                    "paged-vs-slab parity and shared-mode prefill "
                    "savings")
    ap.add_argument("--router", type=int, default=0,
                    help="also run a fleet leg: N in-process replicas "
                    "over the same export behind serving_router's "
                    "ReplicaRouter (tps/p95 vs the single-replica "
                    "rows, byte parity asserted); 0 = off (--smoke "
                    "always runs a 2-replica leg)")
    ap.add_argument("--no_parity", action="store_true",
                    help="skip the on-vs-off byte-identity assertion")
    ap.add_argument("--thread_sanitizer", action="store_true",
                    help="arm the engine's THR01 runtime ownership "
                    "checks on every scheduler-on leg (debug; --smoke "
                    "always runs its own armed leg + seeded-violation "
                    "probe)")
    args = ap.parse_args(argv)
    if args.smoke and (args.weight_quant != "off"
                       or args.kv_cache_dtype != "auto"):
        ap.error("--smoke already runs its own fully quantized int8 "
                 "leg (int8 weights + int8 paged pool, drift bound + "
                 "capacity probe) — drop --weight_quant/"
                 "--kv_cache_dtype, or run a full-matrix quant leg "
                 "without --smoke")
    if args.kv_cache_dtype == "int8" and not args.paged:
        ap.error("--kv_cache_dtype int8 quantizes the block-paged "
                 "pool — add --paged")
    if args.smoke and args.thread_sanitizer:
        ap.error("--smoke already runs its own armed tsan_on leg AND "
                 "needs rows[0] unarmed for the armed-vs-unarmed "
                 "parity/zero-dispatch checks — arming every leg would "
                 "make them vacuous; drop --thread_sanitizer")
    if args.router and args.smoke:
        ap.error("--smoke already runs its own 2-replica router leg — "
                 "drop --router, or run a full-matrix fleet leg "
                 "without --smoke")
    if args.router and (args.weight_quant != "off"
                        or args.kv_cache_dtype != "auto"):
        ap.error("--router compares the fleet leg byte-for-byte "
                 "against the single-replica scheduler-on row, which "
                 "the LOSSY quant legs cannot satisfy — run them "
                 "separately")
    if args.router < 0:
        ap.error(f"--router takes a replica count >= 0, got "
                 f"{args.router}")
    if args.spec_tokens:
        if args.smoke:
            ap.error("--smoke already runs its own spec_on/spec_off "
                     "legs (repetitive workload, accept-rate and "
                     "dispatch-win assertions) — drop --spec_tokens, "
                     "or run a full-matrix spec leg without --smoke")
        if not args.paged:
            ap.error("--spec_tokens exports the verify program over "
                     "the block-paged stepwise pair — add --paged")
        if args.spec_tokens < 2:
            ap.error(f"--spec_tokens must be >= 2 (anchor + at least "
                     f"one draft lane), got {args.spec_tokens}")
    if args.smoke:
        args.clients, args.requests = 2, 2
        args.slots, args.prompt_len, args.max_new = 2, 8, 4
        args.block_size = min(args.block_size, 4)
    quant = args.weight_quant == "int8" or args.kv_cache_dtype == "int8"

    def matrix_for(vocab, prefix_mode):
        return make_requests(args.clients, args.requests,
                             prompt_len=args.prompt_len,
                             max_new=args.max_new, vocab=vocab,
                             seed=args.seed, prefix_mode=prefix_mode,
                             block_size=args.block_size)

    rows = []
    checks = []          # (description, bool) pairs for the summary
    extra_summary = {}   # measured (non-gate) figures for the summary
    with tempfile.TemporaryDirectory() as d:
        # the plain export: the "on" leg when quant is off, and ALWAYS
        # the scheduler-off bf16 oracle (a quant export's monolithic
        # artifact rides int8 weights too, so it cannot be the drift
        # oracle)
        vocab = build_export(d, prompt_len=args.prompt_len,
                             max_new=args.max_new, slots=args.slots,
                             seed=args.seed,
                             paged=args.paged and not quant,
                             block_size=args.block_size,
                             num_blocks=None if quant
                             else args.num_blocks,
                             pool_bytes=None if quant
                             else args.pool_bytes,
                             spec_tokens=(0 if quant
                                          else args.spec_tokens))
        matrix = matrix_for(vocab, args.prefix_mode)
        # the exported dir always holds the monolithic artifact too,
        # so scheduler=off is the oracle for slab AND paged runs
        if quant:
            with tempfile.TemporaryDirectory() as dq:
                build_export(dq, prompt_len=args.prompt_len,
                             max_new=args.max_new, slots=args.slots,
                             seed=args.seed, paged=args.paged,
                             block_size=args.block_size,
                             num_blocks=args.num_blocks,
                             pool_bytes=args.pool_bytes,
                             weight_quant=args.weight_quant,
                             kv_cache_dtype=args.kv_cache_dtype,
                             spec_tokens=args.spec_tokens)
                rows = [run_mode(dq, matrix, scheduler="on",
                                 prompt_len=args.prompt_len,
                                 mode_name="int8_on",
                                 thread_sanitizer=args.thread_sanitizer,
                                 spec_tokens=args.spec_tokens)]
            rows.append(run_mode(d, matrix, scheduler="off",
                                 prompt_len=args.prompt_len))
        else:
            rows = [run_mode(d, matrix, scheduler="on",
                             prompt_len=args.prompt_len,
                             mode_name=("spec_on" if args.spec_tokens
                                        else "paged_on" if args.paged
                                        else "scheduler_on"),
                             thread_sanitizer=args.thread_sanitizer,
                             spec_tokens=args.spec_tokens),
                    run_mode(d, matrix, scheduler="off",
                             prompt_len=args.prompt_len)]
        if args.smoke:
            with tempfile.TemporaryDirectory() as dp:
                # the paged smoke export also carries the chunked-
                # prefill program: paged_cold serves it with the knob
                # OFF (the bitwise-no-op leg), chunked_on with it ON
                build_export(dp, prompt_len=args.prompt_len,
                             max_new=args.max_new, slots=args.slots,
                             seed=args.seed, paged=True,
                             block_size=args.block_size,
                             prefill_chunk=args.block_size,
                             num_blocks=1 + 4 * args.slots
                             * -(-(args.prompt_len + args.max_new)
                                 // args.block_size))
                # the cold leg must be genuinely cold even when the
                # main matrix was built with --prefix_mode shared —
                # and its parity oracle must run the SAME matrix
                if args.prefix_mode == "cold":
                    cold, cold_off_gens = matrix, rows[1]["_gens"]
                else:
                    cold = matrix_for(vocab, "cold")
                    cold_off_gens = run_mode(
                        dp, cold, scheduler="off",
                        prompt_len=args.prompt_len,
                        mode_name="cold_off")["_gens"]
                paged_cold = run_mode(dp, cold, scheduler="on",
                                      prompt_len=args.prompt_len,
                                      mode_name="paged_cold")
                shared = matrix_for(vocab, "shared")
                # trace=True: the smoke run doubles as the scheduler-
                # timeline gate — the captured Perfetto JSON is
                # validated (per-slot prefill/decode spans, request-id
                # correlation) inside run_mode
                paged_shared = run_mode(dp, shared, scheduler="on",
                                        prompt_len=args.prompt_len,
                                        mode_name="paged_shared",
                                        trace=True)
                shared_off = run_mode(dp, shared, scheduler="off",
                                      prompt_len=args.prompt_len,
                                      mode_name="shared_off")
                # chunked-prefill leg (round 18): same cold matrix,
                # chunking ON — byte parity with the scheduler-off
                # oracle, chunk dispatches replacing every cold
                # monolithic prefill
                chunked_on = run_mode(
                    dp, cold, scheduler="on",
                    prompt_len=args.prompt_len,
                    mode_name="chunked_on",
                    server_kw={"prefill_chunk_tokens":
                               args.block_size})
                # overload leg (round 18): 2x offered load against a
                # 4-deep queue — interactive protected, best_effort
                # shed with 429 + Retry-After, shed accounting exact
                overload_row = run_overload(
                    dp, vocab=vocab, seed=args.seed,
                    prompt_len=args.prompt_len,
                    max_new=args.max_new)
                # slo_report leg (round 19): the same overload shape
                # with the history sampler + objectives armed —
                # servetop-computed attainment/goodput must reconcile
                # EXACTLY with the harness ledger and the request-log
                # replay, and the induced best_effort burn must write
                # exactly one rate-limited slo_burn bundle agreeing
                # with live /metrics
                slo_report_row = run_slo_report(
                    dp, vocab=vocab, seed=args.seed,
                    prompt_len=args.prompt_len,
                    max_new=args.max_new)
            # the int8 leg: same cold matrix against a fully quantized
            # export (int8 weights + int8 KV pool) — gated on the
            # documented drift bound vs the bf16 oracle, plus the
            # equal-bytes capacity probe
            with tempfile.TemporaryDirectory() as di:
                total = args.prompt_len + args.max_new
                bps = -(-total // args.block_size)
                build_export(di, prompt_len=args.prompt_len,
                             max_new=args.max_new, slots=args.slots,
                             seed=args.seed, paged=True,
                             block_size=args.block_size,
                             num_blocks=1 + 4 * args.slots * bps,
                             weight_quant="int8",
                             kv_cache_dtype="int8")
                int8_row = run_mode(di, cold, scheduler="on",
                                    prompt_len=args.prompt_len,
                                    mode_name="int8_on")
            agreement = token_agreement(int8_row["_gens"],
                                        cold_off_gens)
            int8_row["int8_agreement"] = round(agreement, 4)
            cap_bf16, cap_int8 = int8_capacity_check(
                prompt_len=args.prompt_len, max_new=args.max_new,
                seed=args.seed, block_size=args.block_size)
            int8_row["capacity_bf16"] = cap_bf16
            int8_row["capacity_int8"] = cap_int8
            # THR01 runtime-sanitizer legs: the ARMED engine must
            # serve the same matrix byte- and dispatch-identically to
            # the plain leg (rows[0] — so the disabled default
            # provably adds/loses zero dispatches), and the seeded
            # cross-thread violation probe must be caught with the
            # field + thread named in the error
            tsan_row = run_mode(d, matrix, scheduler="on",
                                prompt_len=args.prompt_len,
                                mode_name="tsan_on",
                                thread_sanitizer=True)
            tsan_caught, _tsan_msg = thread_sanitizer_check(
                d, matrix[0][0][0])
            tsan_row["tsan_violation_caught"] = tsan_caught
            # chaos_on leg (round 14): the SAME matrix with a one-shot
            # transient decode fault armed through the runtime/faults
            # seams — the engine's bounded re-dispatch must heal it
            # INVISIBLY: byte parity with the fault-disabled leg
            # (rows[0]), identical dispatch counts (the retry repeats
            # an executable call but no scheduler step), exactly one
            # re-dispatch counted, zero failed requests
            from distributed_tensorflow_example_tpu.runtime import \
                faults as _faults
            _faults.install(_faults.parse_spec(
                "engine.decode_step:step=2", seed=args.seed))
            try:
                chaos_row = run_mode(d, matrix, scheduler="on",
                                     prompt_len=args.prompt_len,
                                     mode_name="chaos_on")
            finally:
                _faults.install(None)
            # spec legs (round 16): self-drafting speculative decoding
            # on a REPETITIVE workload (the drafter's food) against a
            # verify-program export — byte parity vs the spec-off
            # oracle over the same export, accept_rate > 0, strictly
            # fewer verify dispatches than emitted tokens, and a real
            # dispatch-count win (the emitted-tokens-per-dispatch > 1
            # acceptance gate). max_new is raised so greedy decode has
            # room to settle into the repetitive fixed points the
            # drafter mines.
            spec_max_new = max(args.max_new, 12)
            spec_k = 4
            with tempfile.TemporaryDirectory() as dsp:
                build_export(dsp, prompt_len=args.prompt_len,
                             max_new=spec_max_new, slots=args.slots,
                             seed=args.seed, paged=True,
                             block_size=args.block_size,
                             num_blocks=1 + 4 * args.slots
                             * -(-(args.prompt_len + spec_max_new)
                                 // args.block_size),
                             spec_tokens=spec_k)
                rep = make_repetitive_requests(
                    args.clients, args.requests,
                    prompt_len=args.prompt_len, max_new=spec_max_new,
                    vocab=vocab, seed=args.seed)
                spec_off_row = run_mode(dsp, rep, scheduler="on",
                                        prompt_len=args.prompt_len,
                                        mode_name="spec_off")
                spec_row = run_mode(dsp, rep, scheduler="on",
                                    prompt_len=args.prompt_len,
                                    mode_name="spec_on",
                                    spec_tokens=spec_k)
            sreg = spec_row["registry"]
            # flightrec_off leg (round 17): rows[0] runs with the
            # flight recorder's always-on ring (the default); turning
            # it OFF must be byte- and dispatch-identical — the ring's
            # cost is observability only — and the tps ratio is
            # reported so a hardware window can baseline the (absence
            # of) overhead
            flightrec_off_row = run_mode(
                d, matrix, scheduler="on", prompt_len=args.prompt_len,
                mode_name="flightrec_off",
                server_kw={"flight_recorder": False})
            # slo_on leg (round 19): the SAME matrix with the history
            # sampler + SLO objectives armed — the sampler is a pure
            # registry reader, so the leg must stay byte- AND
            # dispatch-identical to rows[0] (armed-vs-plain parity,
            # the PR-17 flight-recorder pattern)
            slo_on_row = run_mode(
                d, matrix, scheduler="on", prompt_len=args.prompt_len,
                mode_name="slo_on",
                server_kw={"history_interval_s": 3600.0,
                           "slo_spec": "interactive:hit_rate=0.99"})
            # router leg (round 15): the same matrix through a
            # 2-replica fleet — greedy bytes must not depend on which
            # replica serves (or on the router being in the path)
            router_row = run_router_mode(d, matrix, replicas=2)
            # the decode-stall probe (round 18): long-context A/B,
            # chunked stall bounded at one chunk dispatch
            stall = chunk_stall_probe(seed=args.seed)
            extra_summary["chunk_stall_off_ms"] = \
                stall["off"]["stall_max_ms"]
            extra_summary["chunk_stall_on_ms"] = \
                stall["on"]["stall_max_ms"]
            extra_summary["chunk_stall_p95_off_ms"] = \
                stall["off"]["stall_p95_ms"]
            extra_summary["chunk_stall_p95_on_ms"] = \
                stall["on"]["stall_p95_ms"]
            # wall ratio reported, not gated: the per-dispatch overhead
            # that dominates the tiny CPU probe amortizes away at real
            # model sizes — the hardware window baselines the tps side
            # (BASELINE.md decision-rule pattern, DESIGN.md §21)
            extra_summary["chunk_wall_ratio"] = round(
                stall["on"]["wall_s"] / stall["off"]["wall_s"], 3) \
                if stall["off"]["wall_s"] else None
            rows += [paged_cold, paged_shared, shared_off, chunked_on,
                     overload_row, slo_report_row, int8_row,
                     tsan_row, chaos_row, spec_off_row, spec_row,
                     flightrec_off_row, slo_on_row, router_row]
            # always-on tps / recorder-off tps: ~1.0 expected (the
            # ring's per-span cost is µs against ms-scale dispatches);
            # reported, not gated — CPU smoke noise would make a
            # strict bound flaky, the hardware window baselines it
            extra_summary["flightrec_on_tps_ratio"] = round(
                rows[0]["tokens_per_s"]
                / flightrec_off_row["tokens_per_s"], 3) \
                if flightrec_off_row["tokens_per_s"] else None
            checks += [
                # round-18 gates: chunked prefill is exact and a
                # provable no-op when off; overload degrades by class
                # with honest pushback; the worst-case decode stall
                # under a long-prompt storm is chunk-bounded
                ("chunked_parity_with_off",
                 chunked_on["_gens"] == cold_off_gens),
                ("chunked_prefill_dispatches",
                 chunked_on["registry"].get(
                     "serving_prefill_chunks_total", 0) > 0),
                ("chunk_noop_when_off",
                 paged_cold["registry"].get(
                     "serving_prefill_chunks_total", 0) == 0),
                ("overload_interactive_zero_failures",
                 not overload_row["errors"]
                 and overload_row["interactive_requests"]
                 == overload_row["interactive_expected"]),
                ("overload_interactive_no_deadline_misses",
                 overload_row["deadline_expired"] == 0),
                ("overload_sheds_with_retry_after",
                 overload_row["shed_429"] > 0
                 and overload_row["missing_retry_after"] == 0),
                ("overload_shed_accounting",
                 overload_row["shed_total"]
                 == overload_row["shed_429"] > 0),
                ("overload_recovers_healthy",
                 overload_row["pressure_final"] == "healthy"),
                ("overload_p95_within_deadline",
                 overload_row["latency_p95_ms"]
                 <= overload_row["deadline_ms"]),
                # round-19 gates: the measurement half of the SLO
                # story — exact three-way reconciliation, exactly one
                # rate-limited slo_burn bundle agreeing with the live
                # page, goodput visible and bounded by throughput,
                # and the armed sampler a provable no-op
                ("slo_report_reconciles",
                 slo_report_row["reconciled"]
                 and not slo_report_row["errors"]),
                ("slo_report_interactive_all_served",
                 slo_report_row["interactive_ok"]
                 == slo_report_row["interactive_expected"]),
                ("slo_report_sheds_best_effort",
                 slo_report_row["best_effort_shed"] > 0),
                ("slo_burn_exactly_one_bundle",
                 slo_report_row["burn_bundles"] == 1),
                ("slo_burn_rate_limited",
                 slo_report_row["burn_suppressed"] >= 1),
                ("slo_burn_bundle_matches_metrics",
                 slo_report_row["bundle_matches_metrics"]),
                ("slo_burn_advisory_on_healthz",
                 "best_effort:hit_rate"
                 in slo_report_row["healthz_breaching"]),
                ("slo_goodput_positive_and_bounded",
                 0 < slo_report_row["goodput_tokens"]
                 <= slo_report_row["tokens"]),
                ("slo_on_parity_with_plain",
                 slo_on_row["_gens"] == rows[0]["_gens"]),
                ("slo_on_dispatch_parity",
                 (slo_on_row["decode_steps"], slo_on_row["prefills"])
                 == (rows[0]["decode_steps"], rows[0]["prefills"])),
                ("chunk_stall_parity", stall["parity"]),
                ("chunk_stall_bounded_below_monolithic",
                 stall["on"]["stall_max_ms"]
                 < stall["off"]["stall_max_ms"]),
                ("chunk_stall_p95_drops",
                 stall["on"]["stall_p95_ms"]
                 < stall["off"]["stall_p95_ms"]),
                ("router_parity_with_single_replica",
                 router_row["_gens"] == rows[0]["_gens"]),
                ("router_zero_client_failures",
                 not router_row["errors"]),
                ("router_counts_every_request",
                 router_row["router_requests"]
                 == router_row["requests"]),
                ("router_registry_p95_positive",
                 router_row["fleet_registry_p95_ms"] > 0),
                # round-17 gates: tracing-always-on parity and the
                # bucket audit (no default-registered histogram may
                # saturate its top finite bucket under the smoke load)
                ("flightrec_off_parity_with_on",
                 flightrec_off_row["_gens"] == rows[0]["_gens"]),
                ("flightrec_off_dispatch_parity",
                 (flightrec_off_row["decode_steps"],
                  flightrec_off_row["prefills"])
                 == (rows[0]["decode_steps"], rows[0]["prefills"])),
                ("no_saturated_histograms",
                 not any(r.get("saturated_histograms")
                         for r in [rows[0], paged_cold, paged_shared,
                                   router_row])),
                ("tsan_parity_with_unarmed",
                 tsan_row["_gens"] == rows[0]["_gens"]),
                ("tsan_zero_dispatch_delta",
                 (tsan_row["decode_steps"], tsan_row["prefills"])
                 == (rows[0]["decode_steps"], rows[0]["prefills"])),
                ("tsan_catches_cross_thread", tsan_caught),
                ("paged_vs_slab_parity",
                 paged_cold["_gens"] == cold_off_gens),
                ("shared_vs_cold_admission_parity",
                 paged_shared["_gens"] == shared_off["_gens"]),
                ("shared_prefills_below_cold",
                 paged_shared["prefills"] < paged_cold["prefills"]),
                ("scheduler_trace_valid",
                 paged_shared.get("trace_events", 0) > 0),
                ("int8_drift_within_bound",
                 agreement >= INT8_MIN_AGREEMENT),
                ("int8_admits_more_than_bf16", cap_int8 > cap_bf16),
                ("chaos_parity_with_fault_disabled",
                 chaos_row["_gens"] == rows[0]["_gens"]),
                ("chaos_dispatch_count_parity",
                 (chaos_row["decode_steps"], chaos_row["prefills"])
                 == (rows[0]["decode_steps"], rows[0]["prefills"])),
                ("chaos_exactly_one_redispatch",
                 chaos_row["registry"].get(
                     "serving_redispatches_total") == 1),
                ("chaos_zero_failed_requests",
                 chaos_row["registry"].get(
                     "serving_requests_failed_total") == 0),
                # round-16 spec gates: exactness, a real accept rate,
                # and the dispatch-count win speculation exists for
                ("spec_parity_with_off",
                 spec_row["_gens"] == spec_off_row["_gens"]),
                ("spec_accept_rate_positive",
                 sreg.get("serving_spec_accepted_total", 0) > 0
                 and spec_row.get("accept_rate", 0) > 0),
                ("spec_verify_dispatches_below_emitted_tokens",
                 sreg["serving_verify_steps_total"]
                 < sreg["serving_tokens_out_total"]),
                ("spec_emitted_per_verify_dispatch_above_one",
                 sreg["serving_verify_steps_total"] > 0
                 and sreg["serving_spec_emitted_total"]
                 > sreg["serving_verify_steps_total"]),
                ("spec_total_dispatch_win",
                 sreg["serving_decode_steps_total"]
                 + sreg["serving_verify_steps_total"]
                 < spec_off_row["registry"][
                     "serving_decode_steps_total"]),
                ("spec_off_zero_verify_dispatches",
                 spec_off_row["registry"][
                     "serving_verify_steps_total"] == 0),
            ]
        elif args.router:
            # the full-matrix fleet leg: N replicas, same matrix,
            # byte parity against the single-replica scheduler-on row
            router_row = run_router_mode(d, matrix,
                                         replicas=args.router)
            rows.append(router_row)
            checks += [
                ("router_parity_with_single_replica",
                 router_row["_gens"] == rows[0]["_gens"]),
                ("router_zero_client_failures",
                 not router_row["errors"]),
            ]

    parity = agreement = None
    if quant:
        # int8 vs the bf16 oracle: byte parity is not the contract —
        # the documented token-agreement bound is
        agreement = round(token_agreement(rows[0]["_gens"],
                                          rows[1]["_gens"]), 4)
    elif not args.no_parity:
        parity = rows[0]["_gens"] == rows[1]["_gens"]
    ok = (all(not r["errors"] for r in rows)
          and parity is not False
          and (agreement is None or agreement >= INT8_MIN_AGREEMENT)
          and all(v for _, v in checks))
    for row in rows:
        row.pop("_gens", None)      # the overload row carries none
        print(json.dumps(row))
    on, off = rows[0], rows[1]
    summary = {
        "summary": True,
        "ok": ok,
        "greedy_parity": parity,
        "speedup_tokens_per_s": round(
            on["tokens_per_s"] / off["tokens_per_s"], 3)
        if off["tokens_per_s"] else None,
        "dispatch_ratio": round(
            off["decode_steps"] / on["decode_steps"], 3)
        if on["decode_steps"] else None,
    }
    if agreement is not None:
        summary["int8_agreement"] = agreement
        summary["int8_agreement_bound"] = INT8_MIN_AGREEMENT
    summary.update(extra_summary)
    summary.update({name: v for name, v in checks})
    print(json.dumps(summary))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
