#!/usr/bin/env python
"""GPT-small training-step profile + loss-chunk sweep (VERDICT r4 #5).

GPT-small trains at 34.4% MFU (172.6 ms/step, s512 b32) vs BERT-base's
51.0% at comparable scale, and no profile names the gap's owner. The
candidate suspects: the weight-tied vocab-einsum LM head (+ its
embedding gradient), the chunked-loss recompute (each chunk re-runs the
[B, chunk, V] logits under jax.checkpoint in the backward), and the
causal-attention structure. This script:

  time CHUNK — step time at s512 b32 with lm_loss_chunk=CHUNK
               (0 = full logits: measures what the chunked path costs)
  trace DIR  — jax.profiler capture of the round-4 bench config
               (chunk=512), reduced to PROFILE_r05_gpt.txt via
               utils.trace_summary

Fresh process per cell; one JSON line per cell; findings in BASELINE.md.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BATCH, SEQ = 32, 512


def _build(chunk: int):
    import numpy as np

    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           OptimizerConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
    from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
        SyncReplicas)
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)

    cfg = TrainConfig(model="gpt", dtype="bfloat16",
                      data=DataConfig(batch_size=BATCH, seq_len=SEQ),
                      optimizer=OptimizerConfig(name="adamw",
                                                learning_rate=1e-4),
                      lm_loss_chunk=chunk)
    model = get_model("gpt", cfg)
    mesh = build_mesh()
    sync = SyncReplicas(model.loss, make_optimizer(cfg.optimizer), mesh)
    state = sync.init(model.init, seed=0, prng_impl="rbg")
    rs = np.random.RandomState(0)
    placed = sync.shard_batch({
        "input_ids": rs.randint(0, cfg.data.vocab_size, (BATCH, SEQ),
                                dtype=np.int32),
        "attention_mask": np.ones((BATCH, SEQ), np.int32),
    })
    return sync, state, placed


def timed_cell(chunk: int, *, steps=20, warmup=5) -> dict:
    import jax

    sync, state, placed = _build(chunk)
    compiled = sync.step.lower(state, placed).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    ma = compiled.memory_analysis()
    for _ in range(warmup):
        state, m = compiled(state, placed)
    jax.block_until_ready(state.params)

    def timed():
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = compiled(state, placed)
        jax.block_until_ready(state.params)
        return time.perf_counter() - t0

    dt = max(timed(), timed())
    step_s = dt / steps
    flops = float(ca.get("flops", 0.0))
    return {
        "chunk": chunk, "step_ms": round(step_s * 1e3, 1),
        "eps_chip": round(BATCH / step_s, 1),
        "mfu": round(flops / step_s / 197e12, 4),
        "flops_T": round(flops / 1e12, 3),
        "bytes_GB": round(float(ca.get("bytes accessed", 0.0)) / 1e9, 2),
        "temp_MiB": round(ma.temp_size_in_bytes / 2**20),
    }


def trace(outdir: str, chunk: int = 512) -> dict:
    import jax

    sync, state, placed = _build(chunk)
    compiled = sync.step.lower(state, placed).compile()
    for _ in range(3):
        state, m = compiled(state, placed)
    jax.block_until_ready(state.params)
    jax.profiler.start_trace(outdir)
    for _ in range(5):
        state, m = compiled(state, placed)
    jax.block_until_ready(state.params)
    jax.profiler.stop_trace()
    return {"trace": outdir, "chunk": chunk}


def main() -> None:
    if sys.argv[1:2] == ["--all"]:
        env = dict(os.environ,
                   DTX_JAX_CACHE=os.environ.get("DTX_JAX_CACHE",
                                                "/tmp/dtx_jax_cache"))
        me = os.path.abspath(__file__)
        for c in (512, 0, 128, 256):
            subprocess.run([sys.executable, me, "time", str(c)],
                           env=env, check=False)
        return
    mode, arg = sys.argv[1], sys.argv[2]
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DTX_JAX_CACHE", "/tmp/dtx_jax_cache"))
    try:
        if mode == "time":
            out = timed_cell(int(arg))
        elif mode == "trace":
            out = trace(arg)
        else:
            raise SystemExit(f"unknown mode {mode!r}")
        print(json.dumps(out), flush=True)
    except Exception as e:  # noqa: BLE001 — OOM at compile is a finding
        print(json.dumps({"mode": mode, "arg": arg,
                          "error": f"{type(e).__name__}: {str(e)[:250]}"}),
              flush=True)


if __name__ == "__main__":
    main()
