#!/usr/bin/env python
"""Benchmark: MNIST MLP sync-replica training throughput (examples/sec/chip).

The driver-defined headline metric (BASELINE.json:2). The reference
publishes no numbers (BASELINE.md), so the recorded single-chip measurement
in ``bench_baseline.json`` is the baseline; ``vs_baseline`` is
measured/baseline (>1 is faster than the recorded baseline).

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import os
import sys
import time

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from distributed_tensorflow_example_tpu.config import (  # noqa: E402
    DataConfig, OptimizerConfig, TrainConfig)
from distributed_tensorflow_example_tpu.data.mnist import synthetic_mnist  # noqa: E402
from distributed_tensorflow_example_tpu.models import get_model  # noqa: E402
from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh  # noqa: E402
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (  # noqa: E402
    SyncReplicas)
from distributed_tensorflow_example_tpu.train.optimizers import (  # noqa: E402
    make_optimizer)

BATCH = 8192
WARMUP = 10
STEPS = 100


def main() -> None:
    n_dev = len(jax.devices())
    mesh = build_mesh()          # all devices on the data axis
    cfg = TrainConfig(model="mlp", dtype="bfloat16",
                      data=DataConfig(batch_size=BATCH),
                      optimizer=OptimizerConfig(name="sgd", learning_rate=0.5))
    model = get_model("mlp", cfg)
    tx = make_optimizer(cfg.optimizer)
    sync = SyncReplicas(model.loss, tx, mesh)
    state = sync.init(model.init, seed=0)

    data = synthetic_mnist(num_train=BATCH * 2, num_test=16)
    batches = [
        sync.shard_batch({"x": data["train_x"][i * BATCH:(i + 1) * BATCH],
                          "y": data["train_y"][i * BATCH:(i + 1) * BATCH]})
        for i in range(2)
    ]

    for i in range(WARMUP):
        state, m = sync.step(state, batches[i % 2])
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for i in range(STEPS):
        state, m = sync.step(state, batches[i % 2])
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    eps_chip = STEPS * BATCH / dt / n_dev

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "bench_baseline.json")
    vs = 1.0
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f).get("examples_per_sec_per_chip")
        if base:
            vs = eps_chip / base

    print(json.dumps({
        "metric": "mnist_mlp_examples_per_sec_per_chip",
        "value": round(eps_chip, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
