#!/usr/bin/env python
"""Benchmark suite: sync-replica training throughput on the driver metric.

The driver-defined headline metric (BASELINE.json:2) is examples/sec/chip
on MNIST + ResNet-50; this suite measures nine workloads on whatever
devices are present (the driver runs it on one real TPU chip):

- ``mnist_mlp``   — the reference-parity workload (BASELINE.json:7)
- ``resnet50``    — ImageNet shapes, bf16, synthetic data (BASELINE.json:10)
- ``bert_base``   — MLM step time, seq 128 (BASELINE.json:11)
- ``moe_bert``    — expert-parallel flagship, 8 experts top-1, b64
- ``bert_large``  — the big dense model, b64
- ``bert_long``   — composed long context: S=4096 flash, b4 (remat=none
  since the round-5 sweep — BASELINE.md "Round-5 remat sweep")
- ``gpt_small``   — causal-LM train, s512 b32, fused blockwise LM loss
  (``lm_loss_impl="fused"`` since round 7 — BASELINE.md "Vocab chain")
- ``gpt_long``    — causal long context: S=4096 causal flash + fused
  LM loss, b4 (fused replaced ``lm_loss_chunk=512`` in round 7: no
  [B,S,V] logits AND no seq-chunk recompute; queued-dispatch
  methodology like bert_long)
- ``gpt_decode``  — KV-cache greedy decode, b8 prompt 128 + 128 new;
  tokens/s/chip via the one-dispatch compiled generation, riding the
  stacked-scan decode fast path (models/gpt.py decode_impl="stacked":
  lax.scan over restacked layer params, fused QKV, single-query Pallas
  cache attention on TPU); timed as the median of >=5 repeats
  (median_repeats) so the row's spread is published and < ±2%

Eight are training throughput, one is decode; a regression in ANY of
the nine moves ``vs_baseline``.

For each, an MFU estimate = step FLOPs / measured step time / chip peak
(bf16) is recorded, with its basis published per row as
``{key}_mfu_basis``: ``"cost_analysis"`` = XLA-reported FLOPs for the
compiled step; ``"analytic"`` = cost-analysis FLOPs PLUS the closed-form
flash-attention FLOPs XLA cannot see inside the Pallas custom call
(flash_attention.attention_train_flops) — so the bert_long/gpt_long MFU
rows are comparable to the seq-128 rows (VERDICT r5 weak #1). The same
augmented number feeds robust_time's physical-impossibility check. The
reference publishes no numbers (BASELINE.md), so ``bench_baseline.json``
holds this repo's own first measurements; ``vs_baseline`` is
measured/baseline of the headline metric (>1 is faster).

Every training row also publishes ``{key}_peak_mib`` (XLA memory-
analysis peak for the compiled step, when the backend reports it) so
memory levers — the fused LM loss killing the [B,S,V] logits
residency, remat, storage dtypes — are regression-visible columns, not
folklore — and ``{key}_anomaly_count`` (the on-device non-finite-step
counter carried in TrainState), so a "fast but silently skipping
steps" regression is a visible nonzero column, not a quiet throughput
win.

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}
"""

import json
import os
import sys
import time

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from distributed_tensorflow_example_tpu.config import (  # noqa: E402
    DataConfig, OptimizerConfig, TrainConfig)
from distributed_tensorflow_example_tpu.data.mnist import synthetic_mnist  # noqa: E402
from distributed_tensorflow_example_tpu.models import get_model  # noqa: E402
from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh  # noqa: E402
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (  # noqa: E402
    SyncReplicas)
from distributed_tensorflow_example_tpu.train.optimizers import (  # noqa: E402
    make_optimizer)

# chip peak bf16 FLOP/s by device_kind substring (public TPU specs)
_PEAK_BF16 = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def _chip_peak() -> float | None:
    d = jax.devices()[0]
    if d.platform != "tpu":
        return None
    kind = d.device_kind.lower()
    for key, peak in _PEAK_BF16.items():
        if key in kind:
            return peak
    return None


def _peak_mib(compiled) -> float | None:
    """XLA memory-analysis peak for one compiled step, in MiB (None when
    the backend doesn't report it — CPU builds often return 0). The
    published ``{key}_peak_mib`` column is what makes memory levers
    (``--lm_loss_impl fused`` killing the [B,S,V] logits residency,
    remat, bf16 storage) regression-visible, not folklore."""
    try:
        ma = compiled.memory_analysis()
        if isinstance(ma, (list, tuple)):
            ma = ma[0]
        peak = getattr(ma, "peak_memory_in_bytes", 0)
        return peak / 2**20 if peak else None
    except Exception:
        return None


def _step_flops(compiled) -> float | None:
    """XLA cost-analysis FLOPs for one compiled step (None if unavailable).

    Pallas custom calls are opaque to the cost analysis (their FLOPs count
    as zero) — flash workloads add ``_flash_step_flops`` on top.
    """
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        f = ca.get("flops")
        return float(f) if f and f > 0 else None
    except Exception:
        return None


def _flash_step_flops(cfg, model, model_name: str, batch: int,
                      host_batch: dict | None) -> float | None:
    """Closed-form attention FLOPs for one train step when (and only
    when) the Pallas flash kernel actually engages — the piece XLA's
    cost analysis cannot see. None for xla-attention configs and for
    shapes where flash falls back to XLA (the fallback's einsums ARE
    counted by the cost analysis; adding the analytic number there would
    double-count)."""
    if cfg.attention_impl != "flash" or not host_batch:
        return None
    ids = host_batch.get("input_ids")
    if ids is None:
        return None
    from distributed_tensorflow_example_tpu.config import (
        flash_attention_kwargs)
    from distributed_tensorflow_example_tpu.ops.pallas.flash_attention \
        import attention_train_flops, effective_bwd_variant, kernel_engages
    fkw = flash_attention_kwargs(cfg)
    mc = model.cfg
    seq = int(ids.shape[1])
    head_dim = mc.hidden // mc.heads
    blocks = {k: fkw[k] for k in ("block_q", "block_k", "bwd_block")
              if k in fkw}
    if not kernel_engages(seq, head_dim, **blocks):
        return None
    return attention_train_flops(
        batch, seq, mc.hidden, mc.layers,
        causal=model_name.startswith("gpt"),
        # count what EXECUTES: fused silently degrades to split past
        # the VMEM slab limit
        bwd_variant=effective_bwd_variant(
            seq, head_dim, fkw.get("bwd_variant", "split")))


def robust_time(timed_pass, *, steps: int, flops=None, peak=None,
                n_dev: int = 1) -> tuple[float, bool]:
    """Artifact-resistant wall-time of ``timed_pass`` (seconds, suspect).

    The axon tunnel occasionally returns from block_until_ready without
    the work having run (observed: BERT 'completing' at 21x MFU inside a
    long-lived multi-workload process). The artifact is always absurdly
    FAST, so: take the slower of two passes, and retry while the result
    is physically impossible (> 95% of peak when flops are known) or the
    two passes disagree wildly (> 3x — the fallback check for devices/
    workloads without a flops estimate). The slight upward bias of
    max-of-two is accepted: a conservative gate beats a corrupted one.
    ``suspect=True`` flags a measurement that stayed impossible after
    every retry — callers must surface it, not publish it as real.
    """
    dt = bad = 0.0
    for attempt in range(3):
        a, b = timed_pass(), timed_pass()
        dt = max(a, b)
        mfu_est = (flops / (dt / steps) / (peak * n_dev)
                   if (flops and peak) else None)
        impossible = (mfu_est is not None and mfu_est > 0.95)
        wild = min(a, b) > 0 and (max(a, b) / min(a, b)) > 3.0
        bad = impossible or wild
        if not bad:
            break
    return dt, bool(bad)


def median_repeats(timed_single, *, reps: int, floor_s: float | None = None,
                   retries: int = 3) -> tuple[float, float, bool]:
    """Median-of-repeats timing for the decode gate row (seconds).

    The decode wall-clock carries ~100 ms/call of tunnel overhead
    (~50% of the measurement — BASELINE.md decode roofline), so a
    max-of-two estimate let tunnel jitter move the gate row ±5%
    (VERDICT r5 weak #4). ``timed_single`` times ONE generation; this
    takes the MEDIAN of ``reps`` such timings — robust to both the
    absurdly-fast tunnel artifact (a corrupt low outlier cannot become
    the median while most repeats are honest) and slow dispatch
    hiccups. Retries the whole sample while the median sits below
    ``floor_s`` (the physically-impossible bound, e.g. half the
    weight-traffic floor); ``suspect=True`` if it never recovers.

    Returns ``(median_s, spread, suspect)`` where ``spread`` is the
    max relative deviation of any repeat from the median — the
    publishable ±noise figure the gate row's < ±2% target is judged
    by.
    """
    if reps < 1:
        raise ValueError(f"reps must be >= 1, got {reps}")
    med = spread = 0.0
    suspect = False
    for attempt in range(retries):
        ts = sorted(timed_single() for _ in range(reps))
        med = ts[(len(ts) - 1) // 2]
        spread = max(abs(t - med) for t in ts) / med if med > 0 else 0.0
        suspect = floor_s is not None and med < floor_s
        if not suspect:
            break
    return med, spread, suspect


def decode_device_component(short_s: float, long_s: float,
                            new_short: int, new_long: int,
                            ) -> tuple[float, float]:
    """Two-point fit splitting a generation's wall-clock into per-token
    device time and per-call overhead (both ms).

    Each decode CALL pays ~100 ms of host/tunnel overhead around the
    device steady state (measured: gen_ms ≈ 99 + 0.84·new, BASELINE.md
    decode roofline) — ~50% of the b8 prompt128+new128 gate row's
    wall-clock, so tunnel jitter could move that row ±5% with zero repo
    change (VERDICT r5 weak #4). Timing the SAME program at two
    generation lengths cancels the per-call constant: the slope
    ``(long - short) / (new_long - new_short)`` is the per-token-step
    device component (tunnel jitter hits both medians once each, not
    per token), and the intercept is the published overhead estimate.
    """
    if new_long <= new_short:
        raise ValueError(f"need new_long > new_short, got "
                         f"{new_long} <= {new_short}")
    slope_ms = (long_s - short_s) / (new_long - new_short) * 1e3
    overhead_ms = short_s * 1e3 - slope_ms * new_short
    return slope_ms, overhead_ms


def _run(model_name: str, *, batch: int, steps: int, warmup: int,
         opt: OptimizerConfig, make_batch, extra_cfg: dict | None = None,
         cfg_over: dict | None = None,
         steps_per_call: int = 1, prng_impl: str | None = None):
    """Time `steps` sync steps; returns (examples/sec/chip, step_ms, mfu,
    mfu_basis, peak_mib, suspect, anomaly_count) — ``peak_mib`` is the
    compiled step's XLA memory-analysis peak (None when unreported),
    ``suspect`` marks a measurement robust_time could not de-corrupt
    (callers surface it, never publish it as real), and
    ``anomaly_count`` is the run's cumulative non-finite-step count from
    the on-device detector.

    ``steps_per_call > 1`` uses the device-side multi-step loop
    (iterations_per_loop) — essential for latency-bound microbenchmarks
    (MNIST MLP) where per-step host dispatch would dominate the
    measurement; compute-bound workloads pipeline fine without it.
    """
    n_dev = len(jax.devices())
    mesh = build_mesh()          # all devices on the data axis
    cfg = TrainConfig(model=model_name, dtype="bfloat16",
                      data=DataConfig(batch_size=batch,
                                      **(extra_cfg or {})),
                      optimizer=opt, **(cfg_over or {}))
    model = get_model(model_name, cfg)
    tx = make_optimizer(cfg.optimizer)
    sync = SyncReplicas(model.loss, tx, mesh)
    state = sync.init(model.init, seed=0, prng_impl=prng_impl)

    k = steps_per_call
    if k > 1:
        host = [make_batch(model, batch, i) for i in range(k)]
        stacked = {key: np.stack([b[key] for b in host]) for key in host[0]}
        placed = sync.shard_stacked_batch(stacked)
        step_fn, n_calls = sync.multi_step, max(1, steps // k)
        steps = n_calls * k
    else:
        host = [make_batch(model, batch, i) for i in range(2)]
        placed2 = [sync.shard_batch(b) for b in host]
        placed = placed2[0]
        step_fn, n_calls = sync.step, steps

    # the AOT-compiled executable is reused for the run itself: lower/
    # compile does not populate the jit dispatch cache, so calling step_fn
    # afterwards would compile the same program a second time
    compiled = step_fn.lower(state, placed).compile()
    peak_mib = _peak_mib(compiled)
    flops = _step_flops(compiled)
    if flops and k > 1:
        flops /= k               # cost_analysis covers the whole K-step scan
    # flash configs: add the in-kernel attention FLOPs the cost analysis
    # cannot see, and say so in the published basis
    attn_flops = _flash_step_flops(cfg, model, model_name, batch, host[0])
    if flops and attn_flops:
        flops += attn_flops
    mfu_basis = "analytic" if (flops and attn_flops) else "cost_analysis"

    for i in range(max(1, warmup // k)):
        state, m = compiled(state, placed if k > 1 else placed2[i % 2])
    jax.block_until_ready(state.params)

    def timed_pass():
        nonlocal state
        t0 = time.perf_counter()
        for i in range(n_calls):
            state, m = compiled(state,
                                placed if k > 1 else placed2[i % 2])
        jax.block_until_ready(state.params)
        return time.perf_counter() - t0

    peak = _chip_peak()
    dt, suspect = robust_time(timed_pass, steps=steps, flops=flops,
                              peak=peak, n_dev=n_dev)
    step_s = dt / steps
    eps_chip = batch / step_s / n_dev
    mfu = (flops / step_s / (peak * n_dev)) if (flops and peak) else None
    # cumulative non-finite-step count from the on-device anomaly
    # detector: a "fast but silently skipping steps" regression shows up
    # as a nonzero column in the gate, not as a quiet throughput win
    anomalies = int(jax.device_get(state.anomaly_count))
    return (eps_chip, step_s * 1e3, mfu, mfu_basis, peak_mib, suspect,
            anomalies)


def _mnist_batch(model, batch, i):
    data = synthetic_mnist(num_train=batch, num_test=16, seed=i)
    return {"x": data["train_x"], "y": data["train_y"]}


def _dummy_batch(model, batch, i):
    return model.dummy_batch(batch)


def _gpt_batch_at(seq: int):
    """Causal-LM batch maker at a fixed sequence length (dummy_batch
    caps at 128, and the model's max_len can exceed the workload's seq
    — gpt keeps max_len >= 1024)."""
    def make(model, batch, i):
        s = min(seq, model.cfg.max_len)
        rs = np.random.RandomState(i)
        return {
            "input_ids": rs.randint(0, model.cfg.vocab_size, (batch, s),
                                    dtype=np.int32),
            "attention_mask": np.ones((batch, s), np.int32),
        }
    return make


def _run_decode(*, batch: int, prompt: int, max_new: int, reps: int,
                warmup: int, tiny: bool, gen_kwargs: dict | None = None,
                amortize_new: int | None = None):
    """tokens/s/chip for the compiled KV-cache generation (the stacked
    fast path by default; ``gen_kwargs`` overrides decode_impl /
    decode_attention / tokens_per_dispatch / weight_quant for the
    lever sweep in experiments/decode_roofline.py). The whole
    generation is ONE dispatch on ONE device, each repeat synchronously
    drained via device_get (see the timing note below). The published
    number is the MEDIAN of ``reps`` per-generation timings after
    warmup (median_repeats — the de-noised gate methodology; spread is
    the row's published ±noise).

    ``amortize_new``: additionally time the same program at this longer
    generation length and publish the two-point DEVICE component
    (``decode_device_component``) — the tunnel-jitter-immune number the
    gate row regresses on once baselined. Returns a dict of row fields.
    """
    import functools

    from distributed_tensorflow_example_tpu.config import (DataConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.models.base import cast_floating
    import jax.numpy as jnp

    name = "gpt_tiny" if tiny else "gpt"
    cfg = TrainConfig(model=name, dtype="bfloat16",
                      param_dtype="bfloat16",
                      data=DataConfig(batch_size=batch))
    model = get_model(name, cfg)
    params = cast_floating(model.init(jax.random.key(0)), jnp.bfloat16)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, model.cfg.vocab_size, (batch, prompt),
                                 dtype=np.int32))
    gen = jax.jit(functools.partial(model.generate,
                                    max_new_tokens=max_new,
                                    **(gen_kwargs or {})))
    # time via device_get of the tokens, NOT block_until_ready: through
    # the axon tunnel block_until_ready returns in ~0.1 ms for this
    # program without the work having run (measured round 5 — every
    # queued/blocked variant read 100-1000x faster than the weight-
    # traffic bound), while the host transfer cannot complete before
    # the computation has. The [B, max_new] int32 transfer is ~4 KB —
    # negligible against a ~10^2 ms generation.
    np.asarray(gen(params, ids))
    for _ in range(warmup):
        np.asarray(gen(params, ids))

    # physical floor: one bf16 read of every param per token-step at
    # the v5e's 819 GB/s. Readings below half of it are corrupt.
    n_param = sum(int(p.size)
                  for p in jax.tree_util.tree_leaves(params))
    bound_ms = n_param * 2 / 819e9 * 1e3
    if (gen_kwargs or {}).get("weight_quant") == "int8":
        # int8 weights halve the per-token read, so the corruption
        # floor halves with it — a legit int8 reading near ITS bound
        # must not be flagged suspect against the bf16 one
        bound_ms /= 2
    on_tpu = jax.devices()[0].platform == "tpu"

    def timed_single():
        t0 = time.perf_counter()
        np.asarray(gen(params, ids))
        return time.perf_counter() - t0

    per_gen, spread, suspect = median_repeats(
        timed_single, reps=reps,
        # off-TPU the bf16 weight bound is meaningless (no 819 GB/s HBM)
        floor_s=(bound_ms * 0.5 * max_new / 1e3) if on_tpu else None)
    # per-chip = the whole number: the generation is a single-device
    # jit (no mesh), so dividing by the host's visible device count
    # would under-report on any multi-device host
    row = {
        "tokens_s_chip": batch * max_new / per_gen,
        "token_step_ms": per_gen / max_new * 1e3,
        "weight_bound_ms": bound_ms,
        "spread": spread,
        "suspect": suspect,
    }
    if amortize_new is not None:
        gen_long = jax.jit(functools.partial(
            model.generate, max_new_tokens=amortize_new,
            **(gen_kwargs or {})))
        np.asarray(gen_long(params, ids))          # compile
        for _ in range(warmup):
            np.asarray(gen_long(params, ids))

        def timed_long():
            t0 = time.perf_counter()
            np.asarray(gen_long(params, ids))
            return time.perf_counter() - t0

        per_long, spread_long, suspect_long = median_repeats(
            timed_long, reps=reps,
            floor_s=(bound_ms * 0.5 * amortize_new / 1e3)
            if on_tpu else None)
        dev_ms, overhead_ms = decode_device_component(
            per_gen, per_long, max_new, amortize_new)
        # a non-positive slope (longer generation measured FASTER) is
        # physically impossible — a corrupt leg slipped past the floor
        # check; flag it so the gate excludes the row
        row.update(device_token_ms=dev_ms, call_overhead_ms=overhead_ms,
                   long_spread=spread_long,
                   suspect=suspect or suspect_long or dev_ms <= 0)
    return row


def _run_serving(*, clients: int, requests: int, prompt_len: int,
                 max_new: int, slots: int, tiny: bool) -> dict:
    """The continuous-batching serving row: closed-loop clients against
    the in-process REST server with the scheduler ON (the
    experiments/serving_load.py harness). Published as
    ``{key}_serving_tps`` / ``{key}_serving_p95_ms`` so the next TPU
    window baselines the serving path, plus the dispatch counters the
    continuous-batching invariant is judged by (decode dispatches ~
    max per-request length per wave, not the per-request sum).

    Round 12 adds the fully quantized leg (int8 decode weights + int8
    paged KV pool): ``serving_int8_tps``, ``serving_int8_drift_rate``
    (token drift vs the bf16 leg on the SAME seeded matrix — the
    ROADMAP item-1 quality gate's observable), and per-dtype
    ``bytes_resident_peak`` so the ~2x-capacity-at-fixed-HBM claim is
    a baselined column, not folklore."""
    import tempfile

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "experiments"))
    import serving_load

    on_tpu = jax.devices()[0].platform == "tpu"
    platforms = ("cpu", "tpu") if on_tpu else ("cpu",)
    model_name = "gpt_tiny" if tiny else "gpt"
    # the shared-prefix workload needs sys_len (a block multiple) < the
    # prompt capacity WITH suffix room — a block of prompt_len/4 keeps
    # that true for any prompt_len >= 8 (16 at the CPU config would
    # leave no suffix room and make_requests rejects it loudly)
    block_size = 16 if prompt_len >= 32 else max(2, prompt_len // 4)
    with tempfile.TemporaryDirectory() as d:
        vocab = serving_load.build_export(
            d, prompt_len=prompt_len, max_new=max_new, slots=slots,
            model_name=model_name, platforms=platforms)
        matrix = serving_load.make_requests(
            clients, requests, prompt_len=prompt_len, max_new=max_new,
            vocab=vocab, seed=0)
        row = serving_load.run_mode(d, matrix, scheduler="on",
                                    prompt_len=prompt_len)
    # paged + shared-prefix leg (round 10): same closed-loop matrix
    # shape but every prompt opens with one seeded system prefix — the
    # prefix-cache hit rate the next TPU window baselines
    with tempfile.TemporaryDirectory() as d:
        serving_load.build_export(
            d, prompt_len=prompt_len, max_new=max_new, slots=slots,
            model_name=model_name, platforms=platforms, paged=True,
            block_size=block_size)
        shared = serving_load.make_requests(
            clients, requests, prompt_len=prompt_len, max_new=max_new,
            vocab=vocab, seed=0, prefix_mode="shared",
            block_size=block_size)
        prow = serving_load.run_mode(d, shared, scheduler="on",
                                     prompt_len=prompt_len,
                                     mode_name="paged_shared")
    # quantized leg (round 12): int8 decode weights + int8 paged KV
    # pool against the SAME shared matrix — drift is measured against
    # the bf16 paged leg's token streams (identical seeds), and the
    # per-dtype residency peaks make the capacity doubling a column
    with tempfile.TemporaryDirectory() as d:
        serving_load.build_export(
            d, prompt_len=prompt_len, max_new=max_new, slots=slots,
            model_name=model_name, platforms=platforms, paged=True,
            block_size=block_size, weight_quant="int8",
            kv_cache_dtype="int8")
        irow = serving_load.run_mode(d, shared, scheduler="on",
                                     prompt_len=prompt_len,
                                     mode_name="int8_shared")
    # speculative leg (round 16): the repetitive workload the
    # self-drafter mines, against a verify-program export —
    # `{key}_serving_spec_tps` / `{key}_serving_accept_rate` are the
    # next TPU window's baselines for the ROADMAP item-1 verdict
    # (tokens-per-dispatch uplift at the measured accept rate)
    with tempfile.TemporaryDirectory() as d:
        serving_load.build_export(
            d, prompt_len=prompt_len, max_new=max_new, slots=slots,
            model_name=model_name, platforms=platforms, paged=True,
            block_size=block_size, spec_tokens=4)
        rep = serving_load.make_repetitive_requests(
            clients, requests, prompt_len=prompt_len, max_new=max_new,
            vocab=vocab, seed=0)
        srow = serving_load.run_mode(d, rep, scheduler="on",
                                     prompt_len=prompt_len,
                                     mode_name="spec_on",
                                     spec_tokens=4)
    # fleet router leg (round 17): the same closed-loop matrix through
    # a 2-replica in-process fleet — `{key}_router_p95_ms` /
    # `{key}_router_failover_total` / `{key}_router_hedge_win_rate`
    # open the serving-fleet trajectory (BENCH had no fleet keys), all
    # sourced from the MERGED registry, not client stopwatches
    with tempfile.TemporaryDirectory() as d:
        serving_load.build_export(
            d, prompt_len=prompt_len, max_new=max_new, slots=slots,
            model_name=model_name, platforms=platforms)
        rrow = serving_load.run_router_mode(d, matrix, replicas=2,
                                            hedge_after_ms=200)
    # counters come from the registry snapshot each run_mode captured
    # (the /metrics exposition = the same atomic snapshot /stats
    # renders) — not re-derived from response bookkeeping, so the
    # bench row can never drift from what the server itself reports
    reg, preg = row["registry"], prow["registry"]
    ireg = irow["registry"]
    decode_steps = int(reg["serving_decode_steps_total"])
    slot_steps = int(reg["serving_decode_slot_steps_total"])
    admissions = int(preg["serving_admissions_total"])
    hits = int(preg.get("serving_prefix_cache_hits_total", 0))
    out = {
        "serving_tps": row["tokens_per_s"],
        "serving_p95_ms": row["latency_p95_ms"],
        "serving_decode_steps": decode_steps,
        "serving_steps_shared": round(slot_steps / decode_steps, 3)
        if decode_steps else 0.0,
        "serving_errors": len(row["errors"]),
        "serving_paged_tps": prow["tokens_per_s"],
        "serving_prefix_hit_rate": round(hits / admissions, 3)
        if admissions else 0.0,
        "serving_prefill_tokens_saved": int(
            preg["serving_prefill_tokens_saved_total"]),
        "serving_paged_errors": len(prow["errors"]),
        "serving_int8_tps": irow["tokens_per_s"],
        "serving_int8_drift_rate": round(
            1.0 - serving_load.token_agreement(irow["_gens"],
                                               prow["_gens"]), 4),
        "serving_int8_errors": len(irow["errors"]),
        # round-19 SLO columns: goodput (deadline-met tokens/s —
        # distinct from raw serving_tps; equal on this deadline-less
        # matrix, divergent the moment a deadline workload sheds or
        # expires) and attainment, both sourced from the registry's
        # serving_slo_*/goodput counters, never client bookkeeping
        "serving_goodput_tps": round(
            row["tokens_per_s"]
            * int(reg.get("serving_goodput_tokens_total", 0))
            / int(reg["serving_tokens_out_total"]), 2)
        if int(reg.get("serving_tokens_out_total", 0)) else 0.0,
        "serving_slo_attainment": round(
            int(reg.get("serving_slo_good_total", 0))
            / int(reg["serving_slo_served_total"]), 4)
        if int(reg.get("serving_slo_served_total", 0)) else 0.0,
        "serving_slo_attainment_interactive": round(
            int(reg.get("serving_slo_good_interactive_total", 0))
            / int(reg["serving_slo_served_interactive_total"]), 4)
        if int(reg.get("serving_slo_served_interactive_total", 0))
        else 0.0,
        "serving_bytes_resident_peak": int(
            preg.get("serving_bytes_resident_peak", 0)),
        "serving_int8_bytes_resident_peak": int(
            ireg.get("serving_bytes_resident_peak", 0)),
        # round-16 speculative columns: throughput on the repetitive
        # workload, the measured accept rate, and the dispatch economy
        # (emitted tokens per dispatch — > 1.0 is the whole point)
        "serving_spec_tps": srow["tokens_per_s"],
        "serving_accept_rate": float(
            srow["registry"].get("serving_spec_accept_rate", 0.0)),
        "serving_spec_errors": len(srow["errors"]),
        "serving_spec_tokens_per_dispatch": round(
            int(srow["registry"]["serving_tokens_out_total"])
            / max(1, int(srow["registry"]["serving_decode_steps_total"])
                  + int(srow["registry"]["serving_verify_steps_total"])
                  + int(srow["registry"]["serving_prefills_total"])), 3),
        # round-17 fleet columns: the router trajectory the next TPU
        # window baselines (ROADMAP items 2/3 name these as their
        # proof surface)
        "router_tps": rrow["tokens_per_s"],
        "router_p95_ms": rrow["fleet_registry_p95_ms"],
        "router_failover_total": rrow["router_failovers"],
        "router_hedge_win_rate": round(
            rrow["router_hedge_wins"] / rrow["router_hedges"], 3)
        if rrow["router_hedges"] else 0.0,
        "router_errors": len(rrow["errors"]),
    }
    # per-request latency breakdown (queue vs prefill vs decode) from
    # the request-scoped `timings` field — the p95 gate's diagnosis
    # companion: when p95 moves, this row says WHICH phase moved
    for phase, pct in row.get("breakdown_ms", {}).items():
        out[f"serving_{phase}_p95_ms"] = pct["p95"]
    return out


def _long_batch(model, batch, i):
    """BERT batch at the model's FULL configured sequence length
    (dummy_batch caps at 128 for the seq-128 workloads)."""
    c = model.cfg
    s = c.max_len
    m = c.max_predictions
    rs = np.random.RandomState(i)
    return {
        "input_ids": rs.randint(0, c.vocab_size, (batch, s),
                                dtype=np.int32),
        "token_type_ids": np.zeros((batch, s), np.int32),
        "attention_mask": np.ones((batch, s), np.int32),
        "masked_positions": np.tile(np.arange(m, dtype=np.int32),
                                    (batch, 1)),
        "masked_labels": rs.randint(0, c.vocab_size, (batch, m),
                                    dtype=np.int32),
        "masked_weights": np.ones((batch, m), np.float32),
    }


def _workloads(on_tpu: bool, scale: int) -> "list[dict]":
    """The gate workload table. ``only``: BENCH_ONLY aliases; ``key``:
    the extra/baseline prefix. Off-TPU, transformer workloads swap in
    tiny model variants (sanity only, numbers meaningless).

    Config notes that earned their place:
    - mnist: 1000 steps = 50 measured dispatches — 10 dispatches left
      the number at the mercy of axon-tunnel latency jitter (observed
      12.8M-15.0M swings; BASELINE.md "discrepancy" note).
    - bert @ b128: the v5e sweet spot (mfu 0.382 @ 64 -> 0.410 @ 128 ->
      0.383 @ 256 measured r3); rbg = TPU-native RNG (dropout masks
      dominate threefry's cost: 112.4 -> 89.1 ms/step measured).
    - moe_bert/bert_large @ b64: the measured sweet spots (BASELINE.md).
    - bert_long: the composed long-context capability (flash +
      remat=none @ S=4096 b4 — the regime the plain XLA path cannot
      reach); its MFU adds the closed-form flash-kernel FLOPs
      (mfu_basis="analytic") and is comparable to the seq-128 rows.
    - gpt_decode: the gate ratio moves to the two-point DEVICE
      component (device_token_ms) as soon as a baseline for it exists —
      wall-clock tokens/s keeps ~100 ms/call of tunnel overhead in the
      denominator (~50% of the measurement) and its jitter was the gate
      row's dominant noise (VERDICT r5 weak #4).
    """
    adamw = OptimizerConfig(name="adamw", learning_rate=1e-4)
    rbg = "rbg" if on_tpu else None
    return [
        dict(key="mnist_mlp", only={"mnist"}, model="mlp", batch=8192,
             steps=1000 if on_tpu else 10, warmup=100 if on_tpu else 2,
             opt=OptimizerConfig(name="sgd", learning_rate=0.5),
             make_batch=_mnist_batch,
             steps_per_call=20 if on_tpu else 5, ms_digits=3),
        dict(key="resnet50", only={"resnet50"}, model="resnet50",
             batch=max(8, 128 // scale), steps=30 if on_tpu else 3,
             warmup=5 if on_tpu else 1,
             opt=OptimizerConfig(name="momentum", learning_rate=0.1),
             make_batch=_dummy_batch),
        dict(key="bert_base", only={"bert"}, model="bert",
             batch=max(8, 128 // scale), steps=20 if on_tpu else 2,
             warmup=5 if on_tpu else 1, opt=adamw,
             make_batch=_dummy_batch, prng_impl=rbg),
        dict(key="moe_bert", only={"moe", "moe_bert"},
             model="moe_bert" if on_tpu else "moe_bert_tiny",
             batch=max(8, 64 // scale), steps=20 if on_tpu else 2,
             warmup=5 if on_tpu else 1, opt=adamw,
             make_batch=_dummy_batch, prng_impl=rbg),
        dict(key="bert_large", only={"bert_large"},
             model="bert_large" if on_tpu else "bert_tiny",
             batch=max(8, 64 // scale), steps=20 if on_tpu else 2,
             warmup=5 if on_tpu else 1, opt=adamw,
             make_batch=_dummy_batch, prng_impl=rbg),
        dict(key="bert_long", only={"bert_long"},
             model="bert" if on_tpu else "bert_tiny",
             batch=4 if on_tpu else 2, steps=8 if on_tpu else 1,
             warmup=2 if on_tpu else 1, opt=adamw,
             make_batch=_long_batch,
             extra_cfg={"seq_len": 4096 if on_tpu else 256},
             # remat=none since round 5: 36% faster at this shape and
             # fits in ~8.4 GiB of 16 (BASELINE.md "Round-5 remat
             # sweep"; baseline re-based with a methodology note).
             # lm_loss_impl=fused since round 7 (BASELINE.md "Vocab
             # chain"): the MLM head rides the blockwise core — a
             # composition row at M=80 positions, not a win
             cfg_over={"attention_impl": "flash", "remat": "none",
                       "lm_loss_impl": "fused"},
             prng_impl=rbg, eps_digits=2),
        dict(key="gpt_small", only={"gpt", "gpt_small"},
             model="gpt" if on_tpu else "gpt_tiny",
             batch=max(8, 32 // scale), steps=20 if on_tpu else 2,
             warmup=5 if on_tpu else 1, opt=adamw,
             make_batch=_gpt_batch_at(512 if on_tpu else 128),
             # fused LM loss since round 7: the ~21 ms/step vocab chain
             # (logits fwd/bwd + tied-embed grad + softmax reductions +
             # accuracy argmax — BASELINE.md "Vocab chain") collapses
             # to the blockwise scan; the full-logits path stays the
             # parity oracle, re-base rule pre-committed in BASELINE.md
             extra_cfg={"seq_len": 512 if on_tpu else 128},
             cfg_over={"lm_loss_impl": "fused"},
             prng_impl=rbg),
        dict(key="gpt_long", only={"gpt_long"},
             model="gpt" if on_tpu else "gpt_tiny",
             batch=4 if on_tpu else 2, steps=8 if on_tpu else 1,
             warmup=2 if on_tpu else 1, opt=adamw,
             make_batch=_gpt_batch_at(4096 if on_tpu else 128),
             extra_cfg={"seq_len": 4096 if on_tpu else 128},
             # fused since round 7: replaces lm_loss_chunk=512 — no
             # [B,S,V] tensor AND no seq-chunk recompute (the chunk
             # knob survives as the fallback; BASELINE.md "Vocab chain")
             cfg_over={"attention_impl": "flash", "remat": "none",
                       "lm_loss_impl": "fused"},
             prng_impl=rbg, eps_digits=2),
        # reps=7: median-of-repeats de-noising (VERDICT r5 weak #4) —
        # odd count gives a true middle element, 7 keeps the row under
        # ~2 s of measurement while the median shrugs off single-call
        # tunnel jitter; decode rides the stacked fast path by default
        dict(key="gpt_decode", only={"gpt_decode", "decode"},
             decode=dict(batch=8, prompt=128 if on_tpu else 16,
                         max_new=128 if on_tpu else 8,
                         reps=7 if on_tpu else 1,
                         warmup=2 if on_tpu else 0, tiny=not on_tpu,
                         # 4x-longer second leg: the two-point fit that
                         # isolates the device component from the
                         # ~100 ms/call tunnel overhead
                         amortize_new=512 if on_tpu else 32)),
        # continuous-batching serving row (round 9): closed-loop
        # clients through the scheduler-on REST server — throughput +
        # p95 latency + the shared-dispatch counters, baselined at the
        # next TPU window (BASELINE.md "Serving")
        dict(key="gpt", only={"serving", "gpt_serving"},
             serving=dict(clients=8, requests=4 if on_tpu else 2,
                          prompt_len=128 if on_tpu else 16,
                          max_new=64 if on_tpu else 8,
                          slots=8, tiny=not on_tpu)),
    ]


def vs_baseline_geomean(extra: dict, base: dict) -> float:
    """Geomean of measured/baseline over the gate workloads.

    A workload whose measurement carries the ``*_suspect`` flag (the
    tunnel's return-without-blocking artifact robust_time could not
    de-corrupt — always absurdly FAST) is EXCLUDED: a corrupt reading
    must never inflate the gate. mnist prefers its dedicated baseline
    key and falls back to the legacy round-1 name — never both.

    gpt_decode regresses on the tunnel-jitter-immune DEVICE component
    (``gpt_decode_device_token_ms``, lower = faster, so the ratio
    inverts) as soon as BOTH the baseline and the measurement carry it;
    until the device-component baseline exists it stays on wall-clock
    tokens/s — re-base with a methodology note at the first on-chip
    run that records the new key.
    """
    mnist_base = (base.get("mnist_mlp_eps_chip")
                  or base.get("examples_per_sec_per_chip"))
    ratios = []
    for key, b in (("mnist_mlp_eps_chip", mnist_base),
                   ("resnet50_eps_chip", base.get("resnet50_eps_chip")),
                   ("bert_base_eps_chip", base.get("bert_base_eps_chip")),
                   ("moe_bert_eps_chip", base.get("moe_bert_eps_chip")),
                   ("bert_large_eps_chip", base.get("bert_large_eps_chip")),
                   ("bert_long_eps_chip", base.get("bert_long_eps_chip")),
                   ("gpt_small_eps_chip", base.get("gpt_small_eps_chip")),
                   ("gpt_long_eps_chip", base.get("gpt_long_eps_chip")),
                   ("gpt_decode_tokens_s_chip",
                    base.get("gpt_decode_tokens_s_chip"))):
        if extra.get(key.replace("_eps_chip", "_suspect")
                     .replace("_tokens_s_chip", "_suspect")):
            continue
        if key == "gpt_decode_tokens_s_chip":
            dev_b = base.get("gpt_decode_device_token_ms")
            dev_m = extra.get("gpt_decode_device_token_ms")
            # both must be POSITIVE: a negative slope (corrupt leg that
            # dodged the suspect flag) in a ratio would NaN the geomean
            if dev_b and dev_m and dev_b > 0 and dev_m > 0:
                ratios.append(dev_b / dev_m)   # ms: lower is faster
                continue
        if extra.get(key) and b:
            ratios.append(extra[key] / b)
    return float(np.prod(ratios) ** (1 / len(ratios))) if ratios else 1.0


def main() -> None:
    # persistent compilation cache: the 6-workload gate is ~6
    # executables x ~40-60 s of (remote) compile when cold — enough to
    # brush up against driver timeouts. Verified to work through the
    # axon tunnel (second-process compile 2.3 s -> 0.8 s); a warmed
    # cache makes the round-end bench compile-free (measured 3 min for
    # the full gate). Set HERE, not at import: importers of bench
    # helpers (tests, bench_scaling) must not inherit the cache.
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("DTX_JAX_CACHE",
                                     "/tmp/dtx_jax_cache"))
    only = os.environ.get("BENCH_ONLY", "").split(",") if \
        os.environ.get("BENCH_ONLY") else None
    on_tpu = jax.devices()[0].platform == "tpu"
    scale = 1 if on_tpu else 16

    extra: dict[str, float | None] = {}
    for w in _workloads(on_tpu, scale):
        if only is not None and not (w["only"] & set(only)):
            continue
        key = w["key"]
        if "serving" in w:
            row = _run_serving(**w["serving"])
            for k, v in row.items():
                extra[f"{key}_{k}"] = v
            continue
        if "decode" in w:
            row = _run_decode(**w["decode"])
            extra[f"{key}_tokens_s_chip"] = round(row["tokens_s_chip"])
            extra[f"{key}_token_step_ms"] = round(row["token_step_ms"], 3)
            extra[f"{key}_weight_bound_ms"] = round(
                row["weight_bound_ms"], 3)
            extra[f"{key}_spread"] = round(row["spread"], 4)
            if "device_token_ms" in row:
                extra[f"{key}_device_token_ms"] = round(
                    row["device_token_ms"], 4)
                extra[f"{key}_call_overhead_ms"] = round(
                    row["call_overhead_ms"], 2)
                extra[f"{key}_long_spread"] = round(row["long_spread"], 4)
            if row["suspect"]:
                extra[f"{key}_suspect"] = True
            # int8 weight-quant leg (round 12): same program shape with
            # the decode weights dequantized inside the scan — the
            # promoted lever-table row, published so the next TPU
            # window verifies the ~2x tokens/s/chip target (ROADMAP
            # item 1). No second amortize leg: the int8 row regresses
            # on token_step_ms until its device-component baseline
            # exists.
            irow = _run_decode(**dict(
                w["decode"], amortize_new=None,
                gen_kwargs={"weight_quant": "int8"}))
            extra[f"{key}_int8_token_ms"] = round(
                irow["token_step_ms"], 3)
            extra[f"{key}_int8_tokens_s_chip"] = round(
                irow["tokens_s_chip"])
            if irow["suspect"]:
                extra[f"{key}_int8_suspect"] = True
            continue
        eps, ms, mfu, mfu_basis, peak_mib, suspect, anomalies = _run(
            w["model"], batch=w["batch"], steps=w["steps"],
            warmup=w["warmup"], opt=w["opt"],
            make_batch=w["make_batch"],
            extra_cfg=w.get("extra_cfg"), cfg_over=w.get("cfg_over"),
            steps_per_call=w.get("steps_per_call", 1),
            prng_impl=w.get("prng_impl"))
        extra[f"{key}_eps_chip"] = round(eps, w.get("eps_digits", 1))
        extra[f"{key}_step_ms"] = round(ms, w.get("ms_digits", 2))
        # always published, even at 0: the gate diffs rows, and a column
        # that only appears when nonzero cannot be watched for regressions
        extra[f"{key}_anomaly_count"] = anomalies
        if mfu:
            extra[f"{key}_mfu"] = round(mfu, 4)
            extra[f"{key}_mfu_basis"] = mfu_basis
        if peak_mib:
            extra[f"{key}_peak_mib"] = round(peak_mib)
        if suspect:
            extra[f"{key}_suspect"] = True

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "bench_baseline.json")
    base = {}
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f)

    # headline: MNIST MLP examples/sec/chip (the one metric with a recorded
    # round-1 baseline; ResNet-50/BERT baselines recorded from this round on).
    # A suspect-flagged mnist reading is corrupt by the code's own
    # verdict — publish 0.0 (with the flag in extra) rather than the
    # absurd number as the governing metric
    headline = (0.0 if extra.get("mnist_mlp_suspect")
                else extra.get("mnist_mlp_eps_chip", 0.0))
    vs = vs_baseline_geomean(extra, base)

    print(json.dumps({
        "metric": "mnist_mlp_examples_per_sec_per_chip",
        "value": headline,
        "unit": "examples/sec/chip",
        "vs_baseline": round(vs, 3),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
