"""Fine-tune from a checkpoint and ship a servable — the model lifecycle.

The reference era's workflow after training was: warm-start a new run
from a pretrained checkpoint (``tf.train.init_from_checkpoint``), keep
an exponential moving average of the weights
(``tf.train.ExponentialMovingAverage``), and export a SavedModel for
serving. This example runs that whole lifecycle on the TPU-native
framework, end to end, on synthetic data::

    python examples/finetune_export.py --workdir /tmp/lifecycle

Steps (each maps to one framework feature):

1. pretrain  — a short MNIST run, checkpointed (``CheckpointManager``).
2. fine-tune — a FRESH run whose params warm-start from step 1's
   checkpoint (``--warm_start`` / ``ckpt.warm_start``; the optimizer
   state and global step start over, which is what distinguishes
   fine-tuning from resuming), with an EMA shadow (``--ema_decay``).
3. export    — the fine-tuned forward (EMA weights) serialized to a
   self-contained StableHLO artifact (``serving.export_model``).
4. serve     — the artifact loaded back WITHOUT the model object and
   queried (``serving.load_servable``).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import distributed_tensorflow_example_tpu as dtx
from distributed_tensorflow_example_tpu.config import (CheckpointConfig,
                                                       DataConfig,
                                                       MeshShape,
                                                       OptimizerConfig,
                                                       TrainConfig)
from distributed_tensorflow_example_tpu.data.mnist import synthetic_mnist
from distributed_tensorflow_example_tpu.serving import (load_servable,
                                                        serving_signature)
from distributed_tensorflow_example_tpu.train.optimizers import (
    find_ema_params)


def run(workdir: str, pretrain_steps: int = 60,
        finetune_steps: int = 40) -> dict:
    data = synthetic_mnist(2048, 512)
    train = {"x": data["train_x"], "y": data["train_y"]}
    evals = {"x": data["test_x"], "y": data["test_y"]}

    # -- 1. pretrain ----------------------------------------------------
    # data=-1: every visible device on the data axis (the CLI default)
    pre_cfg = TrainConfig(
        model="mlp", train_steps=pretrain_steps,
        mesh=MeshShape(data=-1),
        data=DataConfig(batch_size=256),
        optimizer=OptimizerConfig(name="momentum", learning_rate=0.3),
        checkpoint=CheckpointConfig(directory=os.path.join(workdir, "pre"),
                                    save_steps=pretrain_steps))
    with dtx.Trainer(dtx.get_model("mlp", pre_cfg), pre_cfg, train,
                     eval_arrays=evals) as tr:
        _, pre_summary = tr.train()

    # -- 2. fine-tune (warm start + EMA) --------------------------------
    ft_cfg = TrainConfig(
        model="mlp", train_steps=finetune_steps,
        mesh=MeshShape(data=-1),
        data=DataConfig(batch_size=256),
        optimizer=OptimizerConfig(name="momentum", learning_rate=0.05,
                                  ema_decay=0.95),
        checkpoint=CheckpointConfig(
            directory=os.path.join(workdir, "ft"),
            warm_start=os.path.join(workdir, "pre"),
            save_steps=finetune_steps))
    model = dtx.get_model("mlp", ft_cfg)
    with dtx.Trainer(model, ft_cfg, train, eval_arrays=evals) as tr:
        state, ft_summary = tr.train()

    # -- 3. export the EMA weights --------------------------------------
    export_dir = os.path.join(workdir, "servable")
    ema = find_ema_params(state.opt_state)
    dtx.export_model(model, ema, state.extras, export_dir,
                     platforms=("cpu", "tpu"))

    # -- 4. serve from the artifact alone -------------------------------
    servable = load_servable(export_dir)
    feats = serving_signature({k: v[:16] for k, v in evals.items()})
    logits = np.asarray(servable(feats))
    acc = float((logits.argmax(-1) == evals["y"][:16]).mean())
    return {
        "pretrain_eval": pre_summary["eval"],
        "finetune_eval": ft_summary["eval"],
        "servable_accuracy_16": acc,
        "export_dir": export_dir,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--workdir", required=True)
    args = p.parse_args(argv)
    out = run(args.workdir)
    print({k: (round(v, 4) if isinstance(v, float) else v)
           for k, v in out.items()})
    return 0


if __name__ == "__main__":
    sys.exit(main())
