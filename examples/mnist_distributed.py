"""Distributed MNIST training — the reference example script, TPU-native.

This file is deliberately shaped like the canonical
distributed-tensorflow-example trainer (SURVEY.md §2.1, §3.1–3.3): the
same flags, the same ClusterSpec/Server bring-up, the same
``if job_name == "ps": server.join()`` branch, the same
variables→placement / model / sync-optimizer / supervised-loop order —
so a user of the reference can read this top to bottom and see exactly
where each familiar block landed in the TPU-native framework. Block
comments name the reference construct being replaced.

Run it single-process (the common case on a TPU host)::

    python examples/mnist_distributed.py --train_steps 500

or with the legacy launch-script surface::

    python examples/mnist_distributed.py \
        --job_name ps --task_index 0 \
        --ps_hosts ps0:2222 --worker_hosts w0:2222,w1:2222   # exits 0
"""

import argparse
import os
import sys
import time

# running from a source checkout: make the package importable without an
# install (python examples/mnist_distributed.py just works)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from distributed_tensorflow_example_tpu.ckpt.checkpoint import (
    CheckpointManager, restore_or_init)
from distributed_tensorflow_example_tpu.cluster import ClusterSpec
from distributed_tensorflow_example_tpu.config import (OptimizerConfig,
                                                       parse_hosts)
from distributed_tensorflow_example_tpu.data.loader import make_loader
from distributed_tensorflow_example_tpu.data.mnist import get_mnist
from distributed_tensorflow_example_tpu.models.mlp import MLP
from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
    SyncReplicas)
from distributed_tensorflow_example_tpu.runtime.server import Server
from distributed_tensorflow_example_tpu.train.optimizers import make_optimizer


def parse_flags(argv=None):
    # -- tf.app.flags parity (SURVEY.md §5.6): the reference's exact
    #    distributed flag surface plus its hyperparameter knobs
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--ps_hosts", default="",
                   help="comma-separated host:port list (no PS role on "
                        "TPU; accepted for launch-script compatibility)")
    p.add_argument("--worker_hosts", default="")
    p.add_argument("--job_name", default="worker", choices=["ps", "worker"])
    p.add_argument("--task_index", type=int, default=0)
    p.add_argument("--data_dir", default=None,
                   help="IDX files directory; omit for synthetic MNIST")
    p.add_argument("--hidden_units", type=int, default=100)
    p.add_argument("--batch_size", type=int, default=256,
                   help="GLOBAL batch size (the reference's per-worker "
                        "batch times worker count)")
    p.add_argument("--learning_rate", type=float, default=0.5)
    p.add_argument("--train_steps", type=int, default=1000)
    p.add_argument("--ckpt_dir", default=None)
    p.add_argument("--log_every_steps", type=int, default=100)
    return p.parse_args(argv)


def main(argv=None) -> int:
    flags = parse_flags(argv)

    # -- ClusterSpec({"ps": [...], "worker": [...]}) (SURVEY.md §3.1).
    #    Empty host lists -> single-process; the spec still drives
    #    jax.distributed bring-up when worker_hosts names several hosts.
    cluster = None
    if flags.ps_hosts or flags.worker_hosts:
        cluster = ClusterSpec({"ps": parse_hosts(flags.ps_hosts),
                               "worker": parse_hosts(flags.worker_hosts)})

    # -- tf.train.Server(cluster, job_name, task_index): one runtime
    #    handle per process. On TPU the PS role hosts nothing, so the
    #    reference's `if job_name == "ps": server.join()` branch logs the
    #    no-PS notice and exits 0 — old launch scripts keep working.
    server = Server(cluster, job_name=flags.job_name,
                    task_index=flags.task_index)
    if flags.job_name == "ps":
        server.join()
        return 0

    # -- tf.device(replica_device_setter(...)) (SURVEY.md §3.2): variable
    #    placement is a NamedSharding rule-set over the device mesh, not a
    #    per-op device string. build_mesh() puts every chip on the data
    #    axis (pure sync-DP, the reference's topology); the model's
    #    default rules replicate params — add fsdp/model axes for
    #    sharded placements.
    mesh = build_mesh()

    # -- model + loss (SURVEY.md §2.1: 784 -> hidden -> 10 softmax xent)
    model = MLP(in_dim=784, hidden=flags.hidden_units, num_classes=10)

    # -- SyncReplicasOptimizer(base_opt, replicas_to_aggregate=W)
    #    (SURVEY.md §3.3): the accumulate-average-apply-barrier protocol
    #    is ONE compiled step — grads psum-mean over the data axis, apply,
    #    step += 1. The base optimizer chain is optax, like the
    #    reference's GradientDescentOptimizer underneath the wrapper.
    tx = make_optimizer(OptimizerConfig(name="sgd",
                                        learning_rate=flags.learning_rate))
    sync = SyncReplicas(model.loss, tx, mesh)

    # -- Supervisor.prepare_or_wait_for_session (SURVEY.md §3.2):
    #    restore-or-init, identical decision on every process.
    mgr = (CheckpointManager(flags.ckpt_dir)
           if flags.ckpt_dir else None)
    state, restored = restore_or_init(mgr, sync.init, model.init, seed=0)
    start_step = int(jax.device_get(state.step))
    if restored:
        print(f"restored checkpoint at step {start_step}", flush=True)

    # -- input pipeline (SURVEY.md §2.1): in-memory MNIST, deterministic
    #    per-process sharding replaces the feed_dict next_batch loop
    data = get_mnist(flags.data_dir, synthetic=flags.data_dir is None)
    # start_step fast-forwards the deterministic batch sequence on resume
    # (exact-resume: the restored run consumes exactly the batches an
    # uninterrupted run would have)
    batches = make_loader(
        {"x": data["train_x"], "y": data["train_y"]},
        flags.batch_size,
        process_index=jax.process_index(),
        num_processes=jax.process_count(),
        shuffle=True, seed=0, start_step=start_step)

    # -- the training loop (SURVEY.md §3.3): sess.run([train_op, loss])
    #    becomes one compiled-step call; the chief's aggregator thread,
    #    token queue, and 2x param-size network transfers do not exist —
    #    the all-reduce rides ICI inside the step.
    t0, last_log = time.time(), start_step
    for step in range(start_step, flags.train_steps):
        state, metrics = sync.step(state, sync.shard_batch(next(batches)))
        if (step + 1) % flags.log_every_steps == 0:
            loss = float(jax.device_get(metrics["loss"]))
            dt = time.time() - t0
            sps = (step + 1 - last_log) / dt if dt > 0 else float("inf")
            print(f"step {step + 1}: loss={loss:.4f} ({sps:.1f} steps/s)",
                  flush=True)
            t0, last_log = time.time(), step + 1

    # -- chief checkpoint thread (SURVEY.md §3.4): process 0 writes,
    #    max_to_keep ring; here a single end-of-run save
    if mgr is not None:
        mgr.save(state)
        mgr.close()

    # -- final eval (SURVEY.md §2.1 train loop + eval)
    test = {"x": data["test_x"], "y": data["test_y"]}
    metrics = model.eval_metrics(state.params, state.extras,
                                 {k: jax.numpy.asarray(v)
                                  for k, v in test.items()})
    acc = float(jax.device_get(metrics["accuracy"]))
    print(f"final test accuracy: {acc:.4f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
