"""Train a causal LM and generate from it — the decoder workflow.

The reference era had no decoder-only models; this example shows the
framework's causal half end to end, on synthetic token data::

    python examples/train_and_generate.py --workdir /tmp/lm

Steps (each maps to one framework feature):

1. train    — a short ``gpt_tiny`` next-token run over the sync
   data-parallel mesh, checkpointed (``Trainer`` + ``CheckpointManager``;
   eval reports loss / perplexity / token accuracy).
2. reload   — the checkpoint restored into a fresh process the same way
   any training run resumes (``restore_or_init``).
3. generate — greedy AND temperature-sampled continuations from a
   prompt via the KV-cache decode path (``GPT.generate``: one full
   prefill forward, then the whole generation as a single compiled
   ``lax.scan`` over a static-shape cache).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--workdir", default="/tmp/dtx_lm")
    ap.add_argument("--train_steps", type=int, default=60)
    ap.add_argument("--prompt_len", type=int, default=8)
    ap.add_argument("--new_tokens", type=int, default=16)
    ap.add_argument("--cpu", action="store_true",
                    help="force the virtual 8-device CPU mesh")
    args = ap.parse_args(argv)

    if args.cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from distributed_tensorflow_example_tpu.ckpt.checkpoint import (
        CheckpointManager, restore_or_init)
    from distributed_tensorflow_example_tpu.config import (CheckpointConfig,
                                                           DataConfig,
                                                           MeshShape,
                                                           OptimizerConfig,
                                                           TrainConfig)
    from distributed_tensorflow_example_tpu.data.bert_data import get_lm_data
    from distributed_tensorflow_example_tpu.models import get_model
    from distributed_tensorflow_example_tpu.parallel.mesh import build_mesh
    from distributed_tensorflow_example_tpu.parallel.sync_replicas import (
        SyncReplicas)
    from distributed_tensorflow_example_tpu.train.optimizers import (
        make_optimizer)
    from distributed_tensorflow_example_tpu.train.trainer import Trainer

    ckpt_dir = os.path.join(args.workdir, "ckpt")

    # 1. train -----------------------------------------------------------
    cfg = TrainConfig(
        model="gpt_tiny", train_steps=args.train_steps,
        mesh=MeshShape(data=-1),       # all devices on the data axis
        data=DataConfig(batch_size=32, seq_len=64),
        optimizer=OptimizerConfig(name="adamw", learning_rate=3e-3),
        checkpoint=CheckpointConfig(directory=ckpt_dir,
                                    save_steps=args.train_steps),
        eval_every_steps=args.train_steps, seed=0)
    model = get_model("gpt_tiny", cfg)
    train_arrays, eval_arrays = get_lm_data(
        None, vocab_size=model.cfg.vocab_size, seq_len=64, synthetic=True)
    with Trainer(model, cfg, train_arrays, eval_arrays,
                 mesh=build_mesh(cfg.mesh)) as trainer:
        _, summary = trainer.train()
    print(f"trained to step {summary['final_step']}: "
          f"perplexity {summary['eval']['perplexity']:.1f}, "
          f"token accuracy {summary['eval']['token_accuracy']:.3f}")

    # 2. reload ----------------------------------------------------------
    sync = SyncReplicas(model.loss, make_optimizer(cfg.optimizer),
                        build_mesh(cfg.mesh))
    state, restored = restore_or_init(
        CheckpointManager(ckpt_dir),
        lambda: sync.init(model.init, seed=cfg.seed))
    assert restored, "checkpoint must be found"

    # 3. generate --------------------------------------------------------
    # prompt: the start of a held-out eval sequence; the synthetic corpus
    # has bigram structure, so a trained model visibly continues patterns
    prompt = jnp.asarray(
        eval_arrays["input_ids"][:2, :args.prompt_len])
    greedy = jax.jit(
        lambda p, i: model.generate(p, i, args.new_tokens))(
        state.params, prompt)
    sampled = model.generate(state.params, prompt, args.new_tokens,
                             temperature=0.8, rng=jax.random.key(0))
    # nucleus sampling with EOS early-stop: the serving-style call —
    # top_p keeps the smallest high-probability token set, eos_id stops
    # a row the moment it emits that token (pad_id fills the tail), and
    # the whole thing is still one compiled dispatch
    eos = int(np.asarray(greedy)[0, args.new_tokens // 2])
    nucleus = model.generate(state.params, prompt, args.new_tokens,
                             temperature=0.8, top_p=0.9, eos_id=eos,
                             pad_id=-1, rng=jax.random.key(1))
    # ragged prompts: row 1 uses only half its prompt (prompt_mask is
    # right-padded per row); generation continues each row from ITS
    # real tokens — parity with per-row dense decode is test-asserted
    pmask = np.ones(prompt.shape, np.int32)
    pmask[1, args.prompt_len // 2:] = 0
    ragged = model.generate(state.params, prompt, args.new_tokens,
                            prompt_mask=jnp.asarray(pmask))
    for b in range(prompt.shape[0]):
        print(f"prompt : {np.asarray(prompt)[b].tolist()}")
        print(f"greedy : {np.asarray(greedy)[b].tolist()}")
        print(f"sampled: {np.asarray(sampled)[b].tolist()}")
        print(f"nucleus(eos={eos}): {np.asarray(nucleus)[b].tolist()}")
        print(f"ragged : {np.asarray(ragged)[b].tolist()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
