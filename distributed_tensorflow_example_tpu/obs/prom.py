"""Prometheus text-format exposition of a registry snapshot.

``GET /metrics`` (serving_http.py) renders the SAME
:meth:`~.registry.Registry.snapshot` that backs ``/stats`` — the two
views cannot drift because neither holds its own counters. Output is
the classic text format (version 0.0.4): ``# HELP`` / ``# TYPE``
preamble per metric, histogram ``_bucket{le=...}`` series in ascending
``le`` order ending at ``+Inf``, then ``_sum`` and ``_count``.

Only the snapshot dict comes in — no live registry reference — so the
renderer can also serve merged snapshots
(:func:`~.registry.merge_snapshots`) without caring where they came
from.
"""

from __future__ import annotations

from typing import Any

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(v: float | int) -> str:
    """Prometheus number formatting: integers bare, floats via repr
    (full precision — the /stats consistency contract is exact)."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def _fmt_le(b: float) -> str:
    """Bucket bounds print without a trailing ``.0`` for whole numbers
    (``le="1"`` not ``le="1.0"``) — the convention Prometheus's own
    client libraries follow."""
    return str(int(b)) if float(b) == int(b) else repr(float(b))


def render(snapshot: dict[str, dict[str, Any]]) -> str:
    """Snapshot -> exposition text. Metric order is sorted by name so
    the output is deterministic (diffable in tests and scrapes)."""
    lines: list[str] = []
    for name in sorted(snapshot):
        rec = snapshot[name]
        kind = rec["type"]
        if rec.get("help"):
            # escape per the exposition format: backslash then newline
            h = rec["help"].replace("\\", r"\\").replace("\n", r"\n")
            lines.append(f"# HELP {name} {h}")
        lines.append(f"# TYPE {name} {kind}")
        if kind in ("counter", "gauge"):
            lines.append(f"{name} {_fmt(rec['value'])}")
            continue
        acc = 0
        for bound, count in rec["buckets"]:
            acc += count
            lines.append(f'{name}_bucket{{le="{_fmt_le(bound)}"}} {acc}')
        acc += rec["inf"]
        lines.append(f'{name}_bucket{{le="+Inf"}} {acc}')
        lines.append(f"{name}_sum {_fmt(rec['sum'])}")
        lines.append(f"{name}_count {rec['count']}")
    return "\n".join(lines) + "\n"


def parse(text: str) -> dict[str, float]:
    """Minimal inverse for tests and the bench row: ``{sample_name ->
    value}`` including ``_bucket{le=...}`` series keyed with their
    label (``name_bucket{le="0.5"}``). Not a general parser — it reads
    exactly what :func:`render` writes."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        out[key] = float(val)
    return out
