"""Prometheus text-format exposition of a registry snapshot.

``GET /metrics`` (serving_http.py) renders the SAME
:meth:`~.registry.Registry.snapshot` that backs ``/stats`` — the two
views cannot drift because neither holds its own counters. Output is
the classic text format (version 0.0.4): ``# HELP`` / ``# TYPE``
preamble per metric, histogram ``_bucket{le=...}`` series in ascending
``le`` order ending at ``+Inf``, then ``_sum`` and ``_count``.

Only the snapshot dict comes in — no live registry reference — so the
renderer can also serve merged snapshots
(:func:`~.registry.merge_snapshots`) without caring where they came
from.
"""

from __future__ import annotations

from typing import Any

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(v: float | int) -> str:
    """Prometheus number formatting: integers bare, floats via repr
    (full precision — the /stats consistency contract is exact)."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def _fmt_le(b: float) -> str:
    """Bucket bounds print without a trailing ``.0`` for whole numbers
    (``le="1"`` not ``le="1.0"``) — the convention Prometheus's own
    client libraries follow."""
    return str(int(b)) if float(b) == int(b) else repr(float(b))


def render(snapshot: dict[str, dict[str, Any]]) -> str:
    """Snapshot -> exposition text. Metric order is sorted by name so
    the output is deterministic (diffable in tests and scrapes)."""
    lines: list[str] = []
    for name in sorted(snapshot):
        rec = snapshot[name]
        kind = rec["type"]
        if rec.get("help"):
            # escape per the exposition format: backslash then newline
            h = rec["help"].replace("\\", r"\\").replace("\n", r"\n")
            lines.append(f"# HELP {name} {h}")
        lines.append(f"# TYPE {name} {kind}")
        if kind in ("counter", "gauge"):
            lines.append(f"{name} {_fmt(rec['value'])}")
            continue
        acc = 0
        for bound, count in rec["buckets"]:
            acc += count
            lines.append(f'{name}_bucket{{le="{_fmt_le(bound)}"}} {acc}')
        acc += rec["inf"]
        lines.append(f'{name}_bucket{{le="+Inf"}} {acc}')
        lines.append(f"{name}_sum {_fmt(rec['sum'])}")
        lines.append(f"{name}_count {rec['count']}")
    return "\n".join(lines) + "\n"


def parse(text: str) -> dict[str, float]:
    """Minimal inverse for tests and the bench row: ``{sample_name ->
    value}`` including ``_bucket{le=...}`` series keyed with their
    label (``name_bucket{le="0.5"}``). Not a general parser — it reads
    exactly what :func:`render` writes."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        out[key] = float(val)
    return out


def quantile_from_parsed(parsed: dict[str, float], name: str,
                         q: float) -> float:
    """Histogram quantile (Prometheus ``histogram_quantile`` rule:
    linear interpolation within the first bucket whose cumulative count
    reaches the rank) from a :func:`parse`-shaped sample dict —
    ``{name}_bucket{{le=...}}`` series + ``{name}_count``. Returns the
    upper bound of the +Inf-rank case as the largest finite bound (the
    conventional clamp), and 0.0 for an empty histogram. The fleet
    bench keys (``gpt_router_p95_ms``) source percentiles from the
    MERGED registry through this, not from client-side stopwatches."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q must be in [0, 1], got {q}")
    total = parsed.get(f"{name}_count", 0)
    if not total:
        return 0.0
    prefix = f'{name}_bucket{{le="'
    buckets: list[tuple[float, float]] = []
    for key, val in parsed.items():
        if not key.startswith(prefix):
            continue
        le = key[len(prefix):-2]          # strip trailing '"}'
        if le != "+Inf":
            buckets.append((float(le), val))
    buckets.sort()
    rank = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in buckets:
        if cum >= rank:
            if cum == prev_cum:
                return bound
            return prev_bound + (bound - prev_bound) * (
                (rank - prev_cum) / (cum - prev_cum))
        prev_bound, prev_cum = bound, cum
    return buckets[-1][0] if buckets else 0.0


def _num(val: str) -> int | float:
    """Exposition number -> int when it round-trips exactly (counters
    and gauges rendered from int values must merge back as ints so
    /stats equality checks stay exact)."""
    f = float(val)
    return int(f) if f == int(f) else f


def _unescape_help(h: str) -> str:
    """Exact inverse of :func:`render`'s help escaping (``\\`` then
    ``\\n``) — what makes ``parse_snapshot(render(s)) == s`` hold even
    for multi-line help text (the round-trip completeness contract
    tests/test_obs.py pins)."""
    out: list[str] = []
    i = 0
    while i < len(h):
        c = h[i]
        if c == "\\" and i + 1 < len(h):
            nxt = h[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def parse_snapshot(text: str) -> dict[str, dict]:
    """Exposition text -> a :meth:`~.registry.Registry.snapshot`-shaped
    dict, the exact inverse of :func:`render` — so a FLEET front-end
    (serving_router.py) can scrape each replica's ``/metrics`` page and
    combine them through :func:`~.registry.merge_snapshots` without a
    side channel to the replicas' in-process registries. Histogram
    ``_bucket`` series are de-cumulated back to per-bucket counts (the
    snapshot layout merge_snapshots sums); ``# TYPE`` lines drive the
    record shape; ``# HELP`` text is unescaped back to the registered
    string, so ``parse_snapshot(render(s)) == s`` exactly."""
    out: dict[str, dict] = {}
    helps: dict[str, str] = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, h = line[len("# HELP "):].partition(" ")
            helps[name] = _unescape_help(h)
            continue
        if line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            if kind == "histogram":
                out[name] = {"type": "histogram", "buckets": [],
                             "inf": 0, "sum": 0.0, "count": 0,
                             "help": helps.get(name, "")}
            else:
                out[name] = {"type": kind, "value": 0,
                             "help": helps.get(name, "")}
            continue
        if line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        if key in out and out[key]["type"] in ("counter", "gauge"):
            out[key]["value"] = _num(val)
            continue
        # histogram series: <name>_bucket{le="..."} / _sum / _count
        if key.endswith("_sum") and key[:-4] in out:
            out[key[:-4]]["sum"] = float(val)
        elif key.endswith("_count") and key[:-6] in out:
            out[key[:-6]]["count"] = int(float(val))
        elif "_bucket{le=" in key:
            name = key.split("_bucket{le=", 1)[0]
            rec = out.get(name)
            if rec is None:
                continue
            le = key.split('le="', 1)[1].rstrip('"}')
            acc = int(float(val))
            prior = (sum(c for _, c in rec["buckets"]) + rec["inf"])
            if le == "+Inf":
                rec["inf"] = acc - prior
            else:
                rec["buckets"].append((float(le), acc - prior))
    return out
