"""SLO attainment & error-budget burn rates over a snapshot history.

PR 14 made the scheduler SLO-*aware* (priority admission, feasibility
shedding, the brownout ladder); this module makes the SLO *measured*:
declarative per-priority-class objectives evaluated over the
time-series ring (obs/timeseries.py) into attainment and multi-window
burn rates. Everything here is a pure function of an injected history
and clock — no threads, no sleeps, no registry references — so the
whole burn-rate story unit-tests on fabricated samples.

**Objectives** (``--slo_spec``, grammar ``class:kind=target[@goal]``
joined with ``;``):

=================  =====================================================
kind                the SLI it compiles to (good / total over a window)
=================  =====================================================
``hit_rate``        deadline hit rate: ``serving_slo_good_<class>_total``
                    over ``serving_slo_served_<class>_total`` (the
                    per-retirement counters the engine feeds; class
                    ``all`` reads the aggregate pair). ``=X`` IS the
                    goal.
``p95_ms``          latency: observations of
                    ``serving_latency_<class>_seconds`` at or under
                    the target (interpolated within the bucket —
                    :func:`~.timeseries.good_below`) over the window
                    count; class ``all`` reads the global
                    ``serving_request_latency_seconds``. ``@goal``
                    defaults to 0.95. The measured windowed p95 is
                    also reported (:func:`~.timeseries.quantile`).
``availability``    requests not failed by the server:
                    ``served - serving_requests_failed_total`` over
                    served (class ``all`` only — failures are not
                    classed). ``=X`` IS the goal.
=================  =====================================================

**Burn rate** (the SRE error-budget rule): with error rate ``e = 1 -
good/total`` over a window and budget ``1 - goal``, ``burn = e /
(1 - goal)`` — 1.0 means the budget exactly sustains the SLO period,
N means the budget is gone N× faster. :func:`evaluate` computes burn
over a FAST and a SLOW window and flags ``breach`` only when BOTH
exceed the threshold with observations in both (the classic
multi-window rule: the slow window proves it is real, the fast window
proves it is still happening — a breach can't be tripped by one
stray request after a quiet hour, nor held forever by an incident
that already ended).

The server (serving_http.py) hangs :func:`evaluate` off the sampler's
``on_sample`` hook and turns a breach into a rate-limited ``slo_burn``
flight-recorder bundle; ``/healthz`` carries :func:`summarize` as an
ADVISORY field (it never changes the status code — SLO burn is an
operator page, not a load-balancer signal).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

from . import timeseries as ts

#: objective kinds (the grammar's vocabulary)
KINDS = ("hit_rate", "p95_ms", "availability")

#: priority classes + the aggregate pseudo-class
CLASSES = ("interactive", "batch", "best_effort", "all")

#: default evaluation windows/threshold (server knobs override)
FAST_WINDOW_S = 60.0
SLOW_WINDOW_S = 600.0
BURN_THRESHOLD = 2.0


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective: ``goal`` of class ``cls`` traffic
    must be good, where good is defined by ``kind`` (and ``target``
    for the latency kind, in milliseconds)."""
    cls: str
    kind: str
    target: float       # p95_ms: the latency bound (ms); else == goal
    goal: float         # required good fraction, in (0, 1)

    def __post_init__(self):
        if self.cls not in CLASSES:
            raise ValueError(f"objective class must be one of "
                             f"{CLASSES}, got {self.cls!r}")
        if self.kind not in KINDS:
            raise ValueError(f"objective kind must be one of {KINDS}, "
                             f"got {self.kind!r}")
        if self.kind == "availability" and self.cls != "all":
            raise ValueError(
                "availability objectives are class 'all' only "
                "(serving_requests_failed_total is not classed)")
        if not 0.0 < self.goal < 1.0:
            raise ValueError(f"goal must be in (0, 1), got {self.goal}")
        if self.kind == "p95_ms" and self.target <= 0:
            raise ValueError(f"p95_ms target must be > 0 ms, got "
                             f"{self.target}")

    def key(self) -> str:
        return f"{self.cls}:{self.kind}"

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def parse_slo_spec(spec: str) -> list[Objective]:
    """``class:kind=target[@goal];...`` -> objectives, loudly.

    >>> parse_slo_spec("interactive:p95_ms=250@0.95;all:availability=0.999")
    """
    out: list[Objective] = []
    seen: set[str] = set()
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        head, sep, val = part.partition("=")
        if not sep:
            raise ValueError(
                f"slo_spec entry {part!r}: expected "
                "class:kind=target[@goal]")
        cls, sep, kind = head.strip().partition(":")
        if not sep:
            raise ValueError(
                f"slo_spec entry {part!r}: expected class:kind "
                f"(classes {CLASSES}, kinds {KINDS})")
        val, _, goal_s = val.partition("@")
        try:
            target = float(val)
            goal = float(goal_s) if goal_s else None
        except ValueError as e:
            raise ValueError(f"slo_spec entry {part!r}: {e}") from None
        kind = kind.strip()
        if kind == "p95_ms":
            goal = 0.95 if goal is None else goal
        else:
            if goal is not None:
                raise ValueError(
                    f"slo_spec entry {part!r}: {kind} takes no @goal "
                    "(the =value IS the goal)")
            goal = target
        obj = Objective(cls=cls.strip(), kind=kind, target=target,
                        goal=goal)
        if obj.key() in seen:
            raise ValueError(f"slo_spec repeats objective {obj.key()!r}")
        seen.add(obj.key())
        out.append(obj)
    if not out:
        raise ValueError(f"slo_spec {spec!r} declares no objectives")
    return out


def default_objectives() -> list[Objective]:
    """The objectives an armed sampler evaluates when ``--slo_spec``
    is unset: interactive latency + hit rate, fleet availability."""
    return [
        Objective("interactive", "p95_ms", 1000.0, 0.95),
        Objective("interactive", "hit_rate", 0.99, 0.99),
        Objective("all", "availability", 0.999, 0.999),
    ]


# ---------------------------------------------------------------------------
# SLI: (good, total) over a window
# ---------------------------------------------------------------------------

def _counter_pair(cls: str) -> tuple[str, str]:
    if cls == "all":
        return "serving_slo_good_total", "serving_slo_served_total"
    return (f"serving_slo_good_{cls}_total",
            f"serving_slo_served_{cls}_total")


def _latency_hist(cls: str) -> str:
    if cls == "all":
        return "serving_request_latency_seconds"
    return f"serving_latency_{cls}_seconds"


def sli(win: Sequence[ts.Sample], obj: Objective
        ) -> tuple[float, float]:
    """The objective's ``(good, total)`` event counts over the window
    — every kind reduces to this ratio shape, which is what makes one
    burn-rate formula serve all three."""
    if obj.kind == "hit_rate":
        g, s = _counter_pair(obj.cls)
        return float(ts.delta(win, g)), float(ts.delta(win, s))
    if obj.kind == "availability":
        _, s = _counter_pair(obj.cls)
        served = float(ts.delta(win, s))
        failed = float(ts.delta(win, "serving_requests_failed_total"))
        return max(0.0, served - failed), served
    # p95_ms: observations at/under the bound over window count
    name = _latency_hist(obj.cls)
    d = ts.delta(win, name)
    total = float(d["count"]) if isinstance(d, dict) else 0.0
    if total <= 0:
        return 0.0, 0.0
    return ts.good_below(win, name, obj.target / 1e3), total


def burn_rate(good: float, total: float, goal: float) -> float:
    """Error-budget burn: ``(1 - good/total) / (1 - goal)``; 0.0 with
    no observations (an idle window burns nothing)."""
    if total <= 0:
        return 0.0
    err = 1.0 - good / total
    return err / (1.0 - goal)


def evaluate(history: Sequence[ts.Sample],
             objectives: Sequence[Objective], *,
             now: float | None = None,
             fast_s: float = FAST_WINDOW_S,
             slow_s: float = SLOW_WINDOW_S,
             threshold: float = BURN_THRESHOLD) -> list[dict[str, Any]]:
    """Evaluate every objective over the history: attainment (slow
    window — the canonical reporting window), fast/slow burn rates,
    and the multi-window ``breach`` flag. Pure: ``now`` defaults to
    the newest sample's stamp, so a dumped history evaluates
    identically offline."""
    fast = ts.window(history, fast_s, now)
    slow = ts.window(history, slow_s, now)
    out: list[dict[str, Any]] = []
    for obj in objectives:
        g_f, t_f = sli(fast, obj)
        g_s, t_s = sli(slow, obj)
        b_f = burn_rate(g_f, t_f, obj.goal)
        b_s = burn_rate(g_s, t_s, obj.goal)
        rec: dict[str, Any] = {
            "class": obj.cls, "kind": obj.kind,
            "target": obj.target, "goal": obj.goal,
            "good": round(g_s, 3), "total": round(t_s, 3),
            "attainment": round(g_s / t_s, 6) if t_s > 0 else None,
            "burn_fast": round(b_f, 4), "burn_slow": round(b_s, 4),
            "breach": (t_f > 0 and t_s > 0
                       and b_f >= threshold and b_s >= threshold),
        }
        if obj.kind == "p95_ms":
            rec["measured_p95_ms"] = round(
                ts.quantile(slow, _latency_hist(obj.cls), 0.95) * 1e3,
                3)
        out.append(rec)
    return out


def summarize(results: Sequence[dict[str, Any]]) -> dict[str, Any]:
    """The compact advisory block ``/healthz`` carries: objective
    count, the breaching ``class:kind`` keys, and the worst burn with
    its owner — enough for an operator probe, with the full story on
    ``GET /stats/history``."""
    worst = max(results, key=lambda r: r["burn_fast"], default=None)
    return {
        "objectives": len(results),
        "breaching": [f"{r['class']}:{r['kind']}" for r in results
                      if r["breach"]],
        "worst_burn": (None if worst is None else {
            "objective": f"{worst['class']}:{worst['kind']}",
            "burn_fast": worst["burn_fast"],
            "burn_slow": worst["burn_slow"],
            "attainment": worst["attainment"]}),
    }
