"""Unified telemetry: metrics registry, Prometheus exposition, tracing.

Three layers (DESIGN.md §14):

- :mod:`.registry` — process-local counters / gauges / fixed-bucket
  histograms; one lock, atomic snapshots, near-zero disabled path.
  The serving engine and the trainer both keep their counters HERE,
  so ``/stats``, ``/metrics``, bench rows, and hook logs read one
  source of truth.
- :mod:`.prom` — a snapshot rendered as Prometheus text format
  (``GET /metrics``).
- :mod:`.trace` — span API + ring-buffer recorder dumping
  chrome://tracing / Perfetto trace-event JSON (``POST /trace/start``
  / ``/trace/stop``); shares its event writer with the offline
  ``utils/trace_summary.py --chrome`` converter. Round 17 adds
  :class:`~.trace.TraceContext` (``traceparent``-shaped distributed
  trace propagation) and per-process drain for the fleet stitcher.
- :mod:`.stitch` — clock-offset estimation + the fleet trace stitcher
  behind the router's ``GET /trace/fleet`` (DESIGN.md §20).
- :mod:`.flightrec` — the always-on black-box flight recorder: auto-
  captured, rate-limited incident bundles off the existing failure
  seams (DESIGN.md §20).
"""

from .registry import (Counter, Gauge, Histogram, Registry,  # noqa: F401
                       all_registries, merge_snapshots)
from .trace import (ChromeTraceWriter, TraceContext,  # noqa: F401
                    TraceRecorder, add_span, parse_traceparent,
                    recorder, set_recorder, span)
