"""Metric time-series: a bounded ring of registry snapshots + pure
window queries.

``/metrics`` and ``/stats`` are point-in-time views — they can say how
many tokens have ever been served, never how many per second *right
now*, and the SLO layer (obs/slo.py) needs exactly the latter: rates,
deltas, and histogram quantiles **over a window**. This module is the
measurement substrate:

- :class:`SnapshotSampler` periodically captures the registry's
  existing atomic snapshot (the SAME dict ``/metrics`` renders, so a
  sample can never disagree with the live page) into a bounded ring of
  ``(t, snapshot)`` pairs. The clock is injectable and :meth:`~
  SnapshotSampler.sample` is an ordinary method, so every unit test
  drives time by hand — zero sleeps. The background thread is optional
  (:meth:`~SnapshotSampler.start`); a server that never constructs a
  sampler pays nothing: sampling is a pure *reader* of the registry,
  no request-path code ever checks for it (the armed-vs-plain parity
  contract, PR-13 pattern).
- Pure window queries over a ``[(t, snapshot), ...]`` history:
  :func:`window`, :func:`delta`, :func:`rate_per_s`,
  :func:`quantile` (histogram-quantile-over-window — the bucket
  *delta* between the window's edge samples fed through
  :func:`~.prom.quantile_from_parsed`, so windowed percentiles use
  exactly the Prometheus interpolation rule the fleet bench keys
  already trust), and :func:`good_below` (interpolated count of
  window observations at or under a bound — the latency-SLI numerator).
- :func:`rollup` merges N replicas' histories into ONE fleet history:
  each replica's timestamps are first corrected into the caller's
  clock (the router uses :func:`~.stitch.estimate_offset` per replica,
  the same NTP-style estimate the fleet trace stitcher applies), then
  samples are binned on a common grid and merged per bin with
  :func:`~.registry.merge_snapshots` — the registry was built
  mergeable precisely so this rollup could exist. Only bins every
  replica covers are emitted, so merged counter series stay monotonic
  (a missing replica would otherwise read as a fleet-wide counter
  *dip*).

Serialization: :func:`to_payload` / :func:`parse_payload` define the
``GET /stats/history`` JSON shape (samples as ``[t, snapshot]`` pairs)
shared by the replica endpoint, the router rollup, and
``tools/servetop.py``'s offline mode.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterable, Sequence

from . import prom
from .registry import merge_snapshots
from ..utils.logging import get_logger

log = get_logger("timeseries")

#: one history sample: (capture time in the owning process's
#: perf_counter clock, registry snapshot dict)
Sample = tuple[float, dict]


class SnapshotSampler:
    """Bounded ring of ``(t, snapshot)`` captures of one snapshot
    function.

    ``snapshot_fn`` is the server's ``_metrics_snapshot`` (gauges
    freshened, atomic); ``clock`` is injectable so tests never sleep;
    ``on_sample`` (optional) runs after every capture with the sampler
    itself — the server hangs its SLO evaluation + burn-rate breach
    check there. A raising ``on_sample`` is logged and swallowed: the
    sampler is observability, and observability must never take the
    serving path down.

    Thread model: :meth:`sample` is safe from any thread (ring
    mutations under one lock); :meth:`start` runs it on a daemon
    thread every ``interval_s`` (first capture immediately, so a
    just-started server already has its zero baseline);
    :meth:`stop` parks the thread promptly even mid-wait.
    """

    def __init__(self, snapshot_fn: Callable[[], dict], *,
                 interval_s: float = 1.0, max_samples: int = 600,
                 clock: Callable[[], float] = time.perf_counter,
                 on_sample: Callable[["SnapshotSampler"], None]
                 | None = None):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        if max_samples < 2:
            raise ValueError(f"max_samples must be >= 2 (window math "
                             f"needs two edges), got {max_samples}")
        self.snapshot_fn = snapshot_fn
        self.interval_s = float(interval_s)
        self.max_samples = int(max_samples)
        self.clock = clock
        self.on_sample = on_sample
        self._lock = threading.Lock()
        self._ring: list[Sample] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- capture -------------------------------------------------------
    def sample(self) -> Sample:
        """Capture one ``(t, snapshot)`` pair into the ring (oldest
        sample drops at ``max_samples``) and run ``on_sample``.
        Returns the new sample."""
        s = (self.clock(), self.snapshot_fn())
        with self._lock:
            self._ring.append(s)
            if len(self._ring) > self.max_samples:
                del self._ring[0]
        if self.on_sample is not None:
            try:
                self.on_sample(self)
            except Exception as e:      # noqa: BLE001 — see docstring
                log.warning("on_sample callback failed: %s", e)
        return s

    def peek(self) -> Sample:
        """One ``(t, snapshot)`` capture WITHOUT storing it or running
        ``on_sample`` — the ``/stats/history`` freshness sample. The
        ring holds only cadence samples, so concurrent pollers can
        never erode its time coverage below the burn windows it was
        sized for."""
        return (self.clock(), self.snapshot_fn())

    def history(self) -> list[Sample]:
        """A consistent copy of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- background cadence --------------------------------------------
    def start(self) -> "SnapshotSampler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.sample()
                except Exception as e:  # noqa: BLE001 — keep sampling
                    log.warning("history sample failed: %s", e)
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop,
                                        name="snapshot-sampler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---------------------------------------------------------------------------
# pure window queries over [(t, snapshot), ...]
# ---------------------------------------------------------------------------

def window(history: Sequence[Sample], seconds: float | None,
           now: float | None = None) -> list[Sample]:
    """The sub-history within ``seconds`` of ``now`` (default: the
    newest sample's own stamp — so a quiesced history windows against
    itself, not against a wall clock that kept running). An explicit
    ``now`` cuts BOTH ends: samples newer than ``now`` are excluded,
    so an offline replay at a mid-incident instant can never compute
    burn from data that had not happened yet. ``seconds`` None/<=0
    keeps every sample up to ``now``."""
    hist = list(history)
    if not hist:
        return hist
    if now is not None:
        t_end = float(now)
        hist = [s for s in hist if s[0] <= t_end]
    else:
        t_end = hist[-1][0]
    if seconds is None or seconds <= 0:
        return hist
    lo = t_end - float(seconds)
    return [s for s in hist if s[0] >= lo]


def _edges(win: Sequence[Sample]) -> tuple[Sample, Sample] | None:
    return (win[0], win[-1]) if len(win) >= 2 else None


def delta(win: Sequence[Sample], name: str):
    """Change of metric ``name`` across the window: counters/gauges
    return ``last - first`` (0 when the window has under two samples
    or the name is absent); histograms return a de-accumulated record
    ``{"buckets": [(le, count)], "inf", "sum", "count"}`` of ONLY the
    window's observations."""
    e = _edges(win)
    if e is None:
        return 0
    (_, a), (_, b) = e
    ra, rb = a.get(name), b.get(name)
    if rb is None:
        return 0
    if rb["type"] in ("counter", "gauge"):
        va = ra["value"] if ra is not None else 0
        return rb["value"] - va
    buckets_a = {le: c for le, c in (ra or {}).get("buckets", ())}
    return {
        "buckets": [(le, c - buckets_a.get(le, 0))
                    for le, c in rb.get("buckets", ())],
        "inf": rb.get("inf", 0) - (ra or {}).get("inf", 0),
        "sum": rb.get("sum", 0.0) - (ra or {}).get("sum", 0.0),
        "count": rb.get("count", 0) - (ra or {}).get("count", 0),
    }


def duration_s(win: Sequence[Sample]) -> float:
    """Window span in seconds (0.0 with under two samples)."""
    e = _edges(win)
    return (e[1][0] - e[0][0]) if e else 0.0


def rate_per_s(win: Sequence[Sample], name: str) -> float:
    """Counter rate over the window: delta / span (0.0 when the span
    is empty — a one-sample history has no rate, not an infinite
    one)."""
    dt = duration_s(win)
    if dt <= 0:
        return 0.0
    d = delta(win, name)
    if isinstance(d, dict):
        raise ValueError(f"{name!r} is a histogram — rate_per_s reads "
                         "counters/gauges (use delta() for buckets)")
    return d / dt


def _hist_delta_as_parsed(win: Sequence[Sample], name: str
                          ) -> dict[str, float] | None:
    """The window's histogram delta in :func:`~.prom.parse` shape, so
    quantiles ride :func:`~.prom.quantile_from_parsed` unchanged."""
    d = delta(win, name)
    if not isinstance(d, dict) or d["count"] <= 0:
        return None
    parsed: dict[str, float] = {f"{name}_count": d["count"]}
    acc = 0
    for le, c in d["buckets"]:
        acc += c
        parsed[f'{name}_bucket{{le="{prom._fmt_le(le)}"}}'] = acc
    return parsed


def quantile(win: Sequence[Sample], name: str, q: float) -> float:
    """Histogram quantile of ONLY the window's observations (seconds,
    for the latency histograms): bucket deltas between the window's
    edge samples through the Prometheus interpolation rule
    (:func:`~.prom.quantile_from_parsed`). 0.0 for an empty window —
    same convention as an empty histogram."""
    parsed = _hist_delta_as_parsed(win, name)
    if parsed is None:
        return 0.0
    return prom.quantile_from_parsed(parsed, name, q)


def good_below(win: Sequence[Sample], name: str,
               bound: float) -> float:
    """How many of the window's histogram observations were <=
    ``bound`` — the latency-SLI numerator (obs/slo.py ``p95_ms``
    objectives). Exact at bucket bounds; linearly interpolated inside
    the bucket containing ``bound`` (the same assumption the quantile
    rule makes in the other direction). Observations beyond the last
    finite bucket count only if the bound is +inf."""
    d = delta(win, name)
    if not isinstance(d, dict) or d["count"] <= 0:
        return 0.0
    acc = 0.0
    prev_le = 0.0
    for le, c in d["buckets"]:
        if bound >= le:
            acc += c
        else:
            if bound > prev_le and le > prev_le:
                acc += c * (bound - prev_le) / (le - prev_le)
            return acc
        prev_le = le
    if bound == float("inf"):
        acc += d["inf"]
    return acc


# ---------------------------------------------------------------------------
# fleet rollup
# ---------------------------------------------------------------------------

def rollup(histories: dict[str, Sequence[Sample]], *,
           offsets: dict[str, float] | None = None,
           bin_s: float = 1.0) -> list[Sample]:
    """Merge per-replica histories into one fleet history.

    ``histories`` maps replica name -> its ``[(t, snapshot)]`` samples
    in the REPLICA's clock; ``offsets`` maps name -> clock offset
    (remote minus local, :func:`~.stitch.estimate_offset`) applied as
    ``t_local = t_remote - offset`` — the stitcher's correction rule.
    Corrected samples are binned on a ``bin_s`` grid; within one bin a
    replica contributes its NEWEST sample (two quick samples must not
    double its counters), and only bins covered by EVERY replica are
    merged (:func:`~.registry.merge_snapshots`) — a bin missing a
    replica would render as a fleet-wide counter dip. Returns merged
    ``(t, snapshot)`` pairs, ``t`` = the newest member stamp, oldest
    first."""
    if bin_s <= 0:
        raise ValueError(f"bin_s must be > 0, got {bin_s}")
    offsets = offsets or {}
    live = {n: h for n, h in histories.items() if h}
    if not live:
        return []
    # per replica: {bin index -> (corrected_t, snapshot)} keeping the
    # newest sample per bin
    binned: dict[str, dict[int, Sample]] = {}
    for name, hist in live.items():
        off = float(offsets.get(name, 0.0))
        per: dict[int, Sample] = {}
        for t, snap in hist:
            tc = float(t) - off
            b = int(tc // bin_s)
            cur = per.get(b)
            if cur is None or tc >= cur[0]:
                per[b] = (tc, snap)
        binned[name] = per
    common = set.intersection(*(set(p) for p in binned.values()))
    out: list[Sample] = []
    for b in sorted(common):
        members = [binned[name][b] for name in sorted(binned)]
        out.append((max(t for t, _ in members),
                    merge_snapshots(*(s for _, s in members))))
    return out


# ---------------------------------------------------------------------------
# the GET /stats/history payload shape
# ---------------------------------------------------------------------------

def to_payload(history: Iterable[Sample], **meta: Any) -> dict:
    """History -> the JSON shape ``GET /stats/history`` serves
    (samples as ``[t, snapshot]`` lists; ``meta`` keys ride the top
    level)."""
    return {"samples": [[t, snap] for t, snap in history], **meta}


def parse_payload(payload: dict) -> list[Sample]:
    """The inverse: payload -> ``[(t, snapshot)]`` (tuples restored,
    timestamps floated) — what servetop's offline mode and the router
    rollup read."""
    return [(float(t), snap) for t, snap in payload.get("samples", ())]
