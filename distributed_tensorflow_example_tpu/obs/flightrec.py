"""Black-box flight recorder: auto-captured incident bundles.

Tracing used to be useful only for incidents someone predicted: the
ring had to be armed via ``POST /trace/start`` BEFORE the failure. The
flight recorder inverts that — the bounded ring runs always-on
(obs/trace.py ``arm_always_on``; the per-call cost is bounded by the
same <2 µs guard as the disabled path), and the serving stack's
existing failure seams call :meth:`FlightRecorder.incident` at the
moment something breaks:

=====================  ==================================================
bundle ``cause``        seam that fires it
=====================  ==================================================
``watchdog_stall``      ``PredictServer.health()`` sees the engine
                        heartbeat aged past ``stall_after_s`` (the
                        probe that demotes the replica also evidences
                        it)
``engine_fatal_rebuild``  ``GenerationEngine._loop``'s pool-consumed
                        handler, just before failing every in-flight
                        request and rebuilding the pool
``poison_eviction``     ``GenerationEngine._dispatch_decode`` evicting
                        the newest-admitted slot after a repeated
                        shared-step failure
``breaker_open``        a replica's circuit breaker tripping open at
                        the router
``replica_death``       the router's prober marking a replica dead
=====================  ==================================================

Each incident atomically writes ONE timestamped JSON bundle to
``--incident_dir`` (temp file + ``os.replace`` — a crash mid-write can
never leave a half bundle), rate-limited PER CAUSE (default one per
30 s; a wedged replica probed 20×/s must produce one bundle, not a
disk full of them). Bundle contents: the cause + detail, the last-N
spans from the always-on ring (non-destructive tail — an operator's
later ``/trace/export`` still sees them), a full registry snapshot
(the same atomic snapshot ``/metrics`` renders, so bundle counters are
checkable against the live page), the request-log tail, any caller
context (health payload, breaker states), and the owning process's
config fingerprint.

Parity contract (the PR-9/10 pattern): ``--flight_recorder off``
leaves serving byte- and dispatch-identical to the armed-but-quiet
run — arming only ever ADDS observability, never behavior
(tests/test_fleet_chaos.py pins this).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Callable

from . import trace as obs_trace
from ..utils.logging import get_logger

log = get_logger("flightrec")


def config_fingerprint(config: dict[str, Any]) -> str:
    """Stable short hash of a knob dict — the "what was this process
    actually running" field incident triage starts from."""
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class FlightRecorder:
    """One process's incident-bundle writer.

    ``snapshot_fn`` returns the registry snapshot to embed (the
    server's ``_metrics_snapshot`` — the SAME atomic read ``/metrics``
    renders); ``config`` is the knob dict fingerprinted into every
    bundle; ``counter``/``suppressed_counter`` (optional registry
    counters) publish bundle/rate-limit activity; ``clock`` is
    injectable so rate-limit unit tests need no sleeps.
    """

    def __init__(self, incident_dir: str, *, process: str = "serving",
                 snapshot_fn: Callable[[], dict] | None = None,
                 config: dict[str, Any] | None = None,
                 request_log_path: str | None = None,
                 max_spans: int = 512, min_interval_s: float = 30.0,
                 counter=None, suppressed_counter=None,
                 clock=time.monotonic):
        if not incident_dir:
            raise ValueError("FlightRecorder needs an incident_dir")
        self.incident_dir = incident_dir
        self.process = process
        self.snapshot_fn = snapshot_fn
        self.config = dict(config or {})
        self.request_log_path = request_log_path
        self.max_spans = int(max_spans)
        self.min_interval_s = float(min_interval_s)
        self.clock = clock
        self._counter = counter
        self._suppressed = suppressed_counter
        self._lock = threading.Lock()
        self._last_by_cause: dict[str, float] = {}
        self._seq = 0
        os.makedirs(incident_dir, exist_ok=True)

    # -- the one write path -------------------------------------------
    def incident(self, cause: str, detail: str = "",
                 extra: dict[str, Any] | None = None) -> str | None:
        """Write one incident bundle; returns its path, or None when
        the per-cause rate limit suppressed it. Never raises into the
        failure seam that called it — an incident dump that killed the
        scheduler thread would turn observability into an outage."""
        now = self.clock()
        with self._lock:
            last = self._last_by_cause.get(cause)
            if last is not None and now - last < self.min_interval_s:
                if self._suppressed is not None:
                    self._suppressed.inc()
                return None
            self._last_by_cause[cause] = now
            self._seq += 1
            seq = self._seq
        # the counter advances BEFORE the registry snapshot lands in
        # the bundle, so the bundle is self-consistent with the live
        # /metrics page (a bundle claiming incidents_total=0 while
        # being incident #1 would fail the snapshot-vs-page contract);
        # it therefore counts incidents CAPTURED — a failed write below
        # is logged, and the rate-limit stamp is rolled back so the
        # NEXT occurrence retries instead of being suppressed for a
        # bundle that never landed
        if self._counter is not None:
            self._counter.inc()
        try:
            path = self._write(cause, detail, extra or {}, seq)
        except Exception as e:    # noqa: BLE001 — see docstring
            log.warning("incident bundle for %s failed: %s", cause, e)
            with self._lock:
                if self._last_by_cause.get(cause) == now:
                    del self._last_by_cause[cause]
            return None
        log.warning("incident bundle (%s): %s", cause, path)
        return path

    def _write(self, cause: str, detail: str, extra: dict,
               seq: int) -> str:
        rec = obs_trace.recorder()
        spans = rec.tail(self.max_spans, process=self.process)
        bundle = {
            "cause": cause,
            "detail": detail,
            "process": self.process,
            "time_unix": time.time(),
            "clock": time.perf_counter(),
            "config": self.config,
            "config_fingerprint": config_fingerprint(self.config),
            "spans": [[p, lane, name, t0, t1, args]
                      for p, lane, name, t0, t1, args in spans],
            "spans_recorded": rec.spans_recorded,
            "events_dropped": rec.events_dropped,
            "tracing_enabled": rec.enabled,
            **extra,
        }
        if self.snapshot_fn is not None:
            try:
                bundle["registry"] = self.snapshot_fn()
            except Exception as e:     # noqa: BLE001 — partial > none
                bundle["registry_error"] = f"{type(e).__name__}: {e}"
        if self.request_log_path:
            bundle["request_log_tail"] = self._log_tail()
        # the wall-clock millisecond stamp keeps names unique ACROSS
        # restarts: a supervisor-restarted process re-seeds _seq at 1,
        # and a seq-only name would os.replace the crashed run's
        # bundle — exactly the evidence a black box exists to keep
        fname = (f"incident-{self.process}-{cause}-"
                 f"{int(bundle['time_unix'] * 1e3)}-{seq:03d}.json")
        path = os.path.join(self.incident_dir, fname)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def _log_tail(self, max_lines: int = 50,
                  max_bytes: int = 64 * 1024) -> list[str]:
        """Last lines of the request log (bounded read — the log can be
        arbitrarily long; the bundle must not be)."""
        try:
            with open(self.request_log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                lines = f.read().decode(errors="replace").splitlines()
            return lines[-max_lines:]
        except OSError:
            return []
