"""Metrics registry: process-local counters, gauges, and histograms.

The serving and training paths each grew their own counter story —
``GenerationEngine`` kept ~15 ad-hoc ``+= 1`` ints behind ``/stats``,
the trainer logged through the JSONL sink, and the two could silently
disagree. This registry is the one source of truth both read:

- :class:`Counter` (monotonic), :class:`Gauge` (set/inc/dec), and
  :class:`Histogram` (fixed buckets, ``sum``/``count``) — the three
  Prometheus-exposable primitives (obs/prom.py renders a snapshot as
  text format).
- **One lock, atomic snapshot**: every mutation and :meth:`Registry.
  snapshot` serialize on a single re-entrant lock, so a snapshot can
  never observe a torn multi-counter invariant (e.g. ``hits + misses ==
  admissions``) — callers group related increments under
  :meth:`Registry.atomic`. This is what fixes the round-9 ``/stats``
  race where HTTP threads read engine counters mid-mutation.
- **Near-zero disabled fast path**: a disabled registry's ``inc`` /
  ``set`` / ``observe`` return after ONE attribute check — no lock, no
  allocation — so ``--metrics off`` costs one branch per site.
- **Mergeable**: snapshots of same-named metrics add cleanly
  (:func:`merge_snapshots`) — counters/histogram buckets sum, gauges
  take the last writer — the multi-registry ``/metrics`` page and any
  future multi-host aggregation ride this.

Naming convention (enforced only by discipline, documented in
DESIGN.md §14): ``<subsystem>_<what>_<unit>``, counters end ``_total``,
histograms name their unit (``_seconds``). Namespaced registries also
feed a durable process-wide {name -> ever-touched} accumulator
(:func:`process_metric_names`) so the test suite's dead-counter lint
(tests/conftest.py) can ask "which registered metrics did the whole
suite never increment?" even after the owning engines are gone.
"""

from __future__ import annotations

import bisect
import threading
import weakref
from typing import Any, Iterable

# every live registry, for process-wide introspection; weak so
# short-lived engines don't accumulate
_ALL_REGISTRIES: "weakref.WeakSet[Registry]" = weakref.WeakSet()
_ALL_LOCK = threading.Lock()

# durable name-level accumulator for the tier-1 dead-counter lint:
# registries die with their engines (weak refs above), but "was metric
# name X ever mutated anywhere in this process" must survive them.
# Only NAMESPACED registries contribute (the production serving/
# training registries carry one; throwaway unit-test registries don't,
# so probe metrics can't pollute the suite banner).
_METRIC_NAMES: dict[str, bool] = {}


def all_registries() -> list["Registry"]:
    """Every registry still alive in this process (creation order is
    not guaranteed — consumers aggregate, they don't index)."""
    with _ALL_LOCK:
        return list(_ALL_REGISTRIES)


def process_metric_names() -> dict[str, bool]:
    """{metric name -> ever mutated} across every namespaced registry
    this process created, INCLUDING ones already garbage-collected —
    the tier-1 telemetry banner's data source."""
    with _ALL_LOCK:
        return dict(_METRIC_NAMES)


# latency-shaped default: 1ms .. 60s, roughly log-spaced. Fixed at
# registration time — merging requires identical buckets, so the
# default is deliberately one-size-fits-serving-and-training.
# Buckets are CONFIGURABLE per histogram at registration
# (``registry.histogram(name, buckets=...)``); same-named histograms
# across replicas must register identical bounds or the fleet
# ``/metrics`` merge refuses loudly (merge_snapshots).
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# request-phase serving buckets, audited against measured --smoke
# latencies (round 17): queue waits and prefix-cache mounts land at
# tens-to-hundreds of µs on CPU — entirely inside DEFAULT_BUCKETS'
# first (1 ms) bucket, where every percentile query degenerates to
# "≤1ms" — so sub-millisecond bounds are added below; the 60 s top
# bound stays (nothing measured approaches it, and the load harness's
# saturation check now pins that no default-registered histogram
# overflows its top finite bucket at p99).
SERVING_LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005) + DEFAULT_BUCKETS


class _NoopCM:
    """Shared do-nothing context manager for disabled-registry
    ``atomic()`` groups."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_CM = _NoopCM()


class _Metric:
    """Shared base: name/help/touched bookkeeping. ``touched`` flips on
    the first mutation ever (even one that lands value 0) — the
    dead-counter lint's signal, distinct from "value is still 0"."""

    __slots__ = ("name", "help", "_reg", "touched")

    def __init__(self, reg: "Registry", name: str, help: str):
        self._reg = reg
        self.name = name
        self.help = help
        self.touched = False

    def _mark_touched(self) -> None:
        """First mutation only (callers guard on ``touched``): flips
        the instance flag and, for namespaced registries, the durable
        process-wide name accumulator the tier-1 lint reads."""
        self.touched = True
        if self._reg.namespace:
            _METRIC_NAMES[self.name] = True


class Counter(_Metric):
    """Monotonic counter. ``inc`` of a negative amount is a bug and
    raises (a counter that can go down is a gauge)."""

    __slots__ = ("_value",)

    def __init__(self, reg, name, help):
        super().__init__(reg, name, help)
        self._value = 0

    def inc(self, n: int | float = 1) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        if n < 0:
            raise ValueError(f"counter {self.name} inc({n}): counters "
                             "are monotonic — use a Gauge")
        with reg._lock:
            self._value += n
            if not self.touched:
                self._mark_touched()

    @property
    def value(self):
        with self._reg._lock:
            return self._value


class Gauge(_Metric):
    """Last-write-wins scalar (queue depth, live slots, free blocks)."""

    __slots__ = ("_value",)

    def __init__(self, reg, name, help):
        super().__init__(reg, name, help)
        self._value = 0

    def set(self, v: int | float) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            self._value = v
            if not self.touched:
                self._mark_touched()

    def inc(self, n: int | float = 1) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            self._value += n
            if not self.touched:
                self._mark_touched()

    def dec(self, n: int | float = 1) -> None:
        self.inc(-n)

    @property
    def value(self):
        with self._reg._lock:
            return self._value


class Histogram(_Metric):
    """Fixed-bucket histogram: cumulative bucket counts (Prometheus
    ``le`` semantics) + ``sum`` + ``count``. Buckets are immutable
    after registration — that is what makes two snapshots mergeable."""

    __slots__ = ("buckets", "_counts", "_sum", "_count")

    def __init__(self, reg, name, help, buckets: Iterable[float]):
        super().__init__(reg, name, help)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {self.name}: needs >= 1 bucket")
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)    # +1 = the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            self._counts[bisect.bisect_left(self.buckets, v)] += 1
            self._sum += v
            self._count += 1
            if not self.touched:
                self._mark_touched()

    @property
    def count(self) -> int:
        with self._reg._lock:
            return self._count


class Registry:
    """One namespace of metrics; all mutation and snapshotting
    serialize on ``_lock`` (re-entrant, so grouped updates under
    :meth:`atomic` can still call ``inc`` per metric)."""

    def __init__(self, *, enabled: bool = True, namespace: str = ""):
        self.enabled = enabled
        self.namespace = namespace
        self._lock = threading.RLock()
        self._metrics: dict[str, _Metric] = {}
        with _ALL_LOCK:
            _ALL_REGISTRIES.add(self)

    # -- registration --------------------------------------------------
    def _register(self, cls, name: str, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}, not {cls.__name__}")
                return m
            m = cls(self, name, help, **kw)
            self._metrics[name] = m
            if self.namespace:
                _METRIC_NAMES.setdefault(name, False)
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._register(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, buckets=buckets)

    # -- atomicity -----------------------------------------------------
    def atomic(self):
        """Hold the registry lock across several mutations so a
        concurrent :meth:`snapshot` sees all or none of them::

            with reg.atomic():
                admissions.inc()
                misses.inc()

        Disabled registry: a shared no-op context manager — grouped
        sites keep the one-branch-per-site cost the disabled fast
        path promises (the inner ``inc`` calls are no-ops anyway)."""
        return self._lock if self.enabled else _NOOP_CM

    # -- reading -------------------------------------------------------
    def snapshot(self) -> dict[str, dict[str, Any]]:
        """One atomic copy of every metric: ``{name: {"type": ...,
        "value"| "buckets"/"sum"/"count", "help"}}`` — plain data, safe
        to hand across threads / serialize."""
        with self._lock:
            out: dict[str, dict[str, Any]] = {}
            for name, m in self._metrics.items():
                if isinstance(m, Counter):
                    out[name] = {"type": "counter", "value": m._value,
                                 "help": m.help}
                elif isinstance(m, Gauge):
                    out[name] = {"type": "gauge", "value": m._value,
                                 "help": m.help}
                else:
                    h: Histogram = m            # type: ignore[assignment]
                    out[name] = {"type": "histogram",
                                 "buckets": list(zip(h.buckets,
                                                     h._counts[:-1])),
                                 "inf": h._counts[-1],
                                 "sum": h._sum, "count": h._count,
                                 "help": m.help}
            return out

    def lint_untouched(self) -> list[str]:
        """Names of metrics registered but NEVER mutated — the
        dead-counter signal the tier-1 telemetry banner prints. A
        counter that was inc'd to its current value of 0 does not
        count as dead (``touched`` tracks mutation, not value)."""
        with self._lock:
            return sorted(n for n, m in self._metrics.items()
                          if not m.touched)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)


def merge_snapshots(*snaps: dict[str, dict[str, Any]]
                    ) -> dict[str, dict[str, Any]]:
    """Combine snapshots (e.g. engine + batcher + trainer registries
    into one /metrics page): counters and histogram buckets/sum/count
    ADD; gauges take the later snapshot's value; a type conflict for a
    shared name is a loud error, never a silent overwrite."""
    out: dict[str, dict[str, Any]] = {}
    for snap in snaps:
        for name, rec in snap.items():
            cur = out.get(name)
            if cur is None:
                out[name] = {k: (list(v) if isinstance(v, list) else v)
                             for k, v in rec.items()}
                continue
            if cur["type"] != rec["type"]:
                raise ValueError(
                    f"metric {name!r}: cannot merge {cur['type']} with "
                    f"{rec['type']}")
            if cur["type"] == "counter":
                cur["value"] += rec["value"]
            elif cur["type"] == "gauge":
                cur["value"] = rec["value"]
            else:
                if [b for b, _ in cur["buckets"]] != \
                        [b for b, _ in rec["buckets"]]:
                    raise ValueError(
                        f"histogram {name!r}: bucket bounds differ — "
                        "snapshots are only mergeable with identical "
                        "buckets")
                cur["buckets"] = [(b, c1 + c2) for (b, c1), (_, c2)
                                  in zip(cur["buckets"], rec["buckets"])]
                cur["inf"] += rec["inf"]
                cur["sum"] += rec["sum"]
                cur["count"] += rec["count"]
    return out
