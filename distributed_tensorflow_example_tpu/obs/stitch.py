"""Fleet trace stitcher: N per-process span exports -> ONE timeline.

Per-replica tracing (obs/trace.py) stamps spans with each process's own
``time.perf_counter()`` — a monotonic clock with an ARBITRARY epoch, so
two processes' timestamps are mutually meaningless. This module is the
piece that makes a fleet request render as one Perfetto timeline
(``GET /trace/fleet`` on the router):

- :func:`estimate_offset` — the NTP-style clock-offset estimate from
  probe request/response timestamps: the router stamps ``t_send`` /
  ``t_recv`` around each health probe in ITS clock, the replica's
  ``/healthz`` body carries ``mono_now`` in THE REPLICA'S clock, and
  ``offset = mono_now - (t_send + t_recv) / 2`` (remote minus local
  midpoint) for each sample. The MEDIAN over recent samples rejects
  the occasional slow probe (whose midpoint assumption — symmetric
  network delay — is worst). Pure function of injected timestamps, so
  the unit tests need no wall-clock sleeps.
- :func:`stitch` — merge the router's own export with every replica's
  ``GET /trace/export`` payload into chrome trace-event JSON through
  the shared :class:`~.trace.ChromeTraceWriter`: the FIRST export (the
  router) anchors the timeline and renders as the top process group;
  each replica becomes its own process group with its spans corrected
  into the anchor's clock (``t_anchor = t_remote - offset``). Span
  args (request_id / trace_id / parent_id / span_id) pass through
  untouched — they are the correlation the stitched view exists for.

Export payload shape (producer: ``PredictServer.trace_export`` /
``ReplicaRouter.fleet_trace``)::

    {"process": "replica0", "clock": <perf_counter now>,
     "spans": [[process, lane, name, t0, t1, args|null], ...],
     "events_dropped": 0}

The ``process`` field wins over each span tuple's own label — the
router relabels an external replica's generic "serving" export with
its fleet-side replica name, so lane grouping matches the routing
spans' ``replica=...`` args.
"""

from __future__ import annotations

import statistics
from typing import Any, Iterable, Sequence

from .trace import ChromeTraceWriter


def estimate_offset(samples: Iterable[Sequence[float]]) -> float:
    """Median clock offset (REMOTE clock minus LOCAL clock) from
    ``(t_send, t_recv, remote_now)`` probe samples, all in seconds.
    0.0 with no samples — an unmeasured replica renders uncorrected
    rather than not at all."""
    offs = [float(r) - (float(a) + float(b)) / 2.0
            for a, b, r in samples]
    return statistics.median(offs) if offs else 0.0


def stitch(exports: Sequence[dict], *,
           offsets: dict[str, float] | None = None) -> dict[str, Any]:
    """Merge per-process span exports into one Perfetto-loadable trace.

    ``exports[0]`` is the anchor (the router: its process group renders
    on top and its clock defines the timeline); ``offsets`` maps each
    export's ``process`` name to its clock offset REMOTE-minus-anchor
    seconds (:func:`estimate_offset`; absent/0.0 = no correction).
    Every span's args ride through; the stitched metadata records the
    applied offsets so a reader can audit the correction.
    """
    offsets = offsets or {}
    corrected: list[tuple[str, str, str, float, float, dict | None]] = []
    for exp in exports:
        pname = exp.get("process", "?")
        off = float(offsets.get(pname, 0.0))
        for item in exp.get("spans", ()):
            _, lane, name, t0, t1, args = item
            corrected.append((pname, lane, name, float(t0) - off,
                              float(t1) - off, args or None))
    base = min((s[3] for s in corrected), default=0.0)
    w = ChromeTraceWriter()
    # declare process groups in EXPORT order first (router on top —
    # the writer assigns pids by first sight)
    for exp in exports:
        w.pid(exp.get("process", "?"))
    for pname, lane, name, t0, t1, args in sorted(corrected,
                                                  key=lambda s: s[3]):
        pid = w.pid(pname)
        tid = w.tid(pid, lane)
        w.complete(pid=pid, tid=tid, name=name, ts_us=(t0 - base) * 1e6,
                   dur_us=(t1 - t0) * 1e6, args=args)
    out = w.to_dict()
    out["metadata"] = {
        "processes": [e.get("process", "?") for e in exports],
        "clock_offsets_s": {p: round(float(o), 9)
                            for p, o in offsets.items()},
        "events_dropped": sum(int(e.get("events_dropped", 0))
                              for e in exports),
    }
    return out


def spans_for_trace(stitched: dict, trace_id: str) -> list[dict]:
    """Complete events of ``stitched`` whose args carry ``trace_id`` —
    the one-request slice of a fleet timeline (the offline ``--fleet``
    summary and the fleet-chaos structural assertions both read this
    way)."""
    return [e for e in stitched.get("traceEvents", ())
            if e.get("ph") == "X"
            and (e.get("args") or {}).get("trace_id") == trace_id]


def summarize_fleet(stitched: dict) -> dict[str, Any]:
    """Offline summary of a stitched export (``trace_summary.py
    --fleet``): per-process span/lane counts and busy time, the span-
    name vocabulary, and per-trace-id request groups with their end-to-
    end duration in the anchor clock."""
    xs = [e for e in stitched.get("traceEvents", ())
          if e.get("ph") == "X"]
    procs: dict[int, str] = {}
    lanes: dict[tuple[int, int], str] = {}
    for e in stitched.get("traceEvents", ()):
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            procs[e["pid"]] = e["args"]["name"]
        elif e.get("name") == "thread_name":
            lanes[(e["pid"], e["tid"])] = e["args"]["name"]
    per_proc: dict[str, dict[str, Any]] = {}
    for e in xs:
        p = procs.get(e["pid"], str(e["pid"]))
        rec = per_proc.setdefault(p, {"spans": 0, "lanes": set(),
                                      "busy_ms": 0.0})
        rec["spans"] += 1
        rec["lanes"].add(lanes.get((e["pid"], e["tid"]),
                                   str(e["tid"])))
        rec["busy_ms"] += e["dur"] / 1e3
    traces: dict[str, dict[str, Any]] = {}
    for e in xs:
        tid = (e.get("args") or {}).get("trace_id")
        if not tid:
            continue
        rec = traces.setdefault(tid, {"spans": 0, "processes": set(),
                                      "t0_us": e["ts"], "t1_us": e["ts"]})
        rec["spans"] += 1
        rec["processes"].add(procs.get(e["pid"], str(e["pid"])))
        rec["t0_us"] = min(rec["t0_us"], e["ts"])
        rec["t1_us"] = max(rec["t1_us"], e["ts"] + e["dur"])
    return {
        "processes": {
            p: {"spans": r["spans"], "lanes": sorted(r["lanes"]),
                "busy_ms": round(r["busy_ms"], 3)}
            for p, r in per_proc.items()},
        "span_names": sorted({e["name"] for e in xs}),
        "traces": {
            t: {"spans": r["spans"],
                "processes": sorted(r["processes"]),
                "duration_ms": round((r["t1_us"] - r["t0_us"]) / 1e3,
                                     3)}
            for t, r in traces.items()},
        "clock_offsets_s": (stitched.get("metadata") or {}).get(
            "clock_offsets_s", {}),
    }
