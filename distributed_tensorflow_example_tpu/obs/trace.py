"""Request-scoped tracing: span API + bounded ring-buffer recorder.

The only tracing the stack had was the *offline* xplane reducer
(utils/trace_summary.py) — good for "where did the compiled step spend
device time", useless for "where did THIS slow request spend its
800 ms" or "is the scheduler starving on prefill vs decode" on a live
server. This module is the live half:

- :func:`span` — ``with span("prefill", lane="slot0",
  request_id=rid):`` records one complete event into the process
  recorder. When tracing is off it returns a shared no-op context
  manager after a single attribute check: zero allocations, zero
  recorder calls (the overhead guard in tests/test_obs.py pins this).
- :class:`TraceRecorder` — a bounded ring buffer (oldest events drop
  first; ``events_dropped`` counts them) holding (process, lane, name,
  t0, t1, args) tuples stamped with ``time.perf_counter()``.
  ``add()`` takes explicit timestamps so retroactive spans work — the
  scheduler records a request's queue-wait AT admission, from its
  submit stamp.
- :class:`ChromeTraceWriter` — the ONE chrome/Perfetto trace-event
  emitter: process/thread name metadata ("M") events plus complete
  ("X") events in microseconds. Both this recorder's dump and
  ``trace_summary.py --chrome`` (the xplane producer) write through
  it, so the two producers can never disagree on the format.

Lanes are (process, thread) string pairs — e.g. ``("serving",
"slot3")`` or ``("training", "data")`` — mapped to stable pid/tid
integers at dump time. The scheduler gives every cache slot its own
lane so per-slot spans tile without overlapping; Perfetto renders each
as one row.

Round 17 — distributed tracing + the always-on ring:

- :class:`TraceContext` carries a W3C-``traceparent``-shaped context
  (``00-<trace_id:32hex>-<span_id:16hex>-<01|00>``) across process
  boundaries: the fleet router opens one root context per client
  request and forwards a child context per attempt; the replica
  parents its engine spans under it (``trace_id``/``parent_id`` span
  args), so the fleet stitcher (obs/stitch.py) can reassemble one
  timeline per request.
- The recorder gains **per-process drain** (:meth:`TraceRecorder.
  drain` — ``GET /trace/export`` empties only the exporting server's
  process label, so N in-process replicas sharing the ring never steal
  each other's spans) and a non-destructive :meth:`TraceRecorder.tail`
  (the flight recorder's last-N-spans bundle source).
- The flight recorder (obs/flightrec.py) runs the ring ALWAYS-ON:
  servers arm it at construction (without clearing a capture someone
  else armed) instead of waiting for ``POST /trace/start``, so an
  incident bundle always has history. The armed per-call cost is one
  lock + deque append — bounded by the same <2 µs/call guard as the
  disabled path (tests/test_obs.py).
- :func:`process_span_stats` accumulates recorded/dropped counts
  across every recorder this process ever armed — the tier-1 TRACE
  banner's data source (tests/conftest.py).
"""

from __future__ import annotations

import dataclasses
import secrets
import threading
import time
from collections import deque
from typing import Any

# process-wide span accounting for the tier-1 TRACE banner: survives
# recorder swaps (set_recorder) the way the registry's name accumulator
# survives engine teardown. Updated inside the recorder lock.
_SPAN_TOTALS = {"recorded": 0, "dropped": 0}


def process_span_stats() -> dict[str, int]:
    """{"recorded": N, "dropped": M} across every recorder this process
    armed — the TRACE line in the tier-1 telemetry banner."""
    return dict(_SPAN_TOTALS)


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One hop of a distributed trace: the ``traceparent`` triple.

    ``trace_id`` names the whole client request fleet-wide;
    ``span_id`` names the SENDER's span (the receiver's parent);
    ``sampled`` is the propagated record/don't-record decision (the
    router's ``--trace_sample`` draw — an unsampled context still
    carries the ids so logs correlate, but receivers attach no span
    args for it)."""

    trace_id: str
    span_id: str
    sampled: bool = True

    def child(self) -> "TraceContext":
        """Same trace, fresh span id — one per forward attempt."""
        return TraceContext(self.trace_id, new_span_id(), self.sampled)

    def to_traceparent(self) -> str:
        return (f"00-{self.trace_id}-{self.span_id}-"
                f"{'01' if self.sampled else '00'}")

    def span_args(self) -> dict[str, str]:
        """The args a receiver merges into spans recorded under this
        context ({} when unsampled) — trace_id groups, parent_id
        parents."""
        if not self.sampled:
            return {}
        return {"trace_id": self.trace_id, "parent_id": self.span_id}


def new_trace_id() -> str:
    return secrets.token_hex(16)


def new_span_id() -> str:
    return secrets.token_hex(8)


def parse_traceparent(header: str | None) -> TraceContext | None:
    """``traceparent`` header -> :class:`TraceContext`, or None for a
    missing/malformed value (propagation is best-effort: a garbled
    header must degrade to local-only tracing, never to a 4xx)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id,
                        sampled=bool(int(flags, 16) & 1))


class ChromeTraceWriter:
    """Builds a chrome://tracing / Perfetto trace-event JSON dict.

    Shared by the live recorder and the offline xplane converter: call
    :meth:`pid` / :meth:`tid` to name processes/threads (metadata
    events are emitted once per name) and :meth:`complete` per "X"
    event; :meth:`to_dict` yields the loadable object.
    """

    def __init__(self):
        self.events: list[dict[str, Any]] = []
        self._pids: dict[str, int] = {}
        self._tids: dict[tuple[int, str], int] = {}

    def pid(self, process_name: str) -> int:
        p = self._pids.get(process_name)
        if p is None:
            p = len(self._pids) + 1
            self._pids[process_name] = p
            self.events.append({"ph": "M", "pid": p,
                                "name": "process_name",
                                "args": {"name": process_name}})
        return p

    def tid(self, pid: int, thread_name: str) -> int:
        key = (pid, thread_name)
        t = self._tids.get(key)
        if t is None:
            t = sum(1 for (p, _) in self._tids if p == pid) + 1
            self._tids[key] = t
            self.events.append({"ph": "M", "pid": pid, "tid": t,
                                "name": "thread_name",
                                "args": {"name": thread_name}})
        return t

    def complete(self, *, pid: int, tid: int, name: str, ts_us: float,
                 dur_us: float, args: dict | None = None) -> None:
        ev: dict[str, Any] = {"ph": "X", "pid": pid, "tid": tid,
                              "name": name, "ts": ts_us,
                              # Perfetto drops true-zero durations
                              "dur": max(dur_us, 0.001)}
        if args:
            ev["args"] = args
        self.events.append(ev)

    def to_dict(self) -> dict[str, Any]:
        return {"traceEvents": self.events, "displayTimeUnit": "ms"}


class TraceRecorder:
    """Bounded in-memory span store. ``start()`` arms it and anchors
    the timebase; ``stop()`` disarms; ``to_chrome()`` dumps whatever
    the ring currently holds (callable while armed — a live snapshot).
    Thread-safe: spans arrive from scheduler/HTTP/trainer threads."""

    def __init__(self, max_events: int = 65536):
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1, got {max_events}")
        self.max_events = max_events
        self._buf: deque[tuple] = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self.enabled = False
        self._t0 = 0.0
        self.spans_recorded = 0
        self.events_dropped = 0

    def start(self) -> None:
        with self._lock:
            self._buf.clear()
            self._t0 = time.perf_counter()
            self.spans_recorded = 0
            self.events_dropped = 0
            self.enabled = True

    def stop(self) -> None:
        self.enabled = False

    def add(self, process: str, lane: str, name: str, t0: float,
            t1: float, args: dict | None = None) -> None:
        """One complete span, ``t0``/``t1`` in ``time.perf_counter()``
        seconds. Spans that began before ``start()`` are clamped to the
        capture window (a queue-wait recorded retroactively must not
        render at negative timestamps)."""
        if not self.enabled:
            return
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.events_dropped += 1
                _SPAN_TOTALS["dropped"] += 1
            self._buf.append((process, lane, name, max(t0, self._t0),
                              max(t1, self._t0), args))
            self.spans_recorded += 1
            _SPAN_TOTALS["recorded"] += 1

    def drain(self, process: str | None = None) -> list[tuple]:
        """Remove and return spans (sorted by start time) — ALL of
        them, or only one ``process`` label's. Per-process drain is the
        ``GET /trace/export`` contract: N in-process replicas share ONE
        ring (distinct labels), and each export must empty only its own
        lane group. Draining does not disarm."""
        with self._lock:
            if process is None:
                items = list(self._buf)
                self._buf.clear()
            else:
                items = [it for it in self._buf if it[0] == process]
                if items:
                    keep = [it for it in self._buf if it[0] != process]
                    self._buf.clear()
                    self._buf.extend(keep)
        return sorted(items, key=lambda it: it[3])

    def tail(self, n: int, process: str | None = None) -> list[tuple]:
        """The newest ``n`` spans (optionally one process label's),
        WITHOUT removing them — the flight recorder's bundle source
        (an incident dump must not eat the capture an operator might
        still export)."""
        with self._lock:
            items = [it for it in self._buf
                     if process is None or it[0] == process]
        items.sort(key=lambda it: it[3])
        return items[-n:] if n > 0 else []

    def to_chrome(self) -> dict[str, Any]:
        """Ring contents as chrome trace-event JSON (via the shared
        :class:`ChromeTraceWriter`). Lanes become threads; events sort
        by timestamp inside the dump so truncated rings still render
        coherently."""
        with self._lock:
            items = sorted(self._buf, key=lambda it: it[3])
            t0 = self._t0
            dropped = self.events_dropped
        w = ChromeTraceWriter()
        for process, lane, name, s, e, args in items:
            pid = w.pid(process)
            tid = w.tid(pid, lane)
            w.complete(pid=pid, tid=tid, name=name,
                       ts_us=(s - t0) * 1e6, dur_us=(e - s) * 1e6,
                       args=args)
        out = w.to_dict()
        out["metadata"] = {"events_dropped": dropped,
                           "max_events": self.max_events}
        return out


class _NoopSpan:
    """The disabled fast path: one shared instance, enter/exit do
    nothing. ``span()`` hands this back after a single enabled check —
    no allocation, no recorder traffic."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _LiveSpan:
    __slots__ = ("_rec", "_process", "_lane", "_name", "_args", "_t0")

    def __init__(self, rec, process, lane, name, args):
        self._rec = rec
        self._process = process
        self._lane = lane
        self._name = name
        self._args = args
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._rec.add(self._process, self._lane, self._name, self._t0,
                      time.perf_counter(), self._args or None)
        return False


# the process recorder: one per process, disabled until someone calls
# recorder().start() (the POST /trace/start route, a trainer with
# obs.trace_path, or a test)
_recorder = TraceRecorder()


def recorder() -> TraceRecorder:
    return _recorder


def set_recorder(rec: TraceRecorder) -> TraceRecorder:
    """Swap the process recorder (the server does this to honor
    ``--trace_buffer_events``); returns the new one."""
    global _recorder
    _recorder = rec
    return rec


def ensure_capacity(max_events: int) -> TraceRecorder:
    """Resize the process recorder to ``max_events`` — UNLESS a capture
    is currently armed (another server/trainer in this process owns it;
    swapping would silently discard its spans). The one owner of this
    check-then-swap invariant; both ``--trace_buffer_events`` call
    sites go through it. Returns the (possibly unchanged) recorder."""
    rec = _recorder
    if rec.max_events != max_events and not rec.enabled:
        return set_recorder(TraceRecorder(max_events))
    return rec


def arm_always_on(max_events: int = 65536) -> TraceRecorder:
    """The flight-recorder arming path: size the process recorder (the
    usual armed-capture guard applies) and START it — unless a capture
    is already armed, which must not be cleared out from under its
    owner (a second in-process server, or an operator's live
    ``POST /trace/start`` capture). Idempotent."""
    rec = ensure_capacity(max_events)
    if not rec.enabled:
        rec.start()
    return rec


def span(name: str, *, process: str = "serving", lane: str = "main",
         **args):
    """Context manager recording one complete event on ``(process,
    lane)``. Extra keyword args (``request_id=...``) land in the
    event's ``args`` — the request-correlation hook."""
    rec = _recorder
    if not rec.enabled:
        return _NOOP
    return _LiveSpan(rec, process, lane, name, args)


def add_span(name: str, t0: float, t1: float, *, process: str = "serving",
             lane: str = "main", **args) -> None:
    """Retroactive span with explicit perf_counter stamps (queue-wait
    is only known at admission). Same disabled fast path as
    :func:`span`."""
    rec = _recorder
    if not rec.enabled:
        return
    rec.add(process, lane, name, t0, t1, args or None)
