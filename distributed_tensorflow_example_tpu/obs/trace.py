"""Request-scoped tracing: span API + bounded ring-buffer recorder.

The only tracing the stack had was the *offline* xplane reducer
(utils/trace_summary.py) — good for "where did the compiled step spend
device time", useless for "where did THIS slow request spend its
800 ms" or "is the scheduler starving on prefill vs decode" on a live
server. This module is the live half:

- :func:`span` — ``with span("prefill", lane="slot0",
  request_id=rid):`` records one complete event into the process
  recorder. When tracing is off it returns a shared no-op context
  manager after a single attribute check: zero allocations, zero
  recorder calls (the overhead guard in tests/test_obs.py pins this).
- :class:`TraceRecorder` — a bounded ring buffer (oldest events drop
  first; ``events_dropped`` counts them) holding (process, lane, name,
  t0, t1, args) tuples stamped with ``time.perf_counter()``.
  ``add()`` takes explicit timestamps so retroactive spans work — the
  scheduler records a request's queue-wait AT admission, from its
  submit stamp.
- :class:`ChromeTraceWriter` — the ONE chrome/Perfetto trace-event
  emitter: process/thread name metadata ("M") events plus complete
  ("X") events in microseconds. Both this recorder's dump and
  ``trace_summary.py --chrome`` (the xplane producer) write through
  it, so the two producers can never disagree on the format.

Lanes are (process, thread) string pairs — e.g. ``("serving",
"slot3")`` or ``("training", "data")`` — mapped to stable pid/tid
integers at dump time. The scheduler gives every cache slot its own
lane so per-slot spans tile without overlapping; Perfetto renders each
as one row.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any


class ChromeTraceWriter:
    """Builds a chrome://tracing / Perfetto trace-event JSON dict.

    Shared by the live recorder and the offline xplane converter: call
    :meth:`pid` / :meth:`tid` to name processes/threads (metadata
    events are emitted once per name) and :meth:`complete` per "X"
    event; :meth:`to_dict` yields the loadable object.
    """

    def __init__(self):
        self.events: list[dict[str, Any]] = []
        self._pids: dict[str, int] = {}
        self._tids: dict[tuple[int, str], int] = {}

    def pid(self, process_name: str) -> int:
        p = self._pids.get(process_name)
        if p is None:
            p = len(self._pids) + 1
            self._pids[process_name] = p
            self.events.append({"ph": "M", "pid": p,
                                "name": "process_name",
                                "args": {"name": process_name}})
        return p

    def tid(self, pid: int, thread_name: str) -> int:
        key = (pid, thread_name)
        t = self._tids.get(key)
        if t is None:
            t = sum(1 for (p, _) in self._tids if p == pid) + 1
            self._tids[key] = t
            self.events.append({"ph": "M", "pid": pid, "tid": t,
                                "name": "thread_name",
                                "args": {"name": thread_name}})
        return t

    def complete(self, *, pid: int, tid: int, name: str, ts_us: float,
                 dur_us: float, args: dict | None = None) -> None:
        ev: dict[str, Any] = {"ph": "X", "pid": pid, "tid": tid,
                              "name": name, "ts": ts_us,
                              # Perfetto drops true-zero durations
                              "dur": max(dur_us, 0.001)}
        if args:
            ev["args"] = args
        self.events.append(ev)

    def to_dict(self) -> dict[str, Any]:
        return {"traceEvents": self.events, "displayTimeUnit": "ms"}


class TraceRecorder:
    """Bounded in-memory span store. ``start()`` arms it and anchors
    the timebase; ``stop()`` disarms; ``to_chrome()`` dumps whatever
    the ring currently holds (callable while armed — a live snapshot).
    Thread-safe: spans arrive from scheduler/HTTP/trainer threads."""

    def __init__(self, max_events: int = 65536):
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1, got {max_events}")
        self.max_events = max_events
        self._buf: deque[tuple] = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self.enabled = False
        self._t0 = 0.0
        self.spans_recorded = 0
        self.events_dropped = 0

    def start(self) -> None:
        with self._lock:
            self._buf.clear()
            self._t0 = time.perf_counter()
            self.spans_recorded = 0
            self.events_dropped = 0
            self.enabled = True

    def stop(self) -> None:
        self.enabled = False

    def add(self, process: str, lane: str, name: str, t0: float,
            t1: float, args: dict | None = None) -> None:
        """One complete span, ``t0``/``t1`` in ``time.perf_counter()``
        seconds. Spans that began before ``start()`` are clamped to the
        capture window (a queue-wait recorded retroactively must not
        render at negative timestamps)."""
        if not self.enabled:
            return
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.events_dropped += 1
            self._buf.append((process, lane, name, max(t0, self._t0),
                              max(t1, self._t0), args))
            self.spans_recorded += 1

    def to_chrome(self) -> dict[str, Any]:
        """Ring contents as chrome trace-event JSON (via the shared
        :class:`ChromeTraceWriter`). Lanes become threads; events sort
        by timestamp inside the dump so truncated rings still render
        coherently."""
        with self._lock:
            items = sorted(self._buf, key=lambda it: it[3])
            t0 = self._t0
            dropped = self.events_dropped
        w = ChromeTraceWriter()
        for process, lane, name, s, e, args in items:
            pid = w.pid(process)
            tid = w.tid(pid, lane)
            w.complete(pid=pid, tid=tid, name=name,
                       ts_us=(s - t0) * 1e6, dur_us=(e - s) * 1e6,
                       args=args)
        out = w.to_dict()
        out["metadata"] = {"events_dropped": dropped,
                           "max_events": self.max_events}
        return out


class _NoopSpan:
    """The disabled fast path: one shared instance, enter/exit do
    nothing. ``span()`` hands this back after a single enabled check —
    no allocation, no recorder traffic."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _LiveSpan:
    __slots__ = ("_rec", "_process", "_lane", "_name", "_args", "_t0")

    def __init__(self, rec, process, lane, name, args):
        self._rec = rec
        self._process = process
        self._lane = lane
        self._name = name
        self._args = args
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._rec.add(self._process, self._lane, self._name, self._t0,
                      time.perf_counter(), self._args or None)
        return False


# the process recorder: one per process, disabled until someone calls
# recorder().start() (the POST /trace/start route, a trainer with
# obs.trace_path, or a test)
_recorder = TraceRecorder()


def recorder() -> TraceRecorder:
    return _recorder


def set_recorder(rec: TraceRecorder) -> TraceRecorder:
    """Swap the process recorder (the server does this to honor
    ``--trace_buffer_events``); returns the new one."""
    global _recorder
    _recorder = rec
    return rec


def ensure_capacity(max_events: int) -> TraceRecorder:
    """Resize the process recorder to ``max_events`` — UNLESS a capture
    is currently armed (another server/trainer in this process owns it;
    swapping would silently discard its spans). The one owner of this
    check-then-swap invariant; both ``--trace_buffer_events`` call
    sites go through it. Returns the (possibly unchanged) recorder."""
    rec = _recorder
    if rec.max_events != max_events and not rec.enabled:
        return set_recorder(TraceRecorder(max_events))
    return rec


def span(name: str, *, process: str = "serving", lane: str = "main",
         **args):
    """Context manager recording one complete event on ``(process,
    lane)``. Extra keyword args (``request_id=...``) land in the
    event's ``args`` — the request-correlation hook."""
    rec = _recorder
    if not rec.enabled:
        return _NOOP
    return _LiveSpan(rec, process, lane, name, args)


def add_span(name: str, t0: float, t1: float, *, process: str = "serving",
             lane: str = "main", **args) -> None:
    """Retroactive span with explicit perf_counter stamps (queue-wait
    is only known at admission). Same disabled fast path as
    :func:`span`."""
    rec = _recorder
    if not rec.enabled:
        return
    rec.add(process, lane, name, t0, t1, args or None)
