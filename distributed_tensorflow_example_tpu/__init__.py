"""distributed_tensorflow_example_tpu — a TPU-native distributed training framework.

A from-scratch reimplementation of the capabilities of the classic
parameter-server distributed-TensorFlow example
(``Amano-Ginji/distributed-tensorflow-example``, see ``SURVEY.md``), designed
idiomatically for TPU hardware on JAX/XLA:

- The PS/worker gRPC topology (``tf.train.ClusterSpec`` / ``tf.train.Server``,
  SURVEY.md §2.2) becomes a :class:`~.cluster.ClusterSpec` +
  :class:`~.runtime.server.Server` parity layer that maps the legacy
  ``--job_name/--task_index`` CLI onto JAX process / TPU-slice coordinates.
- ``tf.train.SyncReplicasOptimizer``'s accumulate-N-then-apply-then-barrier
  protocol (SURVEY.md §2.2, §3.3) becomes a single jit-compiled train step in
  :mod:`~.parallel.sync_replicas` whose gradient mean rides one fused XLA
  all-reduce over ICI instead of O(params) point-to-point RecvTensor RPCs.
- Round-robin PS variable placement (``tf.train.replica_device_setter``)
  becomes :mod:`~.parallel.sharding` NamedSharding rules over a device mesh
  (replicated, fsdp-sharded, or tensor-parallel).
- ``Supervisor`` / ``MonitoredTrainingSession`` scaffolding (hooks, checkpoint
  threads, steps/sec counters) becomes :mod:`~.train.trainer` +
  :mod:`~.train.hooks` + :mod:`~.ckpt`.

Import alias convention used throughout docs and tests::

    import distributed_tensorflow_example_tpu as dtx
"""

__version__ = "0.1.0"

from . import config as config
from .cluster import ClusterSpec
from .parallel.mesh import MeshConfig, build_mesh, AxisNames
from .train.state import TrainState

__all__ = [
    "__version__",
    "config",
    "ClusterSpec",
    "MeshConfig",
    "build_mesh",
    "AxisNames",
    "TrainState",
    "Trainer",
    "get_model",
    "warm_start",
    "export_model",
    "load_servable",
]


def __getattr__(name):
    # the user-facing workflow entry points, imported lazily: eager
    # imports would pull jax model/trainer machinery into every
    # `import dtx` (e.g. bench scripts that only want config)
    if name == "Trainer":
        from .train.trainer import Trainer
        return Trainer
    if name == "get_model":
        from .models import get_model
        return get_model
    if name == "warm_start":
        from .ckpt.warm_start import warm_start
        return warm_start
    if name == "export_model":
        from .serving import export_model
        return export_model
    if name == "load_servable":
        from .serving import load_servable
        return load_servable
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
