"""CIFAR-10: binary-format parser + learnable synthetic fallback.

Real format (the ``cifar-10-batches-bin`` distribution): records of
1 label byte + 3072 pixel bytes (CHW planar R,G,B, 32x32), 10000 records
per ``data_batch_N.bin`` / ``test_batch.bin`` file. Output is NHWC float32
in [0,1] — the TPU-native conv layout.
"""

from __future__ import annotations

import os

import numpy as np

_REC = 1 + 3072
_TRAIN_FILES = [f"data_batch_{i}.bin" for i in range(1, 6)]
_TEST_FILE = "test_batch.bin"


def read_cifar_bin(path: str) -> tuple[np.ndarray, np.ndarray]:
    raw = np.fromfile(path, dtype=np.uint8)
    if raw.size % _REC:
        raise ValueError(f"{path}: size {raw.size} not a multiple of "
                         f"record size {_REC}")
    raw = raw.reshape(-1, _REC)
    labels = raw[:, 0].astype(np.int32)
    # CHW planar → NHWC
    imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return imgs.astype(np.float32) / 255.0, labels


def _reader():
    """Prefer the C++ parser (does the CHW→NHWC conversion natively); fall
    back to the numpy implementation. Identical outputs (tested)."""
    try:
        from . import native
        if native.available():
            return native.read_cifar_bin
    except Exception:
        pass
    return read_cifar_bin


def load_cifar10(data_dir: str) -> dict[str, np.ndarray]:
    # accept either the dir itself or the standard subdir name
    sub = os.path.join(data_dir, "cifar-10-batches-bin")
    root = sub if os.path.isdir(sub) else data_dir
    read = _reader()
    xs, ys = [], []
    for f in _TRAIN_FILES:
        x, y = read(os.path.join(root, f))
        xs.append(x)
        ys.append(y)
    tx, ty = np.concatenate(xs), np.concatenate(ys)
    vx, vy = read(os.path.join(root, _TEST_FILE))
    return {"train_x": tx, "train_y": ty, "test_x": vx, "test_y": vy}


def synthetic_cifar10(num_train: int = 4096, num_test: int = 512,
                      seed: int = 0, noise: float = 0.15
                      ) -> dict[str, np.ndarray]:
    """Class-conditional color-texture prototypes, 32x32x3 in [0,1]."""
    rs = np.random.RandomState(seed)
    protos = rs.rand(10, 32, 32, 3).astype(np.float32) * 0.6 + 0.2

    def draw(n, rstate):
        y = rstate.randint(0, 10, size=n).astype(np.int32)
        x = protos[y] + rstate.randn(n, 32, 32, 3).astype(np.float32) * noise
        return np.clip(x, 0.0, 1.0), y

    tx, ty = draw(num_train, rs)
    vx, vy = draw(num_test, np.random.RandomState(seed + 1))
    return {"train_x": tx, "train_y": ty, "test_x": vx, "test_y": vy}


def get_cifar10(data_dir: str | None, synthetic: bool = False,
                **synth_kw) -> dict[str, np.ndarray]:
    if data_dir and not synthetic:
        return load_cifar10(data_dir)
    return synthetic_cifar10(**synth_kw)


def augment_batch(x: np.ndarray, *, epoch: int, indices: np.ndarray,
                  seed: int, pad: int = 4) -> np.ndarray:
    """The standard CIFAR ResNet recipe (He et al.): zero-pad ``pad`` px,
    random HxW crop, horizontal flip with p=0.5.

    Determinism: each image's rng keys on (seed, epoch, its GLOBAL
    dataset index), so the augmented stream is process-count independent
    and replays bit-exactly on resume — the same contract as the
    streaming-ImageNet augmentation (data/streaming.py).
    """
    n, h, w, c = x.shape
    padded = np.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    out = np.empty_like(x)
    for j, i in enumerate(indices):
        rng = np.random.default_rng([seed, epoch, int(i)])
        dy = int(rng.integers(0, 2 * pad + 1))
        dx = int(rng.integers(0, 2 * pad + 1))
        img = padded[j, dy:dy + h, dx:dx + w]
        if rng.random() < 0.5:
            img = img[:, ::-1]
        out[j] = img
    return out


def make_augment_transform(seed: int, pad: int = 4):
    """ShardedLoader ``transform`` hook applying :func:`augment_batch`
    to the ``x`` key (labels untouched)."""
    def transform(batch, epoch, indices):
        return dict(batch, x=augment_batch(batch["x"], epoch=epoch,
                                           indices=indices, seed=seed,
                                           pad=pad))
    return transform
