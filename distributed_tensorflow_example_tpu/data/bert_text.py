"""Real-text BERT MLM pipeline: WordPiece tokenization + packing.

Closes the gap between the pre-tokenized ``.npy`` path (bert_data.py)
and raw text: point it at a text corpus plus a ``vocab.txt`` and it
produces the framework's static-shape MLM batch layout. Tokenization
uses ``transformers.BertTokenizerFast`` with the LOCAL vocab file only —
an optional dependency (like trace_summary's TF protos), never imported
on the non-text training path, and nothing is fetched from the network.

Layout expectations: ``vocab.txt`` one token per line (line number = id)
containing [PAD], [UNK], [CLS], [SEP], [MASK]; special ids are read from
the tokenizer, and random-replacement tokens during masking are drawn
from ids above the highest special id — so keep specials at the front of
the vocab (the standard layout).

Packing follows BERT pretraining: each document's token stream is
chunked into (seq_len - 2)-sized pieces, wrapped with [CLS]/[SEP], and
the final short chunk is padded. Blank lines separate documents.
"""

from __future__ import annotations

import os

import numpy as np

from .bert_data import apply_mlm_masking


def _tokenizer(vocab_file: str, do_lower_case: bool = True):
    try:
        from transformers import BertTokenizerFast
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "text tokenization needs the transformers wheel (optional "
            "dependency of the text pipeline only)") from e
    return BertTokenizerFast(vocab_file=vocab_file,
                             do_lower_case=do_lower_case)


def _iter_documents(text_path: str, exclude: str | None = None):
    """Documents from a .txt file (blank-line separated) or every *.txt
    in a directory (one document per blank-line-separated block).
    ``exclude`` drops one path — the vocab.txt living in the same corpus
    directory must never be tokenized as training text."""
    skip = os.path.abspath(exclude) if exclude else None
    paths = ([text_path] if os.path.isfile(text_path) else
             sorted(os.path.join(text_path, f)
                    for f in os.listdir(text_path) if f.endswith(".txt")))
    paths = [p for p in paths if os.path.abspath(p) != skip]
    if not paths:
        raise FileNotFoundError(
            f"no corpus .txt files under {text_path!r} (vocab.txt alone "
            "is not a corpus)")
    for p in paths:
        with open(p) as f:
            doc: list[str] = []
            for line in f:
                line = line.strip()
                if line:
                    doc.append(line)
                elif doc:
                    yield " ".join(doc)
                    doc = []
            if doc:
                yield " ".join(doc)


def tokenize_corpus(text_path: str, vocab_file: str, *,
                    seq_len: int = 128, do_lower_case: bool = True
                    ) -> tuple[np.ndarray, dict[str, int]]:
    """Tokenize + pack a text corpus -> ([N, seq_len] int32, special ids).

    Returns the packed sequences and ``{"pad", "cls", "sep", "mask",
    "unk", "vocab_size", "first_regular"}``.
    """
    tok = _tokenizer(vocab_file, do_lower_case)
    ids = {"pad": tok.pad_token_id, "cls": tok.cls_token_id,
           "sep": tok.sep_token_id, "mask": tok.mask_token_id,
           "unk": tok.unk_token_id, "vocab_size": tok.vocab_size}
    ids["first_regular"] = max(ids["pad"], ids["cls"], ids["sep"],
                               ids["mask"], ids["unk"]) + 1
    if ids["first_regular"] >= ids["vocab_size"]:
        raise ValueError(
            f"vocab.txt must place the special tokens at the FRONT: the "
            f"highest special id is {ids['first_regular'] - 1} but the "
            f"vocab has only {ids['vocab_size']} entries, leaving no "
            "regular-token range for MLM random replacement")
    body = seq_len - 2
    rows: list[np.ndarray] = []
    for doc in _iter_documents(text_path, exclude=vocab_file):
        stream = tok(doc, add_special_tokens=False)["input_ids"]
        for start in range(0, len(stream), body):
            chunk = stream[start:start + body]
            if not chunk:
                continue
            row = np.full(seq_len, ids["pad"], np.int32)
            row[0] = ids["cls"]
            row[1:1 + len(chunk)] = chunk
            row[1 + len(chunk)] = ids["sep"]
            rows.append(row)
    if not rows:
        raise ValueError(f"corpus at {text_path!r} tokenized to nothing")
    return np.stack(rows), ids


def get_bert_text_data(text_path: str, vocab_file: str, *,
                       seq_len: int = 128, max_predictions: int = 20,
                       mask_prob: float = 0.15, seed: int = 0,
                       test_fraction: float = 0.05
                       ) -> tuple[dict, dict, int]:
    """(train_arrays, eval_arrays, vocab_size) in the framework batch
    layout — the text-corpus analogue of bert_data.get_bert_data."""
    seqs, ids = tokenize_corpus(text_path, vocab_file, seq_len=seq_len)
    # deterministic split AFTER a seeded shuffle: adjacent chunks come
    # from the same document, so a tail split would skew eval
    rs = np.random.RandomState(seed)
    perm = rs.permutation(len(seqs))
    seqs = seqs[perm]
    n_test = max(1, int(len(seqs) * test_fraction)) if len(seqs) > 1 else 0
    test, train = seqs[:n_test], seqs[n_test:]
    if len(train) == 0:
        train = test                      # single-sequence corpora: smoke
    kw = dict(vocab_size=ids["vocab_size"],
              max_predictions=max_predictions, mask_prob=mask_prob,
              specials=(ids["pad"], ids["cls"], ids["sep"], ids["mask"],
                        ids["unk"]),
              pad=ids["pad"], mask=ids["mask"],
              first_regular=ids["first_regular"])
    return (apply_mlm_masking(train, seed=seed + 2, **kw),
            apply_mlm_masking(test if n_test else train,
                              seed=seed + 3, **kw),
            ids["vocab_size"])
