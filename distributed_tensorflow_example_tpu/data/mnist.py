"""MNIST: IDX-format parser + learnable synthetic fallback.

Parses the real ``train-images-idx3-ubyte``(.gz) files when a data dir is
given (format: 16-byte header ``magic, n, rows, cols`` big-endian, then
uint8 pixels; labels: 8-byte header). Without data (zero-egress sandbox),
``synthetic_mnist`` draws class-conditional Gaussian digit prototypes so a
784→100→10 MLP can actually learn — keeping the reference's
train-to-accuracy behavior testable (SURVEY.md §6 parity gate).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

_IMG_MAGIC = 2051
_LBL_MAGIC = 2049


def _open(path: str):
    if os.path.exists(path):
        return open(path, "rb")
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    raise FileNotFoundError(path)


def read_idx_images(path: str) -> np.ndarray:
    with _open(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != _IMG_MAGIC:
            raise ValueError(f"{path}: bad IDX image magic {magic}")
        buf = f.read(n * rows * cols)
    return np.frombuffer(buf, np.uint8).reshape(n, rows, cols)


def read_idx_labels(path: str) -> np.ndarray:
    with _open(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != _LBL_MAGIC:
            raise ValueError(f"{path}: bad IDX label magic {magic}")
        buf = f.read(n)
    return np.frombuffer(buf, np.uint8)


def _reader_pair(path: str):
    """Prefer the C++ parsers (data/native.py) for plain files; gzip and
    native-unavailable fall back to the numpy parsers above. Both return
    identical arrays (asserted in tests/test_native_loader.py)."""
    if os.path.exists(path):            # plain (non-.gz) file
        try:
            from . import native
            if native.available():
                return native.read_idx_images, native.read_idx_labels
        except Exception:
            pass
    return read_idx_images, read_idx_labels


def load_mnist(data_dir: str) -> dict[str, np.ndarray]:
    """Returns {'train_x','train_y','test_x','test_y'}; x in [0,1] f32
    flattened to 784 (the reference's input shape), y int32."""
    def split(img, lbl):
        ip = os.path.join(data_dir, img)
        read_imgs, read_lbls = _reader_pair(ip)
        x = read_imgs(ip)
        y = read_lbls(os.path.join(data_dir, lbl))
        return (x.reshape(len(x), -1).astype(np.float32) / 255.0,
                y.astype(np.int32))

    tx, ty = split("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    vx, vy = split("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")
    return {"train_x": tx, "train_y": ty, "test_x": vx, "test_y": vy}


def synthetic_mnist(num_train: int = 8192, num_test: int = 1024,
                    seed: int = 0, noise: float = 0.25
                    ) -> dict[str, np.ndarray]:
    """Class-conditional 'digits' with MNIST-like statistics: 10 fixed
    *sparse* stroke prototypes (~18% active pixels — real MNIST averages
    ~19% nonzero), samples = prototype + noise on active pixels, clipped to
    [0,1]. Matching the sparsity matters: it keeps input norms (and thus
    gradient scale) near real MNIST's so the reference's classic
    hyperparameters (lr≈0.5 SGD) remain stable, and the parity MLP reaches
    >0.95 accuracy."""
    rs = np.random.RandomState(seed)
    mask = (rs.rand(10, 784) < 0.18).astype(np.float32)
    protos = (mask * (0.5 + 0.5 * rs.rand(10, 784))).astype(np.float32)

    def draw(n, rstate):
        y = rstate.randint(0, 10, size=n).astype(np.int32)
        x = protos[y] + rstate.randn(n, 784).astype(np.float32) * noise \
            * (protos[y] > 0)
        return np.clip(x, 0.0, 1.0), y

    tx, ty = draw(num_train, rs)
    vx, vy = draw(num_test, np.random.RandomState(seed + 1))
    return {"train_x": tx, "train_y": ty, "test_x": vx, "test_y": vy}


def get_mnist(data_dir: str | None, synthetic: bool = False,
              **synth_kw) -> dict[str, np.ndarray]:
    """Real MNIST when ``data_dir`` is given (raising if files are missing
    — silently training on synthetic data would corrupt accuracy claims),
    synthetic otherwise."""
    if data_dir and not synthetic:
        return load_mnist(data_dir)
    return synthetic_mnist(**synth_kw)
