"""Input pipeline.

The reference fed MNIST through the legacy ``input_data.read_data_sets`` +
``mnist.train.next_batch`` feed_dict path (SURVEY.md §2.1 'Input pipeline').
Here:

- format parsers are real (IDX/CIFAR binary), pure numpy, no TF dependency;
- when no data directory is available (this sandbox has zero egress) each
  dataset has a *learnable* synthetic generator with the exact real shapes,
  so end-to-end training/accuracy tests remain meaningful;
- :class:`~.loader.ShardedLoader` does seeded shuffling, per-process
  sharding, and host-side prefetch.
"""

from .loader import Batch, ShardedLoader, make_loader
from .mnist import load_mnist, synthetic_mnist

__all__ = ["Batch", "ShardedLoader", "make_loader", "load_mnist",
           "synthetic_mnist"]
