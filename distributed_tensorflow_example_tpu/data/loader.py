"""ShardedLoader: seeded shuffling, per-process sharding, prefetch.

Replaces the reference's ``mnist.train.next_batch`` feed_dict loop and the
era's queue-runner input machinery (SURVEY.md §2.1-2.2 'Legacy queue
input'): instead of queue threads feeding a graph, the loader yields numpy
batches that the trainer places onto the mesh with a NamedSharding.

Determinism contract (SURVEY.md §7 hard-parts item 2): with the same seed,
the *global* batch sequence is identical regardless of process count — each
process materializes its contiguous slice of the global batch — which is
what makes N-chip sync training bit-comparable to 1-chip big-batch.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator

import numpy as np

Batch = dict[str, np.ndarray]


class ShardedLoader:
    """Iterates (x, y, ...) arrays as per-process batch dicts.

    Args:
      arrays: dict of equal-length numpy arrays (leading dim = examples).
      global_batch: total batch size across all processes.
      process_index/num_processes: this host's shard of each global batch.
      shuffle: reshuffle each epoch with a seed derived from (seed, epoch) —
        identical on every process, as the reference's identical graph-side
        shuffling was.
      drop_remainder: keep batches full (static shapes for jit).
      transform: optional per-batch hook ``transform(batch, epoch,
        global_indices) -> batch`` — the augmentation seam. To keep the
        determinism contract, a transform must key any randomness on
        (its own seed, epoch, global index), never on call order.
    """

    def __init__(self, arrays: Batch, global_batch: int, *,
                 process_index: int = 0, num_processes: int = 1,
                 shuffle: bool = True, seed: int = 0,
                 drop_remainder: bool = True,
                 transform: Callable[[Batch, int, np.ndarray], Batch]
                 | None = None):
        if global_batch % num_processes:
            raise ValueError(
                f"global_batch {global_batch} not divisible by "
                f"{num_processes} processes")
        self.arrays = arrays
        self.keys = sorted(arrays)
        self.n = len(arrays[self.keys[0]])
        for k in self.keys:
            if len(arrays[k]) != self.n:
                raise ValueError("array length mismatch")
        self.global_batch = global_batch
        self.local_batch = global_batch // num_processes
        self.process_index = process_index
        self.num_processes = num_processes
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.transform = transform
        self.epoch = 0

    @property
    def steps_per_epoch(self) -> int:
        return (self.n // self.global_batch if self.drop_remainder
                else -(-self.n // self.global_batch))

    def epoch_batches(self, epoch: int | None = None) -> Iterator[Batch]:
        """One epoch of per-process batches."""
        epoch = self.epoch if epoch is None else epoch
        idx = np.arange(self.n)
        if self.shuffle:
            np.random.RandomState((self.seed, epoch)).shuffle(idx)
        nb = self.steps_per_epoch
        for b in range(nb):
            g0 = b * self.global_batch
            gidx = idx[g0:g0 + self.global_batch]
            if len(gidx) < self.global_batch and self.drop_remainder:
                return
            # this process's contiguous slice of the global batch
            l0 = self.process_index * self.local_batch
            lidx = gidx[l0:l0 + self.local_batch]
            batch = {k: self.arrays[k][lidx] for k in self.keys}
            if self.transform is not None:
                batch = self.transform(batch, epoch, lidx)
            yield batch

    def __iter__(self) -> Iterator[Batch]:
        """Endless batches, advancing epochs (next_batch parity)."""
        while True:
            yield from self.epoch_batches(self.epoch)
            self.epoch += 1


class PrefetchIterator:
    """Host-side background prefetch — the queue-runner thread reborn
    (SURVEY.md §2.2 Coordinator/QueueRunner) as a bounded queue between the
    loader thread and the device feed."""

    def __init__(self, it: Iterator[Any], depth: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._it = it
        self._done = object()
        self._err: BaseException | None = None
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Blocking put that gives up when the consumer closed us —
        an abandoned iterator must not strand its producer thread on a
        full queue forever (rollback rebuilds the loader mid-run)."""
        while not self._closed:
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _run(self):
        try:
            for item in self._it:
                if not self._put(item):
                    return               # closed: stop producing
        except BaseException as e:   # propagate like Coordinator did
            self._err = e
        finally:
            self._put(self._done)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self) -> None:
        """Release the producer thread (idempotent). Pending batches are
        discarded; the thread exits at its next queue interaction."""
        self._closed = True
        try:
            while True:
                self._q.get_nowait()     # unblock a producer mid-put
        except queue.Empty:
            pass


def make_loader(arrays: Batch, global_batch: int, *, prefetch: int = 0,
                native: bool = False, start_step: int = 0,
                **kw) -> Iterator[Batch]:
    """Build a batch iterator. ``native=True`` uses the C++ loader
    (data/native.py) when the library is available — any N-array batch
    layout (ABI v2; BERT's 6-array batches included); otherwise silently
    falls back to the Python path — both yield bit-identical batch
    sequences.

    ``start_step`` fast-forwards the deterministic batch sequence so a
    restored run consumes exactly the batches an uninterrupted run would
    have (exact-resume semantics; the restore-or-init story of SURVEY.md
    §3.5 extends to the data stream). Epoch seeding makes the skip cheap:
    only the current epoch's prefix is discarded.
    """
    loader: ShardedLoader | None = None
    if native and arrays and kw.get("transform") is not None:
        import logging
        logging.getLogger("dtx.loader").info(
            "native loader bypassed: a batch transform (augmentation) "
            "needs the Python path")
    if native and arrays and kw.get("transform") is None:
        # the C++ loader slices raw arrays; a transform needs the Python
        # path (bit-identity between the two holds only untransformed)
        from . import native as native_mod
        if native_mod.available():
            kw.pop("drop_remainder", None)   # native is always drop_remainder
            kw.pop("transform", None)        # None here (guard above)
            nat = native_mod.NativeLoader(arrays, global_batch, **kw)
            it = _fast_forward(nat, iter(nat), start_step)
            from ..runtime import faults
            return faults.guard_iterator(it)   # same seam as the Python path
    loader = ShardedLoader(arrays, global_batch, **kw)
    it = _fast_forward(loader, iter(loader), start_step)
    # fault-injection seam (runtime/faults.py 'loader.next'): a bare
    # identity when no registry is installed — the production path stays
    # an unwrapped generator. Injected transient IO errors are absorbed
    # by the guard's bounded retry + exponential backoff, mirroring the
    # policy real IO gets in the streaming decode path.
    from ..runtime import faults
    it = faults.guard_iterator(it)
    return PrefetchIterator(it, prefetch) if prefetch > 0 else it


def _fast_forward(loader, it: Iterator[Batch], start_step: int
                  ) -> Iterator[Batch]:
    if start_step <= 0:
        return it
    spe = loader.steps_per_epoch
    loader.epoch = start_step // spe       # jump whole epochs for free
    skip = start_step % spe
    for _ in range(skip):                  # discard the epoch prefix
        next(it)
    return it
