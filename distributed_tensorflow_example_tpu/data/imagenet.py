"""ImageNet: folder-tree loader (PIL) + synthetic fallback.

Real layout: ``<data_dir>/{train,val}/<class_dir>/*.{JPEG,jpg,png}`` with
class dirs sorted for label assignment (the torchvision convention). Images
are resized (short side) and center-cropped to ``image_size``. Decoding is
host-side PIL — adequate for fine-tune-scale runs; the C++ native loader
path is the place for a decode pipeline if profiling demands it.

Synthetic: ImageNet-shaped (224x224x3, 1000 classes) class-conditional
textures so ResNet-50 end-to-end runs and benchmarks need no dataset.
"""

from __future__ import annotations

import os

import numpy as np

_EXTS = (".jpeg", ".jpg", ".png")


def _list_classes(split_dir: str) -> list[str]:
    return sorted(d for d in os.listdir(split_dir)
                  if os.path.isdir(os.path.join(split_dir, d)))


def load_imagenet_folder(data_dir: str, split: str = "train", *,
                         image_size: int = 224,
                         max_per_class: int | None = None
                         ) -> dict[str, np.ndarray]:
    """Eagerly decodes a folder tree into arrays. Use ``max_per_class`` to
    bound memory (full ImageNet does not fit in host RAM as float32)."""
    try:
        from PIL import Image
    except ImportError as e:                      # pragma: no cover
        raise RuntimeError("PIL is required for real ImageNet decoding") from e

    split_dir = os.path.join(data_dir, split)
    classes = _list_classes(split_dir)
    if not classes:
        raise FileNotFoundError(f"no class dirs under {split_dir}")
    xs, ys = [], []
    for label, cls in enumerate(classes):
        cdir = os.path.join(split_dir, cls)
        files = sorted(f for f in os.listdir(cdir)
                       if f.lower().endswith(_EXTS))
        if max_per_class:
            files = files[:max_per_class]
        for f in files:
            img = Image.open(os.path.join(cdir, f)).convert("RGB")
            w, h = img.size
            scale = image_size / min(w, h)
            img = img.resize((round(w * scale), round(h * scale)))
            w, h = img.size
            left, top = (w - image_size) // 2, (h - image_size) // 2
            img = img.crop((left, top, left + image_size, top + image_size))
            xs.append(np.asarray(img, np.float32) / 255.0)
            ys.append(label)
    return {f"{split}_x": np.stack(xs),
            f"{split}_y": np.asarray(ys, np.int32)}


def synthetic_imagenet(num_train: int = 512, num_test: int = 128,
                       num_classes: int = 1000, image_size: int = 224,
                       seed: int = 0, noise: float = 0.1
                       ) -> dict[str, np.ndarray]:
    """ImageNet-shaped synthetic data. Prototypes are low-res textures
    upsampled to full size (keeps the generator's memory footprint small
    while remaining class-separable)."""
    rs = np.random.RandomState(seed)
    small = rs.rand(num_classes, 16, 16, 3).astype(np.float32)
    reps = image_size // 16

    def draw(n, rstate):
        y = rstate.randint(0, num_classes, size=n).astype(np.int32)
        proto = np.repeat(np.repeat(small[y], reps, axis=1), reps, axis=2)
        x = proto + rstate.randn(*proto.shape).astype(np.float32) * noise
        return np.clip(x, 0.0, 1.0), y

    tx, ty = draw(num_train, rs)
    vx, vy = draw(num_test, np.random.RandomState(seed + 1))
    return {"train_x": tx, "train_y": ty, "test_x": vx, "test_y": vy}


def get_imagenet(data_dir: str | None, synthetic: bool = False,
                 max_per_class: int | None = None,
                 **synth_kw) -> dict[str, np.ndarray]:
    """``max_per_class`` bounds the eager decode — full ImageNet as float32
    host arrays is ~770 GB, so pass a bound (CLI: ``--max_per_class``) for
    anything beyond fine-tune scale. No silent default cap: truncating the
    dataset without the user asking would corrupt accuracy comparisons. A
    streaming decode path belongs to the native loader."""
    if data_dir and not synthetic:
        train = load_imagenet_folder(data_dir, "train",
                                     max_per_class=max_per_class)
        # never cap val: eval numbers must be comparable across runs with
        # different train caps (val is ~50/class — no memory pressure)
        val = load_imagenet_folder(data_dir, "val")
        return {"train_x": train["train_x"], "train_y": train["train_y"],
                "test_x": val["val_x"], "test_y": val["val_y"]}
    return synthetic_imagenet(**synth_kw)
