"""ImageNet: folder-tree loader (PIL) + synthetic fallback.

Real layout: ``<data_dir>/{train,val}/<class_dir>/*.{JPEG,jpg,png}`` with
class dirs sorted for label assignment (the torchvision convention). Images
are resized (short side) and center-cropped to ``image_size``. Decoding is
host-side PIL — adequate for fine-tune-scale runs; the C++ native loader
path is the place for a decode pipeline if profiling demands it.

Synthetic: ImageNet-shaped (224x224x3, 1000 classes) class-conditional
textures so ResNet-50 end-to-end runs and benchmarks need no dataset.
"""

from __future__ import annotations

import math
import os

import numpy as np

_EXTS = (".jpeg", ".jpg", ".png")


def _list_classes(split_dir: str) -> list[str]:
    return sorted(d for d in os.listdir(split_dir)
                  if os.path.isdir(os.path.join(split_dir, d)))


def _open_image(src):
    """PIL open from a path OR encoded bytes (TFRecord 'image/encoded'
    features decode through the same routine as files)."""
    import io

    from PIL import Image
    if isinstance(src, (bytes, bytearray, memoryview)):
        return Image.open(io.BytesIO(src))
    return Image.open(src)


def decode_image(path: str, image_size: int, *,
                 fast: bool = False) -> np.ndarray:
    """Decode + short-side resize + center crop -> [S,S,3] f32 in [0,1].
    The one decode routine shared by the eager loader and the streaming
    pipeline so both produce bit-identical pixels (with ``fast=False``).
    ``path`` may also be the encoded image bytes (TFRecord path).

    ``fast=True`` enables JPEG DCT-domain downscaling (``Image.draft``):
    libjpeg decodes at 1/2–1/8 scale directly when the source is much
    larger than the target — measured 1.9× decode throughput at 224 from
    1024×768 sources for a ~0.016 mean-pixel deviation. Opt-in because
    the pixel stream differs from the plain decode.
    """
    img = _open_image(path)
    if fast:
        img.draft("RGB", (image_size, image_size))
    img = img.convert("RGB")
    w, h = img.size
    scale = image_size / min(w, h)
    img = img.resize((round(w * scale), round(h * scale)))
    w, h = img.size
    left, top = (w - image_size) // 2, (h - image_size) // 2
    img = img.crop((left, top, left + image_size, top + image_size))
    return np.asarray(img, np.float32) / 255.0


def augment_image(path: str, image_size: int,
                  rng: np.random.Generator, *,
                  fast: bool = False) -> np.ndarray:
    """Training augmentation: random-resized crop (scale 0.08–1.0, ratio
    3/4–4/3 — the standard ResNet ImageNet recipe) + horizontal flip,
    -> [S,S,3] f32 in [0,1].

    Determinism: the caller derives ``rng`` from (seed, epoch, global
    image index), so the augmented pixel stream is independent of process
    count and batch composition, and exact-resume replays it bit-exactly.
    ``path`` may also be the encoded image bytes (TFRecord path).
    """
    img = _open_image(path)
    if fast:
        # DCT-scale decode — but conservatively: random-resized crop may
        # take as little as 8% of the area (a 0.283x-short-side window),
        # so draft to ~4x the target to keep even the smallest crop at or
        # above native target resolution (no systematic upsample blur).
        # Draft therefore engages only for very large sources here; the
        # big win stays on the plain-decode path. Crop geometry uses the
        # drafted size — deterministic per (seed, epoch, index).
        img.draft("RGB", (4 * image_size, 4 * image_size))
    img = img.convert("RGB")
    w, h = img.size
    area = float(w * h)
    crop = None
    for _ in range(10):
        target = area * rng.uniform(0.08, 1.0)
        ratio = math.exp(rng.uniform(math.log(3 / 4), math.log(4 / 3)))
        cw = int(round(math.sqrt(target * ratio)))
        ch = int(round(math.sqrt(target / ratio)))
        if 0 < cw <= w and 0 < ch <= h:
            left = int(rng.integers(0, w - cw + 1))
            top = int(rng.integers(0, h - ch + 1))
            crop = img.crop((left, top, left + cw, top + ch))
            break
    if crop is None:                       # degenerate aspect: center crop
        side = min(w, h)
        left, top = (w - side) // 2, (h - side) // 2
        crop = img.crop((left, top, left + side, top + side))
    arr = np.asarray(crop.resize((image_size, image_size)),
                     np.float32) / 255.0
    if rng.random() < 0.5:
        arr = arr[:, ::-1]
    return np.ascontiguousarray(arr)


def index_image_folder(data_dir: str, split: str = "train", *,
                       max_per_class: int | None = None
                       ) -> tuple[list[str], np.ndarray]:
    """(paths, labels) for a torchvision-layout folder tree — the cheap
    metadata pass the streaming pipeline builds on (no pixel IO)."""
    split_dir = os.path.join(data_dir, split)
    classes = _list_classes(split_dir)
    if not classes:
        raise FileNotFoundError(f"no class dirs under {split_dir}")
    paths: list[str] = []
    labels: list[int] = []
    for label, cls in enumerate(classes):
        cdir = os.path.join(split_dir, cls)
        files = sorted(f for f in os.listdir(cdir)
                       if f.lower().endswith(_EXTS))
        if max_per_class:
            files = files[:max_per_class]
        paths.extend(os.path.join(cdir, f) for f in files)
        labels.extend([label] * len(files))
    return paths, np.asarray(labels, np.int32)


def load_imagenet_folder(data_dir: str, split: str = "train", *,
                         image_size: int = 224,
                         max_per_class: int | None = None
                         ) -> dict[str, np.ndarray]:
    """Eagerly decodes a folder tree into arrays. Use ``max_per_class`` to
    bound memory (full ImageNet does not fit in host RAM as float32)."""
    try:
        from PIL import Image
    except ImportError as e:                      # pragma: no cover
        raise RuntimeError("PIL is required for real ImageNet decoding") from e

    # one file-selection pass shared with the streaming pipeline: the
    # eager/streaming bit-identity guarantee rests on indexing + decoding
    # through the same code
    paths, labels = index_image_folder(data_dir, split,
                                       max_per_class=max_per_class)
    xs = [decode_image(p, image_size) for p in paths]
    return {f"{split}_x": np.stack(xs), f"{split}_y": labels}


def load_imagenet_tfrecords(data_dir: str, split: str = "val", *,
                            image_size: int = 224,
                            max_images: int | None = None,
                            label_offset: int = 0
                            ) -> dict[str, np.ndarray]:
    """Eagerly decode image TFRecord shards (the classic
    ``validation-00000-of-00128`` distribution format) into arrays —
    the eval-split counterpart of the streaming TFRecord pipeline.
    Records are ``tf.train.Example`` with ``image/encoded`` +
    ``image/class/label``; ``label_offset`` must match the train
    side's (tf-slim shards are 1-indexed: pass -1)."""
    from .tfrecord import (decode_example, extract_image_label,
                           split_shards, tfrecord_iterator)
    shards = split_shards(data_dir, split)
    if not shards:
        raise FileNotFoundError(
            f"no {split} TFRecord shards under {data_dir!r}")
    xs, ys = [], []
    for path in shards:
        for rec in tfrecord_iterator(path):
            img, label = extract_image_label(decode_example(rec))
            xs.append(decode_image(img, image_size))
            ys.append(label + label_offset)
            if max_images is not None and len(xs) >= max_images:
                break
        if max_images is not None and len(xs) >= max_images:
            break
    return {f"{split}_x": np.stack(xs),
            f"{split}_y": np.asarray(ys, np.int32)}


def synthetic_imagenet(num_train: int = 512, num_test: int = 128,
                       num_classes: int = 1000, image_size: int = 224,
                       seed: int = 0, noise: float = 0.1
                       ) -> dict[str, np.ndarray]:
    """ImageNet-shaped synthetic data. Prototypes are low-res textures
    upsampled to full size (keeps the generator's memory footprint small
    while remaining class-separable)."""
    rs = np.random.RandomState(seed)
    small = rs.rand(num_classes, 16, 16, 3).astype(np.float32)
    reps = image_size // 16

    def draw(n, rstate):
        y = rstate.randint(0, num_classes, size=n).astype(np.int32)
        proto = np.repeat(np.repeat(small[y], reps, axis=1), reps, axis=2)
        x = proto + rstate.randn(*proto.shape).astype(np.float32) * noise
        return np.clip(x, 0.0, 1.0), y

    tx, ty = draw(num_train, rs)
    vx, vy = draw(num_test, np.random.RandomState(seed + 1))
    return {"train_x": tx, "train_y": ty, "test_x": vx, "test_y": vy}


def get_imagenet(data_dir: str | None, synthetic: bool = False,
                 max_per_class: int | None = None,
                 **synth_kw) -> dict[str, np.ndarray]:
    """``max_per_class`` bounds the eager decode — full ImageNet as float32
    host arrays is ~770 GB, so pass a bound (CLI: ``--max_per_class``) for
    anything beyond fine-tune scale. No silent default cap: truncating the
    dataset without the user asking would corrupt accuracy comparisons. A
    streaming decode path belongs to the native loader."""
    if data_dir and not synthetic:
        train = load_imagenet_folder(data_dir, "train",
                                     max_per_class=max_per_class)
        # never cap val: eval numbers must be comparable across runs with
        # different train caps (val is ~50/class — no memory pressure)
        val = load_imagenet_folder(data_dir, "val")
        return {"train_x": train["train_x"], "train_y": train["train_y"],
                "test_x": val["val_x"], "test_y": val["val_y"]}
    return synthetic_imagenet(**synth_kw)
