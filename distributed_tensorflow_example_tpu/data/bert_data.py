"""BERT MLM data pipeline: masking + synthetic corpus + pre-tokenized files.

Produces the static-shape batch layout BERT-style TPU pretraining uses
(fixed ``max_predictions`` masked slots per sequence):

    input_ids, token_type_ids, attention_mask: [N, S] int32
    masked_positions, masked_labels: [N, M] int32; masked_weights: [N, M] f32

Masking follows the canonical BERT recipe: 15% of positions chosen, of
which 80% → [MASK], 10% → random token, 10% kept. Special ids follow the
bert-base-uncased convention ([PAD]=0, [CLS]=101, [SEP]=102, [MASK]=103).

Real data: a directory containing ``tokens.npy`` (or ``train.npy`` +
``test.npy``) of shape [N, S] int32 pre-tokenized sequences — tokenization
itself is out of scope for the training framework (zero-egress sandboxes
have no vocab files).

Synthetic corpus: Zipf-distributed tokens with deterministic bigram
structure, so MLM training has real signal (a masked token is predictable
from its neighbors) and loss curves behave qualitatively like natural text.
"""

from __future__ import annotations

import os

import numpy as np

PAD, CLS, SEP, MASK = 0, 101, 102, 103
_SPECIALS = (PAD, CLS, SEP, MASK)
_FIRST_REGULAR = 110            # ids below this are reserved/special


def synthetic_corpus(num_seqs: int = 2048, seq_len: int = 128,
                     vocab_size: int = 30522, seed: int = 0) -> np.ndarray:
    """[N, S] int32 token sequences with bigram structure: token t is
    followed by (t*7+11)%V with prob 0.5 else a Zipf draw — masked tokens
    are partially predictable from context."""
    rs = np.random.RandomState(seed)
    v_eff = vocab_size - _FIRST_REGULAR

    def zipf_draw(n):
        # bounded zipf over the regular-token range
        z = rs.zipf(1.3, size=n)
        return (np.minimum(z, v_eff) - 1) + _FIRST_REGULAR

    seqs = np.empty((num_seqs, seq_len), np.int32)
    seqs[:, 0] = CLS
    cur = zipf_draw(num_seqs)
    seqs[:, 1] = cur
    for j in range(2, seq_len - 1):
        follow = (cur * 7 + 11) % v_eff + _FIRST_REGULAR
        fresh = zipf_draw(num_seqs)
        take = rs.rand(num_seqs) < 0.5
        cur = np.where(take, follow, fresh).astype(np.int32)
        seqs[:, j] = cur
    seqs[:, -1] = SEP
    return seqs


def apply_mlm_masking(seqs: np.ndarray, *, vocab_size: int,
                      max_predictions: int = 20, mask_prob: float = 0.15,
                      seed: int = 0,
                      specials: tuple[int, ...] | None = None,
                      pad: int = PAD, mask: int = MASK,
                      first_regular: int = _FIRST_REGULAR
                      ) -> dict[str, np.ndarray]:
    """Canonical BERT masking → static-shape batch arrays.

    Defaults follow the bert-base-uncased id convention; a custom vocab
    (data/bert_text.py) passes its own ``specials``/``pad``/``mask`` and
    ``first_regular`` (the lowest id random-replacement tokens may use).
    """
    if specials is None:
        specials = _SPECIALS
    rs = np.random.RandomState(seed)
    n, s = seqs.shape
    m = max_predictions

    # fully vectorized (a per-row Python loop is a minutes-long startup
    # wall at pretraining scale): draw a random key per position, push
    # non-maskable positions to the back, take each row's first k sorted
    maskable = ~np.isin(seqs, specials)
    cand_counts = maskable.sum(axis=1)
    k = np.minimum.reduce([
        np.full(n, m),
        cand_counts,
        np.maximum(1, np.round(cand_counts * mask_prob).astype(np.int64)),
    ])
    k = np.where(cand_counts == 0, 0, k)      # all-PAD rows: no predictions

    keys = rs.rand(n, s) + np.where(maskable, 0.0, 10.0)
    order = np.argsort(keys, axis=1)[:, :m].astype(np.int32)   # [n, m]
    sel = np.arange(m)[None, :] < k[:, None]                    # validity
    positions = np.where(sel, order, 0).astype(np.int32)
    orig = np.take_along_axis(seqs, positions, axis=1)
    labels = np.where(sel, orig, 0).astype(np.int32)
    weights = sel.astype(np.float32)

    decide = rs.rand(n, m)
    rand_tok = rs.randint(first_regular, vocab_size, size=(n, m))
    new_tok = np.where(decide < 0.8, mask,
                       np.where(decide < 0.9, rand_tok, orig)).astype(np.int32)
    input_ids = seqs.copy()
    rows = np.broadcast_to(np.arange(n)[:, None], (n, m))[sel]
    input_ids[rows, positions[sel]] = new_tok[sel]

    return {
        "input_ids": input_ids.astype(np.int32),
        "token_type_ids": np.zeros((n, s), np.int32),
        "attention_mask": (seqs != pad).astype(np.int32),
        "masked_positions": positions,
        "masked_labels": labels,
        "masked_weights": weights,
    }


def load_tokenized(data_dir: str) -> tuple[np.ndarray, np.ndarray]:
    """Pre-tokenized [N,S] int32 arrays: train.npy + test.npy, a single
    tokens.npy split 95/5, or TFRecords of ``tf.train.Example`` records
    carrying an ``input_ids`` Int64List — the BERT
    create_pretraining_data format (``train*.tfrecord`` +
    ``test*.tfrecord``, or any ``*.tfrecord`` split 95/5)."""
    tr, te = (os.path.join(data_dir, f) for f in ("train.npy", "test.npy"))
    if os.path.exists(tr) and os.path.exists(te):
        return np.load(tr).astype(np.int32), np.load(te).astype(np.int32)
    single = os.path.join(data_dir, "tokens.npy")
    if os.path.exists(single):
        toks = np.load(single).astype(np.int32)
        cut = max(1, int(len(toks) * 0.95))
        return toks[:cut], toks[cut:]
    from .tfrecord import find_tfrecords, load_token_records
    train_recs = find_tfrecords(data_dir, "train")
    test_recs = find_tfrecords(data_dir, "test")
    if train_recs and test_recs:
        return (load_token_records(train_recs),
                load_token_records(test_recs))
    any_recs = find_tfrecords(data_dir)
    if any_recs:
        toks = load_token_records(any_recs)
        cut = max(1, int(len(toks) * 0.95))
        return toks[:cut], toks[cut:]
    raise FileNotFoundError(
        f"no train.npy/test.npy, tokens.npy, or *.tfrecord under "
        f"{data_dir!r}")


def _load_seqs(data_dir, seq_len, vocab_size, synthetic,
               num_train, num_test, seed):
    """Shared token-source resolution for the MLM and causal-LM
    pipelines: pre-tokenized files (truncated to seq_len with a warning
    — the file's full length would be a quadratically costlier workload
    than asked for) or the synthetic corpus."""
    if data_dir and not synthetic:
        train_seqs, test_seqs = load_tokenized(data_dir)
        if train_seqs.shape[1] > seq_len:
            import logging
            logging.getLogger("dtx.data").warning(
                "truncating pre-tokenized sequences from %d to seq_len=%d",
                train_seqs.shape[1], seq_len)
            train_seqs = train_seqs[:, :seq_len]
            test_seqs = test_seqs[:, :seq_len]
        return train_seqs, test_seqs
    return (synthetic_corpus(num_train, seq_len, vocab_size, seed),
            synthetic_corpus(num_test, seq_len, vocab_size, seed + 1))


def get_bert_data(data_dir: str | None, *, vocab_size: int = 30522,
                  seq_len: int = 128, max_predictions: int = 20,
                  mask_prob: float = 0.15, synthetic: bool = False,
                  num_train: int = 2048, num_test: int = 256,
                  seed: int = 0) -> tuple[dict, dict]:
    """Returns (train_arrays, eval_arrays) in the framework batch layout."""
    train_seqs, test_seqs = _load_seqs(data_dir, seq_len, vocab_size,
                                       synthetic, num_train, num_test,
                                       seed)
    kw = dict(vocab_size=vocab_size, max_predictions=max_predictions,
              mask_prob=mask_prob)
    return (apply_mlm_masking(train_seqs, seed=seed + 2, **kw),
            apply_mlm_masking(test_seqs, seed=seed + 3, **kw))


def get_lm_data(data_dir: "str | None", *, vocab_size: int = 30522,
                seq_len: int = 128, synthetic: bool = False,
                num_train: int = 2048, num_test: int = 256,
                seed: int = 0) -> "tuple[dict, dict]":
    """Causal-LM batches: the same token sources as the MLM pipeline
    (pre-tokenized ``.npy`` files or the synthetic corpus) WITHOUT
    masking — the model trains on next-token prediction, so the batch is
    just ``{input_ids, attention_mask}``. PAD positions (token 0, the
    same convention the MLM pipeline uses) are masked out: they carry no
    loss and are invisible as attention keys."""
    train_seqs, test_seqs = _load_seqs(data_dir, seq_len, vocab_size,
                                       synthetic, num_train, num_test,
                                       seed)

    def pack(seqs):
        return {"input_ids": seqs.astype(np.int32),
                "attention_mask": (seqs != PAD).astype(np.int32)}

    return pack(train_seqs), pack(test_seqs)
