"""Streaming image-folder pipeline: decode-per-batch on a thread pool.

The eager path (``imagenet.load_imagenet_folder``) decodes the whole split
up front — fine for fine-tune-scale, impossible for full ImageNet (150 GB
of f32 pixels). This module is the framework's input pipeline for that
scale, the role the reference filled with queue-runner threads feeding the
graph (SURVEY.md §2.2 Coordinator/QueueRunner, §2.1 input pipeline):

- a cheap metadata pass indexes ``(path, label)`` pairs;
- each batch's images are decoded on a thread pool (PIL releases the GIL
  in its decode/resize C paths) only when the batch is needed;
- ``PrefetchIterator`` double-buffers so the host decodes batch k+1 while
  the device trains on batch k;
- memory is bounded by ``prefetch × batch`` decoded images instead of the
  dataset size.

Determinism contract — identical to ``ShardedLoader`` (loader.py): seeded
per-epoch shuffle of the GLOBAL index, each process takes its contiguous
slice, so the global batch sequence is independent of process count and —
with ``augment=False`` — bit-identical to the eager path over the same
files (the shared ``imagenet.decode_image`` guarantees identical pixels).
``augment=True`` (random-resized crop + flip) intentionally departs from
the eager pixels but keeps every determinism property: the per-image rng
keys on (seed, epoch, global index), so the augmented stream is still
process-count independent and replays bit-exactly on resume. Exact-resume
fast-forward works through the same ``epoch``/``steps_per_epoch``
interface.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Iterator

import numpy as np

from .imagenet import augment_image, decode_image, index_image_folder
from .loader import Batch, PrefetchIterator


class StreamingImageFolder:
    """Lazily-decoded torchvision-layout image folder.

    Presents the same iteration surface as ``ShardedLoader`` (epoch
    attribute, ``steps_per_epoch``, endless ``__iter__``) so
    ``make_loader``-style fast-forward and the Trainer work unchanged.
    """

    def __init__(self, data_dir: str, split: str = "train", *,
                 image_size: int = 224,
                 max_per_class: int | None = None,
                 global_batch: int = 128,
                 process_index: int = 0, num_processes: int = 1,
                 shuffle: bool = True, seed: int = 0,
                 decode_threads: int = 8,
                 augment: bool = False,
                 fast_decode: bool = False):
        if global_batch % num_processes:
            raise ValueError(
                f"global_batch {global_batch} not divisible by "
                f"{num_processes} processes")
        self.paths, self.labels = index_image_folder(
            data_dir, split, max_per_class=max_per_class)
        self.n = len(self.paths)
        if self.n < global_batch:
            # fail fast: steps_per_epoch=0 would make __iter__ a silent
            # busy-loop and skip() a ZeroDivisionError
            raise ValueError(
                f"split {split!r} has {self.n} images < global_batch "
                f"{global_batch}")
        self.image_size = image_size
        self.global_batch = global_batch
        self.local_batch = global_batch // num_processes
        self.process_index = process_index
        self.num_processes = num_processes
        self.shuffle = shuffle
        self.seed = seed
        self.augment = augment
        self.fast_decode = fast_decode
        self.epoch = 0
        self._pool = ThreadPoolExecutor(max_workers=max(1, decode_threads))

    @property
    def steps_per_epoch(self) -> int:
        return self.n // self.global_batch      # always drop_remainder

    def _decode(self, indices: np.ndarray, epoch: int) -> Batch:
        if self.augment:
            # per-image rng from (seed, epoch, global index): the
            # augmented stream is process-count independent and replays
            # bit-exactly on resume
            def one(i):
                rng = np.random.default_rng([self.seed, epoch, int(i)])
                return augment_image(self.paths[i], self.image_size, rng,
                                     fast=self.fast_decode)
        else:
            def one(i):
                return decode_image(self.paths[i], self.image_size,
                                    fast=self.fast_decode)
        xs = list(self._pool.map(one, indices))
        return {"x": np.stack(xs), "y": self.labels[indices]}

    def epoch_batches(self, epoch: int | None = None,
                      start: int = 0) -> Iterator[Batch]:
        epoch = self.epoch if epoch is None else epoch
        idx = np.arange(self.n)
        if self.shuffle:
            np.random.RandomState((self.seed, epoch)).shuffle(idx)
        for b in range(start, self.steps_per_epoch):
            g0 = b * self.global_batch
            gidx = idx[g0:g0 + self.global_batch]
            l0 = self.process_index * self.local_batch
            yield self._decode(gidx[l0:l0 + self.local_batch], epoch)

    def skip(self, start_step: int) -> None:
        """Exact-resume fast-forward WITHOUT decoding the skipped batches
        (the eager path's _fast_forward burns a next() per skipped batch;
        here a skipped batch would cost real JPEG decodes)."""
        self.epoch = start_step // self.steps_per_epoch
        self._start_batch = start_step % self.steps_per_epoch

    _start_batch = 0

    def __iter__(self) -> Iterator[Batch]:
        start, self._start_batch = self._start_batch, 0
        while True:
            yield from self.epoch_batches(self.epoch, start=start)
            start = 0
            self.epoch += 1

    def close(self) -> None:
        self._pool.shutdown(wait=False)


class StreamingSource:
    """Trainer-pluggable data source (duck-typed alternative to the
    batch-keyed numpy dict): the Trainer calls :meth:`make_loader` with its
    sharding coordinates instead of wrapping arrays in a ShardedLoader."""

    def __init__(self, data_dir: str, split: str = "train", *,
                 image_size: int = 224, max_per_class: int | None = None,
                 prefetch: int = 2, decode_threads: int = 8,
                 augment: bool = False, fast_decode: bool = False):
        self.data_dir = data_dir
        self.split = split
        self.image_size = image_size
        self.max_per_class = max_per_class
        self.prefetch = prefetch
        self.decode_threads = decode_threads
        self.augment = augment
        self.fast_decode = fast_decode
        self._folder: StreamingImageFolder | None = None

    def make_loader(self, global_batch: int, *, start_step: int = 0,
                    process_index: int = 0, num_processes: int = 1,
                    shuffle: bool = True, seed: int = 0,
                    prefetch: int | None = None, **_unused) -> Iterator[Batch]:
        if self._folder is not None:      # re-entry: release the previous
            self._folder.close()          # decode pool, don't leak it
        self._folder = StreamingImageFolder(
            self.data_dir, self.split, image_size=self.image_size,
            max_per_class=self.max_per_class, global_batch=global_batch,
            process_index=process_index, num_processes=num_processes,
            shuffle=shuffle, seed=seed, decode_threads=self.decode_threads,
            augment=self.augment, fast_decode=self.fast_decode)
        if start_step > 0:
            self._folder.skip(start_step)
        it = iter(self._folder)
        depth = self.prefetch if prefetch is None else prefetch
        return PrefetchIterator(it, depth) if depth > 0 else it

    def close(self) -> None:
        if self._folder is not None:
            self._folder.close()
