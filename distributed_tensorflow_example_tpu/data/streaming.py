"""Streaming image-folder pipeline: decode-per-batch on a thread pool.

The eager path (``imagenet.load_imagenet_folder``) decodes the whole split
up front — fine for fine-tune-scale, impossible for full ImageNet (150 GB
of f32 pixels). This module is the framework's input pipeline for that
scale, the role the reference filled with queue-runner threads feeding the
graph (SURVEY.md §2.2 Coordinator/QueueRunner, §2.1 input pipeline):

- a cheap metadata pass indexes ``(path, label)`` pairs;
- each batch's images are decoded on a thread pool (PIL releases the GIL
  in its decode/resize C paths) only when the batch is needed;
- ``PrefetchIterator`` double-buffers so the host decodes batch k+1 while
  the device trains on batch k;
- memory is bounded by ``prefetch × batch`` decoded images instead of the
  dataset size.

Determinism contract — identical to ``ShardedLoader`` (loader.py): seeded
per-epoch shuffle of the GLOBAL index, each process takes its contiguous
slice, so the global batch sequence is independent of process count and —
with ``augment=False`` — bit-identical to the eager path over the same
files (the shared ``imagenet.decode_image`` guarantees identical pixels).
``augment=True`` (random-resized crop + flip) intentionally departs from
the eager pixels but keeps every determinism property: the per-image rng
keys on (seed, epoch, global index), so the augmented stream is still
process-count independent and replays bit-exactly on resume. Exact-resume
fast-forward works through the same ``epoch``/``steps_per_epoch``
interface.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator

import numpy as np

from ..runtime import faults
from ..utils.logging import get_logger
from .imagenet import augment_image, decode_image, index_image_folder
from .loader import Batch, PrefetchIterator

log = get_logger("streaming")

#: default cap on samples skipped per epoch by the bad-image policy: a
#: handful of truncated JPEGs in a web-scale corpus is routine; hundreds
#: means the dataset (or the filesystem) is broken and the run must say so
MAX_SKIPPED_PER_EPOCH = 64


def _decode_resilient(pool: ThreadPoolExecutor, indices: np.ndarray,
                      one: Callable[[int], tuple[np.ndarray, int]],
                      *, skip_state: dict, what: str) -> Batch:
    """Decode a batch on the thread pool with the self-healing IO policy:
    each sample gets bounded retry + exponential backoff (transient IO),
    and a sample that still fails (truncated/bad image) is SKIPPED — its
    slot is refilled with another sample from the same batch (keeps the
    batch shape static for jit) — with a logged count capped per epoch
    via ``skip_state`` ({'epoch': int, 'count': int, 'total': int,
    'cap': int}). A batch with no decodable sample at all, or a blown
    cap, still raises: self-healing must not quietly train on garbage.
    """
    def attempt(i):
        try:
            return faults.retry_io(lambda: one(int(i)),
                                   what=f"{what} sample {int(i)}")
        except Exception as e:         # undecodable after retries: skip
            return e

    results = list(pool.map(attempt, indices))
    bad = [k for k, r in enumerate(results) if isinstance(r, Exception)]
    if bad:
        good = [k for k, r in enumerate(results)
                if not isinstance(r, Exception)]
        if not good:
            raise RuntimeError(
                f"{what}: every sample in the batch failed to decode "
                f"(first error: {results[bad[0]]}) — refusing to "
                "fabricate a batch")
        skip_state["count"] += len(bad)
        skip_state["total"] += len(bad)
        if skip_state["count"] > skip_state["cap"]:
            raise RuntimeError(
                f"{what}: {skip_state['count']} samples skipped this "
                f"epoch exceeds the cap {skip_state['cap']} — the "
                "dataset or filesystem is broken, not merely flaky")
        log.warning(
            "%s: skipped %d undecodable sample(s) in one batch, refilled "
            "from batch neighbors (%d skipped this epoch, %d this run): %s",
            what, len(bad), skip_state["count"], skip_state["total"],
            "; ".join(str(results[k])[:120] for k in bad[:3]))
        for n, k in enumerate(bad):
            results[k] = results[good[n % len(good)]]
    return {"x": np.stack([x for x, _ in results]),
            "y": np.asarray([y for _, y in results], np.int32)}


class StreamingImageFolder:
    """Lazily-decoded torchvision-layout image folder.

    Presents the same iteration surface as ``ShardedLoader`` (epoch
    attribute, ``steps_per_epoch``, endless ``__iter__``) so
    ``make_loader``-style fast-forward and the Trainer work unchanged.
    """

    def __init__(self, data_dir: str, split: str = "train", *,
                 image_size: int = 224,
                 max_per_class: int | None = None,
                 global_batch: int = 128,
                 process_index: int = 0, num_processes: int = 1,
                 shuffle: bool = True, seed: int = 0,
                 decode_threads: int = 8,
                 augment: bool = False,
                 fast_decode: bool = False,
                 max_skipped_per_epoch: int = MAX_SKIPPED_PER_EPOCH):
        if global_batch % num_processes:
            raise ValueError(
                f"global_batch {global_batch} not divisible by "
                f"{num_processes} processes")
        self.paths, self.labels = index_image_folder(
            data_dir, split, max_per_class=max_per_class)
        # bad-image skip policy bookkeeping (_decode_resilient contract)
        self._skip = {"epoch": 0, "count": 0, "total": 0,
                      "cap": max_skipped_per_epoch}
        self.n = len(self.paths)
        if self.n < global_batch:
            # fail fast: steps_per_epoch=0 would make __iter__ a silent
            # busy-loop and skip() a ZeroDivisionError
            raise ValueError(
                f"split {split!r} has {self.n} images < global_batch "
                f"{global_batch}")
        self.image_size = image_size
        self.global_batch = global_batch
        self.local_batch = global_batch // num_processes
        self.process_index = process_index
        self.num_processes = num_processes
        self.shuffle = shuffle
        self.seed = seed
        self.augment = augment
        self.fast_decode = fast_decode
        self.epoch = 0
        self._pool = ThreadPoolExecutor(max_workers=max(1, decode_threads))

    @property
    def steps_per_epoch(self) -> int:
        return self.n // self.global_batch      # always drop_remainder

    def _decode(self, indices: np.ndarray, epoch: int) -> Batch:
        if self.augment:
            # per-image rng from (seed, epoch, global index): the
            # augmented stream is process-count independent and replays
            # bit-exactly on resume
            def one(i):
                rng = np.random.default_rng([self.seed, epoch, int(i)])
                return (augment_image(self.paths[i], self.image_size, rng,
                                      fast=self.fast_decode),
                        int(self.labels[i]))
        else:
            def one(i):
                return (decode_image(self.paths[i], self.image_size,
                                     fast=self.fast_decode),
                        int(self.labels[i]))
        if self._skip["epoch"] != epoch:     # per-epoch skip-cap window
            self._skip.update(epoch=epoch, count=0)
        return _decode_resilient(self._pool, indices, one,
                                 skip_state=self._skip,
                                 what=f"image folder epoch {epoch}")

    def epoch_batches(self, epoch: int | None = None,
                      start: int = 0) -> Iterator[Batch]:
        epoch = self.epoch if epoch is None else epoch
        idx = np.arange(self.n)
        if self.shuffle:
            np.random.RandomState((self.seed, epoch)).shuffle(idx)
        for b in range(start, self.steps_per_epoch):
            g0 = b * self.global_batch
            gidx = idx[g0:g0 + self.global_batch]
            l0 = self.process_index * self.local_batch
            yield self._decode(gidx[l0:l0 + self.local_batch], epoch)

    def skip(self, start_step: int) -> None:
        """Exact-resume fast-forward WITHOUT decoding the skipped batches
        (the eager path's _fast_forward burns a next() per skipped batch;
        here a skipped batch would cost real JPEG decodes)."""
        self.epoch = start_step // self.steps_per_epoch
        self._start_batch = start_step % self.steps_per_epoch

    _start_batch = 0

    def __iter__(self) -> Iterator[Batch]:
        start, self._start_batch = self._start_batch, 0
        while True:
            yield from self.epoch_batches(self.epoch, start=start)
            start = 0
            self.epoch += 1

    def close(self) -> None:
        self._pool.shutdown(wait=False)


class StreamingTFRecordImages:
    """Lazily-decoded image TFRecord shards — the classic
    ``train-00000-of-01024`` ImageNet distribution format: records are
    ``tf.train.Example`` with ``image/encoded`` (JPEG bytes) and
    ``image/class/label``. Same iteration surface and determinism
    contract as :class:`StreamingImageFolder`.

    The startup index pass reads only record OFFSETS (the C++ scanner
    when built — no Python per record, no payload parse); labels arrive
    with each batch's record reads. Random access over the shard set
    gives the same seeded global shuffle as the folder pipeline —
    no shuffle-buffer approximation.
    """

    #: per-thread cap on cached shard handles: with 1024 shards and a
    #: global shuffle every thread would otherwise accumulate a handle
    #: per shard and blow the FD limit mid-epoch
    MAX_OPEN_PER_THREAD = 16

    def __init__(self, data_dir: str, split: str = "train", *,
                 image_size: int = 224,
                 global_batch: int = 128,
                 process_index: int = 0, num_processes: int = 1,
                 shuffle: bool = True, seed: int = 0,
                 decode_threads: int = 8,
                 augment: bool = False,
                 fast_decode: bool = False,
                 label_offset: int = 0,
                 max_skipped_per_epoch: int = MAX_SKIPPED_PER_EPOCH):
        if global_batch % num_processes:
            raise ValueError(
                f"global_batch {global_batch} not divisible by "
                f"{num_processes} processes")
        self._skip = {"epoch": 0, "count": 0, "total": 0,
                      "cap": max_skipped_per_epoch}
        from .tfrecord import split_shards
        self.shards = split_shards(data_dir, split)
        if not self.shards:
            raise FileNotFoundError(
                f"no {split} TFRecord shards under {data_dir!r}")
        self._offsets: list[np.ndarray] = []
        self._lengths: list[np.ndarray] = []
        shard_ids = []
        slots = []
        for si, path in enumerate(self.shards):
            offs, lens = _shard_index(path)
            self._offsets.append(offs)
            self._lengths.append(lens)
            shard_ids.append(np.full(len(offs), si, np.int32))
            slots.append(np.arange(len(offs), dtype=np.int64))
        self._shard_of = np.concatenate(shard_ids)
        self._slot_of = np.concatenate(slots)
        self.n = len(self._shard_of)
        if self.n < global_batch:
            raise ValueError(
                f"split {split!r} has {self.n} records < global_batch "
                f"{global_batch}")
        self.image_size = image_size
        self.global_batch = global_batch
        self.local_batch = global_batch // num_processes
        self.process_index = process_index
        self.num_processes = num_processes
        self.shuffle = shuffle
        self.seed = seed
        self.augment = augment
        self.fast_decode = fast_decode
        self.label_offset = label_offset
        self.epoch = 0
        self._pool = ThreadPoolExecutor(max_workers=max(1, decode_threads))
        import threading
        self._tls = threading.local()     # per-thread LRU of shard handles
        self._open_lock = threading.Lock()
        self._open_files: "list" = []     # all live handles, for close()

    @property
    def steps_per_epoch(self) -> int:
        return self.n // self.global_batch

    def _read_record(self, i: int) -> bytes:
        from collections import OrderedDict
        si = int(self._shard_of[i])
        slot = int(self._slot_of[i])
        files = getattr(self._tls, "files", None)
        if files is None:
            files = self._tls.files = OrderedDict()
        f = files.get(si)
        if f is None:
            f = open(self.shards[si], "rb")
            files[si] = f
            with self._open_lock:
                self._open_files.append(f)
            if len(files) > self.MAX_OPEN_PER_THREAD:
                _, victim = files.popitem(last=False)
                with self._open_lock:
                    if victim in self._open_files:
                        self._open_files.remove(victim)
                victim.close()
        else:
            files.move_to_end(si)
        f.seek(int(self._offsets[si][slot]))
        return f.read(int(self._lengths[si][slot]))

    def _example(self, i: int):
        from .tfrecord import decode_example, extract_image_label
        img, label = extract_image_label(
            decode_example(self._read_record(i)))
        return img, label + self.label_offset

    def _decode(self, indices: np.ndarray, epoch: int) -> Batch:
        def one(i):
            img_bytes, label = self._example(int(i))
            if self.augment:
                rng = np.random.default_rng([self.seed, epoch, int(i)])
                x = augment_image(img_bytes, self.image_size, rng,
                                  fast=self.fast_decode)
            else:
                x = decode_image(img_bytes, self.image_size,
                                 fast=self.fast_decode)
            return x, label

        if self._skip["epoch"] != epoch:     # per-epoch skip-cap window
            self._skip.update(epoch=epoch, count=0)
        return _decode_resilient(self._pool, indices, one,
                                 skip_state=self._skip,
                                 what=f"tfrecord stream epoch {epoch}")

    def epoch_batches(self, epoch: int | None = None,
                      start: int = 0) -> Iterator[Batch]:
        epoch = self.epoch if epoch is None else epoch
        idx = np.arange(self.n)
        if self.shuffle:
            np.random.RandomState((self.seed, epoch)).shuffle(idx)
        for b in range(start, self.steps_per_epoch):
            g0 = b * self.global_batch
            gidx = idx[g0:g0 + self.global_batch]
            l0 = self.process_index * self.local_batch
            yield self._decode(gidx[l0:l0 + self.local_batch], epoch)

    def skip(self, start_step: int) -> None:
        self.epoch = start_step // self.steps_per_epoch
        self._start_batch = start_step % self.steps_per_epoch

    _start_batch = 0

    def __iter__(self) -> Iterator[Batch]:
        start, self._start_batch = self._start_batch, 0
        while True:
            yield from self.epoch_batches(self.epoch, start=start)
            start = 0
            self.epoch += 1

    def close(self) -> None:
        self._pool.shutdown(wait=True)
        with self._open_lock:
            for f in self._open_files:
                f.close()
            self._open_files.clear()


def _shard_index(path: str):
    """(data_offsets, data_lengths) for one shard: the C++ scanner when
    built, else a pure-Python header scan — both seek past payloads, so
    indexing cost scales with record count, not dataset bytes."""
    from . import native
    if native.available():
        # gzip-rejecting; verify=True: the one full pass over the
        # bytes is the startup index scan — C++ CRC off the GIL makes
        # corruption detection effectively free here (ADVICE r3 #1)
        return native.tfrecord_index(path, verify=True)
    from .tfrecord import index_record_offsets
    return index_record_offsets(path)         # gzip-rejecting


class StreamingSource:
    """Trainer-pluggable data source (duck-typed alternative to the
    batch-keyed numpy dict): the Trainer calls :meth:`make_loader` with its
    sharding coordinates instead of wrapping arrays in a ShardedLoader.

    Backed by an image-folder tree OR TFRecord shards — auto-detected
    from the directory contents (``{split}*.tfrecord`` present wins).
    """

    def __init__(self, data_dir: str, split: str = "train", *,
                 image_size: int = 224, max_per_class: int | None = None,
                 prefetch: int = 2, decode_threads: int = 8,
                 augment: bool = False, fast_decode: bool = False,
                 label_offset: int = 0):
        from .tfrecord import split_shards
        self.data_dir = data_dir
        self.split = split
        self.image_size = image_size
        self.max_per_class = max_per_class
        self.prefetch = prefetch
        self.decode_threads = decode_threads
        self.augment = augment
        self.fast_decode = fast_decode
        self.label_offset = label_offset
        self.tfrecords = bool(split_shards(data_dir, split))
        self._folder = None    # StreamingImageFolder | StreamingTFRecordImages

    def make_loader(self, global_batch: int, *, start_step: int = 0,
                    process_index: int = 0, num_processes: int = 1,
                    shuffle: bool = True, seed: int = 0,
                    prefetch: int | None = None, **_unused) -> Iterator[Batch]:
        if self._folder is not None:      # re-entry: release the previous
            self._folder.close()          # decode pool, don't leak it
        if self.tfrecords:
            if self.max_per_class is not None:
                raise ValueError(
                    "--max_per_class applies to the folder pipeline; "
                    "TFRecord shards carry no class layout to cap")
            self._folder = StreamingTFRecordImages(
                self.data_dir, self.split, image_size=self.image_size,
                global_batch=global_batch,
                process_index=process_index, num_processes=num_processes,
                shuffle=shuffle, seed=seed,
                decode_threads=self.decode_threads,
                augment=self.augment, fast_decode=self.fast_decode,
                label_offset=self.label_offset)
        else:
            if self.label_offset:
                raise ValueError(
                    "label_offset is a TFRecord-shard knob (tf-slim "
                    "1-indexed labels); the folder tree derives labels "
                    "from directory order")
            self._folder = StreamingImageFolder(
                self.data_dir, self.split, image_size=self.image_size,
                max_per_class=self.max_per_class, global_batch=global_batch,
                process_index=process_index, num_processes=num_processes,
                shuffle=shuffle, seed=seed,
                decode_threads=self.decode_threads,
                augment=self.augment, fast_decode=self.fast_decode)
        if start_step > 0:
            self._folder.skip(start_step)
        # same fault seam as make_loader: identity when injection is inert
        it = faults.guard_iterator(iter(self._folder))
        depth = self.prefetch if prefetch is None else prefetch
        return PrefetchIterator(it, depth) if depth > 0 else it

    def close(self) -> None:
        if self._folder is not None:
            self._folder.close()
