"""TFRecord container + tf.train.Example codec (the reference era's
storage format).

The reference stack's input files were TFRecords of serialized
``tf.train.Example`` protos, written by ``tf.python_io.TFRecordWriter``
and consumed through the queue-runner input pipeline (SURVEY.md §2.2
'Legacy queue input'); BERT-style pretraining data ships the same way.
This module implements both layers without a TensorFlow or protobuf
dependency:

- the record framing (u64le length | masked crc32c | data | masked
  crc32c) with CRC-32C in C++ when the native library is available
  (data/_native/dataloader.cpp dl_crc32c / dl_tfrecord_index) and a
  pure-Python table fallback otherwise;
- a hand-rolled wire-format codec for the fixed ``Example`` schema
  (Features → map<string, Feature> → Bytes/Float/Int64List), accepting
  both packed and unpacked repeated encodings.

Format compatibility with the real TensorFlow implementations is
asserted by oracle tests against the installed TF wheel
(tests/test_tfrecord.py).
"""

from __future__ import annotations

import os
import re
import struct
from typing import Any, Iterator

import numpy as np

from . import native

# ---------------------------------------------------------------------------
# CRC-32C + record masking
# ---------------------------------------------------------------------------

_CRC_TABLE: np.ndarray | None = None


def _crc_table() -> np.ndarray:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = np.empty(256, np.uint32)
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table[i] = c
        _CRC_TABLE = table
    return _CRC_TABLE


def _crc32c_py(data: bytes) -> int:
    table = _crc_table()
    c = 0xFFFFFFFF
    for b in data:
        c = (c >> 8) ^ int(table[(c ^ b) & 0xFF])
    return c ^ 0xFFFFFFFF


def crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli). C++ slicing-by-8 when available."""
    if native.available():
        return native.crc32c(data)
    return _crc32c_py(data)


def masked_crc32c(data: bytes) -> int:
    """The TFRecord CRC mask: rotr(crc, 15) + 0xa282ead8 (avoids CRCs of
    CRC-bearing data looking valid)."""
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Record framing
# ---------------------------------------------------------------------------


class TFRecordWriter:
    """``tf.python_io.TFRecordWriter`` parity: append framed records.

    >>> with TFRecordWriter(path) as w:
    ...     w.write(example_bytes)
    """

    def __init__(self, path: str):
        self._f = open(path, "wb")

    def write(self, record: bytes) -> None:
        header = struct.pack("<Q", len(record))
        self._f.write(header)
        self._f.write(struct.pack("<I", masked_crc32c(header)))
        self._f.write(record)
        self._f.write(struct.pack("<I", masked_crc32c(record)))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self) -> "TFRecordWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def is_gzipped(path: str) -> bool:
    """True when the file starts with the gzip magic + deflate method
    byte (tfds/beam pipelines often ship GZIP-compressed TFRecord
    shards). Three bytes, not two: a raw TFRecord whose first record
    length happens to start 0x1f 0x8b must not be misclassified."""
    with open(path, "rb") as f:
        return f.read(3) == b"\x1f\x8b\x08"


def tfrecord_iterator(path: str, *, verify: bool = True
                      ) -> Iterator[bytes]:
    """Stream records from a TFRecord file
    (``tf.compat.v1.io.tf_record_iterator`` parity). ``verify`` (the
    default, matching the reference RecordReader's always-on masked-CRC
    validation — a silently corrupt shard must fail, not feed garbage
    into training; CRC-32C runs in C++ when the native library is
    loaded) checks both per-record CRCs and raises ValueError on
    corruption; pass ``verify=False`` as an explicit opt-out.
    GZIP-compressed files (TFRecordOptions GZIP) are detected by magic
    and streamed through decompression (sequential access only — the
    random-access/offset paths reject gzip with a clear error)."""
    if is_gzipped(path):
        import gzip
        import zlib
        try:
            with gzip.open(path, "rb") as f:
                yield from _iter_stream(f, path, verify, size=None)
        except (EOFError, gzip.BadGzipFile, zlib.error) as e:
            # one CORRUPTION contract for both paths: ValueError.
            # (No broad OSError here: a transient I/O failure must not
            # be rebranded as data corruption)
            raise ValueError(f"{path}: corrupt gzip stream ({e})") from e
        return
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        yield from _iter_stream(f, path, verify, size=size)


#: single-record sanity bound for streams with unknowable total size
_SANITY_CAP = 1 << 40


def _iter_stream(f, path: str, verify: bool,
                 size: "int | None") -> Iterator[bytes]:
    """Record framing over a readable stream. ``size`` (plain files)
    enables the huge-length bound check BEFORE read() — a corrupt
    length must be a clean ValueError, not an attempted 2^64-byte
    allocation; compressed streams have no cheap size, so reads are
    capped at a sanity bound instead."""
    pos = 0
    while True:
        header = f.read(12)
        if not header:
            return
        if len(header) != 12:
            raise ValueError(f"{path}: truncated record header")
        pos += 12
        (length,) = struct.unpack("<Q", header[:8])
        if size is not None:
            remaining = size - pos
            if remaining < 4 or length > remaining - 4:
                raise ValueError(f"{path}: truncated record data")
        elif length > _SANITY_CAP:
            raise ValueError(f"{path}: implausible record length "
                             f"{length} (corrupt stream?)")
        if verify:
            (want,) = struct.unpack("<I", header[8:12])
            if masked_crc32c(header[:8]) != want:
                raise ValueError(f"{path}: corrupt length crc")
        data = f.read(length)
        footer = f.read(4)
        if len(data) != length or len(footer) != 4:
            raise ValueError(f"{path}: truncated record data")
        pos += length + 4
        if verify:
            (want,) = struct.unpack("<I", footer)
            if masked_crc32c(data) != want:
                raise ValueError(f"{path}: corrupt data crc")
        yield data


class TFRecordFile:
    """Index-backed random access over one TFRecord file.

    The index (data offsets + lengths) is built by the C++ scanner when
    the native library is available — including CRC verification off
    the GIL — and by a Python pass otherwise.
    """

    def __init__(self, path: str, *, verify: bool = True):
        self.path = path
        if native.available():
            self._offsets, self._lengths = native.tfrecord_index(
                path, verify=verify)
        else:
            # the seek-based header scan (gzip-rejecting: random access
            # needs raw byte offsets)
            self._offsets, self._lengths = index_record_offsets(path)
            if verify:
                for _ in tfrecord_iterator(path, verify=True):
                    pass
        self._f = open(path, "rb")

    def __len__(self) -> int:
        return len(self._offsets)

    def __getitem__(self, i: int) -> bytes:
        self._f.seek(int(self._offsets[i]))
        return self._f.read(int(self._lengths[i]))

    def __iter__(self) -> Iterator[bytes]:
        for i in range(len(self)):
            yield self[i]

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "TFRecordFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# tf.train.Example wire-format codec
# ---------------------------------------------------------------------------
# Schema (proto3):
#   Example  { Features features = 1; }
#   Features { map<string, Feature> feature = 1; }
#   Feature  { oneof kind { BytesList bytes_list = 1;
#                           FloatList float_list = 2;
#                           Int64List int64_list = 3; } }
#   BytesList { repeated bytes value = 1; }
#   FloatList { repeated float value = 1; }   // packed
#   Int64List { repeated int64 value = 1; }   // packed


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _ld(field: int, payload: bytes) -> bytes:
    """Length-delimited field (wire type 2)."""
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def encode_example(features: dict[str, Any]) -> bytes:
    """Serialize a feature dict to ``tf.train.Example`` bytes.

    Value typing follows tf conventions: bytes/str → BytesList,
    float arrays → FloatList, int arrays → Int64List. Map entries are
    emitted in sorted key order (any order parses back identically).
    """
    feats = bytearray()
    for key in sorted(features):
        val = features[key]
        if isinstance(val, (bytes, str)):
            val = [val]
        arr = val if isinstance(val, (list, tuple)) else np.asarray(val)
        if isinstance(arr, (list, tuple)) and (
                not arr or isinstance(arr[0], (bytes, str))):
            # plain python lists are bytes lists — including EMPTY ones
            # (an untyped [] cannot round-trip as a numeric list; typed
            # empties arrive as numpy arrays and keep their kind)
            items = b"".join(
                _ld(1, v.encode() if isinstance(v, str) else v)
                for v in arr)
            feature = _ld(1, items)                       # bytes_list
        else:
            a = np.asarray(arr)
            if a.dtype.kind == "f":
                packed = a.astype("<f4").tobytes()
                feature = _ld(2, _ld(1, packed))          # float_list
            elif a.dtype.kind in "iu":
                packed = b"".join(
                    _varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                    for v in a.reshape(-1))
                feature = _ld(3, _ld(1, packed))          # int64_list
            else:
                raise TypeError(
                    f"unsupported feature dtype for {key!r}: {a.dtype}")
        entry = _ld(1, key.encode()) + _ld(2, feature)    # map entry
        feats += _ld(1, entry)
    return bytes(_ld(1, bytes(feats)))                    # Example.features


def _parse_fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) over a message buffer."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, pos = _read_varint(buf, pos)
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            v = buf[pos:pos + 4]
            pos += 4
        elif wt == 1:
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _to_int64(u: int) -> int:
    return u - (1 << 64) if u >= (1 << 63) else u


def _decode_feature(buf: bytes) -> Any:
    for field, wt, v in _parse_fields(buf):
        if field == 1 and wt == 2:                        # BytesList
            return [bv for f2, w2, bv in _parse_fields(v)
                    if f2 == 1 and w2 == 2]
        if field == 2:                                    # FloatList
            out: list[float] = []
            for f2, w2, fv in _parse_fields(v):
                if f2 != 1:
                    continue
                if w2 == 2:                               # packed
                    out.extend(np.frombuffer(fv, "<f4").tolist())
                elif w2 == 5:                             # unpacked
                    out.append(struct.unpack("<f", fv)[0])
            return np.asarray(out, np.float32)
        if field == 3:                                    # Int64List
            ints: list[int] = []
            for f2, w2, iv in _parse_fields(v):
                if f2 != 1:
                    continue
                if w2 == 2:                               # packed
                    pos = 0
                    while pos < len(iv):
                        u, pos = _read_varint(iv, pos)
                        ints.append(_to_int64(u))
                elif w2 == 0:                             # unpacked
                    ints.append(_to_int64(iv))
            return np.asarray(ints, np.int64)
    return None


def decode_example(data: bytes) -> dict[str, Any]:
    """Parse ``tf.train.Example`` bytes into {name: value}: BytesList →
    list[bytes], FloatList → f32 array, Int64List → i64 array."""
    out: dict[str, Any] = {}
    for field, wt, v in _parse_fields(data):
        if field != 1 or wt != 2:
            continue                                      # Example.features
        for f2, w2, entry in _parse_fields(v):
            if f2 != 1 or w2 != 2:
                continue                                  # map entry
            key = None
            val = None
            for f3, w3, ev in _parse_fields(entry):
                if f3 == 1 and w3 == 2:
                    key = ev.decode()
                elif f3 == 2 and w3 == 2:
                    val = _decode_feature(ev)
            if key is not None:
                out[key] = val
    return out


# ---------------------------------------------------------------------------
# Dataset-level helpers
# ---------------------------------------------------------------------------


def write_examples(path: str, examples: "list[dict[str, Any]]") -> None:
    """Write a list of feature dicts as one TFRecord file of Examples."""
    with TFRecordWriter(path) as w:
        for ex in examples:
            w.write(encode_example(ex))


def load_token_records(paths: "list[str]", feature: str = "input_ids",
                       *, verify: bool = True) -> np.ndarray:
    """[N, S] int32 token matrix from TFRecords of Examples — the BERT
    pretraining data format (create_pretraining_data-style files). All
    records must carry ``feature`` with one fixed length."""
    rows: list[np.ndarray] = []
    for path in sorted(paths):
        for rec in tfrecord_iterator(path, verify=verify):
            ex = decode_example(rec)
            if feature not in ex:
                raise ValueError(
                    f"{path}: record without {feature!r} feature "
                    f"(has {sorted(ex)})")
            rows.append(np.asarray(ex[feature], np.int32))
    if not rows:
        raise ValueError(f"no records in {paths}")
    lens = {len(r) for r in rows}
    if len(lens) != 1:
        raise ValueError(
            f"records disagree on {feature!r} length: {sorted(lens)}")
    return np.stack(rows)


def find_tfrecords(data_dir: str, prefix: str = "") -> "list[str]":
    """All ``{prefix}*.tfrecord`` files under data_dir, sorted."""
    try:
        names = sorted(os.listdir(data_dir))
    except OSError:
        return []
    return [os.path.join(data_dir, n) for n in names
            if n.startswith(prefix) and n.endswith(".tfrecord")]


def split_shards(data_dir: str, split: str) -> "list[str]":
    """Shard files for a dataset split. Accepts BOTH spellings in the
    wild: ``{split}*.tfrecord`` and the classic extensionless
    ``{split}-00000-of-01024`` (tf-slim/tfds ImageNet shards carry no
    suffix); the tf-slim ``validation-*`` naming satisfies ``val``."""
    def matching(prefix: str) -> "list[str]":
        try:
            names = sorted(os.listdir(data_dir))
        except OSError:
            return []
        # delimiter-or-nothing after the prefix: 'train' must not
        # sweep in 'trainer_debug.tfrecord' (ADVICE r3 #4)
        pat = re.compile(
            rf"{re.escape(prefix)}(-\d+-of-\d+(\.tfrecord)?"
            rf"|([._-].*)?\.tfrecord)$")
        return [os.path.join(data_dir, n) for n in names
                if pat.fullmatch(n)]

    shards = matching(split)
    if not shards and split == "val":
        shards = matching("validation")
    return shards


#: accepted Example feature-key spellings (tf-slim / tfds image exports)
IMAGE_KEYS = ("image/encoded", "image")
LABEL_KEYS = ("image/class/label", "label")


def extract_image_label(example: dict) -> tuple[bytes, int]:
    """(encoded image bytes, integer label) from a decoded image
    Example — the one probing helper shared by the streaming and eager
    loaders."""
    img = label = None
    for k in IMAGE_KEYS:
        if k in example:
            img = example[k][0]              # BytesList -> first entry
            break
    for k in LABEL_KEYS:
        if k in example:
            label = int(np.asarray(example[k]).reshape(-1)[0])
            break
    if img is None or label is None:
        raise ValueError(
            f"record lacks image/label features (has {sorted(example)}; "
            f"wanted one of {IMAGE_KEYS} and one of {LABEL_KEYS})")
    return img, label


def index_record_offsets(path: str) -> "tuple[np.ndarray, np.ndarray]":
    """(data_offsets, data_lengths) for a TFRecord file by header scan
    only — seeks past payloads, so indexing cost scales with record
    COUNT, not dataset bytes (the C++ scanner in data/native.py does the
    same off the GIL; this is the pure-Python fallback)."""
    if is_gzipped(path):
        raise ValueError(
            f"{path} is GZIP-compressed: offset indexing needs byte "
            "offsets; decompress the shard or use tfrecord_iterator")
    size = os.path.getsize(path)
    offs: list[int] = []
    lens: list[int] = []
    with open(path, "rb") as f:
        pos = 0
        while True:
            header = f.read(12)
            if not header:
                break
            if len(header) != 12:
                raise ValueError(f"{path}: truncated record header")
            pos += 12
            (length,) = struct.unpack("<Q", header[:8])
            remaining = size - pos
            if remaining < 4 or length > remaining - 4:
                raise ValueError(f"{path}: truncated record data")
            offs.append(pos)
            lens.append(length)
            pos += length + 4
            f.seek(pos)
    return np.asarray(offs, np.int64), np.asarray(lens, np.int64)
