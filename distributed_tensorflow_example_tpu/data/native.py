"""ctypes bindings for the C++ native loader (data/_native/dataloader.cpp).

The native library accelerates the byte-level work — format parsing, row
gather, batch assembly — in C++ threads off the GIL, while Python retains
the determinism contract: per-epoch permutations come from the same
``np.random.RandomState((seed, epoch))`` as the pure-Python ShardedLoader,
so both loaders yield bit-identical batch sequences.

The .so is built on demand with the in-tree Makefile (g++ is part of the
toolchain); every entry point degrades gracefully — ``available()`` is
False when the library can't be built/loaded and callers fall back to the
pure-Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator

import numpy as np

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native")
_SO = os.path.join(_DIR, "libdtxdata.so")
_ABI = 3

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _make() -> bool:
    """Rebuild the .so, serialized across processes: concurrent `make`s
    would rewrite the library non-atomically under a sibling's dlopen."""
    try:
        import fcntl
        with open(os.path.join(_DIR, ".build.lock"), "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            subprocess.run(["make", "-C", _DIR, "-s"], check=True,
                           capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _needs_build() -> bool:
    """Same staleness rule as the Makefile — decided WITHOUT dlopen, since
    dlopen caches by path and a stale library once loaded cannot be
    reliably replaced in-process."""
    if not os.path.exists(_SO):
        return True
    src = os.path.join(_DIR, "dataloader.cpp")
    return os.path.getmtime(_SO) < os.path.getmtime(src)


def _load() -> ctypes.CDLL | None:
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if _needs_build() and not _make():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        if lib.dl_abi_version() != _ABI:
            _build_failed = True
            return None
        # signatures
        lib.dl_create.restype = ctypes.c_void_p
        lib.dl_create.argtypes = [ctypes.POINTER(ctypes.c_void_p),
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.c_int, ctypes.c_int64,
                                  ctypes.c_int64, ctypes.c_int, ctypes.c_int]
        lib.dl_set_epoch.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_int64]
        lib.dl_acquire.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_void_p)]
        lib.dl_release.argtypes = [ctypes.c_void_p]
        lib.dl_destroy.argtypes = [ctypes.c_void_p]
        for f in ("dl_idx_image_dims", "dl_idx_read_images",
                  "dl_idx_label_count", "dl_idx_read_labels",
                  "dl_cifar_record_count", "dl_cifar_read"):
            getattr(lib, f).restype = ctypes.c_int
        # int64 sizes must be declared: ctypes' default c_int conversion
        # would truncate >=2GiB payloads on the SysV ABI
        lib.dl_idx_image_dims.argtypes = [ctypes.c_char_p, ctypes.c_void_p]
        lib.dl_idx_read_images.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                           ctypes.c_int64]
        lib.dl_idx_label_count.argtypes = [ctypes.c_char_p, ctypes.c_void_p]
        lib.dl_idx_read_labels.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                           ctypes.c_int64]
        lib.dl_cifar_record_count.argtypes = [ctypes.c_char_p,
                                              ctypes.c_void_p]
        lib.dl_cifar_read.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                      ctypes.c_void_p, ctypes.c_int64]
        lib.dl_crc32c.restype = ctypes.c_uint32
        lib.dl_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.dl_tfrecord_index.restype = ctypes.c_int64
        lib.dl_tfrecord_index.argtypes = [ctypes.c_char_p,
                                          ctypes.POINTER(ctypes.c_int64),
                                          ctypes.POINTER(ctypes.c_int64),
                                          ctypes.c_int64, ctypes.c_int]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# Native format parsers (drop-in for the numpy ones)
# ---------------------------------------------------------------------------

def read_idx_images(path: str) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    dims = (ctypes.c_int64 * 3)()
    rc = lib.dl_idx_image_dims(path.encode(), dims)
    if rc:
        raise ValueError(f"dl_idx_image_dims({path!r}) -> {rc}")
    n, r, c = dims[0], dims[1], dims[2]
    out = np.empty(n * r * c, np.uint8)
    rc = lib.dl_idx_read_images(path.encode(),
                                out.ctypes.data_as(ctypes.c_void_p), out.size)
    if rc:
        raise ValueError(f"dl_idx_read_images({path!r}) -> {rc}")
    return out.reshape(n, r, c)


def read_idx_labels(path: str) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    n = ctypes.c_int64()
    rc = lib.dl_idx_label_count(path.encode(), ctypes.byref(n))
    if rc:
        raise ValueError(f"dl_idx_label_count({path!r}) -> {rc}")
    out = np.empty(n.value, np.uint8)
    rc = lib.dl_idx_read_labels(path.encode(),
                                out.ctypes.data_as(ctypes.c_void_p), out.size)
    if rc:
        raise ValueError(f"dl_idx_read_labels({path!r}) -> {rc}")
    return out


def read_cifar_bin(path: str) -> tuple[np.ndarray, np.ndarray]:
    """NHWC float32 [n,32,32,3] in [0,1] + int32 labels, parsed in C++."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    n = ctypes.c_int64()
    rc = lib.dl_cifar_record_count(path.encode(), ctypes.byref(n))
    if rc:
        raise ValueError(f"dl_cifar_record_count({path!r}) -> {rc}")
    x = np.empty((n.value, 32, 32, 3), np.float32)
    y = np.empty(n.value, np.int32)
    rc = lib.dl_cifar_read(path.encode(),
                           x.ctypes.data_as(ctypes.c_void_p),
                           y.ctypes.data_as(ctypes.c_void_p), n.value)
    if rc:
        raise ValueError(f"dl_cifar_read({path!r}) -> {rc}")
    return x, y


def crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli) via the C++ slicing-by-8 kernel."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    return int(lib.dl_crc32c(data, len(data)))


def tfrecord_index(path: str, *, verify: bool = False
                   ) -> tuple[np.ndarray, np.ndarray]:
    """(data_offsets, data_lengths) int64 arrays for a TFRecord file,
    scanned in C++ (verify additionally checks both per-record CRCs).
    GZIP shards are rejected here — the scanner would misparse
    compressed bytes into garbage offsets."""
    from .tfrecord import is_gzipped
    if is_gzipped(path):
        raise ValueError(
            f"{path} is GZIP-compressed: offset indexing needs raw "
            "byte offsets; decompress the shard or use "
            "tfrecord_iterator (sequential)")
    lib = _load()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    n = lib.dl_tfrecord_index(path.encode(), None, None, 0,
                              1 if verify else 0)
    if n < 0:
        raise ValueError(f"dl_tfrecord_index({path!r}) -> {n}")
    offsets = np.empty(n, np.int64)
    lengths = np.empty(n, np.int64)
    rc = lib.dl_tfrecord_index(
        path.encode(),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, 1 if verify else 0)
    if rc < 0:
        raise ValueError(f"dl_tfrecord_index({path!r}) -> {rc}")
    return offsets[:rc], lengths[:rc]


# ---------------------------------------------------------------------------
# Native batch loader (ShardedLoader-compatible iteration)
# ---------------------------------------------------------------------------

class NativeLoader:
    """Threaded C++ batch assembly with the ShardedLoader contract.

    Yields the same batch sequence as
    ``ShardedLoader(arrays, global_batch, process_index, num_processes,
    shuffle, seed)`` — permutations are numpy-seeded, the gather runs in
    C++ worker threads into a prefetch ring.
    """

    def __init__(self, arrays: dict[str, np.ndarray], global_batch: int, *,
                 process_index: int = 0, num_processes: int = 1,
                 shuffle: bool = True, seed: int = 0,
                 depth: int = 4, workers: int = 2):
        lib = _load()
        if lib is None:
            raise RuntimeError("native loader unavailable")
        if global_batch % num_processes:
            raise ValueError("global_batch not divisible by num_processes")
        self._lib = lib
        self.keys = sorted(arrays)   # fixed key order = array order in C++
        if not self.keys:
            raise ValueError("empty batch layout")
        # keep references: the C++ side borrows these buffers
        self._arrays = [np.ascontiguousarray(arrays[k]) for k in self.keys]
        self.n = len(self._arrays[0])
        if any(len(a) != self.n for a in self._arrays):
            raise ValueError("array length mismatch")
        self.global_batch = global_batch
        self.local_batch = global_batch // num_processes
        self.process_index = process_index
        self.num_processes = num_processes
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self._rows = [
            max(1, a.dtype.itemsize
                * int(np.prod(a.shape[1:], dtype=np.int64)))
            for a in self._arrays]
        na = len(self._arrays)
        ptrs = (ctypes.c_void_p * na)(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in self._arrays])
        rows = (ctypes.c_int64 * na)(*self._rows)
        self._handle = lib.dl_create(ptrs, rows, na, self.n,
                                     self.local_batch, depth, workers)
        if not self._handle:
            raise RuntimeError("dl_create failed")
        self._batches_left = 0

    @property
    def steps_per_epoch(self) -> int:
        return self.n // self.global_batch

    def _install_epoch(self) -> None:
        idx = np.arange(self.n, dtype=np.int64)
        if self.shuffle:
            np.random.RandomState((self.seed, self.epoch)).shuffle(idx)
        nb = self.steps_per_epoch
        # this process's contiguous slice of each global batch
        l0 = self.process_index * self.local_batch
        local = np.empty(nb * self.local_batch, np.int64)
        for b in range(nb):
            g0 = b * self.global_batch
            local[b * self.local_batch:(b + 1) * self.local_batch] = \
                idx[g0 + l0:g0 + l0 + self.local_batch]
        rc = self._lib.dl_set_epoch(
            self._handle, local.ctypes.data_as(ctypes.c_void_p), local.size)
        if rc:
            raise RuntimeError(f"dl_set_epoch -> {rc}")
        self._batches_left = nb
        self.epoch += 1

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        na = len(self._arrays)
        shapes = [(self.local_batch,) + a.shape[1:] for a in self._arrays]
        ptrs = (ctypes.c_void_p * na)()
        while True:
            if self._batches_left == 0:
                self._install_epoch()
            rc = self._lib.dl_acquire(self._handle, ptrs)
            if rc:
                raise RuntimeError(f"dl_acquire -> {rc}")
            # copy out before release (device_put would copy anyway; this
            # keeps the ring slot turnover independent of consumer pace)
            batch = {}
            for i, key in enumerate(self.keys):
                nbytes = self.local_batch * self._rows[i]
                batch[key] = np.frombuffer(
                    (ctypes.c_char * nbytes).from_address(ptrs[i]),
                    dtype=self._arrays[i].dtype).reshape(shapes[i]).copy()
            self._lib.dl_release(self._handle)
            self._batches_left -= 1
            yield batch

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.dl_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
