// Native data-loader runtime for distributed_tensorflow_example_tpu.
//
// The reference's input machinery was C++ queue runners feeding the graph
// (SURVEY.md §2.2 'Coordinator/QueueRunner', 'Legacy queue input'); its
// TPU-native equivalent is this library: worker threads gather example rows
// into ready-to-feed batch buffers in a bounded ring, overlapping batch
// assembly with device compute, off the Python GIL.
//
// Division of labor with the Python layer (data/native.py):
//   - Python owns the dataset arrays and the determinism contract: the
//     per-epoch permutation comes from numpy (identical to the pure-Python
//     ShardedLoader), so native and Python loaders yield bit-identical
//     batch sequences.
//   - C++ owns the bytes: IDX/CIFAR file parsing, permutation-driven row
//     gather, batch assembly, prefetch ring, thread lifecycle.
//
// C API (ctypes-friendly): every function is extern "C"; handles are opaque
// pointers; errors are negative return codes (no exceptions cross the ABI).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// File parsing: IDX (MNIST) and CIFAR-10 binary
// ---------------------------------------------------------------------------

static uint32_t be32(const unsigned char* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

// Query an IDX image file: fills dims[0..2] = {n, rows, cols}. Returns 0 on
// success, negative on error.
int dl_idx_image_dims(const char* path, int64_t dims[3]) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  unsigned char hdr[16];
  if (fread(hdr, 1, 16, f) != 16) { fclose(f); return -2; }
  fclose(f);
  if (be32(hdr) != 2051) return -3;  // image magic
  dims[0] = be32(hdr + 4);
  dims[1] = be32(hdr + 8);
  dims[2] = be32(hdr + 12);
  return 0;
}

// Read IDX images into out (n*rows*cols bytes, caller-allocated).
int dl_idx_read_images(const char* path, unsigned char* out, int64_t out_size) {
  int64_t dims[3];
  int rc = dl_idx_image_dims(path, dims);
  if (rc) return rc;
  int64_t want = dims[0] * dims[1] * dims[2];
  if (out_size < want) return -4;
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 16, SEEK_SET);
  int64_t got = (int64_t)fread(out, 1, (size_t)want, f);
  fclose(f);
  return got == want ? 0 : -5;
}

int dl_idx_label_count(const char* path, int64_t* n) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  unsigned char hdr[8];
  if (fread(hdr, 1, 8, f) != 8) { fclose(f); return -2; }
  fclose(f);
  if (be32(hdr) != 2049) return -3;  // label magic
  *n = be32(hdr + 4);
  return 0;
}

int dl_idx_read_labels(const char* path, unsigned char* out, int64_t out_size) {
  int64_t n;
  int rc = dl_idx_label_count(path, &n);
  if (rc) return rc;
  if (out_size < n) return -4;
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 8, SEEK_SET);
  int64_t got = (int64_t)fread(out, 1, (size_t)n, f);
  fclose(f);
  return got == n ? 0 : -5;
}

// CIFAR-10 binary: records of 1 label byte + 3072 pixel bytes (CHW planar).
// Parses into NHWC float32 [n,32,32,3] scaled to [0,1] + int32 labels —
// the exact output of the Python parser, computed here without the
// transpose/copy chain numpy needs.
int dl_cifar_record_count(const char* path, int64_t* n) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fclose(f);
  if (sz % 3073) return -3;
  *n = sz / 3073;
  return 0;
}

int dl_cifar_read(const char* path, float* out_x, int32_t* out_y,
                  int64_t capacity_records) {
  int64_t n;
  int rc = dl_cifar_record_count(path, &n);
  if (rc) return rc;
  if (capacity_records < n) return -4;
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  std::vector<unsigned char> rec(3073);
  const float inv = 1.0f / 255.0f;
  for (int64_t i = 0; i < n; ++i) {
    if (fread(rec.data(), 1, 3073, f) != 3073) { fclose(f); return -5; }
    out_y[i] = rec[0];
    float* dst = out_x + i * 32 * 32 * 3;
    const unsigned char* r = rec.data() + 1;
    const unsigned char* g = r + 1024;
    const unsigned char* b = g + 1024;
    for (int p = 0; p < 1024; ++p) {       // CHW planar -> NHWC
      dst[p * 3 + 0] = r[p] * inv;
      dst[p * 3 + 1] = g[p] * inv;
      dst[p * 3 + 2] = b[p] * inv;
    }
  }
  fclose(f);
  return 0;
}

// ---------------------------------------------------------------------------
// TFRecord container (the tf.python_io / tf.io on-disk format)
// ---------------------------------------------------------------------------
// Per record: u64le length | u32le masked_crc32c(length bytes)
//           | data bytes   | u32le masked_crc32c(data).
// CRC is CRC-32C (Castagnoli, reflected poly 0x82f63b78);
// mask(c) = rotr(c,15) + 0xa282ead8. C++ owns the byte scan (index +
// integrity check off the GIL); Python (data/tfrecord.py) owns record
// framing, the writer, and the Example proto codec.

static uint32_t kCrcTable[8][256];
static std::atomic<bool> g_crc_ready{false};
static std::mutex g_crc_mu;

static void crc32c_init() {
  if (g_crc_ready.load(std::memory_order_acquire)) return;
  std::lock_guard<std::mutex> lk(g_crc_mu);
  if (g_crc_ready.load(std::memory_order_relaxed)) return;
  const uint32_t poly = 0x82f63b78u;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; ++i)
    for (int t = 1; t < 8; ++t)
      kCrcTable[t][i] =
          (kCrcTable[t - 1][i] >> 8) ^ kCrcTable[0][kCrcTable[t - 1][i] & 0xff];
  g_crc_ready.store(true, std::memory_order_release);
}

// Slicing-by-8 CRC-32C (little-endian host; this sandbox is x86-64).
uint32_t dl_crc32c(const unsigned char* p, int64_t n) {
  crc32c_init();
  uint32_t c = 0xffffffffu;
  while (n >= 8) {
    uint64_t w;
    memcpy(&w, p, 8);
    w ^= c;
    c = kCrcTable[7][w & 0xff] ^ kCrcTable[6][(w >> 8) & 0xff] ^
        kCrcTable[5][(w >> 16) & 0xff] ^ kCrcTable[4][(w >> 24) & 0xff] ^
        kCrcTable[3][(w >> 32) & 0xff] ^ kCrcTable[2][(w >> 40) & 0xff] ^
        kCrcTable[1][(w >> 48) & 0xff] ^ kCrcTable[0][(w >> 56) & 0xff];
    p += 8;
    n -= 8;
  }
  while (n-- > 0) c = (c >> 8) ^ kCrcTable[0][(c ^ *p++) & 0xff];
  return c ^ 0xffffffffu;
}

static uint32_t mask_crc(uint32_t c) {
  return ((c >> 15) | (c << 17)) + 0xa282ead8u;
}

// Scan a TFRecord file. Returns the record count (>=0) or a negative
// error: -1 open, -2 truncated header, -3 bad length crc, -4 truncated
// data, -5 bad data crc, -6 capacity too small. offsets/lengths (both
// null for a count-only pass) receive each record's DATA offset/length.
// verify != 0 checks both CRCs per record.
int64_t dl_tfrecord_index(const char* path, int64_t* offsets,
                          int64_t* lengths, int64_t capacity, int verify) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  int64_t fsize = (int64_t)ftell(f);
  fseek(f, 0, SEEK_SET);
  int64_t count = 0;
  std::vector<unsigned char> buf;
  unsigned char hdr[12];
  for (;;) {
    size_t got = fread(hdr, 1, 12, f);
    if (got == 0) break;                       // clean EOF
    if (got != 12) { fclose(f); return -2; }
    uint64_t len;
    memcpy(&len, hdr, 8);
    if (verify) {
      uint32_t want;
      memcpy(&want, hdr + 8, 4);
      if (mask_crc(dl_crc32c(hdr, 8)) != want) { fclose(f); return -3; }
    }
    // bound-check in unsigned space: a corrupt length with the high bit
    // set must hit -4, not wrap negative and pass (then fseek backwards
    // and loop forever)
    int64_t data_off = (int64_t)ftell(f);
    if (fsize - data_off < 4 || len > (uint64_t)(fsize - data_off - 4)) {
      fclose(f);
      return -4;
    }
    if (offsets && lengths) {
      if (count >= capacity) { fclose(f); return -6; }
      offsets[count] = data_off;
      lengths[count] = (int64_t)len;
    }
    if (verify) {
      buf.resize(len);
      if (len && fread(buf.data(), 1, (size_t)len, f) != len) {
        fclose(f);
        return -4;
      }
      unsigned char fc[4];
      if (fread(fc, 1, 4, f) != 4) { fclose(f); return -4; }
      uint32_t want;
      memcpy(&want, fc, 4);
      if (mask_crc(dl_crc32c(buf.data(), (int64_t)len)) != want) {
        fclose(f);
        return -5;
      }
    } else {
      fseek(f, (long)(len + 4), SEEK_CUR);
    }
    ++count;
  }
  fclose(f);
  return count;
}

// ---------------------------------------------------------------------------
// Threaded batch-assembly ring
// ---------------------------------------------------------------------------

struct Slot {
  std::vector<std::vector<unsigned char>> bufs;  // one buffer per array
  int64_t seq = -1;               // batch sequence number held in this slot
  std::atomic<bool> ready{false};
};

struct DLoader {
  std::vector<const unsigned char*> datas;  // borrowed (numpy-owned); the
                                            // batch layout is N parallel
                                            // arrays (BERT batches carry 6)
  std::vector<int64_t> rows;                // bytes per example row, per array
  int64_t n_rows;
  int64_t batch;                  // examples per (local) batch
  int depth;                      // ring depth
  int workers;

  std::vector<int64_t> perm;      // current epoch permutation (global order)
  int64_t n_batches = 0;          // batches per epoch

  std::vector<Slot> slots;
  std::atomic<int64_t> next_to_fill{0};   // batch seq workers claim
  int64_t next_to_serve = 0;               // batch seq consumer expects
  std::atomic<bool> stop{false};
  std::atomic<int64_t> epoch_end{0};      // total batches available so far
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::vector<std::thread> threads;

  void fill(int64_t seq) {
    Slot& s = slots[seq % depth];
    const int64_t base = (seq % n_batches) * batch;
    for (int64_t i = 0; i < batch; ++i) {
      int64_t src = perm[base + i];
      for (size_t a = 0; a < datas.size(); ++a)
        memcpy(s.bufs[a].data() + i * rows[a], datas[a] + src * rows[a],
               (size_t)rows[a]);
    }
    {
      // publish under the lock so a waiter between predicate-check and
      // wait cannot miss the notify
      std::lock_guard<std::mutex> lk(mu);
      s.seq = seq;
      s.ready.store(true, std::memory_order_release);
    }
    cv_ready.notify_all();
  }

  void worker() {
    while (!stop.load(std::memory_order_acquire)) {
      int64_t seq = next_to_fill.load(std::memory_order_relaxed);
      // claim work only within the released window and ring capacity
      if (seq >= epoch_end.load(std::memory_order_acquire) ||
          seq >= next_to_serve_snapshot() + depth) {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait_for(lk, std::chrono::milliseconds(50));
        continue;
      }
      if (!next_to_fill.compare_exchange_strong(seq, seq + 1)) continue;
      // slot must be free (consumer released it)
      Slot& s = slots[seq % depth];
      while (s.ready.load(std::memory_order_acquire) &&
             !stop.load(std::memory_order_acquire)) {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait_for(lk, std::chrono::milliseconds(50));
      }
      if (stop.load(std::memory_order_acquire)) return;
      fill(seq);
    }
  }

  int64_t next_to_serve_snapshot() {
    std::lock_guard<std::mutex> lk(mu);
    return next_to_serve;
  }
};

// Create a loader over N borrowed row-major arrays (the batch dict's
// arrays in a fixed key order — any layout, e.g. BERT's 6-array batches).
// local batch only — the process's shard of the global batch; sharding
// policy stays in Python.
DLoader* dl_create(const unsigned char* const* arrays, const int64_t* row_bytes,
                   int n_arrays, int64_t n_rows, int64_t batch, int depth,
                   int workers) {
  if (!arrays || !row_bytes || n_arrays <= 0 || batch <= 0 || depth <= 0 ||
      n_rows < batch)
    return nullptr;
  for (int a = 0; a < n_arrays; ++a)
    if (!arrays[a] || row_bytes[a] <= 0) return nullptr;
  auto* L = new DLoader();
  L->datas.assign(arrays, arrays + n_arrays);
  L->rows.assign(row_bytes, row_bytes + n_arrays);
  L->n_rows = n_rows; L->batch = batch;
  L->depth = depth; L->workers = workers > 0 ? workers : 2;
  L->slots = std::vector<Slot>(depth);
  for (auto& s : L->slots) {
    s.bufs.resize(n_arrays);
    for (int a = 0; a < n_arrays; ++a)
      s.bufs[a].resize((size_t)(batch * row_bytes[a]));
  }
  for (int i = 0; i < L->workers; ++i)
    L->threads.emplace_back([L] { L->worker(); });
  return L;
}

// Install the next epoch's permutation (length must be a multiple of batch;
// Python truncates to full batches — drop_remainder semantics). Extends the
// released window by perm_len/batch batches.
int dl_set_epoch(DLoader* L, const int64_t* perm, int64_t perm_len) {
  if (!L || perm_len % L->batch) return -1;
  for (int64_t i = 0; i < perm_len; ++i)
    if (perm[i] < 0 || perm[i] >= L->n_rows) return -2;
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->perm.assign(perm, perm + perm_len);
    L->n_batches = perm_len / L->batch;
    // serving position continues; window extends one epoch
    L->epoch_end.store(
        ((L->epoch_end.load() / L->n_batches) + 1) * L->n_batches,
        std::memory_order_release);
  }
  L->cv_free.notify_all();
  return 0;
}

// Blocking: acquire pointers to the next assembled batch — out_ptrs must
// have room for n_arrays pointers. Caller must call dl_release before the
// slot can be refilled. Returns 0, or -1 on shutdown, -2 when no epoch is
// installed.
int dl_acquire(DLoader* L, unsigned char** out_ptrs) {
  if (!L) return -1;
  if (L->epoch_end.load() == 0) return -2;
  Slot& s = L->slots[L->next_to_serve % L->depth];
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_ready.wait(lk, [&] {
    return L->stop.load() ||
           (s.ready.load(std::memory_order_acquire) &&
            s.seq == L->next_to_serve);
  });
  if (L->stop.load()) return -1;
  for (size_t a = 0; a < s.bufs.size(); ++a) out_ptrs[a] = s.bufs[a].data();
  return 0;
}

int dl_release(DLoader* L) {
  if (!L) return -1;
  Slot& s = L->slots[L->next_to_serve % L->depth];
  {
    std::lock_guard<std::mutex> lk(L->mu);
    s.ready.store(false, std::memory_order_release);
    s.seq = -1;
    L->next_to_serve += 1;
  }
  L->cv_free.notify_all();
  return 0;
}

void dl_destroy(DLoader* L) {
  if (!L) return;
  L->stop.store(true, std::memory_order_release);
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->threads) t.join();
  delete L;
}

// Version tag for Python-side compatibility checks. v2: N-array batches
// (dl_create takes array/row-byte vectors, dl_acquire fills N pointers).
int dl_abi_version() { return 3; }

}  // extern "C"
