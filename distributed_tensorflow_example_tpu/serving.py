"""Model export for serving — the SavedModel story, TPU-native.

The reference era shipped trained models as SavedModels (graph +
variables, servable without the training code). The XLA-world
equivalent is :mod:`jax.export`: the jitted forward function is lowered
to StableHLO once, with the trained parameters baked in as constants,
and serialized to a stable, self-contained artifact that any later JAX
process (or the C++ PJRT runtime) can run WITHOUT this framework's
Python code — the same portability contract a SavedModel gave
Session.run (SURVEY.md §2.3).

Artifacts are batch-polymorphic by default: the leading batch dimension
is exported symbolically, so one artifact serves any batch size.

Layout of an export directory::

    <dir>/model.stablehlo     the serialized jax.export artifact
    <dir>/export.json         metadata: model name, input signature,
                              platforms, param count, versions
"""

from __future__ import annotations

import json
import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

# label-side batch keys never consumed by `apply` (loss/eval only):
# pruned from the serving signature so a servable takes features only
_LABEL_KEYS = ("y", "masked_labels", "masked_weights", "__valid__")

_ARTIFACT = "model.stablehlo"
_META = "export.json"


def serving_signature(batch: dict[str, Any]) -> dict[str, Any]:
    """The feature-only view of a training batch."""
    return {k: v for k, v in batch.items() if k not in _LABEL_KEYS}


def _write_artifact(out_dir: str, exported, features, params, model,
                    **extra_meta) -> str:
    """Chief-only artifact + metadata write shared by every exporter
    (one metadata schema, one serializer — exporters add their own keys
    via ``extra_meta``)."""
    artifact = os.path.join(out_dir, _ARTIFACT)
    if jax.process_index() != 0:
        # any gather the caller did was collective (all processes); the
        # artifact write is chief-only — same division as the
        # checkpoint writer
        return artifact
    os.makedirs(out_dir, exist_ok=True)
    with open(artifact, "wb") as f:
        f.write(exported.serialize())
    signature = {
        k: {"shape": list(np.shape(v)), "dtype": str(np.asarray(v).dtype)}
        for k, v in features.items()}
    with open(os.path.join(out_dir, _META), "w") as f:
        json.dump({
            "model": getattr(model, "name", type(model).__name__),
            "input_signature": signature,
            "platforms": list(exported.platforms),
            "param_count": sum(
                int(np.size(p))
                for p in jax.tree_util.tree_leaves(params)),
            "jax_version": jax.__version__,
            "calling_convention_version":
                exported.calling_convention_version,
            **extra_meta,
        }, f, indent=1)
    return artifact


def export_model(model, params, extras, out_dir: str, *,
                 sample_batch: dict[str, Any] | None = None,
                 batch_size: int = 8,
                 platforms: Sequence[str] = ("cpu", "tpu"),
                 batch_polymorphic: bool = True) -> str:
    """Serialize ``model.apply(params, extras, features, train=False)``
    with the parameters baked in; returns the artifact path.

    ``platforms`` lowers one artifact for every listed backend (the
    default covers this sandbox's CPU tests and the TPU target).
    ``batch_polymorphic`` exports the leading dimension symbolically;
    models whose COMPUTATION depends concretely on the batch size (MoE:
    expert capacity = f(token count)) cannot trace symbolically — they
    fall back to a static-batch artifact automatically (recorded in the
    metadata; the servable then accepts exactly ``batch_size``).
    """
    batch = sample_batch or model.dummy_batch(batch_size)
    features = serving_signature(batch)

    # gather to host before baking: closed-over constants must be fully
    # addressable on this process, but fsdp params span hosts (same
    # reason the checkpoint writer allgathers — ckpt/checkpoint.py
    # _to_host)
    from .ckpt.checkpoint import _to_host
    params = jax.tree_util.tree_map(_to_host, params)
    extras = jax.tree_util.tree_map(_to_host, extras)

    def serve(feats):
        logits, _ = model.apply(params, extras, feats, train=False)
        return logits

    def _export(poly: bool):
        if poly:
            specs = jax_export.symbolic_args_specs(
                (features,), "b, ...")[0]
        else:
            specs = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                               jnp.asarray(x).dtype),
                features)
        return jax_export.export(
            jax.jit(serve), platforms=list(platforms))(specs)

    # symbolic-batch traces can fail several ways: concretization (MoE
    # capacity math), inconclusive symbolic-dim comparisons, or plain
    # TypeError from Python int ops on symbolic dims
    _symbolic_failures = (jax.errors.ConcretizationTypeError, TypeError)
    _idop = getattr(jax.core, "InconclusiveDimensionOperation", None)
    if _idop is not None:
        _symbolic_failures += (_idop,)
    if batch_polymorphic:
        try:
            exported = _export(True)
        except _symbolic_failures:
            from .utils.logging import get_logger
            get_logger("serving").warning(
                "batch-polymorphic export impossible (computation "
                "depends on the batch size); exporting static batch %d "
                "— the servable accepts exactly that instance count",
                jax.tree_util.tree_leaves(features)[0].shape[0])
            batch_polymorphic = False
            exported = _export(False)
    else:
        exported = _export(False)

    return _write_artifact(out_dir, exported, features, params, model,
                           batch_polymorphic=batch_polymorphic)


def export_generator(model, params, out_dir: str, *,
                     prompt_len: int, max_new_tokens: int,
                     batch_size: int = 1, temperature: float = 0.0,
                     top_k: int = 0, top_p: float = 0.0,
                     eos_id: int | None = None, pad_id: int = 0,
                     ragged: bool = False,
                     decode_impl: str = "stacked",
                     tokens_per_dispatch: int = 1,
                     platforms: Sequence[str] = ("cpu", "tpu")) -> str:
    """Serialize ``model.generate`` (params baked; greedy or
    temperature/top-k/top-p sampling, optional EOS early-stop) as a
    self-contained decode artifact: the whole generation — prefill +
    the KV-cache decode loop — is ONE StableHLO program mapping
    ``{"input_ids": [B, prompt_len]}`` (plus ``"rng"`` when sampling,
    plus ``"prompt_mask"`` when ``ragged``) to ``[B, max_new_tokens]``
    token ids. Static shapes throughout (the decode loop's cache layout
    depends on prompt and generation lengths, so the artifact is
    inherently static-shape; the metadata records it as such).

    The artifact rides the decode fast path (``decode_impl="stacked"``
    + optional ``tokens_per_dispatch`` amortization — recorded in the
    metadata). Decode attention in the artifact: multi-platform
    exports, and ANY export traced off-TPU, pin the portable XLA path
    (a Mosaic custom call cannot lower for the artifact's other
    platforms — and the kernel's interpret-mode fallback on a non-TPU
    tracing host must never be baked into a TPU artifact). Only a
    TPU-only export traced ON a TPU host keeps the model's own
    (kernel-capable) setting. When sampling, the serve-time PRNG
    contract is recorded as ``prng_impl`` so the HTTP server
    synthesizes ``rng`` key data with the impl the program was traced
    under."""
    from .ckpt.checkpoint import _to_host
    params = jax.tree_util.tree_map(_to_host, params)

    sampled = temperature > 0.0
    tpu_only_on_tpu = (tuple(platforms) == ("tpu",)
                       and jax.default_backend() == "tpu")
    decode_attention = ("xla" if decode_impl == "stacked"
                        and not tpu_only_on_tpu else None)

    def serve(feats):
        return model.generate(
            params, feats["input_ids"], max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id, pad_id=pad_id,
            prompt_mask=feats.get("prompt_mask"),
            decode_impl=decode_impl,
            decode_attention=decode_attention,
            tokens_per_dispatch=tokens_per_dispatch,
            rng=(jax.random.wrap_key_data(feats["rng"])
                 if sampled else None))

    features = {"input_ids": np.zeros((batch_size, prompt_len), np.int32)}
    if ragged:
        features["prompt_mask"] = np.ones((batch_size, prompt_len),
                                          np.int32)
    if sampled:
        features["rng"] = np.zeros(
            np.shape(jax.random.key_data(jax.random.key(0))), np.uint32)
    specs = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        features)
    exported = jax_export.export(
        jax.jit(serve), platforms=list(platforms))(specs)

    extra_meta = {}
    if sampled:
        # the serve-time rng contract: key data synthesized with any
        # OTHER default impl has a different shape/meaning and would
        # surface as an opaque executable error (ADVICE r5) — record
        # the impl the trace consumed so serving_http can honor it
        extra_meta["prng_impl"] = str(
            jax.random.key_impl(jax.random.key(0)))
    return _write_artifact(out_dir, exported, features, params, model,
                           kind="generator", batch_polymorphic=False,
                           max_new_tokens=max_new_tokens,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, eos_id=eos_id, pad_id=pad_id,
                           ragged=ragged, decode_impl=decode_impl,
                           tokens_per_dispatch=tokens_per_dispatch,
                           **extra_meta)


class ServableModel:
    """A loaded export: ``servable(features) -> logits``.

    Runs the deserialized StableHLO artifact — the training framework's
    model code is NOT needed (and not consulted)."""

    def __init__(self, directory: str):
        with open(os.path.join(directory, _META)) as f:
            self.meta = json.load(f)
        with open(os.path.join(directory, _ARTIFACT), "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        self._call = jax.jit(self._exported.call)

    @property
    def input_signature(self) -> dict:
        return self.meta["input_signature"]

    def __call__(self, features: dict[str, Any]):
        return self._call(features)


def load_servable(directory: str) -> ServableModel:
    return ServableModel(directory)
