"""Model export for serving — the SavedModel story, TPU-native.

The reference era shipped trained models as SavedModels (graph +
variables, servable without the training code). The XLA-world
equivalent is :mod:`jax.export`: the jitted forward function is lowered
to StableHLO once, with the trained parameters baked in as constants,
and serialized to a stable, self-contained artifact that any later JAX
process (or the C++ PJRT runtime) can run WITHOUT this framework's
Python code — the same portability contract a SavedModel gave
Session.run (SURVEY.md §2.3).

Artifacts are batch-polymorphic by default: the leading batch dimension
is exported symbolically, so one artifact serves any batch size.

Layout of an export directory::

    <dir>/model.stablehlo     the serialized jax.export artifact
    <dir>/export.json         metadata: model name, input signature,
                              platforms, param count, versions
"""

from __future__ import annotations

import json
import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

# label-side batch keys never consumed by `apply` (loss/eval only):
# pruned from the serving signature so a servable takes features only
_LABEL_KEYS = ("y", "masked_labels", "masked_weights", "__valid__")

_ARTIFACT = "model.stablehlo"
_META = "export.json"
# stepwise-generator artifacts (export_generator stepwise=True): the
# prefill and shared-decode-step programs the continuous-batching
# engine (serving_batch.py) drives, beside the monolithic artifact
_PREFILL = "prefill.stablehlo"
_DECODE = "decode.stablehlo"
# K-token speculative-verify program (export_generator spec_tokens=K,
# paged stepwise artifacts only): the engine's draft-and-verify loop
# dispatches it instead of decode.stablehlo on iterations where any
# live slot carries draft tokens
_VERIFY = "verify.stablehlo"
# chunked-prefill program (export_generator prefill_chunk=C, paged
# only): one C-token slice of a left-aligned prompt prefill, reading
# prior chunks back through the block table — the SLO scheduler
# interleaves these with shared decode steps so a long prompt can
# never stall live decoders for a whole monolithic prefill
_PREFILL_CHUNK = "prefill_chunk.stablehlo"


def serving_signature(batch: dict[str, Any]) -> dict[str, Any]:
    """The feature-only view of a training batch."""
    return {k: v for k, v in batch.items() if k not in _LABEL_KEYS}


def _write_artifact(out_dir: str, exported, features, params, model,
                    **extra_meta) -> str:
    """Chief-only artifact + metadata write shared by every exporter
    (one metadata schema, one serializer — exporters add their own keys
    via ``extra_meta``)."""
    artifact = os.path.join(out_dir, _ARTIFACT)
    if jax.process_index() != 0:
        # any gather the caller did was collective (all processes); the
        # artifact write is chief-only — same division as the
        # checkpoint writer
        return artifact
    os.makedirs(out_dir, exist_ok=True)
    with open(artifact, "wb") as f:
        f.write(exported.serialize())
    signature = {
        k: {"shape": list(np.shape(v)), "dtype": str(np.asarray(v).dtype)}
        for k, v in features.items()}
    with open(os.path.join(out_dir, _META), "w") as f:
        json.dump({
            "model": getattr(model, "name", type(model).__name__),
            "input_signature": signature,
            "platforms": list(exported.platforms),
            "param_count": sum(
                int(np.size(p))
                for p in jax.tree_util.tree_leaves(params)),
            "jax_version": jax.__version__,
            "calling_convention_version":
                exported.calling_convention_version,
            **extra_meta,
        }, f, indent=1)
    return artifact


def export_model(model, params, extras, out_dir: str, *,
                 sample_batch: dict[str, Any] | None = None,
                 batch_size: int = 8,
                 platforms: Sequence[str] = ("cpu", "tpu"),
                 batch_polymorphic: bool = True) -> str:
    """Serialize ``model.apply(params, extras, features, train=False)``
    with the parameters baked in; returns the artifact path.

    ``platforms`` lowers one artifact for every listed backend (the
    default covers this sandbox's CPU tests and the TPU target).
    ``batch_polymorphic`` exports the leading dimension symbolically;
    models whose COMPUTATION depends concretely on the batch size (MoE:
    expert capacity = f(token count)) cannot trace symbolically — they
    fall back to a static-batch artifact automatically (recorded in the
    metadata; the servable then accepts exactly ``batch_size``).
    """
    batch = sample_batch or model.dummy_batch(batch_size)
    features = serving_signature(batch)

    # gather to host before baking: closed-over constants must be fully
    # addressable on this process, but fsdp params span hosts (same
    # reason the checkpoint writer allgathers — ckpt/checkpoint.py
    # _to_host)
    from .ckpt.checkpoint import _to_host
    params = jax.tree_util.tree_map(_to_host, params)
    extras = jax.tree_util.tree_map(_to_host, extras)

    def serve(feats):
        logits, _ = model.apply(params, extras, feats, train=False)
        return logits

    def _export(poly: bool):
        if poly:
            specs = jax_export.symbolic_args_specs(
                (features,), "b, ...")[0]
        else:
            specs = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                               jnp.asarray(x).dtype),
                features)
        return jax_export.export(
            jax.jit(serve), platforms=list(platforms))(specs)

    # symbolic-batch traces can fail several ways: concretization (MoE
    # capacity math), inconclusive symbolic-dim comparisons, or plain
    # TypeError from Python int ops on symbolic dims
    _symbolic_failures = (jax.errors.ConcretizationTypeError, TypeError)
    _idop = getattr(jax.core, "InconclusiveDimensionOperation", None)
    if _idop is not None:
        _symbolic_failures += (_idop,)
    if batch_polymorphic:
        try:
            exported = _export(True)
        except _symbolic_failures:
            from .utils.logging import get_logger
            get_logger("serving").warning(
                "batch-polymorphic export impossible (computation "
                "depends on the batch size); exporting static batch %d "
                "— the servable accepts exactly that instance count",
                jax.tree_util.tree_leaves(features)[0].shape[0])
            batch_polymorphic = False
            exported = _export(False)
    else:
        exported = _export(False)

    return _write_artifact(out_dir, exported, features, params, model,
                           batch_polymorphic=batch_polymorphic)


#: quant metadata schema version recorded in every generator export —
#: the loader refuses artifacts claiming a NEWER schema (fields it
#: cannot validate) instead of shape-erroring deep in the scan
QUANT_SCHEMA = 1


def _normalize_weight_quant(weight_quant) -> str | None:
    """Loud CLI/export validation of the weight-quant knob: ``None`` /
    ``"off"`` -> None, ``"int8"`` -> "int8", anything else raises."""
    if weight_quant in (None, "off"):
        return None
    if weight_quant == "int8":
        return "int8"
    raise ValueError(f"weight_quant must be 'off' or 'int8', got "
                     f"{weight_quant!r}")


def _normalize_kv_cache_dtype(kv_cache_dtype, model_dtype):
    """The KV-cache storage knob: ``None``/``"auto"`` keeps the model
    compute dtype (today's behavior — the quant-off bitwise no-op),
    ``"bf16"`` stores bfloat16 explicitly, ``"int8"`` selects the
    quantized pool (paged artifacts only — the caller enforces that).
    Returns ``(np.dtype for the pool, "int8" | None)``."""
    if kv_cache_dtype in (None, "auto"):
        return np.dtype(jnp.dtype(model_dtype)), None
    if kv_cache_dtype in ("bf16", "bfloat16"):
        return np.dtype(jnp.dtype(jnp.bfloat16)), None
    if kv_cache_dtype == "int8":
        return np.dtype(np.int8), "int8"
    raise ValueError(f"kv_cache_dtype must be 'auto', 'bf16' or "
                     f"'int8', got {kv_cache_dtype!r}")


def export_generator(model, params, out_dir: str, *,
                     prompt_len: int, max_new_tokens: int,
                     batch_size: int = 1, temperature: float = 0.0,
                     top_k: int = 0, top_p: float = 0.0,
                     eos_id: int | None = None, pad_id: int = 0,
                     ragged: bool = False,
                     decode_impl: str = "stacked",
                     tokens_per_dispatch: int = 1,
                     stepwise: bool = False, slots: int = 8,
                     paged: bool = False, block_size: int = 16,
                     num_blocks: int | None = None,
                     weight_quant: str | None = None,
                     kv_cache_dtype: str | None = None,
                     pool_bytes: int | None = None,
                     spec_tokens: int = 0,
                     prefill_chunk: int = 0,
                     platforms: Sequence[str] = ("cpu", "tpu")) -> str:
    """Serialize ``model.generate`` (params baked; greedy or
    temperature/top-k/top-p sampling, optional EOS early-stop) as a
    self-contained decode artifact: the whole generation — prefill +
    the KV-cache decode loop — is ONE StableHLO program mapping
    ``{"input_ids": [B, prompt_len]}`` (plus ``"rng"`` when sampling,
    plus ``"prompt_mask"`` when ``ragged``) to ``[B, max_new_tokens]``
    token ids. Static shapes throughout (the decode loop's cache layout
    depends on prompt and generation lengths, so the artifact is
    inherently static-shape; the metadata records it as such).

    The artifact rides the decode fast path (``decode_impl="stacked"``
    + optional ``tokens_per_dispatch`` amortization — recorded in the
    metadata). Decode attention in the artifact: multi-platform
    exports, and ANY export traced off-TPU, pin the portable XLA path
    (a Mosaic custom call cannot lower for the artifact's other
    platforms — and the kernel's interpret-mode fallback on a non-TPU
    tracing host must never be baked into a TPU artifact). Only a
    TPU-only export traced ON a TPU host keeps the model's own
    (kernel-capable) setting. When sampling, the serve-time PRNG
    contract is recorded as ``prng_impl`` so the HTTP server
    synthesizes ``rng`` key data with the impl the program was traced
    under.

    ``stepwise=True`` additionally exports the TWO programs a
    continuous-batching scheduler (serving_batch.py) needs, beside the
    monolithic artifact:

    - ``prefill.stablehlo`` — one prompt ([1, prompt_len] ids + mask,
      the ragged right-pack contract) plus the whole cache pool and a
      ``slot`` index → first-token logits, the row's pad count, and
      the pool with that slot's [T, H, D] per-layer K/V slab written
      (the full slab is overwritten, so slot reuse needs no cleanup).
    - ``decode.stablehlo`` — ONE shared decode step for every slot:
      per-slot token/pos/pad/alive + pool → next-token logits [slots,
      vocab] + updated pool, riding the stacked-scan fast path with
      PER-ROW cache depths (``GPT.decode_step_batched``).

    Sampling under the scheduler is host-side per request, so the
    stepwise programs return logits (no baked temperature/rng); the
    artifact's own ``temperature``/``top_k``/``top_p``/``eos_id``
    become the scheduler's per-request DEFAULTS, and ``prng_impl`` is
    recorded for the host-side per-request keys. Slot count, prompt
    capacity, and max context are recorded under the ``stepwise``
    metadata key (static shapes — the pool is the program's working
    set, sized at export time).

    ``paged=True`` (requires ``stepwise``) exports BLOCK-PAGED stepwise
    programs instead of the slab pair: the pool is ``[L, num_blocks,
    block_size, H, D]`` shared physical blocks plus a per-slot block
    table, prefill writes whole blocks through a table row
    (left-aligned layout — see ``GPT.paged_prefill``), and the decode
    step reads/writes through ``[slots, blocks_per_slot]`` tables.
    ``num_blocks`` defaults to the slab pool's byte capacity plus the
    reserved null block (block 0 — never allocated; unused table
    entries point at it). Slab artifacts remain exportable (the
    default) as the paged path's parity oracle; ``block_size`` /
    ``num_blocks`` land in the ``stepwise`` metadata so the engine and
    bench rows can report block-level residency.

    Quantized decode (round 12):

    - ``weight_quant="int8"`` bakes the decode-path layer weights as
      symmetric per-output-channel int8 + f32 scales
      (``GPT.stack_decode_params``) into EVERY decode program of this
      export — the monolithic generation, and the stepwise/paged
      decode step — with the dequant inside the scan body, so int8 is
      what crosses HBM per layer step. Prefill stays full precision
      (it is compute-bound, and the monolithic path's prefill already
      is). LOSSY by contract: gated by greedy-drift bounds, not byte
      parity.
    - ``kv_cache_dtype="int8"`` (requires ``paged=True``) stores the
      cache pool int8 with per-token-row f32 scales in parallel
      ``cache_k_scale``/``cache_v_scale`` [L, N, Bs] pools —
      quantize-on-write in prefill and the decode step, dequant fused
      into both decode-attention impls. ``"bf16"`` stores bfloat16
      explicitly; ``"auto"`` (default) keeps the model dtype — the
      bitwise no-op.
    - ``pool_bytes`` sizes the paged pool IN BYTES: ``num_blocks`` =
      the block count whose K/V bytes fit the budget (+ the null
      block), so an int8 pool genuinely holds >= 2x the bf16 block
      count at equal bytes (the scale pools are accounted separately
      in the recorded ``block_bytes`` — ~``8/(H*D)`` relative
      overhead — and in the engine's ``bytes_resident``). Mutually
      exclusive with ``num_blocks``.

    Every generator export records ``quant_schema`` + ``weight_quant``
    (and, stepwise, ``kv_cache_dtype`` / ``kv_scale_shape``) so
    loaders can validate quant expectations loudly instead of
    shape-erroring deep in the scan.

    ``spec_tokens=K`` (K >= 2; requires ``paged=True``) additionally
    exports ``verify.stablehlo`` — the K-token speculative-verify
    program (``GPT.decode_verify_batched_paged``): per-row ``[K]``
    token inputs through the same stacked-scan fast path into the
    paged pool, returning ``[slots, K, V]`` logits, with lanes gated
    per-row by ``n_tok`` so draftless slots ride the dispatch at width
    1. Composes with ``weight_quant="int8"`` and
    ``kv_cache_dtype="int8"`` unchanged (the verify body IS the decode
    body over row-expanded inputs). ``spec_tokens`` lands in the
    ``stepwise`` metadata so the engine and the HTTP server can
    auto-detect spec capability.

    ``prefill_chunk=C`` (requires ``paged=True``; C a positive
    multiple of ``block_size``) additionally exports
    ``prefill_chunk.stablehlo`` — the C-token chunked-prefill program
    (``GPT.paged_prefill_chunk``) the SLO-aware scheduler dispatches
    instead of the monolithic prefill when ``--prefill_chunk_tokens``
    is set, interleaving prompt chunks with shared decode steps so a
    long prompt's admission can never stall live decoders for more
    than one chunk's dispatch. With a float pool the chunked byte
    stream is bit-identical to the monolithic prefill (the repo's
    standing parity discipline); the int8-pool composition rides the
    token-agreement drift gate instead. ``prefill_chunk`` lands in
    the ``stepwise`` metadata so the engine can validate the
    serve-time budget against the exported chunk width."""
    from .ckpt.checkpoint import _to_host
    params = jax.tree_util.tree_map(_to_host, params)

    weight_quant = _normalize_weight_quant(weight_quant)
    cache_dtype, kv_quant = _normalize_kv_cache_dtype(
        kv_cache_dtype, model.dtype)
    if kv_quant and not paged:
        raise ValueError(
            "kv_cache_dtype='int8' quantizes the BLOCK-PAGED pool "
            "(per-block-row scales need the paged layout) — export "
            "with paged=True, or drop the knob")
    if pool_bytes is not None:
        if not paged:
            raise ValueError("pool_bytes sizes the paged block pool "
                             "and requires paged=True")
        if num_blocks is not None:
            raise ValueError("pass pool_bytes OR num_blocks, not both "
                             "(pool_bytes derives num_blocks from the "
                             "byte budget)")
        if pool_bytes < 1:
            raise ValueError(f"pool_bytes must be >= 1, got "
                             f"{pool_bytes}")
    if spec_tokens:
        if spec_tokens < 2:
            raise ValueError(
                f"spec_tokens must be 0 (off) or >= 2 (one anchor "
                f"token + at least one draft lane per verify "
                f"dispatch), got {spec_tokens}")
        if not paged:
            raise ValueError(
                "spec_tokens exports the K-token verify program over "
                "the block-paged pool (draft rejection rewinds per-row "
                "pos through the block tables) — export with "
                "paged=True, or drop the knob")
    if prefill_chunk:
        if not paged:
            raise ValueError(
                "prefill_chunk exports the chunked-prefill program "
                "over the block-paged pool (chunks fill whole blocks "
                "through the table) — export with paged=True, or drop "
                "the knob")
        if prefill_chunk < 1 or prefill_chunk % block_size:
            raise ValueError(
                f"prefill_chunk must be a positive multiple of "
                f"block_size={block_size} (chunks tile the left-"
                f"aligned layout block-granularly), got "
                f"{prefill_chunk}")

    sampled = temperature > 0.0
    tpu_only_on_tpu = (tuple(platforms) == ("tpu",)
                       and jax.default_backend() == "tpu")
    decode_attention = ("xla" if decode_impl == "stacked"
                        and not tpu_only_on_tpu else None)

    def serve(feats):
        return model.generate(
            params, feats["input_ids"], max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id, pad_id=pad_id,
            prompt_mask=feats.get("prompt_mask"),
            decode_impl=decode_impl,
            decode_attention=decode_attention,
            tokens_per_dispatch=tokens_per_dispatch,
            weight_quant=weight_quant,
            rng=(jax.random.wrap_key_data(feats["rng"])
                 if sampled else None))

    features = {"input_ids": np.zeros((batch_size, prompt_len), np.int32)}
    if ragged:
        features["prompt_mask"] = np.ones((batch_size, prompt_len),
                                          np.int32)
    if sampled:
        features["rng"] = np.zeros(
            np.shape(jax.random.key_data(jax.random.key(0))), np.uint32)
    specs = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        features)
    exported = jax_export.export(
        jax.jit(serve), platforms=list(platforms))(specs)

    extra_meta = {}
    if sampled or stepwise:
        # the serve-time rng contract: key data synthesized with any
        # OTHER default impl has a different shape/meaning and would
        # surface as an opaque executable error (ADVICE r5) — record
        # the impl the trace consumed so serving_http can honor it.
        # Stepwise artifacts record it unconditionally: the scheduler
        # samples host-side with per-request keys under this impl.
        extra_meta["prng_impl"] = str(
            jax.random.key_impl(jax.random.key(0)))
    if paged and not stepwise:
        raise ValueError("paged=True exports the block-paged stepwise "
                         "programs and requires stepwise=True")
    if stepwise:
        extra_meta["stepwise"] = _export_stepwise(
            model, params, out_dir, prompt_len=prompt_len,
            max_new_tokens=max_new_tokens, slots=slots,
            decode_attention=decode_attention, platforms=platforms,
            paged=paged, block_size=block_size, num_blocks=num_blocks,
            weight_quant=weight_quant, cache_dtype=cache_dtype,
            kv_quant=kv_quant, pool_bytes=pool_bytes,
            spec_tokens=spec_tokens, prefill_chunk=prefill_chunk)
    return _write_artifact(out_dir, exported, features, params, model,
                           kind="generator", batch_polymorphic=False,
                           prompt_len=prompt_len,
                           max_new_tokens=max_new_tokens,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, eos_id=eos_id, pad_id=pad_id,
                           ragged=ragged, decode_impl=decode_impl,
                           tokens_per_dispatch=tokens_per_dispatch,
                           quant_schema=QUANT_SCHEMA,
                           weight_quant=weight_quant,
                           **extra_meta)


def _trace_and_write_stepwise(out_dir: str, prefill_fn, decode_fn,
                              prefill_specs: dict, decode_specs: dict,
                              platforms: Sequence[str],
                              base_meta: dict, verify_fn=None,
                              verify_specs: dict | None = None,
                              chunk_fn=None,
                              chunk_specs: dict | None = None,
                              **extra_meta) -> dict:
    """The shared tail of both stepwise exporters (slab and paged):
    trace + serialize the prefill/decode pair (plus the optional
    speculative-verify and chunked-prefill programs) to the canonical
    filenames (chief-only write) and assemble the ``stepwise``
    metadata block. ONE copy, so an export-flow change (donation
    hints, platform knobs, a new metadata key the engine reads)
    cannot silently diverge the two artifact kinds."""
    programs = [(_PREFILL, prefill_fn, prefill_specs),
                (_DECODE, decode_fn, decode_specs)]
    if verify_fn is not None:
        programs.append((_VERIFY, verify_fn, verify_specs))
    if chunk_fn is not None:
        programs.append((_PREFILL_CHUNK, chunk_fn, chunk_specs))
    exported = [(name, jax_export.export(
        jax.jit(fn), platforms=list(platforms))(specs))
        for name, fn, specs in programs]
    if jax.process_index() == 0:
        os.makedirs(out_dir, exist_ok=True)
        for name, exp in exported:
            with open(os.path.join(out_dir, name), "wb") as f:
                f.write(exp.serialize())
    return {**base_meta, **extra_meta}


def _export_stepwise(model, params, out_dir: str, *, prompt_len: int,
                     max_new_tokens: int, slots: int,
                     decode_attention: str | None,
                     platforms: Sequence[str], paged: bool = False,
                     block_size: int = 16,
                     num_blocks: int | None = None,
                     weight_quant: str | None = None,
                     cache_dtype=None, kv_quant: str | None = None,
                     pool_bytes: int | None = None,
                     spec_tokens: int = 0,
                     prefill_chunk: int = 0) -> dict:
    """Trace + serialize the prefill and shared-decode-step programs
    (see :func:`export_generator` ``stepwise=True``); returns the
    ``stepwise`` metadata block. Params are already host-gathered."""
    if slots < 1:
        raise ValueError(f"slots must be >= 1, got {slots}")
    c = model.cfg
    total = prompt_len + max_new_tokens
    if total > c.max_len:
        raise ValueError(
            f"prompt_len {prompt_len} + max_new_tokens {max_new_tokens} "
            f"exceeds max_len {c.max_len}")
    if cache_dtype is None:
        cache_dtype = np.dtype(jnp.dtype(model.dtype))

    def base_meta(pool_shape) -> dict:
        return {
            "slots": slots,
            "prompt_len": prompt_len,
            "max_new_tokens": max_new_tokens,
            "max_context": total,
            "pool_shape": list(pool_shape),
            "cache_dtype": str(cache_dtype),
            "kv_cache_dtype": ("int8" if kv_quant else str(cache_dtype)),
            "vocab_size": c.vocab_size,
        }

    if paged:
        return _export_stepwise_paged(
            model, params, out_dir, prompt_len=prompt_len,
            max_new_tokens=max_new_tokens, slots=slots,
            decode_attention=decode_attention, platforms=platforms,
            block_size=block_size, num_blocks=num_blocks,
            cache_dtype=cache_dtype, base_meta=base_meta,
            weight_quant=weight_quant, kv_quant=kv_quant,
            pool_bytes=pool_bytes, spec_tokens=spec_tokens,
            prefill_chunk=prefill_chunk)
    head_dim = c.hidden // c.heads
    pool_shape = (c.layers, slots, total, c.heads, head_dim)

    def prefill_fn(feats):
        last_h, caches, pad = model.ragged_prefill(
            params, feats["input_ids"], feats["prompt_mask"], total)
        kv = model._stack_caches(caches)        # {"k"/"v": [L,1,T,H,D]}
        slot = feats["slot"]
        ck = jax.lax.dynamic_update_slice(
            feats["cache_k"], kv["k"].astype(feats["cache_k"].dtype),
            (0, slot, 0, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            feats["cache_v"], kv["v"].astype(feats["cache_v"].dtype),
            (0, slot, 0, 0, 0))
        return {"logits": model.lm_logits(params, last_h[:, None])[:, 0],
                "pad": pad, "cache_k": ck, "cache_v": cv}

    stacked = model.stack_decode_params(params, weight_quant=weight_quant)

    def decode_fn(feats):
        logits, new = model.decode_step_batched(
            params, stacked,
            {"k": feats["cache_k"], "v": feats["cache_v"]},
            feats["tok"], feats["pos"], feats["pad"], feats["alive"],
            decode_attention=decode_attention)
        return {"logits": logits, "cache_k": new["k"],
                "cache_v": new["v"]}

    pool_specs = {
        "cache_k": jax.ShapeDtypeStruct(pool_shape, cache_dtype),
        "cache_v": jax.ShapeDtypeStruct(pool_shape, cache_dtype)}
    prefill_specs = {
        "input_ids": jax.ShapeDtypeStruct((1, prompt_len), np.int32),
        "prompt_mask": jax.ShapeDtypeStruct((1, prompt_len), np.int32),
        "slot": jax.ShapeDtypeStruct((), np.int32), **pool_specs}
    decode_specs = {
        "tok": jax.ShapeDtypeStruct((slots,), np.int32),
        "pos": jax.ShapeDtypeStruct((slots,), np.int32),
        "pad": jax.ShapeDtypeStruct((slots,), np.int32),
        "alive": jax.ShapeDtypeStruct((slots,), np.int32), **pool_specs}
    return _trace_and_write_stepwise(
        out_dir, prefill_fn, decode_fn, prefill_specs, decode_specs,
        platforms, base_meta(pool_shape))


def _export_stepwise_paged(model, params, out_dir: str, *,
                           prompt_len: int, max_new_tokens: int,
                           slots: int, decode_attention: str | None,
                           platforms: Sequence[str], block_size: int,
                           num_blocks: int | None, cache_dtype,
                           base_meta, weight_quant: str | None = None,
                           kv_quant: str | None = None,
                           pool_bytes: int | None = None,
                           spec_tokens: int = 0,
                           prefill_chunk: int = 0) -> dict:
    """The block-paged stepwise pair (``export_generator``
    ``paged=True``): prefill writes a prompt's whole blocks through a
    table row, the shared decode step reads/writes through per-slot
    tables. Same artifact filenames as the slab pair — the ``paged``
    metadata key is the dispatch contract.

    ``kv_quant="int8"``: the pools are int8 with per-token-row f32
    scales in parallel ``cache_k_scale``/``cache_v_scale`` [L, N, Bs]
    pools threaded through both programs. ``pool_bytes`` derives
    ``num_blocks`` from the K/V byte budget — the lever that makes
    int8 hold 2x the bf16 block count at fixed HBM (the small scale
    pools are accounted in the recorded ``block_bytes``, not the block
    budget — ~8/(H·D) relative overhead)."""
    c = model.cfg
    total = prompt_len + max_new_tokens
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    blocks_per_slot = -(-total // block_size)
    prompt_blocks = -(-prompt_len // block_size)
    head_dim = c.hidden // c.heads
    # bytes of one block's K+V payload at the storage dtype (int8
    # itemsize 1 — exactly half of bf16, the capacity doubling)
    kv_block_bytes = 2 * c.layers * block_size * c.heads * head_dim \
        * int(np.dtype(cache_dtype).itemsize)
    # total per-block residency incl. the int8 scale rows (k+v, f32)
    block_bytes = kv_block_bytes + (
        2 * c.layers * block_size * 4 if kv_quant else 0)
    if pool_bytes is not None:
        num_blocks = 1 + pool_bytes // kv_block_bytes
    if num_blocks is None:
        # default: the slab pool's token capacity, block-granular,
        # plus the reserved null block — equal bytes, equal worst case
        num_blocks = 1 + slots * blocks_per_slot
    usable = num_blocks - 1
    if usable < blocks_per_slot:
        raise ValueError(
            f"num_blocks {num_blocks} leaves {usable} usable blocks "
            f"(block 0 is the reserved null block) but one full-depth "
            f"request needs {blocks_per_slot} blocks of {block_size} "
            "tokens — raise num_blocks or block_size"
            + (f" (pool_bytes {pool_bytes} at {kv_block_bytes} K/V "
               "bytes per block)" if pool_bytes is not None else ""))
    pool_shape = (c.layers, num_blocks, block_size, c.heads, head_dim)
    scale_shape = (c.layers, num_blocks, block_size)

    pool_specs = {
        "cache_k": jax.ShapeDtypeStruct(pool_shape, cache_dtype),
        "cache_v": jax.ShapeDtypeStruct(pool_shape, cache_dtype)}
    if kv_quant:
        pool_specs.update({
            "cache_k_scale": jax.ShapeDtypeStruct(scale_shape,
                                                  np.float32),
            "cache_v_scale": jax.ShapeDtypeStruct(scale_shape,
                                                  np.float32)})

    def prefill_fn(feats):
        if kv_quant:
            logits, ck, cv, cks, cvs = model.paged_prefill(
                params, feats["input_ids"], feats["prompt_mask"],
                feats["cache_k"], feats["cache_v"], feats["table_row"],
                k_scale=feats["cache_k_scale"],
                v_scale=feats["cache_v_scale"])
            return {"logits": logits, "cache_k": ck, "cache_v": cv,
                    "cache_k_scale": cks, "cache_v_scale": cvs}
        logits, ck, cv = model.paged_prefill(
            params, feats["input_ids"], feats["prompt_mask"],
            feats["cache_k"], feats["cache_v"], feats["table_row"])
        return {"logits": logits, "cache_k": ck, "cache_v": cv}

    stacked = model.stack_decode_params(params, weight_quant=weight_quant)

    def decode_fn(feats):
        pools = {"k": feats["cache_k"], "v": feats["cache_v"]}
        if kv_quant:
            pools.update({"k_scale": feats["cache_k_scale"],
                          "v_scale": feats["cache_v_scale"]})
        logits, new = model.decode_step_batched_paged(
            params, stacked, pools,
            feats["block_tables"], feats["tok"], feats["pos"],
            feats["pad"], feats["alive"],
            decode_attention=decode_attention)
        out = {"logits": logits, "cache_k": new["k"],
               "cache_v": new["v"]}
        if kv_quant:
            out.update({"cache_k_scale": new["k_scale"],
                        "cache_v_scale": new["v_scale"]})
        return out

    prefill_specs = {
        "input_ids": jax.ShapeDtypeStruct((1, prompt_len), np.int32),
        "prompt_mask": jax.ShapeDtypeStruct((1, prompt_len), np.int32),
        "table_row": jax.ShapeDtypeStruct((prompt_blocks,), np.int32),
        **pool_specs}
    decode_specs = {
        "tok": jax.ShapeDtypeStruct((slots,), np.int32),
        "pos": jax.ShapeDtypeStruct((slots,), np.int32),
        "pad": jax.ShapeDtypeStruct((slots,), np.int32),
        "alive": jax.ShapeDtypeStruct((slots,), np.int32),
        "block_tables": jax.ShapeDtypeStruct((slots, blocks_per_slot),
                                             np.int32),
        **pool_specs}
    verify_fn = verify_specs = None
    if spec_tokens:
        def verify_fn(feats):
            pools = {"k": feats["cache_k"], "v": feats["cache_v"]}
            if kv_quant:
                pools.update({"k_scale": feats["cache_k_scale"],
                              "v_scale": feats["cache_v_scale"]})
            logits, new = model.decode_verify_batched_paged(
                params, stacked, pools,
                feats["block_tables"], feats["tok"], feats["pos"],
                feats["pad"], feats["alive"], feats["n_tok"],
                decode_attention=decode_attention)
            out = {"logits": logits, "cache_k": new["k"],
                   "cache_v": new["v"]}
            if kv_quant:
                out.update({"cache_k_scale": new["k_scale"],
                            "cache_v_scale": new["v_scale"]})
            return out

        verify_specs = {
            **{k: v for k, v in decode_specs.items() if k != "tok"},
            "tok": jax.ShapeDtypeStruct((slots, spec_tokens), np.int32),
            "n_tok": jax.ShapeDtypeStruct((slots,), np.int32)}
    chunk_fn = chunk_specs = None
    if prefill_chunk:
        # clamp the exported chunk width at the prompt capacity rounded
        # to whole blocks — a wider chunk than the prompt can ever fill
        # would only trace dead lanes
        prefill_chunk = min(prefill_chunk, prompt_blocks * block_size)

        def chunk_fn(feats):
            scales = ({"k_scale": feats["cache_k_scale"],
                       "v_scale": feats["cache_v_scale"]}
                      if kv_quant else {})
            out = model.paged_prefill_chunk(
                params, feats["input_ids"], feats["chunk_mask"],
                feats["start"], feats["cache_k"], feats["cache_v"],
                feats["table_row"], feats["chunk_blocks"], **scales)
            res = {"logits": out[0], "cache_k": out[1],
                   "cache_v": out[2]}
            if kv_quant:
                res.update({"cache_k_scale": out[3],
                            "cache_v_scale": out[4]})
            return res

        chunk_specs = {
            "input_ids": jax.ShapeDtypeStruct((1, prefill_chunk),
                                              np.int32),
            "chunk_mask": jax.ShapeDtypeStruct((1, prefill_chunk),
                                               np.int32),
            "start": jax.ShapeDtypeStruct((), np.int32),
            "table_row": jax.ShapeDtypeStruct((prompt_blocks,),
                                              np.int32),
            "chunk_blocks": jax.ShapeDtypeStruct(
                (prefill_chunk // block_size,), np.int32),
            **pool_specs}
    quant_meta = {}
    if kv_quant:
        quant_meta = {"kv_scale_shape": list(scale_shape),
                      "kv_scale_dtype": "float32"}
    return _trace_and_write_stepwise(
        out_dir, prefill_fn, decode_fn, prefill_specs, decode_specs,
        platforms, base_meta(pool_shape),
        verify_fn=verify_fn, verify_specs=verify_specs,
        chunk_fn=chunk_fn, chunk_specs=chunk_specs,
        paged=True, block_size=block_size, num_blocks=num_blocks,
        blocks_per_slot=blocks_per_slot, prompt_blocks=prompt_blocks,
        layout="left_aligned", block_bytes=block_bytes,
        spec_tokens=spec_tokens, prefill_chunk=prefill_chunk,
        **quant_meta)


def validate_quant_meta(meta: dict, *, where: str = "artifact") -> None:
    """Loud load-time validation of an artifact's quantization
    metadata — every mismatch names the ``export.json`` field instead
    of shape-erroring deep inside the scan. Artifacts predating the
    quant schema (no ``quant_schema`` key) pass untouched: they carry
    no quant features (callers may count them via
    ``serving_quant_fallback_total``)."""
    schema = meta.get("quant_schema")
    if schema is None:
        return
    if not isinstance(schema, int) or schema < 1 or schema > QUANT_SCHEMA:
        raise ValueError(
            f"{where}: metadata field 'quant_schema'={schema!r} is not "
            f"supported by this loader (understands 1..{QUANT_SCHEMA}) "
            "— re-export the artifact or upgrade the server")
    wq = meta.get("weight_quant")
    if wq not in (None, "int8"):
        raise ValueError(
            f"{where}: metadata field 'weight_quant'={wq!r} names an "
            "unknown weight quantization (known: null, 'int8')")
    sm = meta.get("stepwise")
    if not sm:
        return
    kd = sm.get("kv_cache_dtype", sm.get("cache_dtype"))
    if kd == "int8":
        if not sm.get("paged"):
            raise ValueError(
                f"{where}: metadata field 'stepwise.kv_cache_dtype'="
                "'int8' requires a paged artifact ('stepwise.paged' is "
                "false) — the int8 pool's scale rows ride the block "
                "layout")
        want = [sm["pool_shape"][i] for i in (0, 1, 2)]   # [L, N, Bs]
        got = sm.get("kv_scale_shape")
        if got != want:
            raise ValueError(
                f"{where}: metadata field 'stepwise.kv_scale_shape'="
                f"{got!r} does not match the per-token-row layout "
                f"{want} implied by 'stepwise.pool_shape'="
                f"{sm['pool_shape']}")
        sd = sm.get("kv_scale_dtype", "float32")
        try:
            np.dtype(sd)
        except TypeError as e:
            raise ValueError(
                f"{where}: metadata field 'stepwise.kv_scale_dtype'="
                f"{sd!r} is not a dtype: {e}") from e
    elif kd is not None:
        try:
            np.dtype(kd)
        except TypeError as e:
            raise ValueError(
                f"{where}: metadata field 'stepwise.kv_cache_dtype'="
                f"{kd!r} is not a dtype (or 'int8'): {e}") from e


class ServableModel:
    """A loaded export: ``servable(features) -> logits``.

    Runs the deserialized StableHLO artifact — the training framework's
    model code is NOT needed (and not consulted)."""

    def __init__(self, directory: str):
        with open(os.path.join(directory, _META)) as f:
            self.meta = json.load(f)
        validate_quant_meta(self.meta, where=directory)
        with open(os.path.join(directory, _ARTIFACT), "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        self._call = jax.jit(self._exported.call)

    @property
    def input_signature(self) -> dict:
        return self.meta["input_signature"]

    def __call__(self, features: dict[str, Any]):
        return self._call(features)


def load_servable(directory: str) -> ServableModel:
    return ServableModel(directory)


def has_stepwise(directory: str) -> bool:
    """True when ``directory`` holds the stepwise (prefill + shared
    decode step) artifacts a continuous-batching scheduler can drive."""
    return (os.path.exists(os.path.join(directory, _PREFILL))
            and os.path.exists(os.path.join(directory, _DECODE)))


class StepwiseGenerator:
    """A loaded stepwise generator export: the prefill and shared
    decode-step programs plus their metadata, for the
    continuous-batching engine (serving_batch.GenerationEngine).

    Like :class:`ServableModel`, runs the deserialized StableHLO only —
    the model code is not consulted. The cache pool rides through both
    calls as jax arrays; both jits DONATE their inputs so the pool is
    updated in place where the backend supports aliasing (the pool is
    the only multi-megabyte operand, and the caller always replaces its
    reference with the returned pool)."""

    def __init__(self, directory: str):
        with open(os.path.join(directory, _META)) as f:
            self.meta = json.load(f)
        step_meta = self.meta.get("stepwise")
        if not step_meta or not has_stepwise(directory):
            raise ValueError(
                f"{directory!r} holds no stepwise generator artifacts — "
                "re-export with export_generator(..., stepwise=True) "
                "(or serve it with the scheduler off)")
        validate_quant_meta(self.meta, where=directory)
        self.step_meta = step_meta
        #: block-paged artifacts ([L, N, Bs, H, D] pool + block tables)
        #: vs the slab pair ([L, slots, T, H, D]) — the engine branches
        #: its allocator/prefix-cache machinery on this
        self.paged: bool = bool(step_meta.get("paged", False))
        #: "int8" for the quantized pool (parallel scale pools ride
        #: along in make_pool/_split), else the storage float dtype
        self.kv_cache_dtype: str = str(
            step_meta.get("kv_cache_dtype", step_meta["cache_dtype"]))
        #: K of the exported speculative-verify program (0 = the export
        #: carries none — the engine must run spec-off)
        self.spec_tokens: int = int(step_meta.get("spec_tokens", 0))
        verify_path = os.path.join(directory, _VERIFY)
        if self.spec_tokens and not os.path.exists(verify_path):
            raise ValueError(
                f"{directory!r} metadata claims spec_tokens="
                f"{self.spec_tokens} but {_VERIFY} is missing — the "
                "export is torn; re-export with export_generator(..., "
                f"spec_tokens={self.spec_tokens})")
        #: C of the exported chunked-prefill program (0 = none — the
        #: engine must run with chunking off)
        self.prefill_chunk_tokens: int = int(
            step_meta.get("prefill_chunk", 0))
        chunk_path = os.path.join(directory, _PREFILL_CHUNK)
        if self.prefill_chunk_tokens and not os.path.exists(chunk_path):
            raise ValueError(
                f"{directory!r} metadata claims prefill_chunk="
                f"{self.prefill_chunk_tokens} but {_PREFILL_CHUNK} is "
                "missing — the export is torn; re-export with "
                "export_generator(..., prefill_chunk="
                f"{self.prefill_chunk_tokens})")
        with open(os.path.join(directory, _PREFILL), "rb") as f:
            self._prefill_exp = jax_export.deserialize(f.read())
        with open(os.path.join(directory, _DECODE), "rb") as f:
            self._decode_exp = jax_export.deserialize(f.read())
        self._verify_exp = None
        if self.spec_tokens:
            with open(verify_path, "rb") as f:
                self._verify_exp = jax_export.deserialize(f.read())
        self._chunk_exp = None
        if self.prefill_chunk_tokens:
            with open(chunk_path, "rb") as f:
                self._chunk_exp = jax_export.deserialize(f.read())
        # donate ONLY the pool (the multi-megabyte operand): donating
        # the whole feature dict would warn per-call about the small
        # int arrays XLA can't alias into the outputs
        def split(call):
            def fn(pool, rest):
                return call({**rest, **pool})
            return fn

        self._prefill = jax.jit(split(self._prefill_exp.call),
                                donate_argnums=(0,))
        self._decode = jax.jit(split(self._decode_exp.call),
                               donate_argnums=(0,))
        self._verify = (jax.jit(split(self._verify_exp.call),
                                donate_argnums=(0,))
                        if self._verify_exp is not None else None)
        self._chunk = (jax.jit(split(self._chunk_exp.call),
                               donate_argnums=(0,))
                       if self._chunk_exp is not None else None)

    def make_pool(self) -> dict:
        """A zeroed cache pool of the exported shape (the engine's
        one-time allocation) — int8 artifacts include the parallel
        per-token-row scale pools."""
        m = self.step_meta
        shape = tuple(m["pool_shape"])
        dtype = np.dtype(m["cache_dtype"])
        pool = {"cache_k": jnp.zeros(shape, dtype),
                "cache_v": jnp.zeros(shape, dtype)}
        if self.kv_cache_dtype == "int8":
            sshape = tuple(m["kv_scale_shape"])
            sdtype = np.dtype(m.get("kv_scale_dtype", "float32"))
            pool.update({"cache_k_scale": jnp.zeros(sshape, sdtype),
                         "cache_v_scale": jnp.zeros(sshape, sdtype)})
        return pool

    @staticmethod
    def _split(feats: dict) -> tuple[dict, dict]:
        # every cache_* operand (K/V pools + int8 scale pools) is part
        # of the donated pool group; the small int arrays are not
        pool = {k: v for k, v in feats.items()
                if k.startswith("cache_")}
        rest = {k: v for k, v in feats.items()
                if not k.startswith("cache_")}
        return pool, rest

    def prefill(self, feats: dict) -> dict:
        pool, rest = self._split(feats)
        return self._prefill(pool, rest)

    def decode(self, feats: dict) -> dict:
        pool, rest = self._split(feats)
        return self._decode(pool, rest)

    def verify(self, feats: dict) -> dict:
        """The K-token speculative-verify dispatch (``tok`` is
        [slots, spec_tokens]; adds ``n_tok`` [slots]) — only on
        artifacts exported with ``spec_tokens >= 2``."""
        if self._verify is None:
            raise ValueError(
                "this artifact was exported without a verify program "
                "(spec_tokens=0) — re-export with export_generator("
                "..., spec_tokens=K) to enable speculative decoding")
        pool, rest = self._split(feats)
        return self._verify(pool, rest)

    def prefill_chunk(self, feats: dict) -> dict:
        """One C-token chunked-prefill dispatch (``input_ids``/
        ``chunk_mask`` [1, C] + ``start``/``table_row``/
        ``chunk_blocks``) — only on artifacts exported with
        ``prefill_chunk=C``."""
        if self._chunk is None:
            raise ValueError(
                "this artifact was exported without a chunked-prefill "
                "program (prefill_chunk=0) — re-export with "
                "export_generator(..., prefill_chunk=C) to enable "
                "chunked prefill")
        pool, rest = self._split(feats)
        return self._chunk(pool, rest)


def load_stepwise(directory: str) -> StepwiseGenerator:
    return StepwiseGenerator(directory)
