"""Model export for serving — the SavedModel story, TPU-native.

The reference era shipped trained models as SavedModels (graph +
variables, servable without the training code). The XLA-world
equivalent is :mod:`jax.export`: the jitted forward function is lowered
to StableHLO once, with the trained parameters baked in as constants,
and serialized to a stable, self-contained artifact that any later JAX
process (or the C++ PJRT runtime) can run WITHOUT this framework's
Python code — the same portability contract a SavedModel gave
Session.run (SURVEY.md §2.3).

Artifacts are batch-polymorphic by default: the leading batch dimension
is exported symbolically, so one artifact serves any batch size.

Layout of an export directory::

    <dir>/model.stablehlo     the serialized jax.export artifact
    <dir>/export.json         metadata: model name, input signature,
                              platforms, param count, versions
"""

from __future__ import annotations

import json
import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

# label-side batch keys never consumed by `apply` (loss/eval only):
# pruned from the serving signature so a servable takes features only
_LABEL_KEYS = ("y", "masked_labels", "masked_weights", "__valid__")

_ARTIFACT = "model.stablehlo"
_META = "export.json"
# stepwise-generator artifacts (export_generator stepwise=True): the
# prefill and shared-decode-step programs the continuous-batching
# engine (serving_batch.py) drives, beside the monolithic artifact
_PREFILL = "prefill.stablehlo"
_DECODE = "decode.stablehlo"


def serving_signature(batch: dict[str, Any]) -> dict[str, Any]:
    """The feature-only view of a training batch."""
    return {k: v for k, v in batch.items() if k not in _LABEL_KEYS}


def _write_artifact(out_dir: str, exported, features, params, model,
                    **extra_meta) -> str:
    """Chief-only artifact + metadata write shared by every exporter
    (one metadata schema, one serializer — exporters add their own keys
    via ``extra_meta``)."""
    artifact = os.path.join(out_dir, _ARTIFACT)
    if jax.process_index() != 0:
        # any gather the caller did was collective (all processes); the
        # artifact write is chief-only — same division as the
        # checkpoint writer
        return artifact
    os.makedirs(out_dir, exist_ok=True)
    with open(artifact, "wb") as f:
        f.write(exported.serialize())
    signature = {
        k: {"shape": list(np.shape(v)), "dtype": str(np.asarray(v).dtype)}
        for k, v in features.items()}
    with open(os.path.join(out_dir, _META), "w") as f:
        json.dump({
            "model": getattr(model, "name", type(model).__name__),
            "input_signature": signature,
            "platforms": list(exported.platforms),
            "param_count": sum(
                int(np.size(p))
                for p in jax.tree_util.tree_leaves(params)),
            "jax_version": jax.__version__,
            "calling_convention_version":
                exported.calling_convention_version,
            **extra_meta,
        }, f, indent=1)
    return artifact


def export_model(model, params, extras, out_dir: str, *,
                 sample_batch: dict[str, Any] | None = None,
                 batch_size: int = 8,
                 platforms: Sequence[str] = ("cpu", "tpu"),
                 batch_polymorphic: bool = True) -> str:
    """Serialize ``model.apply(params, extras, features, train=False)``
    with the parameters baked in; returns the artifact path.

    ``platforms`` lowers one artifact for every listed backend (the
    default covers this sandbox's CPU tests and the TPU target).
    ``batch_polymorphic`` exports the leading dimension symbolically;
    models whose COMPUTATION depends concretely on the batch size (MoE:
    expert capacity = f(token count)) cannot trace symbolically — they
    fall back to a static-batch artifact automatically (recorded in the
    metadata; the servable then accepts exactly ``batch_size``).
    """
    batch = sample_batch or model.dummy_batch(batch_size)
    features = serving_signature(batch)

    # gather to host before baking: closed-over constants must be fully
    # addressable on this process, but fsdp params span hosts (same
    # reason the checkpoint writer allgathers — ckpt/checkpoint.py
    # _to_host)
    from .ckpt.checkpoint import _to_host
    params = jax.tree_util.tree_map(_to_host, params)
    extras = jax.tree_util.tree_map(_to_host, extras)

    def serve(feats):
        logits, _ = model.apply(params, extras, feats, train=False)
        return logits

    def _export(poly: bool):
        if poly:
            specs = jax_export.symbolic_args_specs(
                (features,), "b, ...")[0]
        else:
            specs = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                               jnp.asarray(x).dtype),
                features)
        return jax_export.export(
            jax.jit(serve), platforms=list(platforms))(specs)

    # symbolic-batch traces can fail several ways: concretization (MoE
    # capacity math), inconclusive symbolic-dim comparisons, or plain
    # TypeError from Python int ops on symbolic dims
    _symbolic_failures = (jax.errors.ConcretizationTypeError, TypeError)
    _idop = getattr(jax.core, "InconclusiveDimensionOperation", None)
    if _idop is not None:
        _symbolic_failures += (_idop,)
    if batch_polymorphic:
        try:
            exported = _export(True)
        except _symbolic_failures:
            from .utils.logging import get_logger
            get_logger("serving").warning(
                "batch-polymorphic export impossible (computation "
                "depends on the batch size); exporting static batch %d "
                "— the servable accepts exactly that instance count",
                jax.tree_util.tree_leaves(features)[0].shape[0])
            batch_polymorphic = False
            exported = _export(False)
    else:
        exported = _export(False)

    return _write_artifact(out_dir, exported, features, params, model,
                           batch_polymorphic=batch_polymorphic)


def export_generator(model, params, out_dir: str, *,
                     prompt_len: int, max_new_tokens: int,
                     batch_size: int = 1, temperature: float = 0.0,
                     top_k: int = 0, top_p: float = 0.0,
                     eos_id: int | None = None, pad_id: int = 0,
                     ragged: bool = False,
                     decode_impl: str = "stacked",
                     tokens_per_dispatch: int = 1,
                     stepwise: bool = False, slots: int = 8,
                     paged: bool = False, block_size: int = 16,
                     num_blocks: int | None = None,
                     platforms: Sequence[str] = ("cpu", "tpu")) -> str:
    """Serialize ``model.generate`` (params baked; greedy or
    temperature/top-k/top-p sampling, optional EOS early-stop) as a
    self-contained decode artifact: the whole generation — prefill +
    the KV-cache decode loop — is ONE StableHLO program mapping
    ``{"input_ids": [B, prompt_len]}`` (plus ``"rng"`` when sampling,
    plus ``"prompt_mask"`` when ``ragged``) to ``[B, max_new_tokens]``
    token ids. Static shapes throughout (the decode loop's cache layout
    depends on prompt and generation lengths, so the artifact is
    inherently static-shape; the metadata records it as such).

    The artifact rides the decode fast path (``decode_impl="stacked"``
    + optional ``tokens_per_dispatch`` amortization — recorded in the
    metadata). Decode attention in the artifact: multi-platform
    exports, and ANY export traced off-TPU, pin the portable XLA path
    (a Mosaic custom call cannot lower for the artifact's other
    platforms — and the kernel's interpret-mode fallback on a non-TPU
    tracing host must never be baked into a TPU artifact). Only a
    TPU-only export traced ON a TPU host keeps the model's own
    (kernel-capable) setting. When sampling, the serve-time PRNG
    contract is recorded as ``prng_impl`` so the HTTP server
    synthesizes ``rng`` key data with the impl the program was traced
    under.

    ``stepwise=True`` additionally exports the TWO programs a
    continuous-batching scheduler (serving_batch.py) needs, beside the
    monolithic artifact:

    - ``prefill.stablehlo`` — one prompt ([1, prompt_len] ids + mask,
      the ragged right-pack contract) plus the whole cache pool and a
      ``slot`` index → first-token logits, the row's pad count, and
      the pool with that slot's [T, H, D] per-layer K/V slab written
      (the full slab is overwritten, so slot reuse needs no cleanup).
    - ``decode.stablehlo`` — ONE shared decode step for every slot:
      per-slot token/pos/pad/alive + pool → next-token logits [slots,
      vocab] + updated pool, riding the stacked-scan fast path with
      PER-ROW cache depths (``GPT.decode_step_batched``).

    Sampling under the scheduler is host-side per request, so the
    stepwise programs return logits (no baked temperature/rng); the
    artifact's own ``temperature``/``top_k``/``top_p``/``eos_id``
    become the scheduler's per-request DEFAULTS, and ``prng_impl`` is
    recorded for the host-side per-request keys. Slot count, prompt
    capacity, and max context are recorded under the ``stepwise``
    metadata key (static shapes — the pool is the program's working
    set, sized at export time).

    ``paged=True`` (requires ``stepwise``) exports BLOCK-PAGED stepwise
    programs instead of the slab pair: the pool is ``[L, num_blocks,
    block_size, H, D]`` shared physical blocks plus a per-slot block
    table, prefill writes whole blocks through a table row
    (left-aligned layout — see ``GPT.paged_prefill``), and the decode
    step reads/writes through ``[slots, blocks_per_slot]`` tables.
    ``num_blocks`` defaults to the slab pool's byte capacity plus the
    reserved null block (block 0 — never allocated; unused table
    entries point at it). Slab artifacts remain exportable (the
    default) as the paged path's parity oracle; ``block_size`` /
    ``num_blocks`` land in the ``stepwise`` metadata so the engine and
    bench rows can report block-level residency."""
    from .ckpt.checkpoint import _to_host
    params = jax.tree_util.tree_map(_to_host, params)

    sampled = temperature > 0.0
    tpu_only_on_tpu = (tuple(platforms) == ("tpu",)
                       and jax.default_backend() == "tpu")
    decode_attention = ("xla" if decode_impl == "stacked"
                        and not tpu_only_on_tpu else None)

    def serve(feats):
        return model.generate(
            params, feats["input_ids"], max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id, pad_id=pad_id,
            prompt_mask=feats.get("prompt_mask"),
            decode_impl=decode_impl,
            decode_attention=decode_attention,
            tokens_per_dispatch=tokens_per_dispatch,
            rng=(jax.random.wrap_key_data(feats["rng"])
                 if sampled else None))

    features = {"input_ids": np.zeros((batch_size, prompt_len), np.int32)}
    if ragged:
        features["prompt_mask"] = np.ones((batch_size, prompt_len),
                                          np.int32)
    if sampled:
        features["rng"] = np.zeros(
            np.shape(jax.random.key_data(jax.random.key(0))), np.uint32)
    specs = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        features)
    exported = jax_export.export(
        jax.jit(serve), platforms=list(platforms))(specs)

    extra_meta = {}
    if sampled or stepwise:
        # the serve-time rng contract: key data synthesized with any
        # OTHER default impl has a different shape/meaning and would
        # surface as an opaque executable error (ADVICE r5) — record
        # the impl the trace consumed so serving_http can honor it.
        # Stepwise artifacts record it unconditionally: the scheduler
        # samples host-side with per-request keys under this impl.
        extra_meta["prng_impl"] = str(
            jax.random.key_impl(jax.random.key(0)))
    if paged and not stepwise:
        raise ValueError("paged=True exports the block-paged stepwise "
                         "programs and requires stepwise=True")
    if stepwise:
        extra_meta["stepwise"] = _export_stepwise(
            model, params, out_dir, prompt_len=prompt_len,
            max_new_tokens=max_new_tokens, slots=slots,
            decode_attention=decode_attention, platforms=platforms,
            paged=paged, block_size=block_size, num_blocks=num_blocks)
    return _write_artifact(out_dir, exported, features, params, model,
                           kind="generator", batch_polymorphic=False,
                           prompt_len=prompt_len,
                           max_new_tokens=max_new_tokens,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, eos_id=eos_id, pad_id=pad_id,
                           ragged=ragged, decode_impl=decode_impl,
                           tokens_per_dispatch=tokens_per_dispatch,
                           **extra_meta)


def _trace_and_write_stepwise(out_dir: str, prefill_fn, decode_fn,
                              prefill_specs: dict, decode_specs: dict,
                              platforms: Sequence[str],
                              base_meta: dict, **extra_meta) -> dict:
    """The shared tail of both stepwise exporters (slab and paged):
    trace + serialize the prefill/decode pair to the canonical
    filenames (chief-only write) and assemble the ``stepwise``
    metadata block. ONE copy, so an export-flow change (donation
    hints, platform knobs, a new metadata key the engine reads) cannot
    silently diverge the two artifact kinds."""
    prefill_exp = jax_export.export(
        jax.jit(prefill_fn), platforms=list(platforms))(prefill_specs)
    decode_exp = jax_export.export(
        jax.jit(decode_fn), platforms=list(platforms))(decode_specs)
    if jax.process_index() == 0:
        os.makedirs(out_dir, exist_ok=True)
        for name, exp in ((_PREFILL, prefill_exp), (_DECODE, decode_exp)):
            with open(os.path.join(out_dir, name), "wb") as f:
                f.write(exp.serialize())
    return {**base_meta, **extra_meta}


def _export_stepwise(model, params, out_dir: str, *, prompt_len: int,
                     max_new_tokens: int, slots: int,
                     decode_attention: str | None,
                     platforms: Sequence[str], paged: bool = False,
                     block_size: int = 16,
                     num_blocks: int | None = None) -> dict:
    """Trace + serialize the prefill and shared-decode-step programs
    (see :func:`export_generator` ``stepwise=True``); returns the
    ``stepwise`` metadata block. Params are already host-gathered."""
    if slots < 1:
        raise ValueError(f"slots must be >= 1, got {slots}")
    c = model.cfg
    total = prompt_len + max_new_tokens
    if total > c.max_len:
        raise ValueError(
            f"prompt_len {prompt_len} + max_new_tokens {max_new_tokens} "
            f"exceeds max_len {c.max_len}")
    cache_dtype = np.dtype(jnp.dtype(model.dtype))

    def base_meta(pool_shape) -> dict:
        return {
            "slots": slots,
            "prompt_len": prompt_len,
            "max_new_tokens": max_new_tokens,
            "max_context": total,
            "pool_shape": list(pool_shape),
            "cache_dtype": str(cache_dtype),
            "vocab_size": c.vocab_size,
        }

    if paged:
        return _export_stepwise_paged(
            model, params, out_dir, prompt_len=prompt_len,
            max_new_tokens=max_new_tokens, slots=slots,
            decode_attention=decode_attention, platforms=platforms,
            block_size=block_size, num_blocks=num_blocks,
            cache_dtype=cache_dtype, base_meta=base_meta)
    head_dim = c.hidden // c.heads
    pool_shape = (c.layers, slots, total, c.heads, head_dim)

    def prefill_fn(feats):
        last_h, caches, pad = model.ragged_prefill(
            params, feats["input_ids"], feats["prompt_mask"], total)
        kv = model._stack_caches(caches)        # {"k"/"v": [L,1,T,H,D]}
        slot = feats["slot"]
        ck = jax.lax.dynamic_update_slice(
            feats["cache_k"], kv["k"].astype(feats["cache_k"].dtype),
            (0, slot, 0, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            feats["cache_v"], kv["v"].astype(feats["cache_v"].dtype),
            (0, slot, 0, 0, 0))
        return {"logits": model.lm_logits(params, last_h[:, None])[:, 0],
                "pad": pad, "cache_k": ck, "cache_v": cv}

    stacked = model.stack_decode_params(params)

    def decode_fn(feats):
        logits, new = model.decode_step_batched(
            params, stacked,
            {"k": feats["cache_k"], "v": feats["cache_v"]},
            feats["tok"], feats["pos"], feats["pad"], feats["alive"],
            decode_attention=decode_attention)
        return {"logits": logits, "cache_k": new["k"],
                "cache_v": new["v"]}

    pool_specs = {
        "cache_k": jax.ShapeDtypeStruct(pool_shape, cache_dtype),
        "cache_v": jax.ShapeDtypeStruct(pool_shape, cache_dtype)}
    prefill_specs = {
        "input_ids": jax.ShapeDtypeStruct((1, prompt_len), np.int32),
        "prompt_mask": jax.ShapeDtypeStruct((1, prompt_len), np.int32),
        "slot": jax.ShapeDtypeStruct((), np.int32), **pool_specs}
    decode_specs = {
        "tok": jax.ShapeDtypeStruct((slots,), np.int32),
        "pos": jax.ShapeDtypeStruct((slots,), np.int32),
        "pad": jax.ShapeDtypeStruct((slots,), np.int32),
        "alive": jax.ShapeDtypeStruct((slots,), np.int32), **pool_specs}
    return _trace_and_write_stepwise(
        out_dir, prefill_fn, decode_fn, prefill_specs, decode_specs,
        platforms, base_meta(pool_shape))


def _export_stepwise_paged(model, params, out_dir: str, *,
                           prompt_len: int, max_new_tokens: int,
                           slots: int, decode_attention: str | None,
                           platforms: Sequence[str], block_size: int,
                           num_blocks: int | None, cache_dtype,
                           base_meta) -> dict:
    """The block-paged stepwise pair (``export_generator``
    ``paged=True``): prefill writes a prompt's whole blocks through a
    table row, the shared decode step reads/writes through per-slot
    tables. Same artifact filenames as the slab pair — the ``paged``
    metadata key is the dispatch contract."""
    c = model.cfg
    total = prompt_len + max_new_tokens
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    blocks_per_slot = -(-total // block_size)
    prompt_blocks = -(-prompt_len // block_size)
    if num_blocks is None:
        # default: the slab pool's token capacity, block-granular,
        # plus the reserved null block — equal bytes, equal worst case
        num_blocks = 1 + slots * blocks_per_slot
    usable = num_blocks - 1
    if usable < blocks_per_slot:
        raise ValueError(
            f"num_blocks {num_blocks} leaves {usable} usable blocks "
            f"(block 0 is the reserved null block) but one full-depth "
            f"request needs {blocks_per_slot} blocks of {block_size} "
            "tokens — raise num_blocks or block_size")
    head_dim = c.hidden // c.heads
    pool_shape = (c.layers, num_blocks, block_size, c.heads, head_dim)

    def prefill_fn(feats):
        logits, ck, cv = model.paged_prefill(
            params, feats["input_ids"], feats["prompt_mask"],
            feats["cache_k"], feats["cache_v"], feats["table_row"])
        return {"logits": logits, "cache_k": ck, "cache_v": cv}

    stacked = model.stack_decode_params(params)

    def decode_fn(feats):
        logits, new = model.decode_step_batched_paged(
            params, stacked,
            {"k": feats["cache_k"], "v": feats["cache_v"]},
            feats["block_tables"], feats["tok"], feats["pos"],
            feats["pad"], feats["alive"],
            decode_attention=decode_attention)
        return {"logits": logits, "cache_k": new["k"],
                "cache_v": new["v"]}

    pool_specs = {
        "cache_k": jax.ShapeDtypeStruct(pool_shape, cache_dtype),
        "cache_v": jax.ShapeDtypeStruct(pool_shape, cache_dtype)}
    prefill_specs = {
        "input_ids": jax.ShapeDtypeStruct((1, prompt_len), np.int32),
        "prompt_mask": jax.ShapeDtypeStruct((1, prompt_len), np.int32),
        "table_row": jax.ShapeDtypeStruct((prompt_blocks,), np.int32),
        **pool_specs}
    decode_specs = {
        "tok": jax.ShapeDtypeStruct((slots,), np.int32),
        "pos": jax.ShapeDtypeStruct((slots,), np.int32),
        "pad": jax.ShapeDtypeStruct((slots,), np.int32),
        "alive": jax.ShapeDtypeStruct((slots,), np.int32),
        "block_tables": jax.ShapeDtypeStruct((slots, blocks_per_slot),
                                             np.int32),
        **pool_specs}
    return _trace_and_write_stepwise(
        out_dir, prefill_fn, decode_fn, prefill_specs, decode_specs,
        platforms, base_meta(pool_shape),
        paged=True, block_size=block_size, num_blocks=num_blocks,
        blocks_per_slot=blocks_per_slot, prompt_blocks=prompt_blocks,
        layout="left_aligned")


class ServableModel:
    """A loaded export: ``servable(features) -> logits``.

    Runs the deserialized StableHLO artifact — the training framework's
    model code is NOT needed (and not consulted)."""

    def __init__(self, directory: str):
        with open(os.path.join(directory, _META)) as f:
            self.meta = json.load(f)
        with open(os.path.join(directory, _ARTIFACT), "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        self._call = jax.jit(self._exported.call)

    @property
    def input_signature(self) -> dict:
        return self.meta["input_signature"]

    def __call__(self, features: dict[str, Any]):
        return self._call(features)


def load_servable(directory: str) -> ServableModel:
    return ServableModel(directory)


def has_stepwise(directory: str) -> bool:
    """True when ``directory`` holds the stepwise (prefill + shared
    decode step) artifacts a continuous-batching scheduler can drive."""
    return (os.path.exists(os.path.join(directory, _PREFILL))
            and os.path.exists(os.path.join(directory, _DECODE)))


class StepwiseGenerator:
    """A loaded stepwise generator export: the prefill and shared
    decode-step programs plus their metadata, for the
    continuous-batching engine (serving_batch.GenerationEngine).

    Like :class:`ServableModel`, runs the deserialized StableHLO only —
    the model code is not consulted. The cache pool rides through both
    calls as jax arrays; both jits DONATE their inputs so the pool is
    updated in place where the backend supports aliasing (the pool is
    the only multi-megabyte operand, and the caller always replaces its
    reference with the returned pool)."""

    def __init__(self, directory: str):
        with open(os.path.join(directory, _META)) as f:
            self.meta = json.load(f)
        step_meta = self.meta.get("stepwise")
        if not step_meta or not has_stepwise(directory):
            raise ValueError(
                f"{directory!r} holds no stepwise generator artifacts — "
                "re-export with export_generator(..., stepwise=True) "
                "(or serve it with the scheduler off)")
        self.step_meta = step_meta
        #: block-paged artifacts ([L, N, Bs, H, D] pool + block tables)
        #: vs the slab pair ([L, slots, T, H, D]) — the engine branches
        #: its allocator/prefix-cache machinery on this
        self.paged: bool = bool(step_meta.get("paged", False))
        with open(os.path.join(directory, _PREFILL), "rb") as f:
            self._prefill_exp = jax_export.deserialize(f.read())
        with open(os.path.join(directory, _DECODE), "rb") as f:
            self._decode_exp = jax_export.deserialize(f.read())
        # donate ONLY the pool (the multi-megabyte operand): donating
        # the whole feature dict would warn per-call about the small
        # int arrays XLA can't alias into the outputs
        def split(call):
            def fn(pool, rest):
                return call({**rest, **pool})
            return fn

        self._prefill = jax.jit(split(self._prefill_exp.call),
                                donate_argnums=(0,))
        self._decode = jax.jit(split(self._decode_exp.call),
                               donate_argnums=(0,))

    def make_pool(self) -> dict:
        """A zeroed cache pool of the exported shape (the engine's
        one-time allocation)."""
        m = self.step_meta
        shape = tuple(m["pool_shape"])
        dtype = np.dtype(m["cache_dtype"])
        return {"cache_k": jnp.zeros(shape, dtype),
                "cache_v": jnp.zeros(shape, dtype)}

    @staticmethod
    def _split(feats: dict) -> tuple[dict, dict]:
        pool = {k: feats[k] for k in ("cache_k", "cache_v")}
        rest = {k: v for k, v in feats.items()
                if k not in ("cache_k", "cache_v")}
        return pool, rest

    def prefill(self, feats: dict) -> dict:
        pool, rest = self._split(feats)
        return self._prefill(pool, rest)

    def decode(self, feats: dict) -> dict:
        pool, rest = self._split(feats)
        return self._decode(pool, rest)


def load_stepwise(directory: str) -> StepwiseGenerator:
    return StepwiseGenerator(directory)
