"""Checkpointing: Saver parity (SURVEY.md §3.4, §5.4)."""

from .checkpoint import CheckpointManager, latest_checkpoint, restore_or_init

__all__ = ["CheckpointManager", "latest_checkpoint", "restore_or_init"]
