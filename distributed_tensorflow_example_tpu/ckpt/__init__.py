"""Checkpointing: Saver parity (SURVEY.md §3.4, §5.4).

``tf_import`` (TF-era checkpoint migration) is a submodule, not a
re-export: it carries an optional TensorFlow dependency that must not
load on the training path —
``from distributed_tensorflow_example_tpu.ckpt import tf_import``.
"""

from .checkpoint import CheckpointManager, latest_checkpoint, restore_or_init

__all__ = ["CheckpointManager", "latest_checkpoint", "restore_or_init"]
