"""Import TensorFlow-era checkpoints — the reference's ``model.ckpt``.

The reference's Saver wrote graph-variable checkpoints
(``model.ckpt-N.{index,data-*}`` + a ``checkpoint`` state file, SURVEY.md
§3.4). A user migrating from the reference has those files; this module
reads them into this framework's param pytrees so training resumes (or
evaluation runs) from the PS-era weights.

TensorFlow is an OPTIONAL dependency here, exactly like
``utils/trace_summary.py``: the framework never imports TF on its training
path; this offline migration tool degrades with a clear error when the
wheel is absent. Only the checkpoint *reader* is used — no graph, no
session.

Usage::

    from distributed_tensorflow_example_tpu.ckpt import tf_import
    arrays = tf_import.load_tf_checkpoint("/old/run/model.ckpt-2000")
    params = tf_import.import_into(
        template_params, arrays, mapping=tf_import.mnist_mlp_mapping(arrays))

``mapping`` is ``{pytree-path: tf-variable-name}`` with ``/``-joined
pytree paths (the same path syntax the npz checkpoints use).
:func:`mnist_mlp_mapping` auto-detects the two variable-naming styles the
reference genre used for the 2-layer MNIST MLP.
"""

from __future__ import annotations

from typing import Mapping

import jax
import numpy as np

from ..utils.pytree import path_str as _path_str

PyTree = object


def load_tf_checkpoint(prefix: str) -> dict[str, np.ndarray]:
    """Read every variable of a TF checkpoint into host arrays.

    ``prefix`` is the checkpoint prefix (``.../model.ckpt-2000``, i.e. the
    path without ``.index``/``.data-*`` suffix), or a directory containing
    a ``checkpoint`` state file (the latest checkpoint is used).
    """
    try:
        import tensorflow as tf
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "importing TF checkpoints needs the tensorflow wheel "
            "(offline migration tool only; the framework itself does not "
            "depend on TF)") from e
    import os
    if os.path.isdir(prefix):
        latest = tf.train.latest_checkpoint(prefix)
        if latest is None:
            raise FileNotFoundError(
                f"no TF checkpoint state under {prefix!r}")
        prefix = latest
    reader = tf.train.load_checkpoint(prefix)
    shapes = reader.get_variable_to_shape_map()
    return {name: np.asarray(reader.get_tensor(name))
            for name in shapes
            # bookkeeping tensors, not model variables
            if not name.startswith("_CHECKPOINTABLE_OBJECT_GRAPH")}


def import_into(template: PyTree, arrays: Mapping[str, np.ndarray],
                mapping: Mapping[str, str], *,
                allow_missing: bool = False) -> PyTree:
    """Place TF variables into a param pytree per ``mapping``.

    Every mapped leaf is shape-checked against the template; unmapped
    template leaves keep their template values (fresh init) — so a
    partial import (e.g. backbone only) is explicit in the mapping, and
    ``allow_missing=False`` (default) raises if a mapped TF name is
    absent from ``arrays``.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    matched: set[str] = set()
    for path, tleaf in flat:
        key = _path_str(path)
        tf_name = mapping.get(key)
        if tf_name is None:
            leaves.append(tleaf)
            continue
        matched.add(key)
        if tf_name not in arrays:
            if allow_missing:
                leaves.append(tleaf)
                continue
            raise KeyError(
                f"mapping sends {key!r} to TF variable {tf_name!r}, which "
                f"the checkpoint does not contain (has: "
                f"{sorted(arrays)[:8]}...)")
        arr = np.asarray(arrays[tf_name])
        tshape = tuple(getattr(tleaf, "shape", arr.shape))
        if tuple(arr.shape) != tshape:
            raise ValueError(
                f"TF variable {tf_name!r} shape {arr.shape} != template "
                f"leaf {key!r} shape {tshape}")
        if hasattr(tleaf, "dtype"):
            arr = arr.astype(tleaf.dtype, copy=False)
        if isinstance(tleaf, jax.Array):
            leaves.append(jax.device_put(arr, tleaf.sharding))
        else:
            leaves.append(jax.numpy.asarray(arr))
    unconsumed = set(mapping) - matched
    if unconsumed:
        # a mapping key that matches NO template path would otherwise
        # silently leave fresh-init weights in place — the
        # trained-from-random failure a migration tool must never allow
        raise KeyError(
            f"mapping keys {sorted(unconsumed)} match no path in the "
            f"template pytree (template paths are '/'-joined, e.g. "
            f"'fc1/kernel'; pass the PARAMS pytree, not a TrainState)")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def mnist_mlp_mapping(arrays: Mapping[str, np.ndarray]
                      ) -> dict[str, str]:
    """Mapping for the reference's 2-layer MNIST MLP (SURVEY.md §2.1).

    The example genre used two naming styles:

    - anonymous ``tf.Variable``s: ``Variable`` (W1), ``Variable_1`` (b1),
      ``Variable_2`` (W2), ``Variable_3`` (b2);
    - scoped ``hid_w/sm_w``-style names (the canonical blog example):
      weights named ``*hid_w*``/``*sm_w*``, biases ``*hid_b*``/``*sm_b*``.

    Detection is by name first, falling back to rank/shape order (two
    rank-2 weights sorted by fan-in, their matching rank-1 biases).
    """
    names = sorted(arrays)

    def find(*subs):
        for n in names:
            if any(s in n for s in subs):
                return n
        return None

    w1 = find("hid_w", "h1/weights", "fc1/kernel", "dense/kernel")
    b1 = find("hid_b", "h1/biases", "fc1/bias", "dense/bias")
    w2 = find("sm_w", "out/weights", "fc2/kernel", "dense_1/kernel")
    b2 = find("sm_b", "out/biases", "fc2/bias", "dense_1/bias")
    if not all((w1, b1, w2, b2)):
        # anonymous-Variable style: identify layers by the chained dims
        # (w1's output dim is w2's input dim) — robust for any width,
        # unlike fan-in ordering which breaks when hidden > in_dim
        ws = [n for n in names if arrays[n].ndim == 2]
        bs = [n for n in names if arrays[n].ndim == 1]
        if len(ws) == 2 and len(bs) == 2:
            a, b = ws
            if arrays[a].shape[1] == arrays[b].shape[0]:
                w1, w2 = a, b
            elif arrays[b].shape[1] == arrays[a].shape[0]:
                w1, w2 = b, a
            if w1 is not None:
                # bias dims match the weights' output dims
                bs.sort(key=lambda n: (arrays[n].shape[0]
                                       != arrays[w1].shape[1]))
                b1, b2 = bs
    if not all((w1, b1, w2, b2)):
        raise ValueError(
            f"cannot identify the 2-layer MLP variables among {names}")
    return {"fc1/kernel": w1, "fc1/bias": b1,
            "fc2/kernel": w2, "fc2/bias": b2}
