"""Checkpoint save/restore with the reference Saver's semantics.

Parity map (SURVEY.md §3.4/§5.4 → here):

- graph-embedded SaveV2 op, chief fetches params from PS  →  process 0
  device_gets the state pytree and writes one ``.npz`` (path-keyed leaves)
  plus a small JSON sidecar (step, leaf metadata).
- ``model.ckpt-N`` + ``checkpoint`` state file  →  ``ckpt-N.npz`` + a JSON
  ``checkpoint`` file recording ``latest`` and ``all_model_checkpoint_paths``.
- ``max_to_keep=5`` ring (saver.py:448)  →  same ring, same default.
- restore-or-init decision (SessionManager.prepare_session:320-335)  →
  :func:`restore_or_init`.
- non-chief never writes  →  only ``jax.process_index() == 0`` writes;
  everyone restores (state is replicated/resharded on load).

Format note: npz (zip of npy) keeps this dependency-free and inspectable;
keys are ``/``-joined pytree paths. PRNG-key leaves are serialized via
``jax.random.key_data`` and rewrapped on load. bfloat16 leaves (npy cannot
represent ml_dtypes' bfloat16 — it round-trips as raw void) are stored as
uint16 bit patterns under a ``__bf16__/`` key prefix and viewed back on
load, so ``param_dtype=bfloat16`` states checkpoint losslessly.

Sharded mode (``sharded=True`` — the TF Saver ``sharded=True`` analogue,
and the path that scales past one host): instead of all-gathering every
leaf to process 0, EACH process writes exactly the shard pieces it owns
(the ``replica_id == 0`` addressable shards of every distributed array)
to its own ``ckpt-N.shard-<p>-of-<P>.npz``; process 0 additionally writes
a tiny ``ckpt-N.shards.json`` anchor and rotates the ring. Save traffic
per host is O(params/P) instead of O(params), writes land in parallel,
and no cross-host gather happens at all. Restore reads back selectively:
a process reads only the pieces overlapping the shards it needs for the
template's sharding (exact-match fast path), falling back to assembling
a full leaf only when the piece layout and the target sharding disagree
(e.g. restoring onto a different mesh).
"""

from __future__ import annotations

import json
import math
import os
import re
import tempfile
import threading
import time
import zlib
from typing import Any

import jax
import ml_dtypes
import numpy as np

from ..runtime import faults
from ..utils.logging import get_logger
from ..utils.pytree import is_prng_key as _is_key, path_str as _path_str

log = get_logger("ckpt")

PyTree = Any

STATE_FILE = "checkpoint"          # parity with TF's 'checkpoint' proto file
PREFIX = "ckpt"

#: reserved npz key: JSON {array key -> crc32 of its raw bytes}, recorded
#: at save and verified on restore (the Orbax-style checksummed-checkpoint
#: pattern) — catches torn/zero-filled/bit-rotted files that still parse
_CRC_KEY = "__crc32__"

#: state leaves added AFTER checkpoints already existed in the wild:
#: when absent from a checkpoint they default to zeros instead of
#: failing the whole restore. ONE list consulted by both restore paths
#: (single-file _unflatten and _restore_sharded) so the formats can
#: never disagree on back-compat.
DEFAULTABLE_LEAVES = ("anomaly_count",)   # round-8 anomaly counter


class CorruptCheckpointError(FileNotFoundError):
    """A checkpoint that exists but cannot be trusted: unreadable zip,
    failed CRC32, or missing shard pieces. Subclasses FileNotFoundError
    so existing no-usable-checkpoint handling (CLI eval paths) keeps
    working; the message names the file, the step, and — when the caller
    fell back — the checkpoint restored instead."""


def _crc32_of(arr: np.ndarray) -> int:
    a = np.ascontiguousarray(arr)
    # numpy arrays expose the buffer protocol: no bytes copy
    return zlib.crc32(a.reshape(-1).view(np.uint8)) if a.size else 0


def _with_crcs(arrays: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    crcs = {k: _crc32_of(np.asarray(v)) for k, v in arrays.items()}
    out = dict(arrays)
    out[_CRC_KEY] = np.frombuffer(json.dumps(crcs).encode(), dtype=np.uint8)
    return out


def _load_npz_verified(path: str, step: int | None = None
                       ) -> dict[str, np.ndarray]:
    """Read every array of an npz with verification (one shared
    implementation: :class:`_VerifiedNpz`). Any failure — unreadable
    zip, bad member CRC, missing or mismatched arrays — becomes one
    clear CorruptCheckpointError instead of a bare zipfile/numpy
    traceback. Checkpoints predating the CRC record load without
    content verification (the zip layer still catches torn members)."""
    z = _VerifiedNpz(path, step)
    try:
        return {k: z[k] for k in z.files}
    finally:
        z.close()


def _to_host(leaf) -> np.ndarray:
    """Fetch a (possibly multi-host-sharded) array to this host. For
    non-fully-addressable arrays (fsdp over processes) every process must
    participate in the gather — mirroring how the reference's SaveV2
    fetched params *from the PS* to the chief (SURVEY.md §3.4)."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(
            leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten(state: PyTree) -> dict[str, np.ndarray]:
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    out: dict[str, np.ndarray] = {}
    for path, leaf in flat:
        key = _path_str(path)
        if _is_key(leaf):
            out["__prngkey__/" + key] = np.asarray(jax.random.key_data(leaf))
            # the key impl (threefry2x32 / rbg) must survive the round
            # trip: wrap_key_data under the wrong impl mis-sizes or
            # silently changes the random stream
            out["__prngimpl__/" + key] = np.frombuffer(
                str(jax.random.key_impl(leaf)).encode(), dtype=np.uint8)
        else:
            arr = _to_host(leaf)
            if arr.dtype == ml_dtypes.bfloat16:
                out["__bf16__/" + key] = arr.view(np.uint16)
            else:
                out[key] = arr
    return out


# ---------------------------------------------------------------------------
# sharded-mode helpers
# ---------------------------------------------------------------------------

_SHARD_META_KEY = "__shardmeta__"      # reserved npz key: JSON piece index


def _norm_index(index, shape) -> tuple[tuple[int, int], ...]:
    """Normalize a shard index (tuple of slices) to ((start, stop), ...)."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, _ = sl.indices(dim)
        out.append((int(start), int(stop)))
    return tuple(out)


def _piece_key(leaf_key: str, start: tuple[int, ...]) -> str:
    return leaf_key + "::" + "_".join(str(s) for s in start)


def _flatten_local(state: PyTree) -> tuple[dict[str, np.ndarray], dict]:
    """This process's owned pieces of the state pytree.

    Ownership: a process owns the ``replica_id == 0`` addressable shards
    of every distributed array (each distinct piece of data has exactly one
    replica 0 globally, so every byte is written exactly once across the
    job). Host-local leaves (python/numpy scalars, PRNG keys, and
    fully-addressable arrays, which are identical on every process) belong
    to process 0.

    Returns ``(pieces, meta)`` where ``pieces`` maps npz keys to arrays
    and ``meta`` records, per leaf: dtype, global shape, kind, and the
    (start, shape) of each piece this process wrote.
    """
    is_proc0 = jax.process_index() == 0
    pieces: dict[str, np.ndarray] = {}
    meta: dict[str, dict] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = _path_str(path)
        if _is_key(leaf):
            if is_proc0:
                arr = np.asarray(jax.random.key_data(leaf))
                pk = _piece_key(key, (0,) * arr.ndim)
                pieces[pk] = arr
                meta[key] = {"kind": "prngkey", "dtype": str(arr.dtype),
                             "impl": str(jax.random.key_impl(leaf)),
                             "shape": list(arr.shape),
                             "pieces": [{"key": pk,
                                         "start": [0] * arr.ndim,
                                         "shape": list(arr.shape)}]}
            continue
        if isinstance(leaf, jax.Array):
            if leaf.is_fully_addressable and not is_proc0:
                # host-local arrays are identical on every process (same
                # init, same step count); process 0's copy is canonical
                continue
            shape = leaf.shape
            entry = {"kind": "array", "dtype": str(leaf.dtype),
                     "shape": list(shape), "pieces": []}
            seen: set = set()
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                bounds = _norm_index(shard.index, shape)
                if bounds in seen:
                    continue
                seen.add(bounds)
                arr = np.asarray(jax.device_get(shard.data))
                if arr.dtype == ml_dtypes.bfloat16:
                    arr = arr.view(np.uint16)
                start = tuple(b[0] for b in bounds)
                pk = _piece_key(key, start)
                pieces[pk] = arr
                entry["pieces"].append({"key": pk, "start": list(start),
                                        "shape": list(arr.shape)})
            if entry["pieces"]:
                meta[key] = entry
            continue
        if is_proc0:
            arr = np.asarray(jax.device_get(leaf))
            stored = (arr.view(np.uint16)
                      if arr.dtype == ml_dtypes.bfloat16 else arr)
            start = (0,) * arr.ndim
            pk = _piece_key(key, start)
            pieces[pk] = stored
            meta[key] = {"kind": "array", "dtype": str(arr.dtype),
                         "shape": list(arr.shape),
                         "pieces": [{"key": pk, "start": list(start),
                                     "shape": list(arr.shape)}]}
    return pieces, meta


class _VerifiedNpz:
    """Lazy npz reader that verifies each member's recorded CRC32 as it
    is read — the sharded restore path keeps its selective-read property
    (a process only reads the pieces its sharding needs) while every
    byte actually consumed is still integrity-checked. Open and read
    errors, and CRC mismatches, all surface as CorruptCheckpointError."""

    def __init__(self, path: str, step: int | None = None):
        self.path = path
        self.step = step
        at = f"step {step} " if step is not None else ""
        try:
            self._z = np.load(path)
            self._crcs = (json.loads(bytes(self._z[_CRC_KEY]).decode())
                          if _CRC_KEY in self._z.files else None)
        except Exception as e:
            raise CorruptCheckpointError(
                f"checkpoint {at}file {path!r} is unreadable "
                f"({type(e).__name__}: {e})") from e
        if self._crcs is not None:
            present = set(self.files)
            if set(self._crcs) != present:
                raise CorruptCheckpointError(
                    f"checkpoint {at}file {path!r} array set does not "
                    f"match its CRC record (missing "
                    f"{sorted(set(self._crcs) - present)}, unrecorded "
                    f"{sorted(present - set(self._crcs))})")

    @property
    def files(self) -> list[str]:
        return [k for k in self._z.files if k != _CRC_KEY]

    def __getitem__(self, key: str) -> np.ndarray:
        at = f"step {self.step} " if self.step is not None else ""
        try:
            v = self._z[key]
        except Exception as e:
            raise CorruptCheckpointError(
                f"checkpoint {at}file {self.path!r} member {key!r} is "
                f"unreadable ({type(e).__name__}: {e})") from e
        if self._crcs is not None and (
                key not in self._crcs or _crc32_of(v) != self._crcs[key]):
            raise CorruptCheckpointError(
                f"checkpoint {at}file {self.path!r} fails CRC32 "
                f"verification at array {key!r} — corrupt on disk")
        return v

    def close(self) -> None:
        self._z.close()


def _merge_metas(loads: dict[str, "np.lib.npyio.NpzFile"]) -> dict[str, dict]:
    """Merge every open shard file's embedded piece index into one leaf
    map; each piece entry gains a ``file`` field naming its shard file."""
    merged: dict[str, dict] = {}
    for p, z in loads.items():
        meta = json.loads(bytes(z[_SHARD_META_KEY]).decode())
        for leaf_key, entry in meta.items():
            tgt = merged.setdefault(
                leaf_key, {**entry, "pieces": []})
            for piece in entry["pieces"]:
                tgt["pieces"].append({**piece, "file": p})
    return merged


def _view_dtype(arr: np.ndarray, dtype: str) -> np.ndarray:
    return arr.view(ml_dtypes.bfloat16) if dtype == "bfloat16" else arr


def _leaf_from_pieces(entry: dict,
                      loads: dict[str, "np.lib.npyio.NpzFile"]):
    """Assemble a full leaf from its saved pieces."""
    dtype = entry["dtype"]
    shape = tuple(entry["shape"])
    out = np.empty(shape, dtype=np.uint16 if dtype == "bfloat16"
                   else np.dtype(dtype))
    covered = 0
    for piece in entry["pieces"]:
        sl = tuple(slice(s, s + d) for s, d in
                   zip(piece["start"], piece["shape"]))
        out[sl] = loads[piece["file"]][piece["key"]]
        covered += int(np.prod(piece["shape"])) if piece["shape"] else 1
    if covered < int(np.prod(shape) if shape else 1):
        raise ValueError(
            f"sharded checkpoint does not cover leaf of shape {shape}: "
            f"{covered} elements present — missing shard files?")
    return out.view(ml_dtypes.bfloat16) if dtype == "bfloat16" else out


def _unflatten(template: PyTree, arrays: dict[str, np.ndarray]) -> PyTree:
    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, tleaf in paths_and_leaves:
        key = _path_str(path)
        if "__prngkey__/" + key in arrays:
            impl_raw = arrays.get("__prngimpl__/" + key)
            kw = ({"impl": bytes(impl_raw).decode()}
                  if impl_raw is not None else {})   # pre-impl ckpts
            leaves.append(jax.random.wrap_key_data(
                np.asarray(arrays["__prngkey__/" + key]), **kw))
            continue
        if "__bf16__/" + key in arrays:
            leaf = arrays["__bf16__/" + key].view(ml_dtypes.bfloat16)
        elif key in arrays:
            leaf = arrays[key]
        elif key in DEFAULTABLE_LEAVES and hasattr(tleaf, "dtype"):
            # checkpoints written before this leaf existed: default it
            # instead of refusing the whole restore
            leaf = np.zeros(tuple(getattr(tleaf, "shape", ())),
                            np.dtype(tleaf.dtype))
        else:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        if hasattr(tleaf, "shape") and tuple(leaf.shape) != tuple(tleaf.shape):
            raise ValueError(
                f"checkpoint leaf {key!r} shape {leaf.shape} != "
                f"template {tleaf.shape}")
        if hasattr(tleaf, "dtype") and leaf.dtype != tleaf.dtype:
            # a bf16 checkpoint restoring into an f32 run (or vice versa)
            # would otherwise continue silently at the wrong precision —
            # param_dtype must match across save and resume
            raise ValueError(
                f"checkpoint leaf {key!r} dtype {leaf.dtype} != template "
                f"{tleaf.dtype}: restore with the same param_dtype the "
                "checkpoint was written with")
        leaves.append(leaf)
    restored = jax.tree_util.tree_unflatten(treedef, leaves)
    # re-place on the template's shardings when it is device-resident
    def place(t, r):
        if isinstance(t, jax.Array) and hasattr(t, "sharding") and not _is_key(t):
            return jax.device_put(r, t.sharding)
        if _is_key(t):
            return r
        return jax.numpy.asarray(r)
    return jax.tree_util.tree_map(place, template, restored)


class CheckpointManager:
    """Write/restore checkpoints with a max_to_keep ring.

    Thread-safe save (the trainer's time-based saver thread mirrors the
    reference's SVTimerCheckpointThread, supervisor.py:1098).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 5,
                 keep_every_n_hours: float = 0.0, async_save: bool = False,
                 sharded: bool = False):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.keep_every_n_hours = keep_every_n_hours
        self.async_save = async_save
        self.sharded = sharded
        if sharded and async_save and jax.process_count() > 1:
            # the sharded commit protocol barriers across hosts after the
            # parallel writes; running that barrier on a background thread
            # would interleave with the training loop's collectives
            raise ValueError(
                "sharded=True with async_save is only supported "
                "single-process: the multi-host commit barrier cannot run "
                "on the writer thread")
        self._lock = threading.Lock()
        # guards the _pending slot itself: save()/wait() can race from the
        # step-based and wall-clock saver threads (ADVICE r2); the write
        # payloads stay serialized by _lock + the 1-worker executor
        self._pending_lock = threading.Lock()
        self._pending: "Future | None" = None
        self._executor = None
        if async_save:
            from concurrent.futures import ThreadPoolExecutor
            # one writer thread, depth-1 queue: the reference's
            # SVTimerCheckpointThread wrote one checkpoint at a time too
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-writer")
        # start the keep-forever clock now (TF Saver semantics): the first
        # interval must actually elapse before a checkpoint is pinned
        self._last_kept_forever = time.time()
        if self.is_writer:
            os.makedirs(directory, exist_ok=True)

    @property
    def is_writer(self) -> bool:
        return jax.process_index() == 0

    # -- state file -------------------------------------------------------
    def _state(self) -> dict:
        p = os.path.join(self.directory, STATE_FILE)
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {"latest": None, "all_model_checkpoint_paths": [],
                "kept_forever": []}

    def _write_state(self, st: dict) -> None:
        p = os.path.join(self.directory, STATE_FILE)
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump(st, f, indent=1)
        os.replace(tmp, p)

    def checkpoint_path(self, step: int) -> str:
        return os.path.join(self.directory, f"{PREFIX}-{step}.npz")

    def shard_anchor_path(self, step: int) -> str:
        return os.path.join(self.directory, f"{PREFIX}-{step}.shards.json")

    def _anchor_exists(self, step: int) -> bool:
        return (os.path.exists(self.checkpoint_path(step))
                or os.path.exists(self.shard_anchor_path(step)))

    def all_steps(self) -> list[int]:
        self.wait()                # async write may not have landed yet
        st = self._state()
        steps = []
        best = [st["best"]["path"]] if st.get("best") else []
        for p in (st["all_model_checkpoint_paths"]
                  + st.get("kept_forever", []) + best):
            m = re.search(rf"{PREFIX}-(\d+)\.(npz|shards\.json)$", p)
            if m and os.path.exists(os.path.join(self.directory, p)):
                steps.append(int(m.group(1)))
        return sorted(set(steps))

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save / restore ---------------------------------------------------
    def wait(self) -> None:
        """Block until an in-flight async write has landed (no-op when
        nothing is pending). Raises the writer thread's exception, if any."""
        with self._pending_lock:
            pending, self._pending = self._pending, None
        if pending is not None:
            pending.result()

    def close(self) -> None:
        """Drain the writer thread and release it. A pending async_save
        error SURFACES here (wait() re-raises it) — but the executor is
        still shut down first-class in that case, so a failed final save
        cannot also leak the writer thread."""
        try:
            self.wait()
        finally:
            if self._executor is not None:
                self._executor.shutdown(wait=True)

    def save(self, state: PyTree, step: int | None = None) -> str | None:
        """Gather to host and write ``ckpt-<step>.npz``; rotate the ring.
        Non-writer processes only participate in the device_get (so all
        hosts stay in lockstep) and return None.

        With ``async_save``, the host gather is still synchronous (it is a
        cross-process collective for non-addressable arrays) but the disk
        write happens on a background thread — the analogue of the
        reference's checkpoint thread running off the training loop
        (supervisor.py:1098). A new save waits for the previous write.
        """
        if step is None:
            step = int(jax.device_get(state.step))
        if self.sharded:
            return self._save_sharded(state, step)
        arrays = _flatten(state)
        if not self.is_writer:
            return None
        if self._executor is not None:
            # depth-1 queue: drain the previous write (surfacing its
            # errors) and submit the new one under ONE lock hold, so two
            # concurrent save() calls cannot both pass the drain and
            # overwrite each other's Future. The drained future is
            # CONSUMED before .result() so its error surfaces exactly
            # once — not again from every later wait()/close()
            with self._pending_lock:
                pending, self._pending = self._pending, None
                if pending is not None:
                    pending.result()
                self._pending = self._executor.submit(
                    self._write, arrays, step)
            return self.checkpoint_path(step)
        return self._write(arrays, step)

    def _atomic_npz(self, arrays: dict[str, np.ndarray], path: str) -> None:
        rule = faults.inject("ckpt.write", detail=f"writing {path!r}")
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                # per-array CRC32s ride inside the file; restore verifies
                np.savez(f, **_with_crcs(arrays))
                # fsync BEFORE rename: without it a crash can commit the
                # rename while the data blocks never hit disk — exactly
                # the truncated-checkpoint failure the verified-restore
                # fallback exists for, but durability is cheaper than
                # recovery
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        dirfd = os.open(self.directory, os.O_RDONLY)
        try:
            os.fsync(dirfd)        # persist the rename itself
        finally:
            os.close(dirfd)
        if rule is not None and rule.corrupt:
            # torn-write simulation: damage the LANDED file — the failure
            # mode the CRC verification + valid-step fallback must absorb
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                if rule.corrupt == "truncate":
                    f.truncate(max(1, int(size * 0.6)))
                else:                          # zero: overwrite a span
                    f.seek(size // 3)
                    f.write(b"\0" * max(1, size // 3))
            log.warning("fault injected: %s landed file %r damaged",
                        rule.describe(), path)

    def _remove_victim(self, victim: str) -> None:
        """Delete a rotated-out checkpoint — all of it, for sharded ones."""
        vp = os.path.join(self.directory, victim)
        if victim.endswith(".shards.json"):
            step = re.search(rf"{PREFIX}-(\d+)\.shards\.json$", victim)
            if step:
                import glob as _glob
                for f in _glob.glob(os.path.join(
                        self.directory,
                        f"{PREFIX}-{step.group(1)}.shard-*.npz")):
                    os.remove(f)
        if os.path.exists(vp):
            os.remove(vp)

    def _commit(self, base: str) -> None:
        """Record anchor ``base`` in the state file + rotate the ring."""
        faults.inject("ckpt.commit", detail=f"committing {base!r}")
        st = self._state()
        now = time.time()
        # a step may only live in ONE list (plus possibly the 'best'
        # pointer): re-saving an existing step (end-of-run save after
        # restore, or a ring entry promoted to kept-forever) must not
        # leave a stale entry behind — ring rotation would os.remove a
        # file the other list still names
        if base in st["all_model_checkpoint_paths"]:
            st["all_model_checkpoint_paths"].remove(base)
        was_kept = base in st.get("kept_forever", [])
        if was_kept:
            st["kept_forever"].remove(base)
        # a re-save of the same step in the OTHER format supersedes it:
        # evict the old anchor (and its shard files) so a stale
        # ckpt-N.npz can never shadow a newer ckpt-N.shards.json in
        # restore(), which prefers the single-file format
        m = re.search(rf"{PREFIX}-(\d+)\.(npz|shards\.json)$", base)
        if m:
            other = (f"{PREFIX}-{m.group(1)}."
                     + ("shards.json" if m.group(2) == "npz" else "npz"))
            if other in st["all_model_checkpoint_paths"]:
                st["all_model_checkpoint_paths"].remove(other)
            if other in st.get("kept_forever", []):
                st["kept_forever"].remove(other)
                was_kept = True       # kept-forever status follows the step
            if other == (st.get("best") or {}).get("path"):
                # the best pointer follows a format re-save of its step —
                # the evicted anchor must not leave it dangling
                st["best"]["path"] = base
            self._remove_victim(other)
        if was_kept or (self.keep_every_n_hours > 0 and
                        now - self._last_kept_forever
                        >= self.keep_every_n_hours * 3600):
            # once kept-forever, always kept-forever: a re-save must not
            # demote the step into the ring where rotation deletes it
            st.setdefault("kept_forever", []).append(base)
            if not was_kept:
                self._last_kept_forever = now
        else:
            st["all_model_checkpoint_paths"].append(base)
        st["latest"] = base
        # ring rotation (max_to_keep, saver.py:448 parity); the 'best'
        # checkpoint survives rotation — it leaves the ring list but
        # its file stays until a better one supersedes it
        while len(st["all_model_checkpoint_paths"]) > self.max_to_keep:
            victim = st["all_model_checkpoint_paths"].pop(0)
            if victim == (st.get("best") or {}).get("path"):
                continue
            self._remove_victim(victim)
        self._write_state(st)

    def _write(self, arrays: dict[str, np.ndarray], step: int) -> str:
        with self._lock:
            path = self.checkpoint_path(step)
            self._atomic_npz(arrays, path)
            self._commit(os.path.basename(path))
            return path

    def _save_sharded(self, state: PyTree, step: int) -> str | None:
        """Every process writes its owned pieces in parallel; process 0
        commits the anchor after a cross-host barrier (two-phase: shard
        files first, then the tiny anchor — a torn save is invisible to
        restore because only the committed anchor is ever consulted)."""
        pieces, meta = _flatten_local(state)
        p, nprocs = jax.process_index(), jax.process_count()
        shard_path = os.path.join(
            self.directory, f"{PREFIX}-{step}.shard-{p}-of-{nprocs}.npz")
        os.makedirs(self.directory, exist_ok=True)
        pieces[_SHARD_META_KEY] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)

        def write_and_commit() -> str:
            with self._lock:
                self._atomic_npz(pieces, shard_path)
                if nprocs > 1:
                    from jax.experimental import multihost_utils
                    multihost_utils.sync_global_devices(
                        f"ckpt-shard-write-{step}")
                if self.is_writer:
                    anchor = self.shard_anchor_path(step)
                    tmp = anchor + ".tmp"
                    with open(tmp, "w") as f:
                        json.dump({"num_shards": nprocs, "step": step,
                                   "files": [f"{PREFIX}-{step}.shard-"
                                             f"{i}-of-{nprocs}.npz"
                                             for i in range(nprocs)]}, f)
                    os.replace(tmp, anchor)
                    self._commit(os.path.basename(anchor))
                if nprocs > 1:
                    from jax.experimental import multihost_utils
                    # non-writers must not read the state file before the
                    # writer's commit lands
                    multihost_utils.sync_global_devices(
                        f"ckpt-shard-commit-{step}")
                return shard_path

        if self._executor is not None:      # single-process only (ctor)
            with self._pending_lock:
                # consume-before-drain, same as save(): a failed write
                # surfaces exactly once
                pending, self._pending = self._pending, None
                if pending is not None:
                    pending.result()
                self._pending = self._executor.submit(write_and_commit)
            return shard_path
        return write_and_commit()

    def save_best(self, state: PyTree, step: int, metric_value: float,
                  *, mode: str = "max") -> bool:
        """Save ``state`` as the new best iff ``metric_value`` improves
        on the recorded best (tf.estimator BestExporter parity). The
        best checkpoint survives ring rotation until superseded; a
        superseded best that no other list references is deleted.
        Collective like :meth:`save` — every process must call it; the
        state-file bookkeeping is writer-only. Returns True when this
        step became the best."""
        if mode not in ("max", "min"):
            raise ValueError(f"keep_best mode must be max|min, got {mode!r}")
        self.wait()
        value = float(metric_value)
        best = self._state().get("best")
        if math.isnan(value):
            # a NaN 'best' would win every comparison forever
            improved = False
        elif best is None or math.isnan(best["value"]):
            improved = True
        else:
            improved = (value > best["value"] if mode == "max"
                        else value < best["value"])
        if jax.process_count() > 1:
            # the verdict must agree across hosts (save() is collective;
            # a stale state-file read on a non-writer would deadlock at
            # the gather) — the writer's view is authoritative, same as
            # _agreed_latest_step
            from jax.experimental import multihost_utils
            improved = bool(multihost_utils.broadcast_one_to_all(
                np.asarray(improved)))
        if not improved:
            return False
        self.save(state, step)
        if not self.is_writer:
            return True
        self.wait()                      # async save must land first
        with self._lock:
            st = self._state()
            old = st.get("best")
            base = os.path.basename(
                self.checkpoint_path(step) if os.path.exists(
                    self.checkpoint_path(step))
                else self.shard_anchor_path(step))
            st["best"] = {"path": base, "step": int(step),
                          "value": value}
            if (old and old["path"] != base
                    and old["path"] not in st["all_model_checkpoint_paths"]
                    and old["path"] not in st.get("kept_forever", [])):
                self._remove_victim(old["path"])
            self._write_state(st)
        return True

    def best_step(self) -> "int | None":
        """Step of the best checkpoint (None when never recorded)."""
        self.wait()
        best = self._state().get("best")
        return int(best["step"]) if best else None

    # -- integrity probing ------------------------------------------------
    def verify_step(self, step: int) -> None:
        """Read EVERY byte of ``step``'s checkpoint and check the recorded
        CRC32s. Raises CorruptCheckpointError (or FileNotFoundError when
        nothing exists at that step); returns None when the checkpoint is
        whole. This is the probe latest_valid_step / _agreed_latest_step
        use to pick a restore target that will actually restore."""
        path = self.checkpoint_path(step)
        if os.path.exists(path):
            # stream one member at a time (same as the sharded probe
            # below): the probe must not spike host RAM by the full
            # checkpoint size just to discard the arrays
            z = _VerifiedNpz(path, step)
            try:
                for k in z.files:
                    z[k]                   # read + CRC-check each
            finally:
                z.close()
            return
        anchor = self.shard_anchor_path(step)
        if not os.path.exists(anchor):
            raise FileNotFoundError(
                f"no checkpoint at step {step} under {self.directory!r}")
        try:
            with open(anchor) as f:
                files = json.load(f)["files"]
        except Exception as e:
            raise CorruptCheckpointError(
                f"checkpoint step {step} anchor {anchor!r} is unreadable "
                f"({type(e).__name__}: {e})") from e
        for b in files:
            p = os.path.join(self.directory, b)
            if not os.path.exists(p):
                raise CorruptCheckpointError(
                    f"checkpoint step {step} is missing shard file {b!r}")
            z = _VerifiedNpz(p, step)
            try:
                for k in z.files:
                    z[k]                       # read + CRC-check each
            finally:
                z.close()

    def latest_valid_step(self, max_step: int | None = None) -> int | None:
        """Newest step whose checkpoint passes verification, probing
        newest→oldest and logging each corrupt candidate it skips —
        the restore-target selector that makes a truncated latest file a
        logged fallback instead of a crashed run. ``max_step`` bounds the
        search (the rollback policy restores at or before the last
        KNOWN-CLEAN step, not merely the newest file)."""
        steps = self.all_steps()
        if max_step is not None:
            steps = [s for s in steps if s <= max_step]
        for step in reversed(steps):
            try:
                self.verify_step(step)
                return step
            except FileNotFoundError as e:
                log.error("checkpoint step %d failed verification (%s) — "
                          "falling back to the previous checkpoint", step, e)
        return None

    def discard_steps_above(self, step: int) -> list[int]:
        """Delete every checkpoint NEWER than ``step`` (writer-only;
        returns the discarded steps). The rollback policy's truncation:
        checkpoints saved after the last clean step embed the rejected
        (skipped-update) trajectory — leaving them on disk would make a
        restart resume the exact trajectory the rollback discarded. The
        'best' pointer is cleared too when it names a discarded step."""
        if not self.is_writer:
            return []
        with self._lock:
            st = self._state()
            discarded: list[int] = []

            def keep(base: str) -> bool:
                m = re.search(rf"{PREFIX}-(\d+)\.(npz|shards\.json)$", base)
                if m and int(m.group(1)) > step:
                    discarded.append(int(m.group(1)))
                    self._remove_victim(base)
                    return False
                return True

            st["all_model_checkpoint_paths"] = [
                b for b in st["all_model_checkpoint_paths"] if keep(b)]
            st["kept_forever"] = [b for b in st.get("kept_forever", [])
                                  if keep(b)]
            best = st.get("best")
            if best and int(best.get("step", -1)) > step:
                keep(best["path"])
                st["best"] = None
            if st["latest"] and not self._anchor_exists_base(st["latest"]):
                remaining = st["all_model_checkpoint_paths"] \
                    + st.get("kept_forever", [])
                st["latest"] = remaining[-1] if remaining else None
            self._write_state(st)
        return sorted(set(discarded))

    def _anchor_exists_base(self, base: str) -> bool:
        return os.path.exists(os.path.join(self.directory, base))

    def restore(self, template: PyTree, step: int | None = None,
                max_step: int | None = None) -> PyTree:
        """Load ``step`` (default: newest VALID) into the template's
        structure & shardings. Raises FileNotFoundError when nothing
        exists and CorruptCheckpointError when the requested step exists
        but cannot be trusted. With ``step=None``, a corrupt newest
        checkpoint is logged and the next-older valid one restored
        instead of crashing; ``max_step`` bounds that walk (the rollback
        policy's clean-step cap — verification happens WHILE reading, so
        the chosen checkpoint is read once, not probe+restore). The
        on-disk format (single-file vs sharded) is auto-detected, so a
        run may switch ``sharded`` modes across restarts."""
        self.wait()                # an in-flight async write may be `step`
        if step is None:
            steps = self.all_steps()
            if max_step is not None:
                steps = [s for s in steps if s <= max_step]
            if not steps:
                raise FileNotFoundError(
                    f"no checkpoint under {self.directory!r}"
                    + (f" at or before step {max_step}"
                       if max_step is not None else ""))
            last_err: Exception | None = None
            for s in reversed(steps):
                try:
                    out = self._restore_step(template, s)
                    if last_err is not None:
                        log.error("restored fallback checkpoint step %d "
                                  "(newer checkpoint was corrupt: %s)",
                                  s, last_err)
                    return out
                except CorruptCheckpointError as e:
                    log.error("checkpoint step %d corrupt (%s) — falling "
                              "back to the previous checkpoint", s, e)
                    last_err = e
            raise CorruptCheckpointError(
                f"every checkpoint under {self.directory!r} (steps "
                f"{steps}) failed verification; last error: {last_err}; "
                "no fallback remains")
        return self._restore_step(template, step)

    def _restore_step(self, template: PyTree, step: int) -> PyTree:
        faults.inject("ckpt.read", detail=f"restoring step {step}")
        path = self.checkpoint_path(step)
        if os.path.exists(path):
            return _unflatten(template, _load_npz_verified(path, step))
        if os.path.exists(self.shard_anchor_path(step)):
            return self._restore_sharded(template, step)
        raise FileNotFoundError(path)

    def _restore_sharded(self, template: PyTree, step: int) -> PyTree:
        with open(self.shard_anchor_path(step)) as f:
            anchor = json.load(f)
        paths = [os.path.join(self.directory, b) for b in anchor["files"]]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            raise CorruptCheckpointError(
                f"sharded checkpoint step {step} is missing shard files "
                f"{[os.path.basename(m) for m in missing]} — all shards "
                "must live on a filesystem every host can read")
        # lazy verified reads: only pieces this process's sharding needs
        # are read, and each is CRC-checked as it is consumed
        loads = {p: _VerifiedNpz(p, step) for p in paths}
        metas = _merge_metas(loads)
        try:
            paths_and_leaves, treedef = \
                jax.tree_util.tree_flatten_with_path(template)
            leaves = []
            for path_, tleaf in paths_and_leaves:
                key = _path_str(path_)
                entry = metas.get(key)
                if entry is None and key in DEFAULTABLE_LEAVES \
                        and hasattr(tleaf, "dtype"):
                    # checkpoints predating this leaf: default it
                    leaves.append(jax.numpy.zeros(
                        tuple(getattr(tleaf, "shape", ())), tleaf.dtype))
                    continue
                if entry is None:
                    raise KeyError(f"sharded checkpoint missing leaf {key!r}")
                if entry["kind"] == "prngkey":
                    kw = ({"impl": entry["impl"]} if "impl" in entry
                          else {})                   # pre-impl ckpts
                    leaves.append(jax.random.wrap_key_data(
                        np.asarray(_leaf_from_pieces(entry, loads)), **kw))
                    continue
                if tuple(entry["shape"]) != tuple(
                        getattr(tleaf, "shape", entry["shape"])):
                    raise ValueError(
                        f"checkpoint leaf {key!r} shape {entry['shape']} != "
                        f"template {tleaf.shape}")
                if hasattr(tleaf, "dtype") and \
                        str(entry["dtype"]) != str(tleaf.dtype):
                    raise ValueError(
                        f"checkpoint leaf {key!r} dtype {entry['dtype']} != "
                        f"template {tleaf.dtype}: restore with the same "
                        "param_dtype the checkpoint was written with")
                if (isinstance(tleaf, jax.Array)
                        and not tleaf.is_fully_addressable):
                    # selective read: each distinct wanted region is read
                    # (or assembled) ONCE, then placed per device. When
                    # every wanted region exactly matches a saved piece
                    # (same mesh on resume — the common case) no global
                    # assembly happens; otherwise the leaf is assembled
                    # once and sliced (resharding restore).
                    shape = tuple(entry["shape"])
                    dtype = entry["dtype"]
                    idx_map = tleaf.sharding.devices_indices_map(shape)
                    devs = list(tleaf.sharding.addressable_devices)
                    wants = {dev: _norm_index(idx_map[dev], shape)
                             for dev in devs}
                    by_bounds = {
                        tuple((s, s + d) for s, d in
                              zip(p["start"], p["shape"])): p
                        for p in entry["pieces"]}
                    region: dict = {}
                    distinct = set(wants.values())
                    if all(w in by_bounds for w in distinct):
                        for w in distinct:
                            p = by_bounds[w]
                            region[w] = _view_dtype(
                                loads[p["file"]][p["key"]], dtype)
                    else:
                        full = _leaf_from_pieces(entry, loads)
                        for w in distinct:
                            region[w] = full[tuple(slice(a, b)
                                                   for a, b in w)]
                    singles = [jax.device_put(region[wants[dev]], dev)
                               for dev in devs]
                    leaves.append(jax.make_array_from_single_device_arrays(
                        shape, tleaf.sharding, singles))
                else:
                    arr = _leaf_from_pieces(entry, loads)
                    if isinstance(tleaf, jax.Array):
                        leaves.append(jax.device_put(arr, tleaf.sharding))
                    else:
                        leaves.append(jax.numpy.asarray(arr))
            return jax.tree_util.tree_unflatten(treedef, leaves)
        finally:
            for z in loads.values():
                z.close()


def latest_checkpoint(directory: str) -> str | None:
    """Path of the newest checkpoint (tf.train.latest_checkpoint parity).
    For a sharded checkpoint this is its ``.shards.json`` anchor."""
    mgr = CheckpointManager(directory)
    step = mgr.latest_step()
    if step is None:
        return None
    single = mgr.checkpoint_path(step)
    return single if os.path.exists(single) else mgr.shard_anchor_path(step)


def _agreed_latest_step(manager: CheckpointManager,
                        max_step: int | None = None) -> int | None:
    """Latest step agreed across ALL processes.

    The restore-or-init decision must be identical everywhere: if process 0
    restores step N while another process inits fresh, the processes run
    different loop lengths and deadlock at the first collective. Only the
    chief's view is authoritative (it is the only writer), so its
    latest_step is broadcast; every process then verifies it can actually
    read that checkpoint — a mismatch means the checkpoint directory is not
    a shared filesystem, which this manager requires for multi-host runs
    (mirroring the reference, where workers restored through the chief's
    session rather than their own disk — session_manager.py:320-335).
    """
    # integrity-probed: the chief picks the newest checkpoint that
    # actually VERIFIES (CRC32s intact, every shard present), so a
    # truncated latest file on a restart becomes a broadcast fallback to
    # the previous valid step instead of a crash on some processes
    if jax.process_count() == 1:
        return manager.latest_valid_step(max_step)
    # only the chief's (authoritative, broadcast) view pays the
    # verification read; other processes' argument is ignored
    local = (manager.latest_valid_step(max_step)
             if jax.process_index() == 0 else None)
    from jax.experimental import multihost_utils
    chief = int(multihost_utils.broadcast_one_to_all(
        np.int64(-1 if local is None else local)))
    chief_step = None if chief < 0 else chief
    if chief_step is not None and not manager._anchor_exists(chief_step):
        raise FileNotFoundError(
            f"process {jax.process_index()} cannot read checkpoint step "
            f"{chief_step} that process 0 will restore: the checkpoint "
            f"directory {manager.directory!r} must be a filesystem shared "
            "by all hosts")
    return chief_step


def _agreed_best_step(manager: CheckpointManager) -> int | None:
    """Best step agreed across ALL processes (chief's view broadcast +
    local readability check — same contract as
    :func:`_agreed_latest_step`, for the keep_best pointer)."""
    local = manager.best_step()
    if jax.process_count() == 1:
        return local
    from jax.experimental import multihost_utils
    chief = int(multihost_utils.broadcast_one_to_all(
        np.int64(-1 if local is None else local)))
    chief_step = None if chief < 0 else chief
    if chief_step is not None and not manager._anchor_exists(chief_step):
        raise FileNotFoundError(
            f"process {jax.process_index()} cannot read best checkpoint "
            f"step {chief_step}: the checkpoint directory "
            f"{manager.directory!r} must be a shared filesystem")
    return chief_step


def restore_or_init(manager: CheckpointManager | None, init_fn,
                    *args, **kwargs):
    """The prepare_session decision (session_manager.py:320-335 parity):
    restore the latest checkpoint when one exists, else run ``init_fn``.

    Multi-host: the decision (and the step restored) is broadcast from
    process 0 so every process takes the same branch — see
    :func:`_agreed_latest_step`.

    Returns ``(state, restored: bool)``.
    """
    if manager is not None and jax.process_count() == 1:
        # single-process: the restore-or-init decision only needs a cheap
        # existence probe; restore(step=None) verifies WHILE reading and
        # falls back past corrupt files itself — one read of the chosen
        # checkpoint instead of a verify pass plus a restore pass. (All
        # candidates corrupt still raises: silently re-initializing from
        # scratch over a damaged directory would be worse than an error.)
        if manager.latest_step() is not None:
            template = init_fn(*args, **kwargs)
            return manager.restore(template, None), True
        return init_fn(*args, **kwargs), False
    # multi-host: the chief's verification read picks the step every
    # process then restores — the extra read is the price of agreement
    step = _agreed_latest_step(manager) if manager is not None else None
    if step is not None:
        template = init_fn(*args, **kwargs)
        return manager.restore(template, step), True
    return init_fn(*args, **kwargs), False
