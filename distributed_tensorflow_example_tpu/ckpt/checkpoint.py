"""Checkpoint save/restore with the reference Saver's semantics.

Parity map (SURVEY.md §3.4/§5.4 → here):

- graph-embedded SaveV2 op, chief fetches params from PS  →  process 0
  device_gets the state pytree and writes one ``.npz`` (path-keyed leaves)
  plus a small JSON sidecar (step, leaf metadata).
- ``model.ckpt-N`` + ``checkpoint`` state file  →  ``ckpt-N.npz`` + a JSON
  ``checkpoint`` file recording ``latest`` and ``all_model_checkpoint_paths``.
- ``max_to_keep=5`` ring (saver.py:448)  →  same ring, same default.
- restore-or-init decision (SessionManager.prepare_session:320-335)  →
  :func:`restore_or_init`.
- non-chief never writes  →  only ``jax.process_index() == 0`` writes;
  everyone restores (state is replicated/resharded on load).

Format note: npz (zip of npy) keeps this dependency-free and inspectable;
keys are ``/``-joined pytree paths. PRNG-key leaves are serialized via
``jax.random.key_data`` and rewrapped on load. bfloat16 leaves (npy cannot
represent ml_dtypes' bfloat16 — it round-trips as raw void) are stored as
uint16 bit patterns under a ``__bf16__/`` key prefix and viewed back on
load, so ``param_dtype=bfloat16`` states checkpoint losslessly.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
from typing import Any

import jax
import ml_dtypes
import numpy as np

from ..utils.pytree import is_prng_key as _is_key, path_str as _path_str

PyTree = Any

STATE_FILE = "checkpoint"          # parity with TF's 'checkpoint' proto file
PREFIX = "ckpt"


def _to_host(leaf) -> np.ndarray:
    """Fetch a (possibly multi-host-sharded) array to this host. For
    non-fully-addressable arrays (fsdp over processes) every process must
    participate in the gather — mirroring how the reference's SaveV2
    fetched params *from the PS* to the chief (SURVEY.md §3.4)."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(
            leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten(state: PyTree) -> dict[str, np.ndarray]:
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    out: dict[str, np.ndarray] = {}
    for path, leaf in flat:
        key = _path_str(path)
        if _is_key(leaf):
            out["__prngkey__/" + key] = np.asarray(jax.random.key_data(leaf))
        else:
            arr = _to_host(leaf)
            if arr.dtype == ml_dtypes.bfloat16:
                out["__bf16__/" + key] = arr.view(np.uint16)
            else:
                out[key] = arr
    return out


def _unflatten(template: PyTree, arrays: dict[str, np.ndarray]) -> PyTree:
    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, tleaf in paths_and_leaves:
        key = _path_str(path)
        if "__prngkey__/" + key in arrays:
            leaves.append(jax.random.wrap_key_data(
                np.asarray(arrays["__prngkey__/" + key])))
            continue
        if "__bf16__/" + key in arrays:
            leaf = arrays["__bf16__/" + key].view(ml_dtypes.bfloat16)
        elif key in arrays:
            leaf = arrays[key]
        else:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        if hasattr(tleaf, "shape") and tuple(leaf.shape) != tuple(tleaf.shape):
            raise ValueError(
                f"checkpoint leaf {key!r} shape {leaf.shape} != "
                f"template {tleaf.shape}")
        if hasattr(tleaf, "dtype") and leaf.dtype != tleaf.dtype:
            # a bf16 checkpoint restoring into an f32 run (or vice versa)
            # would otherwise continue silently at the wrong precision —
            # param_dtype must match across save and resume
            raise ValueError(
                f"checkpoint leaf {key!r} dtype {leaf.dtype} != template "
                f"{tleaf.dtype}: restore with the same param_dtype the "
                "checkpoint was written with")
        leaves.append(leaf)
    restored = jax.tree_util.tree_unflatten(treedef, leaves)
    # re-place on the template's shardings when it is device-resident
    def place(t, r):
        if isinstance(t, jax.Array) and hasattr(t, "sharding") and not _is_key(t):
            return jax.device_put(r, t.sharding)
        if _is_key(t):
            return r
        return jax.numpy.asarray(r)
    return jax.tree_util.tree_map(place, template, restored)


class CheckpointManager:
    """Write/restore checkpoints with a max_to_keep ring.

    Thread-safe save (the trainer's time-based saver thread mirrors the
    reference's SVTimerCheckpointThread, supervisor.py:1098).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 5,
                 keep_every_n_hours: float = 0.0, async_save: bool = False):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.keep_every_n_hours = keep_every_n_hours
        self.async_save = async_save
        self._lock = threading.Lock()
        # guards the _pending slot itself: save()/wait() can race from the
        # step-based and wall-clock saver threads (ADVICE r2); the write
        # payloads stay serialized by _lock + the 1-worker executor
        self._pending_lock = threading.Lock()
        self._pending: "Future | None" = None
        self._executor = None
        if async_save:
            from concurrent.futures import ThreadPoolExecutor
            # one writer thread, depth-1 queue: the reference's
            # SVTimerCheckpointThread wrote one checkpoint at a time too
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-writer")
        # start the keep-forever clock now (TF Saver semantics): the first
        # interval must actually elapse before a checkpoint is pinned
        self._last_kept_forever = time.time()
        if self.is_writer:
            os.makedirs(directory, exist_ok=True)

    @property
    def is_writer(self) -> bool:
        return jax.process_index() == 0

    # -- state file -------------------------------------------------------
    def _state(self) -> dict:
        p = os.path.join(self.directory, STATE_FILE)
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {"latest": None, "all_model_checkpoint_paths": [],
                "kept_forever": []}

    def _write_state(self, st: dict) -> None:
        p = os.path.join(self.directory, STATE_FILE)
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump(st, f, indent=1)
        os.replace(tmp, p)

    def checkpoint_path(self, step: int) -> str:
        return os.path.join(self.directory, f"{PREFIX}-{step}.npz")

    def all_steps(self) -> list[int]:
        self.wait()                # async write may not have landed yet
        st = self._state()
        steps = []
        for p in st["all_model_checkpoint_paths"] + st.get("kept_forever", []):
            m = re.search(rf"{PREFIX}-(\d+)\.npz$", p)
            if m and os.path.exists(os.path.join(self.directory, p)):
                steps.append(int(m.group(1)))
        return sorted(set(steps))

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save / restore ---------------------------------------------------
    def wait(self) -> None:
        """Block until an in-flight async write has landed (no-op when
        nothing is pending). Raises the writer thread's exception, if any."""
        with self._pending_lock:
            pending, self._pending = self._pending, None
        if pending is not None:
            pending.result()

    def close(self) -> None:
        self.wait()
        if self._executor is not None:
            self._executor.shutdown(wait=True)

    def save(self, state: PyTree, step: int | None = None) -> str | None:
        """Gather to host and write ``ckpt-<step>.npz``; rotate the ring.
        Non-writer processes only participate in the device_get (so all
        hosts stay in lockstep) and return None.

        With ``async_save``, the host gather is still synchronous (it is a
        cross-process collective for non-addressable arrays) but the disk
        write happens on a background thread — the analogue of the
        reference's checkpoint thread running off the training loop
        (supervisor.py:1098). A new save waits for the previous write.
        """
        if step is None:
            step = int(jax.device_get(state.step))
        arrays = _flatten(state)
        if not self.is_writer:
            return None
        if self._executor is not None:
            # depth-1 queue: drain the previous write (surfacing its
            # errors) and submit the new one under ONE lock hold, so two
            # concurrent save() calls cannot both pass the drain and
            # overwrite each other's Future
            with self._pending_lock:
                if self._pending is not None:
                    self._pending.result()
                self._pending = self._executor.submit(
                    self._write, arrays, step)
            return self.checkpoint_path(step)
        return self._write(arrays, step)

    def _write(self, arrays: dict[str, np.ndarray], step: int) -> str:
        with self._lock:
            path = self.checkpoint_path(step)
            fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
            os.close(fd)
            np.savez(tmp, **arrays)
            # np.savez appends .npz to names lacking it
            tmp_npz = tmp if tmp.endswith(".npz") else tmp + ".npz"
            os.replace(tmp_npz, path)
            if tmp != tmp_npz and os.path.exists(tmp):
                os.remove(tmp)

            st = self._state()
            base = os.path.basename(path)
            now = time.time()
            # a step may only live in ONE list: re-saving an existing step
            # (end-of-run save after restore, or a ring entry promoted to
            # kept-forever) must not leave a stale entry behind — ring
            # rotation would os.remove a file the other list still names
            if base in st["all_model_checkpoint_paths"]:
                st["all_model_checkpoint_paths"].remove(base)
            was_kept = base in st.get("kept_forever", [])
            if was_kept:
                st["kept_forever"].remove(base)
            if was_kept or (self.keep_every_n_hours > 0 and
                            now - self._last_kept_forever
                            >= self.keep_every_n_hours * 3600):
                # once kept-forever, always kept-forever: a re-save must not
                # demote the step into the ring where rotation deletes it
                st.setdefault("kept_forever", []).append(base)
                if not was_kept:
                    self._last_kept_forever = now
            else:
                st["all_model_checkpoint_paths"].append(base)
            st["latest"] = base
            # ring rotation (max_to_keep, saver.py:448 parity)
            while len(st["all_model_checkpoint_paths"]) > self.max_to_keep:
                victim = st["all_model_checkpoint_paths"].pop(0)
                vp = os.path.join(self.directory, victim)
                if os.path.exists(vp):
                    os.remove(vp)
            self._write_state(st)
            return path

    def restore(self, template: PyTree, step: int | None = None) -> PyTree:
        """Load ``step`` (default: latest) into the template's structure &
        shardings. Raises FileNotFoundError when nothing exists."""
        self.wait()                # an in-flight async write may be `step`
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint under {self.directory!r}")
        path = self.checkpoint_path(step)
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
        return _unflatten(template, arrays)


def latest_checkpoint(directory: str) -> str | None:
    """Path of the newest checkpoint (tf.train.latest_checkpoint parity)."""
    mgr = CheckpointManager(directory)
    step = mgr.latest_step()
    return mgr.checkpoint_path(step) if step is not None else None


def _agreed_latest_step(manager: CheckpointManager) -> int | None:
    """Latest step agreed across ALL processes.

    The restore-or-init decision must be identical everywhere: if process 0
    restores step N while another process inits fresh, the processes run
    different loop lengths and deadlock at the first collective. Only the
    chief's view is authoritative (it is the only writer), so its
    latest_step is broadcast; every process then verifies it can actually
    read that checkpoint — a mismatch means the checkpoint directory is not
    a shared filesystem, which this manager requires for multi-host runs
    (mirroring the reference, where workers restored through the chief's
    session rather than their own disk — session_manager.py:320-335).
    """
    local = manager.latest_step()
    if jax.process_count() == 1:
        return local
    from jax.experimental import multihost_utils
    chief = int(multihost_utils.broadcast_one_to_all(
        np.int64(-1 if local is None else local)))
    chief_step = None if chief < 0 else chief
    if chief_step is not None and not os.path.exists(
            manager.checkpoint_path(chief_step)):
        raise FileNotFoundError(
            f"process {jax.process_index()} cannot read checkpoint step "
            f"{chief_step} that process 0 will restore: the checkpoint "
            f"directory {manager.directory!r} must be a filesystem shared "
            "by all hosts")
    return chief_step


def restore_or_init(manager: CheckpointManager | None, init_fn,
                    *args, **kwargs):
    """The prepare_session decision (session_manager.py:320-335 parity):
    restore the latest checkpoint when one exists, else run ``init_fn``.

    Multi-host: the decision (and the step restored) is broadcast from
    process 0 so every process takes the same branch — see
    :func:`_agreed_latest_step`.

    Returns ``(state, restored: bool)``.
    """
    step = _agreed_latest_step(manager) if manager is not None else None
    if step is not None:
        template = init_fn(*args, **kwargs)
        return manager.restore(template, step), True
    return init_fn(*args, **kwargs), False
