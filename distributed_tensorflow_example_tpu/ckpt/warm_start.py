"""Warm-start: initialize part of a fresh model from a checkpoint.

``tf.train.init_from_checkpoint`` parity (the fine-tuning entry of the
reference era: load a pretrained encoder, keep the fresh head — the
``assignment_map`` scope-mapping contract), built on this repo's
checkpoint format instead of graph init ops. Unlike
``CheckpointManager.restore`` — which is resume (exact tree, step and
optimizer state included) — warm start touches ONLY the parameters the
map selects: the step stays 0, the optimizer state stays fresh, missing
leaves keep their fresh init, and a shape mismatch is a hard error
(same contract as init_from_checkpoint).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any

import jax
import ml_dtypes
import numpy as np

from ..utils.logging import get_logger
from ..utils.pytree import is_prng_key as _is_key, path_str as _path_str
from .checkpoint import (PREFIX, STATE_FILE, _leaf_from_pieces,
                         _merge_metas)

PyTree = Any

_log = get_logger("warm_start")


def load_checkpoint_arrays(ckpt: str, step: int | None = None
                           ) -> dict[str, np.ndarray]:
    """Flat {path: array} from a checkpoint — a ``ckpt-N.npz`` file or a
    checkpoint directory (latest step by default). Handles both on-disk
    formats (monolithic npz and sharded anchors) and restores bf16
    leaves to their real dtype. PRNG-key leaves are omitted (warm start
    never transplants random streams)."""
    if os.path.isfile(ckpt):
        path = ckpt
    else:
        state_file = os.path.join(ckpt, STATE_FILE)
        if step is None:
            if not os.path.exists(state_file):
                raise FileNotFoundError(
                    f"no '{STATE_FILE}' state file under {ckpt!r}")
            with open(state_file) as f:
                latest = json.load(f).get("latest")
            if latest is None:
                raise FileNotFoundError(f"no checkpoint under {ckpt!r}")
            path = os.path.join(ckpt, latest)
        else:
            path = os.path.join(ckpt, f"{PREFIX}-{step}.npz")
            if not os.path.exists(path):
                path = os.path.join(ckpt, f"{PREFIX}-{step}.shards.json")
                if not os.path.exists(path):
                    raise FileNotFoundError(
                        f"no checkpoint at step {step} under {ckpt!r}")

    if path.endswith(".shards.json"):
        with open(path) as f:
            anchor = json.load(f)
        directory = os.path.dirname(path)
        loads = {os.path.join(directory, b): np.load(
            os.path.join(directory, b)) for b in anchor["files"]}
        try:
            out = {}
            for key, entry in _merge_metas(loads).items():
                if entry["kind"] == "prngkey":
                    continue
                out[key] = np.asarray(_leaf_from_pieces(entry, loads))
            return out
        finally:
            for z in loads.values():
                z.close()

    with np.load(path) as z:
        out = {}
        for key in z.files:
            if key.startswith(("__prngkey__/", "__prngimpl__/",
                               "__shardmeta__")):
                continue
            if key.startswith("__bf16__/"):
                out[key[len("__bf16__/"):]] = \
                    z[key].view(ml_dtypes.bfloat16)
            else:
                out[key] = z[key]
        return out


@dataclasses.dataclass
class WarmStartReport:
    """What the map matched: ``restored`` params came from the
    checkpoint, ``fresh`` kept their initializer (no checkpoint key)."""

    restored: list[str]
    fresh: list[str]

    def __str__(self) -> str:
        return (f"warm-start: {len(self.restored)} restored, "
                f"{len(self.fresh)} fresh")


def warm_start(params: PyTree, ckpt: str,
               assignment_map: "dict[str, str] | None" = None, *,
               step: int | None = None, require_all: bool = False,
               ckpt_scope: str = "params"
               ) -> tuple[PyTree, WarmStartReport]:
    """Replace matching leaves of a freshly-initialized ``params`` tree
    with values from ``ckpt``.

    ``assignment_map`` maps checkpoint scopes to model scopes, exactly
    like ``tf.train.init_from_checkpoint``: ``{"encoder/": "enc/"}``
    loads checkpoint key ``encoder/X`` into model path ``enc/X``; the
    default ``{"": ""}`` matches identical paths. Entries apply
    independently (tf semantics): each is tried in insertion order and
    the first that RESOLVES to a checkpoint key wins — so
    ``{"bert/": "", "cls/": ""}`` restores both scopes even though
    every path prefix-matches the first entry.

    Values are cast to the target leaf's dtype and placed on its
    sharding. Shape mismatch → ValueError. A model path with no
    checkpoint key keeps its fresh value (ValueError instead when
    ``require_all``).
    """
    if assignment_map is None:
        assignment_map = {"": ""}
    arrays = load_checkpoint_arrays(ckpt, step=step)
    scope = ckpt_scope + "/" if ckpt_scope else ""
    available = {k[len(scope):]: v for k, v in arrays.items()
                 if k.startswith(scope)}
    if not available:
        raise ValueError(
            f"checkpoint {ckpt!r} holds no {ckpt_scope!r} leaves "
            f"(keys: {sorted(arrays)[:8]}...)")

    # a typo'd checkpoint-scope prefix would otherwise leave every
    # matching model path fresh with no signal (ADVICE r3 #5) — louder
    # than tf.train.init_from_checkpoint: a WARNING under the default
    # partial-restore contract (a scope may legitimately target a head
    # the checkpoint doesn't carry), a hard error under require_all
    for ck_prefix in assignment_map:
        if not any(k.startswith(ck_prefix) for k in available):
            msg = (f"warm start: assignment-map checkpoint scope "
                   f"{ck_prefix!r} matches no checkpoint key (have e.g. "
                   f"{sorted(available)[:5]}...)")
            if require_all:
                raise ValueError(msg)
            _log.warning("%s — the mapped model paths stay at their "
                         "fresh init", msg)

    restored: list[str] = []
    fresh: list[str] = []

    def lookup(path: str) -> np.ndarray | None:
        for ck_prefix, model_prefix in assignment_map.items():
            if path.startswith(model_prefix):
                key = ck_prefix + path[len(model_prefix):]
                if key in available:
                    return available[key]
                # entries apply independently: keep trying later ones
        return None

    def replace(path_tuple, leaf):
        path = _path_str(path_tuple)
        if _is_key(leaf):
            return leaf
        value = lookup(path)
        if value is None:
            fresh.append(path)
            if require_all:
                raise ValueError(
                    f"warm start: no checkpoint value for {path!r} "
                    "(require_all=True)")
            return leaf
        if tuple(value.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"warm start: shape mismatch for {path!r}: checkpoint "
                f"{tuple(value.shape)} vs model {tuple(np.shape(leaf))}")
        restored.append(path)
        value = value.astype(
            getattr(leaf, "dtype", value.dtype))
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            return jax.device_put(value, leaf.sharding)
        return jax.numpy.asarray(value)

    new_params = jax.tree_util.tree_map_with_path(replace, params)
    return new_params, WarmStartReport(restored=restored, fresh=fresh)


def parse_assignment_map(spec: str) -> "dict[str, str] | None":
    """CLI form: ``ckpt_prefix:model_prefix`` pairs, comma-separated
    (``bert/encoder/:encoder/``). Empty string → None (identity map)."""
    spec = spec.strip()
    if not spec:
        return None
    out: dict[str, str] = {}
    for pair in spec.split(","):
        if ":" not in pair:
            raise ValueError(
                f"bad --warm_start_map entry {pair!r} "
                "(want ckpt_prefix:model_prefix)")
        ck, model = pair.split(":", 1)
        if not re.fullmatch(r"[\w/.\-]*", ck + model):
            raise ValueError(f"bad --warm_start_map entry {pair!r}")
        out[ck] = model
    return out
