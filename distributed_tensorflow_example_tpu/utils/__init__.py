"""Utilities: logging, metrics, timing, profiling (SURVEY.md §5.1/§5.5)."""

from .logging import get_logger
from .metrics import MetricsLogger, RateTracker

__all__ = ["get_logger", "MetricsLogger", "RateTracker"]
