"""Shared pytree helpers: path rendering + leaf predicates.

One canonical ``/``-joined path string per leaf, used consistently by
sharding rules (rule regexes match these paths) and checkpoint keys (npz
entries are keyed by them) — a single renderer so the two can never drift.
"""

from __future__ import annotations

import jax


def path_str(path) -> str:
    """Render a jax key-path as 'a/b/0/c'."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def is_prng_key(x) -> bool:
    return isinstance(x, jax.Array) and jax.dtypes.issubdtype(
        x.dtype, jax.dtypes.prng_key)
