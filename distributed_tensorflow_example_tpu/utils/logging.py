"""Logging shim — ``tf_logging`` parity (SURVEY.md §5.5) over stdlib logging."""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(name)s] %(message)s"
_configured = False


def get_logger(name: str = "dtx") -> logging.Logger:
    global _configured
    if not _configured:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(_FORMAT))
        root = logging.getLogger("dtx")
        root.addHandler(h)
        root.setLevel(logging.INFO)
        root.propagate = False
        _configured = True
    return logging.getLogger(name if name.startswith("dtx") else f"dtx.{name}")
