"""Trace summary: aggregate device op time from a jax.profiler capture.

The reference fed per-step RunMetadata into a chrome-trace timeline
(SURVEY.md §5.1, `timeline.py`); the TPU-native capture is a
``jax.profiler.start_trace`` xplane protobuf. This tool reduces that
capture to the numbers a perf investigation actually starts from:

- per-line busy time (interval union — async DMA lines overlap compute,
  so naive event sums overcount several-fold);
- critical-path ("XLA Ops" line) time bucketed by op family
  (convolution/dot, fusion, async-copy, slice/dus, other);
- the top-K ops by total time, with shapes straight from the HLO names.

Usage::

    python -m distributed_tensorflow_example_tpu.utils.trace_summary \
        /tmp/trace_dir [--top 20] [--json] [--chrome out.trace.json]
    python -m distributed_tensorflow_example_tpu.utils.trace_summary \
        --fleet stitched.json [--json]

``--fleet`` summarizes a STITCHED fleet export (the router's
``GET /trace/fleet`` output, obs/stitch.py) offline instead of an
xplane capture: per-process span/lane counts and busy time, the span
vocabulary, per-trace-id request groups with their end-to-end duration
in the router clock, and the applied per-replica clock offsets.

``--chrome`` additionally emits the capture as a chrome://tracing /
Perfetto-loadable trace-event JSON — the direct analogue of the
reference's ``timeline.Timeline.generate_chrome_trace_format``
(SURVEY.md §5.1): one process per xplane device plane, one thread per
line, complete ("X") events in microseconds.

Parsing needs the xplane proto, vendored by the locally installed
TensorFlow wheel (``tensorflow.tsl.profiler.protobuf``) — an OPTIONAL
dependency: the framework never imports TF at runtime; this offline tool
degrades with a clear error when TF is absent.

The round-3 ResNet-50/BERT investigations in BASELINE.md ("ResNet-50
roofline") were produced with exactly this aggregation.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
from typing import Any


def _load_xspaces(trace_dir: str) -> list[tuple[str, Any]]:
    """Every capture file in the directory — a multi-host trace writes one
    xplane.pb per host; summarizing a single arbitrary file would hide
    cross-host imbalance. Returns [(filename, XSpace), ...]."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "trace_summary needs the xplane proto from the tensorflow "
            "wheel (offline tool only; the framework itself does not "
            "depend on TF)") from e
    paths = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {trace_dir}")
    out = []
    for p in paths:
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as f:
            xs.ParseFromString(f.read())
        # key by the path relative to trace_dir: two captures in one dir
        # share basenames (<host>.xplane.pb under timestamped subdirs)
        # and must not overwrite each other in the summary
        out.append((os.path.relpath(p, trace_dir), xs))
    return out


def _union_ms(intervals: list[tuple[int, int]]) -> float:
    """Total covered time of possibly-overlapping [start, end) ps spans."""
    intervals.sort()
    total = 0
    cur_s = cur_e = None
    for s, e in intervals:
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total / 1e9


def _defining_name(full_instruction: str) -> str:
    """The defining op name of an HLO instruction text (the part before
    ' = ') — the one rule shared by the family bucketing and the chrome
    export so the two views can never disagree."""
    return full_instruction.split(" = ")[0]


def _metadata_map(plane) -> dict[int, str]:
    return {m.id: m.name for m in plane.event_metadata.values()}


def _family(op_name: str) -> str:
    """Bucket by the DEFINING op name only — the event name is the full
    instruction text, so matching on the whole string would classify by
    operand names (an op consuming %fusion.44 is not a fusion). Caveat:
    XLA:TPU hides most convolutions and many dots inside fusion bodies, so
    'fusion' time includes the MXU compute they contain — bound MXU time
    with the flops roofline (cost_analysis flops / peak), not with this
    breakdown."""
    n = _defining_name(op_name).lower()
    if "copy-start" in n or "copy-done" in n:
        return "async-copy"
    if "convolution" in n or n.startswith("%dot"):
        return "conv/dot"
    if "fusion" in n:
        return "fusion"
    if "slice" in n or "dynamic" in n:
        return "slice/dus"
    if "all-reduce" in n or "all-gather" in n or "all-to-all" in n \
            or "collective" in n or "permute" in n:
        return "collective"
    if "copy" in n or "transpose" in n or "bitcast" in n:
        return "copy/transpose"
    return "other"


def summarize(trace_dir: str, top: int = 20,
              spaces: list[tuple[str, Any]] | None = None) -> dict[str, Any]:
    """Returns {device: {lines: [...], ops_line: {...}}} for every
    accelerator plane in the capture. ``spaces`` reuses already-parsed
    xplanes (multi-host captures are hundreds of MB per host)."""
    spaces = _load_xspaces(trace_dir) if spaces is None else spaces
    out: dict[str, Any] = {}
    for fname, xs in spaces:
        for plane in xs.planes:
            if "TPU" not in plane.name and "GPU" not in plane.name \
                    and "CPU" not in plane.name:
                continue
            key = (plane.name if len(spaces) == 1
                   else f"{fname}:{plane.name}")
            _summarize_plane(out, key, plane, top)
    if not out:
        raise RuntimeError("no device planes found in the capture")
    return out


def _summarize_plane(out: dict[str, Any], key: str, plane, top: int) -> None:
    meta = _metadata_map(plane)
    lines = []
    ops_line: dict[str, Any] | None = None
    for line in plane.lines:
        spans = []
        fam_ms: collections.Counter = collections.Counter()
        per_op: collections.Counter = collections.Counter()
        per_op_n: collections.Counter = collections.Counter()
        for ev in line.events:
            spans.append((ev.offset_ps, ev.offset_ps + ev.duration_ps))
            name = meta.get(ev.metadata_id, "?")
            fam_ms[_family(name)] += ev.duration_ps / 1e9
            per_op[name] += ev.duration_ps / 1e9
            per_op_n[name] += 1
        if not spans:
            continue
        rec = {
            "line": line.name,
            "events": len(spans),
            "busy_ms": round(_union_ms(spans), 3),
            "families_ms": {k: round(v, 3)
                            for k, v in fam_ms.most_common()},
        }
        lines.append(rec)
        if line.name == "XLA Ops":
            ops_line = dict(rec, top_ops=[
                {"ms": round(ms, 3), "count": per_op_n[name],
                 "op": name[:160]}
                for name, ms in per_op.most_common(top)])
    if lines:
        out[key] = {"lines": lines, "ops": ops_line}


def chrome_trace(trace_dir: str, *,
                 max_events_per_line: int | None = None,
                 spaces: list[tuple[str, Any]] | None = None
                 ) -> dict[str, Any]:
    """Convert a jax.profiler capture into chrome trace-event JSON
    (the reference timeline.py's output format, SURVEY.md §5.1).

    Every xplane plane becomes a chrome 'process', every line a
    'thread'; events are complete ("X") events with microsecond
    timestamps. Event times are absolute — ``XEvent.offset_ps`` is
    relative to its line's ``timestamp_ns``, so the line base is added
    back (then the capture's minimum is subtracted to keep numbers
    small) — which is what makes cross-line/cross-host alignment in the
    viewer correct. Perfetto and chrome://tracing load the result
    directly. ``max_events_per_line`` truncates pathologically dense
    lines (the longest captures carry hundreds of thousands of events).
    """
    from ..obs.trace import ChromeTraceWriter

    spaces = _load_xspaces(trace_dir) if spaces is None else spaces
    bases = [line.timestamp_ns * 1000                     # ns -> ps
             for _, xs in spaces for plane in xs.planes
             for line in plane.lines if line.events]
    if not bases:
        raise RuntimeError("no planes with events found in the capture")
    t0_ps = min(bases)

    # one emitter, two producers: the live scheduler/trainer span
    # recorder (obs/trace.py) and this offline xplane converter both
    # write through ChromeTraceWriter, so the event format (metadata
    # "M" naming + complete "X" events in µs) can never fork
    w = ChromeTraceWriter()
    for fname, xs in spaces:
        for plane in xs.planes:
            if not plane.lines:
                continue
            pid = w.pid(f"{fname}:{plane.name}")
            meta = _metadata_map(plane)
            seen: dict[str, int] = {}
            for line in plane.lines:
                # thread-pool captures repeat line names; the writer
                # keys lanes BY name, so duplicates must be suffixed or
                # two real threads would collapse onto one lane
                k = seen.get(line.name, 0)
                seen[line.name] = k + 1
                lname = f"{line.name} #{k + 1}" if k else line.name
                tid = w.tid(pid, lname)
                line_events = line.events
                if max_events_per_line is not None:
                    line_events = sorted(
                        line_events, key=lambda e: -e.duration_ps
                    )[:max_events_per_line]
                base_ps = line.timestamp_ns * 1000 - t0_ps
                for ev in line_events:
                    full = meta.get(ev.metadata_id, "?")
                    # HLO event names are whole instruction texts; the
                    # defining op name is the readable label
                    w.complete(
                        pid=pid, tid=tid,
                        name=_defining_name(full)[:120],
                        ts_us=(base_ps + ev.offset_ps) / 1e6,  # ps->us
                        dur_us=ev.duration_ps / 1e6,
                        args={"full_name": full[:400]})
    return w.to_dict()


def format_text(summary: dict[str, Any]) -> str:
    parts = []
    for dev, rec in summary.items():
        parts.append(f"== {dev}")
        for ln in rec["lines"]:
            fams = " ".join(f"{k}={v}ms" for k, v in
                            ln["families_ms"].items())
            parts.append(f"  line {ln['line']!r}: busy={ln['busy_ms']}ms "
                         f"events={ln['events']}  {fams}")
        ops = rec.get("ops")
        if ops:
            parts.append("  -- top ops (critical path):")
            for o in ops["top_ops"]:
                parts.append(f"    {o['ms']:9.3f} ms x{o['count']:<5d} "
                             f"{o['op']}")
    return "\n".join(parts)


def format_fleet(summary: dict[str, Any]) -> str:
    parts = []
    for p, rec in summary["processes"].items():
        parts.append(f"process {p!r}: {rec['spans']} span(s), "
                     f"busy={rec['busy_ms']}ms, lanes="
                     f"{', '.join(rec['lanes'])}")
    offs = summary.get("clock_offsets_s") or {}
    if offs:
        parts.append("clock offsets (s): " + " ".join(
            f"{k}={v}" for k, v in sorted(offs.items())))
    parts.append(f"span names: {', '.join(summary['span_names'])}")
    for t, rec in summary["traces"].items():
        parts.append(f"trace {t}: {rec['spans']} span(s) across "
                     f"{', '.join(rec['processes'])}, "
                     f"{rec['duration_ms']}ms end-to-end")
    return "\n".join(parts)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("trace_dir",
                    help="jax.profiler capture dir, or (with --fleet) "
                         "a stitched GET /trace/fleet JSON file")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--fleet", action="store_true",
                    help="summarize a STITCHED fleet trace "
                         "(obs/stitch.py output) instead of an xplane "
                         "capture")
    ap.add_argument("--chrome", metavar="OUT_JSON", default=None,
                    help="also write a chrome://tracing / Perfetto trace "
                         "(timeline.py parity)")
    ap.add_argument("--max_events_per_line", type=int, default=None,
                    help="keep only the N longest events per line in the "
                         "chrome trace (dense captures)")
    args = ap.parse_args(argv)
    if args.fleet:
        from ..obs.stitch import summarize_fleet
        with open(args.trace_dir) as f:
            stitched = json.load(f)
        s = summarize_fleet(stitched)
        print(json.dumps(s, indent=1) if args.json
              else format_fleet(s))
        return 0
    spaces = _load_xspaces(args.trace_dir)     # parse once, use twice
    s = summarize(args.trace_dir, top=args.top, spaces=spaces)
    print(json.dumps(s, indent=1) if args.json else format_text(s))
    if args.chrome:
        trace = chrome_trace(args.trace_dir, spaces=spaces,
                             max_events_per_line=args.max_events_per_line)
        with open(args.chrome, "w") as f:
            json.dump(trace, f)
        print(f"chrome trace: {args.chrome} "
              f"({len(trace['traceEvents'])} events)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
