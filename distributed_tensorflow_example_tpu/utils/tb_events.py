"""TensorBoard event-file writer — dependency-free tf.summary parity.

The reference's Supervisor ran a summary thread writing scalar summaries
into ``events.out.tfevents.*`` files that TensorBoard tails (SURVEY.md
§5.5: tf.summary FileWriter, supervisor.py:675-679). The framework's
primary metrics sink is the JSONL stream (utils/metrics.py), but event
files are the ecosystem's lingua franca, so this module writes them
natively — NO tensorflow/tensorboard import, just the two stable wire
formats involved:

- TFRecord framing: ``<len u64><masked crc32c(len) u32><payload>
  <masked crc32c(payload) u32>`` (little-endian);
- the ``Event``/``Summary`` protobuf messages, hand-encoded (protobuf
  wire format is stable and the three fields used here — wall_time=1,
  step=2, summary=5 with value{tag=1, simple_value=2} — are fixed).

Verified round-trip against TensorFlow's own ``summary_iterator`` in
``tests/test_tb_events.py`` (TF used only as a test oracle).
"""

from __future__ import annotations

import os
import socket
import struct
import time

# the TFRecord framing CRC lives with the container format (one
# implementation, native-accelerated when the C++ library is built)
from ..data.tfrecord import masked_crc32c as _masked_crc


# ---------------------------------------------------------------------------
# minimal protobuf encoding (wire types 0=varint, 1=fixed64, 2=bytes,
# 5=fixed32)
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _int64(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _bytes(field: int, v: bytes) -> bytes:
    return _key(field, 2) + _varint(len(v)) + v


def _scalar_event(step: int, tag: str, value: float,
                  wall_time: float) -> bytes:
    # Summary.Value{ tag=1:string, simple_value=2:float }
    sval = _bytes(1, tag.encode()) + _float(2, float(value))
    summary = _bytes(1, sval)                    # Summary{ value=1 repeated }
    # Event{ wall_time=1:double, step=2:int64, summary=5:message }
    return _double(1, wall_time) + _int64(2, step) + _bytes(5, summary)


def _file_version_event(wall_time: float) -> bytes:
    # Event{ wall_time=1, file_version=3:string } — TB expects "brain.Event:2"
    return _double(1, wall_time) + _bytes(3, b"brain.Event:2")


# ---------------------------------------------------------------------------
# HistogramProto (tf.summary.histogram parity)
# ---------------------------------------------------------------------------
# Fields: min=1 max=2 num=3 sum=4 sum_squares=5 (doubles),
# bucket_limit=6 bucket=7 (packed repeated doubles). Bucket semantics:
# bucket[i] counts values in (bucket_limit[i-1], bucket_limit[i]].

_DBL_MAX = 1.7976931348623157e308


def _packed_doubles(field: int, values) -> bytes:
    import numpy as _np
    payload = _np.asarray(values, _np.float64).tobytes()
    return _key(field, 2) + _varint(len(payload)) + payload


def _tf_bucket_limits(max_abs: float) -> list:
    """TF's default exponential buckets (histogram.cc: 1e-12 growing
    ×1.1), generated only up to the data range, mirrored negative, with
    the DBL_MAX catch-all."""
    pos = []
    v = 1e-12
    while v < max_abs * 1.1 and len(pos) < 1000:
        pos.append(v)
        v *= 1.1
    if not pos:
        pos = [1e-12]
    return [-x for x in reversed(pos)] + pos + [_DBL_MAX]


def _histogram_proto(values) -> bytes:
    import numpy as _np
    v = _np.asarray(values, _np.float64).reshape(-1)
    # the histogram shows the FINITE distribution: NaN/inf would make
    # searchsorted overflow the bucket list (malformed proto) and poison
    # the moments — non-finite debugging belongs to NanHook/checkify
    v = v[_np.isfinite(v)]
    if v.size == 0:
        v = _np.zeros((1,), _np.float64)
    limits = _np.asarray(_tf_bucket_limits(float(_np.max(_np.abs(v)))))
    # bucket i holds values <= limits[i] (and > limits[i-1])
    idx = _np.clip(_np.searchsorted(limits, v, side="left"), 0,
                   len(limits) - 1)
    counts = _np.bincount(idx, minlength=len(limits)).astype(_np.float64)
    nz = _np.nonzero(counts)[0]
    lo, hi = int(nz[0]), int(nz[-1])        # trim empty head/tail
    return (_double(1, float(v.min())) + _double(2, float(v.max()))
            + _double(3, float(v.size)) + _double(4, float(v.sum()))
            + _double(5, float((v * v).sum()))
            + _packed_doubles(6, limits[lo:hi + 1])
            + _packed_doubles(7, counts[lo:hi + 1]))


def _histo_event(step: int, tag: str, values, wall_time: float) -> bytes:
    # Summary.Value{ tag=1:string, histo=5:HistogramProto }
    # (4 is `image` — the legacy summary.proto field numbering)
    value = _bytes(1, tag.encode()) + _bytes(5, _histogram_proto(values))
    summary = _bytes(1, value)
    return _double(1, wall_time) + _int64(2, step) + _bytes(5, summary)


class EventFileWriter:
    """Append scalar summaries to an ``events.out.tfevents.*`` file.

    Usage::

        w = EventFileWriter(logdir)
        w.scalars(step, {"loss": 0.3, "accuracy": 0.9})
        w.close()
    """

    def __init__(self, logdir: str, *, filename_suffix: str = ""):
        os.makedirs(logdir, exist_ok=True)
        name = (f"events.out.tfevents.{int(time.time())}."
                f"{socket.gethostname()}.{os.getpid()}{filename_suffix}")
        self.path = os.path.join(logdir, name)
        self._f = open(self.path, "ab")
        self._record(_file_version_event(time.time()))
        self._f.flush()

    def _record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def scalar(self, step: int, tag: str, value: float,
               wall_time: float | None = None) -> None:
        self._record(_scalar_event(step, tag, value,
                                   time.time() if wall_time is None
                                   else wall_time))

    def scalars(self, step: int, values: dict[str, float],
                wall_time: float | None = None) -> None:
        wt = time.time() if wall_time is None else wall_time
        for tag, v in values.items():
            self.scalar(step, tag, v, wt)
        self._f.flush()

    def histogram(self, step: int, tag: str, values,
                  wall_time: float | None = None) -> None:
        """``tf.summary.histogram`` parity: any array-like of values,
        bucketed TF-style (exponential ×1.1 bins, mirrored)."""
        self._record(_histo_event(step, tag, values,
                                  time.time() if wall_time is None
                                  else wall_time))
        self._f.flush()

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()
