"""Metrics: JSONL sink + rate tracking.

Replaces ``tf.summary`` FileWriter event files and StepCounterHook's
steps/sec (SURVEY.md §5.5) with a JSONL stream (one object per record —
trivially greppable and the format ``bench.py`` consumes) plus
examples/sec/chip computation per the driver metric (BASELINE.json:2).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, TextIO

import jax


class MetricsLogger:
    """Append-only JSONL metrics writer; process 0 writes, like the chief's
    summary thread (supervisor.py:675-679 parity)."""

    def __init__(self, path: str | None = None, *, also_stdout: bool = False):
        self.path = path
        self.also_stdout = also_stdout
        self._f: TextIO | None = None
        if path and jax.process_index() == 0:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a", buffering=1)

    def log(self, record: dict[str, Any]) -> None:
        record = dict(record, time=time.time())
        line = json.dumps(record, default=float)
        if self._f is not None:
            self._f.write(line + "\n")
        if self.also_stdout and jax.process_index() == 0:
            print(line, flush=True)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class RateTracker:
    """steps/sec and examples/sec/chip over a sliding window
    (StepCounterHook parity, basic_session_run_hooks.py:674)."""

    def __init__(self, batch_size: int = 0, num_chips: int | None = None):
        self.batch_size = batch_size
        self.num_chips = num_chips or jax.device_count()
        self._t0: float | None = None
        self._s0 = 0

    def start(self, step: int) -> None:
        self._t0 = time.perf_counter()
        self._s0 = step

    def rates(self, step: int) -> dict[str, float]:
        """Rates since the last start(); restarts the window."""
        now = time.perf_counter()
        if self._t0 is None or step <= self._s0:
            self.start(step)
            return {}
        dt = now - self._t0
        steps = step - self._s0
        out = {
            "steps_per_sec": steps / dt,
            "sec_per_step": dt / steps,
        }
        if self.batch_size:
            out["examples_per_sec"] = steps * self.batch_size / dt
            out["examples_per_sec_per_chip"] = (
                out["examples_per_sec"] / self.num_chips)
        self.start(step)
        return out
