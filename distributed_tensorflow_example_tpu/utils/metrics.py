"""Metrics: JSONL sink + rate tracking.

Replaces ``tf.summary`` FileWriter event files and StepCounterHook's
steps/sec (SURVEY.md §5.5) with a JSONL stream (one object per record —
trivially greppable and the format ``bench.py`` consumes) plus
examples/sec/chip computation per the driver metric (BASELINE.json:2).
"""

from __future__ import annotations

import json
import numbers
import os
import time
from typing import Any, TextIO

import jax


class MetricsLogger:
    """Append-only JSONL metrics writer; process 0 writes, like the chief's
    summary thread (supervisor.py:675-679 parity). With ``tb_logdir`` the
    same records also stream to a TensorBoard event file
    (utils/tb_events.py — the tf.summary FileWriter role, SURVEY.md §5.5):
    every numeric field of a record that carries a ``step`` becomes a
    scalar, one-level-nested dicts flatten to ``outer/inner`` tags."""

    def __init__(self, path: str | None = None, *, also_stdout: bool = False,
                 tb_logdir: str | None = None, registry=None):
        self.path = path
        self.also_stdout = also_stdout
        self._f: TextIO | None = None
        self._tb = None
        # optional obs.registry.Registry: exposes how many structured
        # records this sink has written (a silent-death JSONL stream —
        # disk full, wrong path — shows up as a flatlined counter on
        # /metrics instead of an empty file discovered post-mortem)
        self._c_records = (registry.counter(
            "metrics_records_written_total",
            "structured JSONL records written by MetricsLogger")
            if registry is not None else None)
        if jax.process_index() == 0:
            if path:
                os.makedirs(os.path.dirname(os.path.abspath(path)),
                            exist_ok=True)
                self._f = open(path, "a", buffering=1)
            if tb_logdir:
                from .tb_events import EventFileWriter
                self._tb = EventFileWriter(tb_logdir)

    @staticmethod
    def _flatten_scalars(record: dict[str, Any]) -> dict[str, float]:
        def num(v):
            # numbers.Number covers numpy scalars too — the JSONL sink
            # accepts them via default=float, so the TB sink must as well
            return isinstance(v, numbers.Number)

        out: dict[str, float] = {}
        if "histogram" in record:
            # distribution records go to TB as HistogramProtos via
            # log_histogram; their JSONL summary stats are not scalars
            return out
        for k, v in record.items():
            if k in ("step", "time"):
                continue
            if isinstance(v, dict):
                for k2, v2 in v.items():
                    if num(v2):
                        out[f"{k}/{k2}"] = float(v2)
            elif num(v):
                out[k] = float(v)
        return out

    def log(self, record: dict[str, Any]) -> None:
        record = dict(record, time=time.time())
        line = json.dumps(record, default=float)
        if self._f is not None:
            self._f.write(line + "\n")
            if self._c_records is not None:
                self._c_records.inc()
        if self._tb is not None and "step" in record:
            scalars = self._flatten_scalars(record)
            if scalars:
                self._tb.scalars(int(record["step"]), scalars,
                                 wall_time=record["time"])
        if self.also_stdout and jax.process_index() == 0:
            print(line, flush=True)

    def log_histogram(self, step: int, tag: str, values) -> None:
        """Distribution record: the JSONL gets compact summary stats
        (greppable), the TB sink gets the full HistogramProto
        (tf.summary.histogram parity)."""
        import numpy as np
        v = np.asarray(values, np.float64).reshape(-1)
        if v.size == 0:
            return
        fin = v[np.isfinite(v)]
        stats = ({"min": float(fin.min()), "max": float(fin.max()),
                  "mean": float(fin.mean()), "std": float(fin.std())}
                 if fin.size else {})
        self.log({"step": step, "histogram": tag, **stats,
                  "count": int(v.size),
                  # NaN would be invalid strict JSON; surface the
                  # pathology as a count instead
                  "nonfinite": int(v.size - fin.size)})
        if self._tb is not None:
            self._tb.histogram(step, tag, v)

    def flush(self) -> None:
        """Push buffered JSONL bytes to disk (the serving drain path
        flushes before the process exits on SIGTERM)."""
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None


class RateTracker:
    """steps/sec and examples/sec/chip over a sliding window
    (StepCounterHook parity, basic_session_run_hooks.py:674)."""

    def __init__(self, batch_size: int = 0, num_chips: int | None = None):
        self.batch_size = batch_size
        self.num_chips = num_chips or jax.device_count()
        self._t0: float | None = None
        self._s0 = 0

    def start(self, step: int) -> None:
        self._t0 = time.perf_counter()
        self._s0 = step

    def rates(self, step: int) -> dict[str, float]:
        """Rates since the last start(); restarts the window."""
        now = time.perf_counter()
        if self._t0 is None or step <= self._s0:
            self.start(step)
            return {}
        dt = now - self._t0
        steps = step - self._s0
        out = {
            "steps_per_sec": steps / dt,
            "sec_per_step": dt / steps,
        }
        if self.batch_size:
            out["examples_per_sec"] = steps * self.batch_size / dt
            out["examples_per_sec_per_chip"] = (
                out["examples_per_sec"] / self.num_chips)
        self.start(step)
        return out
