"""Loss functions.

The reference used graph-mode softmax cross-entropy over one-hot MNIST
labels (SURVEY.md §2.1 'Model' row). All losses here reduce with a *mean*
over the batch so that, under data sharding, the gradient all-reduce is a
mean — matching the reference's explicit gradient averaging
(sync_replicas_optimizer.py:36-40 note; SURVEY.md §7 hard-parts item 2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _masked_mean(values: jax.Array, where) -> jax.Array:
    """Mean over examples, restricted by optional example weights
    ``where`` (the padded static-shape eval tail's mask) — the ONE
    masked-mean definition every metric/loss in this module shares."""
    if where is None:
        return jnp.mean(values)
    return jnp.sum(values * where) / jnp.maximum(jnp.sum(where), 1.0)


def softmax_xent(logits: jax.Array, onehot: jax.Array,
                 *, where=None) -> jax.Array:
    """Mean softmax cross-entropy against one-hot (or soft) targets."""
    logz = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    ll = jnp.sum(onehot * (logits - logz), axis=-1)
    return -_masked_mean(ll, where)


def token_nll(logits: jax.Array, labels: jax.Array, *,
              label_smoothing: float = 0.0) -> jax.Array:
    """Per-token negative log-likelihood (gather form, no one-hots) —
    the shared numerics core of :func:`softmax_xent_int_labels` and the
    chunked LM loss (models/gpt.py), so the two can never diverge."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, labels[..., None], axis=-1).squeeze(-1)
    if label_smoothing:
        eps = label_smoothing
        picked = (1.0 - eps) * picked + eps * jnp.mean(logits, axis=-1)
    return logz - picked


def softmax_xent_int_labels(logits: jax.Array, labels: jax.Array,
                            *, where=None,
                            label_smoothing: float = 0.0) -> jax.Array:
    """Mean softmax cross-entropy against integer labels (gather form —
    avoids materializing one-hots for big vocabularies like BERT's).

    ``label_smoothing=ε`` mixes the one-hot target with uniform mass:
    target log-likelihood becomes ``(1-ε)·logit_y + ε·mean(logits) -
    logz`` — algebraically identical to xent against the smoothed
    distribution, still without materializing one-hots.
    """
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}")
    return _masked_mean(
        token_nll(logits, labels, label_smoothing=label_smoothing), where)


def sigmoid_xent(logits: jax.Array, labels: jax.Array) -> jax.Array:
    log_p = jax.nn.log_sigmoid(logits)
    log_not_p = jax.nn.log_sigmoid(-logits)
    return -jnp.mean(labels * log_p + (1.0 - labels) * log_not_p)


def l2_regularization(params, scale: float) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(params)
    return scale * sum(jnp.sum(jnp.square(x)) for x in leaves)


def accuracy(logits: jax.Array, labels: jax.Array,
             *, where=None) -> jax.Array:
    """labels: integer classes. Returns mean accuracy (f32 scalar);
    ``where`` (example weights) restricts the mean — used by the padded
    static-shape eval tail."""
    hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
    return _masked_mean(hit, where)


def topk_accuracy(logits: jax.Array, labels: jax.Array, k: int,
                  *, where=None) -> jax.Array:
    """Top-k accuracy (in_top_k parity — the ImageNet recipes' second
    headline number). Counts a hit when the true class's logit ranks in
    the top k; ties resolve by logit comparison against the true
    class's logit, matching tf.nn.in_top_k semantics closely enough for
    distinct-logit models."""
    true_logit = jnp.take_along_axis(logits, labels[..., None],
                                     axis=-1)
    rank = jnp.sum((logits > true_logit).astype(jnp.int32), axis=-1)
    hit = (rank < k).astype(jnp.float32)
    return _masked_mean(hit, where)
