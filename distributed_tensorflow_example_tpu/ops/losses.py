"""Loss functions.

The reference used graph-mode softmax cross-entropy over one-hot MNIST
labels (SURVEY.md §2.1 'Model' row). All losses here reduce with a *mean*
over the batch so that, under data sharding, the gradient all-reduce is a
mean — matching the reference's explicit gradient averaging
(sync_replicas_optimizer.py:36-40 note; SURVEY.md §7 hard-parts item 2).

LM-head cross-entropy (the [B, S, V] logits chain) lives here too:
:func:`lm_head_xent` is the ONE implementation of weight-tied-head
softmax xent + token accuracy that every language model (GPT causal LM,
BERT MLM and its MoE/pipe variants) calls, with three interchangeable
impls — ``full`` (materialize logits: the parity oracle and kill
switch), ``chunked`` (sequence chunks under ``jax.checkpoint``) and
``fused`` (blockwise over the vocab with a custom VJP: the [.., V]
logits tensor never exists in forward OR backward — Wijmans et al.,
"Cut Your Losses in Large-Vocabulary Language Models", 2024; vocab-
blocked reduction in the spirit of Megatron-LM's vocab-parallel xent).
All three share the same post-logits numerics (:func:`lm_nll_hits` /
the fused forward computes the identical quantities online), so parity
is structural, not a kept-in-sync-by-comment contract.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _masked_mean(values: jax.Array, where) -> jax.Array:
    """Mean over examples, restricted by optional example weights
    ``where`` (the padded static-shape eval tail's mask) — the ONE
    masked-mean definition every metric/loss in this module shares."""
    if where is None:
        return jnp.mean(values)
    return jnp.sum(values * where) / jnp.maximum(jnp.sum(where), 1.0)


def softmax_xent(logits: jax.Array, onehot: jax.Array,
                 *, where=None) -> jax.Array:
    """Mean softmax cross-entropy against one-hot (or soft) targets."""
    logz = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    ll = jnp.sum(onehot * (logits - logz), axis=-1)
    return -_masked_mean(ll, where)


def token_nll(logits: jax.Array, labels: jax.Array, *,
              label_smoothing: float = 0.0) -> jax.Array:
    """Per-token negative log-likelihood (gather form, no one-hots) —
    the post-logits numerics :func:`softmax_xent_int_labels` and
    :func:`lm_nll_hits` (and through it every materialized-logits LM
    loss path) are built on."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, labels[..., None], axis=-1).squeeze(-1)
    if label_smoothing:
        eps = label_smoothing
        picked = (1.0 - eps) * picked + eps * jnp.mean(logits, axis=-1)
    return logz - picked


def softmax_xent_int_labels(logits: jax.Array, labels: jax.Array,
                            *, where=None,
                            label_smoothing: float = 0.0) -> jax.Array:
    """Mean softmax cross-entropy against integer labels (gather form —
    avoids materializing one-hots for big vocabularies like BERT's).

    ``label_smoothing=ε`` mixes the one-hot target with uniform mass:
    target log-likelihood becomes ``(1-ε)·logit_y + ε·mean(logits) -
    logz`` — algebraically identical to xent against the smoothed
    distribution, still without materializing one-hots.
    """
    if not 0.0 <= label_smoothing < 1.0:
        raise ValueError(
            f"label_smoothing must be in [0, 1), got {label_smoothing}")
    return _masked_mean(
        token_nll(logits, labels, label_smoothing=label_smoothing), where)


def sigmoid_xent(logits: jax.Array, labels: jax.Array) -> jax.Array:
    log_p = jax.nn.log_sigmoid(logits)
    log_not_p = jax.nn.log_sigmoid(-logits)
    return -jnp.mean(labels * log_p + (1.0 - labels) * log_not_p)


def l2_regularization(params, scale: float) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(params)
    return scale * sum(jnp.sum(jnp.square(x)) for x in leaves)


def accuracy(logits: jax.Array, labels: jax.Array,
             *, where=None) -> jax.Array:
    """labels: integer classes. Returns mean accuracy (f32 scalar);
    ``where`` (example weights) restricts the mean — used by the padded
    static-shape eval tail."""
    hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
    return _masked_mean(hit, where)


# ---------------------------------------------------------------------------
# LM-head cross-entropy: full / chunked / fused share ONE core
# ---------------------------------------------------------------------------

#: fused-path vocab tile when the caller leaves the knob at 0. 2048 keeps
#: the per-block [N, block] f32 logits tile at 1/15th of the 30,522-vocab
#: full tensor while the [N, H] @ [H, 2048] block matmul stays MXU-dense;
#: experiments/vocab_chain_sweep.py sweeps the choice.
DEFAULT_VOCAB_BLOCK = 2048

LM_LOSS_IMPLS = ("full", "chunked", "fused")


def lm_nll_hits(logits: jax.Array, labels: jax.Array, *,
                accuracy: bool = True):
    """Per-token ``(nll, hit)`` from materialized logits — the ONE
    post-logits numerics every materialized LM loss path (full,
    seq-chunked) runs, and the oracle the fused path's online pass is
    parity-tested against. ``accuracy=False`` statically drops the
    argmax (``hit`` is None): the per-step accuracy argmax costs real
    step time at a 30k vocab (measured 3.2 ms/step on the GPT-small
    bench config — BASELINE.md "Vocab chain")."""
    nll = token_nll(logits, labels)
    if not accuracy:
        return nll, None
    hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
    return nll, hit


def weighted_token_mean(nll: jax.Array, hit, w: jax.Array):
    """Weighted token means -> ``(loss, accuracy)``; ``hit=None`` (the
    argmax was skipped) publishes the -1.0 sentinel so a skipped metric
    can never be mistaken for a real 0-accuracy reading."""
    denom = jnp.maximum(jnp.sum(w), 1.0)
    loss = jnp.sum(nll * w) / denom
    if hit is None:
        return loss, jnp.float32(-1.0)
    return loss, jnp.sum(hit * w) / denom


def _head_logits(h: jax.Array, table: jax.Array, bias, dtype):
    """[..., H] @ [V, H]^T (+ bias) -> [..., V] f32 logits. The one
    LM-head matmul definition: compute dtype on the operands, f32
    accumulation/output (``preferred_element_type``) — identical math
    whether the caller materializes the full vocab or a block of it."""
    if dtype is not None:
        h = h.astype(dtype)
        table = table.astype(dtype)
    logits = jnp.einsum("...th,vh->...tv", h, table,
                        preferred_element_type=jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    return logits


def _vocab_blocks(table: jax.Array, bias, block: int):
    """[V, H] table (+ optional [V] bias) -> ([nb, block, H],
    [nb, block] | None, nb) zero-padded to a whole number of blocks;
    padded columns are masked to -inf by the scan bodies (a zero-padded
    row would contribute exp(h·0) = 1 to the softmax sum)."""
    v, hd = table.shape
    nb = -(-v // block)
    pad = nb * block - v
    if pad:
        table = jnp.pad(table, ((0, pad), (0, 0)))
        bias = None if bias is None else jnp.pad(bias, (0, pad))
    return (table.reshape(nb, block, hd),
            None if bias is None else bias.reshape(nb, block), nb)


def _fused_fwd_pass(h, table, bias, labels, block, dtype):
    """One ``lax.scan`` over vocab blocks: partial logits h @ E[v0:v1]^T,
    online logsumexp (running max + rescaled sumexp), the label's logit
    picked in whichever block holds it, and a running argmax — so
    token_accuracy rides the same pass instead of paying a separate
    full-vocab argmax. Returns per-token (nll, argmax, logz); at most
    one [N, block] logits tile is ever live."""
    v = table.shape[0]
    if dtype is not None:
        h = h.astype(dtype)
        table = table.astype(dtype)
    blocks, biases, nb = _vocab_blocks(table, bias, block)
    offs = jnp.arange(nb, dtype=jnp.int32) * block
    n = h.shape[0]
    init = (jnp.full((n,), -jnp.inf, jnp.float32),   # running max m
            jnp.zeros((n,), jnp.float32),            # sumexp scaled e^-m
            jnp.zeros((n,), jnp.float32),            # label's logit
            jnp.full((n,), -jnp.inf, jnp.float32),   # best logit
            jnp.zeros((n,), jnp.int32))              # best (argmax) index

    def body(carry, xs):
        m, s, picked, best, best_idx = carry
        blk, bb, off = xs
        logits = _head_logits(h, blk, bb, None)          # [n, block] f32
        cols = off + jnp.arange(block, dtype=jnp.int32)
        logits = jnp.where(cols[None, :] < v, logits, -jnp.inf)
        bm = jnp.max(logits, axis=-1)    # finite: every block has a
        m_new = jnp.maximum(m, bm)       # real column (nb = ceil(V/B))
        s = (s * jnp.exp(m - m_new)
             + jnp.sum(jnp.exp(logits - m_new[:, None]), axis=-1))
        rel = labels - off
        in_blk = (rel >= 0) & (rel < block)
        pick = jnp.take_along_axis(
            logits, jnp.clip(rel, 0, block - 1)[:, None], axis=-1)[:, 0]
        picked = jnp.where(in_blk, pick, picked)
        # strict > keeps the EARLIEST tied block, and the in-block argmax
        # keeps the earliest tied column — exactly jnp.argmax's
        # first-occurrence tie rule over the full vocab
        better = bm > best
        best = jnp.where(better, bm, best)
        best_idx = jnp.where(
            better, off + jnp.argmax(logits, axis=-1).astype(jnp.int32),
            best_idx)
        return (m_new, s, picked, best, best_idx), None

    (m, s, picked, _, best_idx), _ = lax.scan(body, init,
                                              (blocks, biases, offs))
    logz = m + jnp.log(s)
    return logz - picked, best_idx, logz


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _fused_nll_argmax(block, dtype, h, table, bias, labels):
    """Fused LM-head xent primal: per-token (nll f32, argmax int32)
    with no [N, V] logits tensor in forward or backward (custom VJP
    below regenerates each block's logits once)."""
    nll, best_idx, _ = _fused_fwd_pass(h, table, bias, labels, block,
                                       dtype)
    return nll, best_idx


def _fused_fwd(block, dtype, h, table, bias, labels):
    nll, best_idx, logz = _fused_fwd_pass(h, table, bias, labels, block,
                                          dtype)
    return (nll, best_idx), (h, table, bias, labels, logz)


def _fused_bwd(block, dtype, res, cts):
    """Blockwise backward: per vocab block, regenerate the [N, block]
    logits once, form d_logits = (softmax - onehot) · g, and accumulate
    dh (scan carry) and the tied-embedding/bias gradient (scan stack →
    [V, H]) — the full-vocab d_logits tensor never exists either."""
    g, _ = cts                    # cotangent for nll; argmax ct is float0
    h, table, bias, labels, logz = res
    v, hd = table.shape
    hc = h.astype(dtype) if dtype is not None else h
    blocks, biases, nb = _vocab_blocks(
        table.astype(dtype) if dtype is not None else table,
        bias, block)
    offs = jnp.arange(nb, dtype=jnp.int32) * block
    gf = g.astype(jnp.float32)

    def body(dh, xs):
        blk, bb, off = xs
        logits = _head_logits(hc, blk, bb, None)
        cols = off + jnp.arange(block, dtype=jnp.int32)
        logits = jnp.where(cols[None, :] < v, logits, -jnp.inf)
        p = jnp.exp(logits - logz[:, None])      # exp(-inf) = 0 on pads
        d = (p - (cols[None, :] == labels[:, None])) * gf[:, None]
        # backward matmuls in f32: the full-logits oracle's VJP
        # accumulates its f32 cotangent the same way
        dh = dh + jnp.einsum("nv,vh->nh", d, blk.astype(jnp.float32),
                             preferred_element_type=jnp.float32)
        dtab = jnp.einsum("nv,nh->vh", d, hc.astype(jnp.float32),
                          preferred_element_type=jnp.float32)
        # no dead dbias reductions on a bias-less (tied) head
        db = None if bias is None else jnp.sum(d, axis=0)
        return dh, (dtab, db)

    dh, (dtabs, dbs) = lax.scan(
        body, jnp.zeros(hc.shape, jnp.float32), (blocks, biases, offs))
    dtable = dtabs.reshape(nb * block, hd)[:v].astype(table.dtype)
    dbias = (None if bias is None
             else dbs.reshape(nb * block)[:v].astype(bias.dtype))
    return (dh.astype(h.dtype), dtable, dbias,
            np.zeros(labels.shape, jax.dtypes.float0))


_fused_nll_argmax.defvjp(_fused_fwd, _fused_bwd)


def fused_linear_xent(h: jax.Array, table: jax.Array, labels: jax.Array,
                      *, bias=None, vocab_block: int = 0, dtype=None):
    """Fused blockwise LM-head cross-entropy: ``h [..., H]`` against the
    (tied) embedding ``table [V, H]`` -> per-token ``(nll f32,
    argmax int32)`` without materializing ``[..., V]`` logits in either
    direction. ``vocab_block`` is the vocab tile (0 =
    ``DEFAULT_VOCAB_BLOCK``); V need not divide it (the tail block is
    padded and masked). ``dtype`` is the matmul compute dtype (bf16 on
    TPU), accumulation stays f32 — same recipe as the full-logits
    einsum it replaces."""
    block = int(vocab_block) if vocab_block else DEFAULT_VOCAB_BLOCK
    if block < 1:
        raise ValueError(
            f"lm_loss_vocab_block={vocab_block} invalid: must be >= 1 "
            "(or 0 for the default)")
    v = table.shape[0]
    lead = h.shape[:-1]
    n = math.prod(lead)
    h2 = h.reshape(n, h.shape[-1])
    lab = labels.reshape(n).astype(jnp.int32)
    nll, idx = _fused_nll_argmax(min(block, max(v, 1)), dtype, h2, table,
                                 bias, lab)
    return nll.reshape(lead), idx.reshape(lead)


def _chunked_lm_xent(h, table, labels, w, *, bias, seq_chunk, dtype,
                     accuracy):
    """Sequence-chunked LM-head xent: per seq chunk, compute the
    [B, chunk, V] logits + nll/hits and DROP them (``jax.checkpoint``
    recomputes in backward), so at most one chunk's logits are ever
    resident. The pre-fused-era memory lever; kept as the fallback."""
    b, s, hd = h.shape
    if s % seq_chunk:
        raise ValueError(
            f"loss_chunk={seq_chunk} must divide seq_len={s} (a silent "
            "full-logits fallback would OOM exactly the configs the "
            "knob exists for)")
    n = s // seq_chunk
    hs = h.reshape(b, n, seq_chunk, hd).transpose(1, 0, 2, 3)
    ts = labels.reshape(b, n, seq_chunk).transpose(1, 0, 2)
    ws = w.reshape(b, n, seq_chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def body(carry, xs):
        hh, tt, ww = xs
        nll, hit = lm_nll_hits(_head_logits(hh, table, bias, dtype), tt,
                               accuracy=accuracy)
        lsum, hsum, wsum = carry
        hadd = jnp.sum(hit * ww) if accuracy else 0.0
        return (lsum + jnp.sum(nll * ww), hsum + hadd,
                wsum + jnp.sum(ww)), None

    (lsum, hsum, wsum), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
               jnp.zeros((), jnp.float32)), (hs, ts, ws))
    denom = jnp.maximum(wsum, 1.0)
    if not accuracy:
        return lsum / denom, jnp.float32(-1.0)
    return lsum / denom, hsum / denom


def lm_head_xent(h: jax.Array, table: jax.Array, labels: jax.Array,
                 weights: jax.Array, *, bias=None, impl: str = "full",
                 seq_chunk: int = 0, vocab_block: int = 0, dtype=None,
                 accuracy: bool = True):
    """THE LM-head loss: weighted-mean softmax cross-entropy + token
    accuracy of ``h [..., T, H]`` decoded against the (tied) embedding
    ``table [V, H]``, returned as ``(loss, accuracy)`` scalars.

    ``impl`` picks the execution strategy — same numbers, different
    memory/time shape:

    - ``"full"``: materialize the [..., T, V] logits (the parity oracle
      and kill switch).
    - ``"chunked"``: sequence chunks of ``seq_chunk`` positions under
      ``jax.checkpoint`` (needs 3-D ``h``; the legacy
      ``--lm_loss_chunk`` path).
    - ``"fused"``: blockwise over ``vocab_block`` vocab columns with a
      custom VJP — no full logits in forward or backward, and the
      accuracy argmax rides the same pass for free.

    ``accuracy=False`` statically drops the argmax on the full/chunked
    paths (returns the -1.0 sentinel) — the ``token_accuracy_every_n``
    lever; the fused path's argmax is free and always on.
    """
    if impl not in LM_LOSS_IMPLS:
        raise ValueError(f"lm_loss_impl must be one of {LM_LOSS_IMPLS}, "
                         f"got {impl!r}")
    if vocab_block and impl != "fused":
        raise ValueError(
            f"lm_loss_vocab_block={vocab_block} tunes the fused vocab "
            f"scan and requires impl='fused', got {impl!r} (a silently "
            "ignored knob is worse than an error)")
    if seq_chunk and impl != "chunked":
        raise ValueError(
            f"seq_chunk={seq_chunk} is the chunked impl's lever; got "
            f"impl={impl!r}")
    w = weights.astype(jnp.float32)
    if impl == "fused":
        nll, pred = fused_linear_xent(h, table, labels, bias=bias,
                                      vocab_block=vocab_block,
                                      dtype=dtype)
        hit = (pred == labels).astype(jnp.float32)
        return weighted_token_mean(nll, hit, w)
    if impl == "chunked":
        if seq_chunk < 1:
            raise ValueError(
                "impl='chunked' needs seq_chunk >= 1 (lm_loss_chunk)")
        if h.ndim != 3:
            raise ValueError(
                f"chunked LM loss chunks the sequence axis of a "
                f"[B, S, H] hidden stream; got ndim={h.ndim}")
        return _chunked_lm_xent(h, table, labels, w, bias=bias,
                                seq_chunk=seq_chunk, dtype=dtype,
                                accuracy=accuracy)
    nll, hit = lm_nll_hits(_head_logits(h, table, bias, dtype), labels,
                           accuracy=accuracy)
    return weighted_token_mean(nll, hit, w)


def topk_accuracy(logits: jax.Array, labels: jax.Array, k: int,
                  *, where=None) -> jax.Array:
    """Top-k accuracy (in_top_k parity — the ImageNet recipes' second
    headline number). Counts a hit when the true class's logit ranks in
    the top k; ties resolve by logit comparison against the true
    class's logit, matching tf.nn.in_top_k semantics closely enough for
    distinct-logit models."""
    true_logit = jnp.take_along_axis(logits, labels[..., None],
                                     axis=-1)
    rank = jnp.sum((logits > true_logit).astype(jnp.int32), axis=-1)
    hit = (rank < k).astype(jnp.float32)
    return _masked_mean(hit, where)
