"""Mixture-of-Experts FFN with expert parallelism over the ``expert`` axis.

No MoE exists in the reference (SURVEY.md §2.5 marks EP absent); it is
built here because the framework reserves the ``expert`` mesh axis as a
first-class parallelism dimension and a reserved axis name is not a
capability (VERDICT r1 missing #6).

Two interchangeable implementations of the same math:

- :func:`moe_ffn` — dense dispatch/combine (Switch-Transformer layout):
  routing builds one-hot dispatch tensors and the whole layer is einsums,
  so under ``jit`` with expert-sharded weights (``P('expert', ...)``)
  GSPMD inserts the token exchange automatically. This is the production
  path: static shapes, MXU-friendly, composes with dp/fsdp/tp.
- :func:`moe_ffn_shard_map` — explicit expert parallelism: tokens sharded
  over ``expert``, a hand-written ``lax.all_to_all`` sends each token
  group to its expert's rank, local FFN, ``all_to_all`` back, combine.
  The literal EP dataflow (the analogue of what the reference's PS would
  have done with per-expert placement), used to assert the dense path's
  semantics in tests — the same auto/explicit pairing as
  ``parallel/sync_replicas.py``.

Routing: top-1 (Switch) or top-k via repeated argmax with masking;
capacity ``C = ceil(T/E · capacity_factor)`` per expert, overflow tokens
dropped (their residual path passes through untouched — standard Switch
semantics). Aux load-balancing loss per Switch Transformer §2.2:
``E · Σ_e fraction_tokens_e · mean_router_prob_e``.

Training-quality mechanisms (ST-MoE / Switch appendix; VERDICT r3 weak
#1): optional router JITTER noise (multiplicative uniform on the router
input, training only) decorrelates routing early in training; the router
Z-LOSS ``mean(logsumexp(logits)²)`` keeps router logits small and
training stable. Both paths also report routing VISIBILITY statistics —
``dropped_fraction`` (assignments lost to capacity overflow; the first
thing that silently goes wrong at scale) and per-expert ``expert_load``
(capacity-slot utilization in [0, 1]) — which MoeBert surfaces into the
per-step metrics stream.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from . import nn
from ..parallel.collectives import shard_map

Params = Any


def moe_ffn_init(rng: jax.Array, n_experts: int, hidden: int,
                 intermediate: int, *, param_dtype=jnp.float32) -> Params:
    """Router + per-expert FFN weights (stacked on a leading E dim, which
    sharding rules place on the ``expert`` axis)."""
    kr, ki, ko = jax.random.split(rng, 3)
    lim = math.sqrt(6.0 / (hidden + intermediate))
    return {
        "router": {"kernel": (jax.random.normal(kr, (hidden, n_experts),
                                                jnp.float32) * 0.02
                              ).astype(param_dtype)},
        "w_in": (jax.random.uniform(ki, (n_experts, hidden, intermediate),
                                    jnp.float32, -lim, lim)
                 ).astype(param_dtype),
        "b_in": jnp.zeros((n_experts, intermediate), param_dtype),
        "w_out": (jax.random.uniform(ko, (n_experts, intermediate, hidden),
                                     jnp.float32, -lim, lim)
                  ).astype(param_dtype),
        "b_out": jnp.zeros((n_experts, hidden), param_dtype),
    }


def aux_loss(frac_tokens: jax.Array, mean_probs: jax.Array,
             n_experts: int, k: int) -> jax.Array:
    """Switch load-balancing loss from routing statistics.

    Separated from :func:`_route` so the shard_map EP path can pmean the
    statistics over the expert axis FIRST and apply the formula to the
    global values — making dense and explicit-EP aux agree exactly (the
    formula is nonlinear in its inputs, so pmean(aux(local)) !=
    aux(pmean(local)))."""
    return n_experts * jnp.sum(frac_tokens / k * mean_probs)


def _route(router_params: Params, x2: jax.Array, n_experts: int, k: int,
           capacity: int, *, rng: jax.Array | None = None,
           jitter: float = 0.0):
    """x2: [T, D] -> (dispatch [T,E,C], combine [T,E,C], stats) where
    ``stats`` = {frac [E], mp [E], z scalar, kept [E]} — callers turn
    frac/mp into the load-balancing loss via :func:`aux_loss`, ``z`` is
    the ST-MoE router z-loss term, ``kept`` the per-expert count of
    assignments that fit under capacity.

    ``jitter`` (with ``rng``) multiplies the ROUTER's input by
    ``U[1-jitter, 1+jitter]`` — routing noise only; the expert compute
    sees the clean activations.

    Top-k by repeated masked argmax; per-expert slot positions via cumsum
    (all static shapes — no sort, no gather, TPU-friendly).
    """
    xr = x2.astype(jnp.float32)
    if jitter > 0.0 and rng is not None:
        xr = xr * jax.random.uniform(rng, x2.shape, jnp.float32,
                                     1.0 - jitter, 1.0 + jitter)
    logits = jnp.einsum("td,de->te", xr,
                        router_params["kernel"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                 # [T, E]
    # ST-MoE router z-loss: mean squared logsumexp keeps logits from
    # drifting large (f32 softmax headroom)
    z = jnp.mean(jnp.square(jax.scipy.special.logsumexp(logits, axis=-1)))

    remaining = probs
    counts = jnp.zeros((n_experts,), jnp.int32)             # slots used
    dispatch = jnp.zeros((x2.shape[0], n_experts, capacity), jnp.float32)
    combine = jnp.zeros_like(dispatch)
    total_assigned = jnp.zeros((x2.shape[0], n_experts), jnp.float32)

    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)             # [T]
        onehot = jax.nn.one_hot(choice, n_experts)          # [T, E]
        # slot index for each token within its chosen expert, in token order
        pos = (jnp.cumsum(onehot, axis=0) - 1 + counts) * onehot   # [T, E]
        keep = (pos < capacity) * onehot
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity)     # [T,E,C]
        d = keep[..., None] * slot
        gate = (probs * onehot).sum(-1, keepdims=True)      # chosen prob
        dispatch = dispatch + d
        combine = combine + d * gate[..., None]
        counts = counts + keep.sum(0).astype(jnp.int32)
        total_assigned = total_assigned + onehot
        remaining = remaining * (1.0 - onehot)              # mask the chosen

    # routing statistics for the Switch load-balance loss + visibility
    frac_tokens = total_assigned.mean(0)                    # [E]
    mean_probs = probs.mean(0)
    stats = {"frac": frac_tokens, "mp": mean_probs, "z": z,
             "kept": counts.astype(jnp.float32)}
    return dispatch, combine, stats


def _expert_compute(params: Params, inp: jax.Array, dtype, *,
                    psum_axis: str | None = None) -> jax.Array:
    """[E, C, D] -> [E, C, D]: the per-expert FFN (batched einsum over E —
    one MXU matmul per expert, stacked).

    ``psum_axis``: Megatron TP inside each expert — the caller holds
    w_in [E, H, I/tp] / w_out [E, I/tp, H] slices, the intermediate dim
    is partial, and the output contraction is closed by a psum over the
    named axis BEFORE the (full, unsharded-along-I) output bias."""
    h = jnp.einsum("ecd,edh->ech", inp.astype(dtype),
                   params["w_in"].astype(dtype),
                   preferred_element_type=jnp.float32)
    h = h + params["b_in"][:, None, :]
    h = jax.nn.gelu(h).astype(dtype)
    out = jnp.einsum("ech,ehd->ecd", h, params["w_out"].astype(dtype),
                     preferred_element_type=jnp.float32)
    if psum_axis is not None:
        out = lax.psum(out, psum_axis)
    return out + params["b_out"][:, None, :]


def capacity_for(tokens: int, n_experts: int,
                 capacity_factor: float) -> int:
    return max(1, math.ceil(tokens / n_experts * capacity_factor))


def _aux_pack(stats: dict, n_experts: int, k: int, tokens: int,
              capacity: int) -> dict:
    """Routing stats -> the aux dict both MoE paths return:

    - ``lb_loss``: Switch load-balancing loss (weight it into training)
    - ``z_loss``: ST-MoE router z-loss (weight it into training)
    - ``dropped_fraction``: share of the T·k routing assignments lost to
      capacity overflow — 0.0 means no token dropped
    - ``expert_load`` [E]: capacity-slot utilization per expert in [0,1]
    """
    kept = stats["kept"]
    return {
        "lb_loss": aux_loss(stats["frac"], stats["mp"], n_experts, k),
        "z_loss": stats["z"],
        "dropped_fraction": 1.0 - jnp.sum(kept) / float(tokens * k),
        "expert_load": kept / float(capacity),
    }


def moe_ffn(params: Params, x: jax.Array, *, n_experts: int, top_k: int = 1,
            capacity_factor: float = 1.25, dtype=jnp.float32,
            rng: jax.Array | None = None, jitter: float = 0.0
            ) -> tuple[jax.Array, dict]:
    """[B, S, D] -> ([B, S, D], aux dict — see :func:`_aux_pack`).
    Dense dispatch/combine MoE. ``rng``+``jitter`` enable router noise
    (training only — pass no rng at eval)."""
    b, s, d = x.shape
    t = b * s
    cap = capacity_for(t, n_experts, capacity_factor)
    x2 = x.reshape(t, d)
    dispatch, combine, stats = _route(params["router"], x2, n_experts,
                                      top_k, cap, rng=rng, jitter=jitter)
    aux = _aux_pack(stats, n_experts, top_k, t, cap)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dtype),
                           x2.astype(dtype),
                           preferred_element_type=jnp.float32)
    expert_out = _expert_compute(params, expert_in, dtype)
    out = jnp.einsum("tec,ecd->td", combine.astype(jnp.float32),
                     expert_out.astype(jnp.float32))
    return out.reshape(b, s, d).astype(x.dtype), aux


def moe_ffn_ep_body(p_local: Params, x_local: jax.Array, *,
                    n_experts: int, n_ranks: int, top_k: int,
                    capacity_factor: float, dtype,
                    axis_name: str, stat_axes,
                    model_axis: str | None = None,
                    rng: jax.Array | None = None,
                    jitter: float = 0.0) -> tuple[jax.Array, dict]:
    """The per-member EP dataflow — call INSIDE an active ``shard_map``
    whose ``axis_name`` axis shards tokens and expert weights (and whose
    ``stat_axes`` shard tokens). :func:`moe_ffn_shard_map` wraps it; the
    pipelined MoE model (EP×PP) calls it per stage tick. One
    implementation, every composition.

    ``x_local``: [B, S, D] — this member's token shard. ``p_local``'s
    expert arrays are the local [e_local, ...] slices. Returns
    (y_local, aux) with aux computed from stats pmean'd over
    ``stat_axes`` (global-batch values; the lb formula is nonlinear, so
    it must see the pmean'd stats)."""
    e_local = n_experts // n_ranks
    bl, sl, dl = x_local.shape
    tl = bl * sl
    x2 = x_local.reshape(tl, dl)
    cap = capacity_for(tl, n_experts, capacity_factor)
    lrng = rng
    if lrng is not None:
        # independent noise per token shard: fold in EVERY axis the
        # tokens are sharded over, not just the expert rank
        for ax in stat_axes:
            lrng = jax.random.fold_in(lrng, lax.axis_index(ax))
    dispatch, combine, stats = _route(p_local["router"], x2,
                                      n_experts, top_k, cap,
                                      rng=lrng, jitter=jitter)
    send = jnp.einsum("tec,td->ecd", dispatch.astype(dtype),
                      x2.astype(dtype),
                      preferred_element_type=jnp.float32)   # [E, C, D]
    # exchange: chunk j of the expert dim goes to rank j; rank r then
    # holds, source-rank-major, every rank's buffers for ITS experts
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                           tiled=True)
    # regroup [n_ranks · e_local, C, D] -> [e_local, n_ranks · C, D]
    recv = recv.reshape(n_ranks, e_local, cap, dl).transpose(1, 0, 2, 3)
    recv = recv.reshape(e_local, n_ranks * cap, dl)
    out = _expert_compute(
        {k: v for k, v in p_local.items() if k != "router"},
        recv, dtype, psum_axis=model_axis)                  # [e_l, nC, D]
    # send results back: invert the regrouping then all_to_all again
    back = out.reshape(e_local, n_ranks, cap, dl).transpose(1, 0, 2, 3)
    back = back.reshape(n_ranks * e_local, cap, dl)
    got = lax.all_to_all(back.astype(jnp.float32), axis_name,
                          split_axis=0, concat_axis=0, tiled=True)
    y = jnp.einsum("tec,ecd->td", combine.astype(jnp.float32), got)
    gstats = jax.tree_util.tree_map(
        lambda v: lax.pmean(v, stat_axes), stats)
    aux = _aux_pack(gstats, n_experts, top_k, tl, cap)
    return y.reshape(bl, sl, dl).astype(x_local.dtype), aux


def moe_ffn_shard_map(params: Params, x: jax.Array, mesh, *,
                      n_experts: int, top_k: int = 1,
                      capacity_factor: float = 1.25, dtype=jnp.float32,
                      axis_name: str = "expert",
                      batch_axes=("data", "fsdp"),
                      model_axis: str | None = None,
                      rng: jax.Array | None = None,
                      jitter: float = 0.0) -> tuple[jax.Array, dict]:
    """Explicit expert-parallel MoE: tokens sharded over the ``expert``
    axis, weights sharded one-expert-group-per-rank, exchange via
    ``lax.all_to_all`` (the EP collective; parallel/collectives.py).

    ``model_axis``: EP × TP — each local expert's FFN kernels are
    additionally Megatron-split over this axis (w_in [e, H, I/tp],
    w_out [e, I/tp, H]); every model rank routes the SAME tokens with
    the same rng (the model axis is deliberately NOT folded into the
    jitter key), runs its kernel slice, and a psum over ``model_axis``
    closes each expert FFN before the output bias.

    Output semantics match :func:`moe_ffn` exactly when no token is
    dropped (capacity is per-(source rank, expert) here, so use a
    generous capacity_factor when asserting parity). The aux statistics
    are pmean'd over every token-sharding axis FIRST — global-batch
    values — so lb/z/dropped match the dense path too; ``expert_load``
    matches when the per-rank capacity divides evenly (see
    tests/test_moe.py). Router jitter folds the rank index into ``rng``
    (each rank draws its own noise), so jittered routing is NOT
    bit-matched to the dense path — parity asserts use jitter=0.
    """
    from jax.sharding import PartitionSpec as P

    n_ranks = mesh.shape[axis_name]
    if n_experts % n_ranks:
        raise ValueError(f"{n_experts} experts not divisible over "
                         f"{n_ranks} '{axis_name}' ranks")
    if model_axis is not None:
        inter = params["w_in"].shape[2]
        if inter % mesh.shape[model_axis]:
            raise ValueError(
                f"intermediate dim {inter} not divisible over "
                f"{mesh.shape[model_axis]} '{model_axis}' ranks")
    batch_axes = tuple(a for a in batch_axes if a in mesh.shape)
    stat_axes = batch_axes + (axis_name,)

    e_local = n_experts // n_ranks

    def body(p_local, x_local):
        return moe_ffn_ep_body(
            p_local, x_local, n_experts=n_experts, n_ranks=n_ranks,
            top_k=top_k, capacity_factor=capacity_factor, dtype=dtype,
            axis_name=axis_name, stat_axes=stat_axes,
            model_axis=model_axis, rng=rng, jitter=jitter)

    xspec = P(batch_axes, axis_name, None)
    tp = model_axis
    pspec = {
        "router": jax.tree_util.tree_map(lambda _: P(), params["router"]),
        "w_in": P(axis_name, None, tp),
        "b_in": P(axis_name, tp),
        "w_out": P(axis_name, tp, None),
        "b_out": P(axis_name, None),
    }
    aux_spec = {"lb_loss": P(), "z_loss": P(), "dropped_fraction": P(),
                "expert_load": P()}
    fn = shard_map(body, mesh=mesh, in_specs=(pspec, xspec),
                       out_specs=(xspec, aux_spec), check_vma=False)
    return fn(params, x)
