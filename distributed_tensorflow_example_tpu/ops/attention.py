"""Multi-head attention with pluggable implementations.

No attention exists in the reference (MLP/CNN era — SURVEY.md §5.7); this
op exists because the framework treats long-context/transformer workloads
as first-class (BERT-base is reference workload 5, BASELINE.json:11).

Implementations:

- ``impl="xla"``: plain jnp einsum chain — XLA fuses it well at BERT-base
  scale; softmax in f32 for bf16 stability.
- ``impl="flash"``: Pallas blocked flash-attention kernel
  (:mod:`.pallas.flash_attention`) — O(S) memory, for long sequences.
- ring/context-parallel attention lives in
  :mod:`~distributed_tensorflow_example_tpu.parallel.ring_attention` and
  reuses these per-block primitives.

Shape convention: [batch, seq, heads, head_dim] (BSHD) throughout.
"""

from __future__ import annotations

import math
from typing import Any, Mapping

import jax
import jax.numpy as jnp


# mask fill value: large negative but finite, so online-softmax recurrences
# (ring/flash) can compute exp(NEG_INF - NEG_INF) paths without inf-inf=nan;
# far enough below any real score that exp underflows to exactly 0
NEG_INF = -1e30


def attention_scores(q: jax.Array, k: jax.Array) -> jax.Array:
    """[B,Sq,H,D] x [B,Sk,H,D] -> [B,H,Sq,Sk] scaled scores (f32)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    return s / math.sqrt(d)


def apply_mask(scores: jax.Array, mask: jax.Array | None,
               *, causal: bool = False,
               q_offset: int | jax.Array = 0,
               k_offset: int | jax.Array = 0) -> jax.Array:
    """mask: broadcastable to [B,1,1,Sk] (1 = attend). Causal uses global
    position offsets so sequence-sharded blocks (ring attention) mask
    correctly. Single source of truth for score masking — the ring and
    flash paths reuse this."""
    neg = jnp.asarray(NEG_INF, scores.dtype)
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, neg)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        qpos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0) + q_offset
        kpos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1) + k_offset
        scores = jnp.where(qpos >= kpos, scores, neg)
    return scores


def multi_head_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         mask: jax.Array | None = None,
                         causal: bool = False,
                         impl: str = "xla",
                         flash_kwargs: Mapping[str, Any] | None = None,
                         ) -> jax.Array:
    """[B,S,H,D] qkv -> [B,S,H,D] context. Softmax in f32.

    Fully-masked query rows (no valid key) return ZEROS under every impl:
    the flash/ring online-softmax recurrences produce 0 there naturally,
    and the xla path zeroes them explicitly (plain softmax over an all-
    NEG_INF row would return the uniform average of V instead). This keeps
    impl= a drop-in swap at padded rows.

    ``flash_kwargs``: kernel tuning levers (block_q/block_k/bwd_block/
    bwd_variant — see :func:`.pallas.flash_attention.flash_attention`);
    only meaningful with ``impl="flash"``, rejected loudly otherwise.
    """
    if impl == "flash":
        from .pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, mask=mask, causal=causal,
                               **(flash_kwargs or {}))
    if flash_kwargs:
        raise ValueError(
            f"flash_kwargs {sorted(flash_kwargs)} tune the Pallas kernel "
            f"and require impl='flash', got impl={impl!r}")
    if impl != "xla":
        raise ValueError(f"unknown attention impl {impl!r}")
    scores = attention_scores(q, k)
    scores = apply_mask(scores, mask, causal=causal)
    probs = jax.nn.softmax(scores, axis=-1)
    if mask is not None or causal:
        # zero fully-masked rows (same semantics as the flash/ring
        # recurrence); unmasked non-causal calls can't have any
        any_valid = jnp.any(scores > NEG_INF / 2, axis=-1, keepdims=True)
        probs = jnp.where(any_valid, probs, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(v.dtype)
