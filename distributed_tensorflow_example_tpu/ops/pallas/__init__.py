"""Pallas TPU kernels for the hot ops.

XLA's fusion covers most of this framework's compute; kernels live here
only where manual scheduling wins: flash attention (O(S) memory via online
softmax, blocked HBM→VMEM movement). See /opt/skills/guides/pallas_guide.md
for the kernel playbook this follows.
"""

from .decode_attention import decode_attention
from .flash_attention import flash_attention

__all__ = ["decode_attention", "flash_attention"]
