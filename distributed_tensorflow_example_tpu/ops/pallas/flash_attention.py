"""Blocked flash attention (forward + backward) as Pallas TPU kernels.

Memory-efficient attention: never materializes the [S, S] score matrix.
VMEM use is O(block), independent of S: K/V blocks are *streamed through
the grid* (the innermost, sequential grid dimension walks K blocks while
the online-softmax state — running max ``m``, normalizer ``l``, output
accumulator — lives in VMEM scratch that persists across grid steps). The
backward recomputes probabilities blockwise from the saved per-row
logsumexp ``L``.

Backward variants (``bwd_variant``):

- ``"split"`` (default, the round-2 kernel): the standard
  flash-attention-2 decomposition — a dq kernel streaming K blocks and a
  dk/dv kernel streaming Q/dO blocks, both operand streams O(block). Each
  kernel recomputes the score block ``s = qk^T`` and the ``dp = do v^T``
  block, so the pair does 7 block matmuls per (q, k) block pair and
  streams every operand twice.
- ``"fused"``: ONE kernel (grid walks k blocks outer, q blocks inner)
  computes dk, dv AND dq in a single pass — s/p/dp/ds are computed once
  and feed all three gradients (5 block matmuls per pair, ~29% fewer bwd
  matmul FLOPs, and K/V are not re-streamed by a second kernel). The dq
  accumulator is a full [S, head_dim] f32 VMEM slab (contributions for a
  q block arrive once per OUTER k step, so no O(block) scratch can hold
  them); the variant therefore engages only while the slab fits VMEM
  (``_FUSED_SLAB_LIMIT``) and falls back to ``"split"`` beyond — at
  S=4096, D=64 the slab is 1 MiB.

Block sizes are levers, not constants: ``block_q``/``block_k`` set the
forward tiles, ``bwd_block`` (one value for both streamed dims) the
backward tiles; ``config.TrainConfig`` exposes all of them next to
``attention_impl`` and ``experiments/flash_sweep.py`` sweeps them.

Layout: inputs [B, S, H, D] (the framework's BSHD convention) are folded to
[B*H, S, D] so the grid is (batch·head, q/k block, k/q block) and every
program's matmuls are [block, D] x [D, block] MXU tiles.

Scope/fallbacks: the kernel path requires MXU/Mosaic-friendly tiles —
S divisible by both block sizes, a lane-aligned K block (multiple of 128),
sublane-aligned Q block (multiple of 8) and D in {64, 128·k}. Anything else
(short sequences, odd head dims) falls back to the XLA path, which is the
right tool there anyway. On non-TPU backends kernels run in Pallas
interpret mode (tests on the virtual CPU mesh exercise the same code path).

Shares mask semantics with ops/attention.py (NEG_INF, 1 = attend); fully
masked query rows yield zeros (matching ``multi_head_attention``).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..attention import NEG_INF

# jax renamed TPUCompilerParams -> CompilerParams across the versions this
# repo meets (sandbox 0.4.x vs the chip runtime); take whichever exists
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

DEFAULT_BLOCK = 128

#: fused-bwd dq slab budget: the [S, head_dim] f32 accumulator must share
#: VMEM (~16 MiB less operand blocks) with the streamed tiles; past this
#: the fused variant silently degrades to "split" (same math, same
#: gradients — an availability boundary like ``_tile_friendly``, not an
#: error)
_FUSED_SLAB_LIMIT = 8 * 2**20

BWD_VARIANTS = ("split", "fused")

#: block matmuls per (q, k) block pair, by phase: the forward does qk^T
#: and pv; the split backward recomputes s and dp in BOTH of its kernels
#: (dq: s, dp, dq; dkv: s, dv, dp, dk); the fused backward computes each
#: once. Basis for ``attention_train_flops``.
_FWD_MATMULS = 2
_BWD_MATMULS = {"split": 7, "fused": 5}


def effective_bwd_variant(seq: int, head_dim: int,
                          bwd_variant: str = "split") -> str:
    """The backward variant that actually EXECUTES for these shapes:
    "fused" degrades to "split" when the dq slab would not fit VMEM
    (``_FUSED_SLAB_LIMIT``). Shared with the MFU accounting — counting
    5 fused matmuls while the 7-matmul split runs would understate
    analytic FLOPs by ~22% exactly where long-S comparability matters.
    """
    if bwd_variant == "fused" and seq * head_dim * 4 > _FUSED_SLAB_LIMIT:
        return "split"
    return bwd_variant


def attention_train_flops(batch: int, seq: int, hidden: int, layers: int,
                          *, causal: bool = False,
                          bwd_variant: str = "split") -> float:
    """Closed-form fwd+bwd FLOPs of the flash kernels for one train step.

    XLA cost analysis cannot see inside a Pallas custom call, so gate MFU
    for flash configs must add this analytically (VERDICT r5 weak #1).
    Each block matmul contracts [S, D] x [D, S] per head per batch element
    — 2·B·S²·hidden FLOPs summed over heads — and the kernel structure
    fixes the matmul count per phase (``_FWD_MATMULS``/``_BWD_MATMULS``).
    Causal grids skip blocks strictly above the diagonal: the live
    fraction is (nk+1)/(2·nk) ≈ 0.5, counted as exactly 0.5 (the +1/2nk
    diagonal sliver is below measurement noise at the gate shapes).
    """
    if bwd_variant not in _BWD_MATMULS:
        raise ValueError(f"bwd_variant must be one of {BWD_VARIANTS}, "
                         f"got {bwd_variant!r}")
    units = _FWD_MATMULS + _BWD_MATMULS[bwd_variant]
    total = units * 2.0 * batch * float(seq) ** 2 * hidden * layers
    return total * (0.5 if causal else 1.0)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_mask(s, mask_row, causal: bool, q_start, k_start,
                blk_q: int, blk_k: int):
    """Apply key-validity row mask and/or causal mask to a score block."""
    if mask_row is not None:
        s = jnp.where(mask_row != 0, s, NEG_INF)
    if causal:
        qpos = lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0) + q_start
        kpos = lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1) + k_start
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward kernel: grid (BH, nq, nk) — nk innermost, sequential, carries the
# online-softmax state in scratch
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *,
                blk_q: int, blk_k: int, nk: int, causal: bool,
                sm_scale: float):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: blocks strictly above the diagonal contribute nothing
    live = ((qi + 1) * blk_q - 1 >= ki * blk_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        mrow = mask_ref[0] if mask_ref is not None else None  # [1, blk_k]
        s = _block_mask(s, mrow, causal, qi * blk_q, ki * blk_k,
                        blk_q, blk_k)
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new) * (s > NEG_INF / 2)
        corr = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jnp.dot(
            p, v, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        m, l, acc = m_scr[...], l_scr[...], acc_scr[...]
        o_ref[0] = (acc / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)
        # logsumexp per row, saved for the backward recompute; kept
        # [blk_q, 1] (a trailing singleton dim matches the array dim, which
        # Mosaic tiles without sublane constraints)
        lse_ref[0] = m + jnp.log(jnp.maximum(l, 1e-20))


def _fwd(q3, k3, v3, mask2, *, heads: int, blk_q: int, blk_k: int,
         causal: bool):
    """q3,k3,v3: [BH, S, D]; mask2: [B, S] or None. Returns (o, L)."""
    bh, s, d = q3.shape
    sm_scale = 1.0 / math.sqrt(d)
    nq, nk = s // blk_q, s // blk_k
    grid = (bh, nq, nk)

    in_specs = [pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0))]
    args = [q3, k3, v3]
    kw = dict(blk_q=blk_q, blk_k=blk_k, nk=nk, causal=causal,
              sm_scale=sm_scale)
    if mask2 is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, blk_k), lambda b, i, j: (b // heads, 0, j)))
        args.append(mask2[:, None, :])
        kernel = functools.partial(_fwd_kernel, **kw)
    else:
        kernel = functools.partial(
            lambda qr, kr, vr, o, lr, m, l, a, **k: _fwd_kernel(
                qr, kr, vr, None, o, lr, m, l, a, **k), **kw)

    o, L = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, blk_q, 1), lambda b, i, j: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                   jax.ShapeDtypeStruct((bh, s, 1), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((blk_q, 1), jnp.float32),
                        pltpu.VMEM((blk_q, 1), jnp.float32),
                        pltpu.VMEM((blk_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*args)
    return o, L


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, L_ref, D_ref, mask_ref,
                   dq_ref, dq_scr, *, blk_q: int, blk_k: int, nk: int,
                   causal: bool, sm_scale: float):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = ((qi + 1) * blk_q - 1 >= ki * blk_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        Lrow, Drow = L_ref[0], D_ref[0]                   # [blk_q, 1]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        mrow = mask_ref[0] if mask_ref is not None else None
        s = _block_mask(s, mrow, causal, qi * blk_q, ki * blk_k,
                        blk_q, blk_k)
        p = jnp.exp(s - Lrow) * (s > NEG_INF / 2)         # [blk_q, blk_k]
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - Drow) * sm_scale
        dq_scr[...] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, L_ref, D_ref, mask_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, blk_q: int,
                    blk_k: int, nq: int, causal: bool, sm_scale: float):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = ((qi + 1) * blk_q - 1 >= ki * blk_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        Lrow, Drow = L_ref[0], D_ref[0]                   # [blk_q, 1]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        mrow = mask_ref[0] if mask_ref is not None else None
        s = _block_mask(s, mrow, causal, qi * blk_q, ki * blk_k,
                        blk_q, blk_k)
        p = jnp.exp(s - Lrow) * (s > NEG_INF / 2)         # [blk_q, blk_k]
        dv_scr[...] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # p.T @ do
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - Drow) * sm_scale
        dk_scr[...] += lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # ds.T @ q

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, L_ref, D_ref, mask_ref,
                      dq_ref, dk_ref, dv_ref, dq_slab, dk_scr, dv_scr, *,
                      blk_q: int, blk_k: int, nq: int, nk: int,
                      causal: bool, sm_scale: float):
    """One-pass backward: grid (BH, nk, nq), BOTH block dims sequential.

    For each (k block, q block) pair the score/probability/ds blocks are
    computed ONCE and feed dk, dv (O(block) scratch over the inner q
    walk, as in the split dkv kernel) and dq (accumulated into the full
    [S, D] f32 ``dq_slab`` — a q block's contributions arrive once per
    OUTER k step, ascending, which matches the split dq kernel's
    accumulation order exactly, so the two variants agree bit-for-bit).
    The dq output block is the whole [S, D] slab with a constant index
    map: Pallas copies it out once per batch-head, not per grid step.
    """
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    q_start = qi * blk_q

    @pl.when(ki == 0)
    def _init_dq():
        dq_slab[pl.dslice(q_start, blk_q), :] = jnp.zeros(
            (blk_q, dq_slab.shape[1]), jnp.float32)

    @pl.when(qi == 0)
    def _init_kv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = ((qi + 1) * blk_q - 1 >= ki * blk_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        Lrow, Drow = L_ref[0], D_ref[0]                   # [blk_q, 1]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        mrow = mask_ref[0] if mask_ref is not None else None
        s = _block_mask(s, mrow, causal, q_start, ki * blk_k,
                        blk_q, blk_k)
        p = jnp.exp(s - Lrow) * (s > NEG_INF / 2)         # [blk_q, blk_k]
        dv_scr[...] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # p.T @ do
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - Drow) * sm_scale
        dk_scr[...] += lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # ds.T @ q
        dq_slab[pl.dslice(q_start, blk_q), :] += jnp.dot(
            ds, k, preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize_kv():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)

    @pl.when(ki == nk - 1)
    def _finalize_dq():
        dq_ref[0, pl.dslice(q_start, blk_q), :] = dq_slab[
            pl.dslice(q_start, blk_q), :].astype(dq_ref.dtype)


def _bwd_fused(q3, k3, v3, do3, L, Dsum, mask2, *, heads: int, blk_q: int,
               blk_k: int, causal: bool):
    bh, s, d = q3.shape
    sm_scale = 1.0 / math.sqrt(d)
    nq, nk = s // blk_q, s // blk_k

    qspec = pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, j, 0))
    kspec = pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, i, 0))
    rowspec = pl.BlockSpec((1, blk_q, 1), lambda b, i, j: (b, j, 0))
    in_specs = [qspec, kspec, kspec, qspec, rowspec, rowspec]
    args = [q3, k3, v3, do3, L, Dsum]
    kw = dict(blk_q=blk_q, blk_k=blk_k, nq=nq, nk=nk, causal=causal,
              sm_scale=sm_scale)
    if mask2 is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, blk_k), lambda b, i, j: (b // heads, 0, i)))
        args.append(mask2[:, None, :])
        kernel = functools.partial(_bwd_fused_kernel, **kw)
    else:
        kernel = functools.partial(
            lambda qr, kr, vr, dor, lr, dr, dq, dk, dv, s0, s1, s2, **k:
            _bwd_fused_kernel(qr, kr, vr, dor, lr, dr, None, dq, dk, dv,
                              s0, s1, s2, **k), **kw)
    dq, dk, dv = pl.pallas_call(
        kernel, grid=(bh, nk, nq), in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, s, d), lambda b, i, j: (b, 0, 0)),
                   pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                   jax.ShapeDtypeStruct(k3.shape, k3.dtype),
                   jax.ShapeDtypeStruct(v3.shape, v3.dtype)],
        scratch_shapes=[pltpu.VMEM((s, d), jnp.float32),
                        pltpu.VMEM((blk_k, d), jnp.float32),
                        pltpu.VMEM((blk_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(*args)
    return dq, dk, dv


def _bwd(q3, k3, v3, o3, do3, L, mask2, *, heads: int, blk_q: int,
         blk_k: int, causal: bool, variant: str = "split"):
    bh, s, d = q3.shape
    sm_scale = 1.0 / math.sqrt(d)
    nq, nk = s // blk_q, s // blk_k
    Dsum = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                   axis=-1, keepdims=True)                # [BH, S, 1]
    if variant == "fused":
        return _bwd_fused(q3, k3, v3, do3, L, Dsum, mask2, heads=heads,
                          blk_q=blk_q, blk_k=blk_k, causal=causal)

    # dq: grid (BH, nq, nk) — K/V streamed innermost
    qspec = pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0))
    rowspec = pl.BlockSpec((1, blk_q, 1), lambda b, i, j: (b, i, 0))
    in_specs = [qspec, kspec, kspec, qspec, rowspec, rowspec]
    args = [q3, k3, v3, do3, L, Dsum]
    kw = dict(blk_q=blk_q, blk_k=blk_k, nk=nk, causal=causal,
              sm_scale=sm_scale)
    if mask2 is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, blk_k), lambda b, i, j: (b // heads, 0, j)))
        args.append(mask2[:, None, :])
        dq_kernel = functools.partial(_bwd_dq_kernel, **kw)
    else:
        dq_kernel = functools.partial(
            lambda qr, kr, vr, dor, lr, dr, dq, scr, **k: _bwd_dq_kernel(
                qr, kr, vr, dor, lr, dr, None, dq, scr, **k), **kw)
    dq = pl.pallas_call(
        dq_kernel, grid=(bh, nq, nk), in_specs=in_specs,
        out_specs=pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        scratch_shapes=[pltpu.VMEM((blk_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*args)

    # dk/dv: grid (BH, nk, nq) — Q/dO/L/D streamed innermost
    qspec = pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, j, 0))
    kspec = pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, i, 0))
    rowspec = pl.BlockSpec((1, blk_q, 1), lambda b, i, j: (b, j, 0))
    in_specs = [qspec, kspec, kspec, qspec, rowspec, rowspec]
    args = [q3, k3, v3, do3, L, Dsum]
    kw = dict(blk_q=blk_q, blk_k=blk_k, nq=nq, causal=causal,
              sm_scale=sm_scale)
    if mask2 is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, blk_k), lambda b, i, j: (b // heads, 0, i)))
        args.append(mask2[:, None, :])
        dkv_kernel = functools.partial(_bwd_dkv_kernel, **kw)
    else:
        dkv_kernel = functools.partial(
            lambda qr, kr, vr, dor, lr, dr, dk, dv, s1, s2, **k:
            _bwd_dkv_kernel(qr, kr, vr, dor, lr, dr, None, dk, dv, s1, s2,
                            **k), **kw)
    dk, dv = pl.pallas_call(
        dkv_kernel, grid=(bh, nk, nq), in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct(k3.shape, k3.dtype),
                   jax.ShapeDtypeStruct(v3.shape, v3.dtype)],
        scratch_shapes=[pltpu.VMEM((blk_k, d), jnp.float32),
                        pltpu.VMEM((blk_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrappers
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _make_flash(heads: int, blk_q: int, blk_k: int, bwd_q: int, bwd_k: int,
                bwd_variant: str, causal: bool, has_mask: bool):
    fwd_kw = dict(heads=heads, blk_q=blk_q, blk_k=blk_k, causal=causal)
    bwd_kw = dict(heads=heads, blk_q=bwd_q, blk_k=bwd_k, causal=causal,
                  variant=bwd_variant)

    @jax.custom_vjp
    def fn(q3, k3, v3, mask2):
        o, _ = _fwd(q3, k3, v3, mask2 if has_mask else None, **fwd_kw)
        return o

    def fwd(q3, k3, v3, mask2):
        o, L = _fwd(q3, k3, v3, mask2 if has_mask else None, **fwd_kw)
        return o, (q3, k3, v3, o, L, mask2)

    def bwd(res, do3):
        q3, k3, v3, o3, L, mask2 = res
        dq, dk, dv = _bwd(q3, k3, v3, o3, do3, L,
                          mask2 if has_mask else None, **bwd_kw)
        dmask = jnp.zeros_like(mask2) if mask2 is not None else None
        return dq, dk, dv, dmask

    fn.defvjp(fwd, bwd)
    return fn


def _tile_friendly(s: int, d: int, blk_q: int, blk_k: int) -> bool:
    """Mosaic tiling constraints for the kernel path: lane-dim K blocks
    must be 128-multiples, sublane-dim Q blocks 8-multiples, and the head
    dim MXU-aligned. Short/odd shapes fall back to XLA (which also dodges
    interpret-mode-passes-but-Mosaic-fails drift on real TPU)."""
    return (s % blk_q == 0 and s % blk_k == 0
            and blk_q % 8 == 0 and blk_k % 128 == 0
            and (d == 64 or d % 128 == 0))


def _resolve_blocks(s: int, block_q: int, block_k: int,
                    bwd_block: int) -> tuple[int, int, int, int]:
    """(fwd_q, fwd_k, bwd_q, bwd_k) clamped to the sequence length; a
    zero ``bwd_block`` inherits the forward tiles."""
    blk_q, blk_k = min(block_q, s), min(block_k, s)
    if bwd_block:
        bwd_q = bwd_k = min(bwd_block, s)
    else:
        bwd_q, bwd_k = blk_q, blk_k
    return blk_q, blk_k, bwd_q, bwd_k


def kernel_engages(seq: int, head_dim: int, *,
                   block_q: int = DEFAULT_BLOCK,
                   block_k: int = DEFAULT_BLOCK,
                   bwd_block: int = 0) -> bool:
    """True iff these shapes/blocks take the Pallas kernel path (vs the
    XLA fallback). Shared with bench.py's MFU accounting: analytic
    attention FLOPs must be added exactly when the custom call (which
    XLA cost analysis cannot see into) actually runs."""
    blk_q, blk_k, bwd_q, bwd_k = _resolve_blocks(seq, block_q, block_k,
                                                 bwd_block)
    return (_tile_friendly(seq, head_dim, blk_q, blk_k)
            and _tile_friendly(seq, head_dim, bwd_q, bwd_k))


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    mask: jax.Array | None = None, causal: bool = False,
                    block_q: int = DEFAULT_BLOCK,
                    block_k: int = DEFAULT_BLOCK,
                    bwd_block: int = 0,
                    bwd_variant: str = "split") -> jax.Array:
    """Drop-in for ``multi_head_attention(impl="xla")``: [B,S,H,D] in/out.

    ``mask``: [B,S] key-validity (1 = attend) or broadcastable [B,1,1,S].
    ``block_q``/``block_k`` tile the forward grid; ``bwd_block`` (0 =
    inherit the forward tiles) tiles BOTH streamed dims of the backward;
    ``bwd_variant`` picks the split (two-kernel) or fused (one-kernel)
    backward — see the module docstring. Falls back to the XLA path for
    tile-unfriendly shapes (see ``_tile_friendly``); nonsensical lever
    values (non-positive blocks, unknown variant) raise instead of
    silently falling back.
    """
    if block_q <= 0 or block_k <= 0 or bwd_block < 0:
        raise ValueError(
            f"block_q/block_k must be positive and bwd_block >= 0, got "
            f"block_q={block_q} block_k={block_k} bwd_block={bwd_block}")
    if bwd_variant not in BWD_VARIANTS:
        raise ValueError(f"bwd_variant must be one of {BWD_VARIANTS}, "
                         f"got {bwd_variant!r}")
    b, s, h, d = q.shape
    blk_q, blk_k, bwd_q, bwd_k = _resolve_blocks(s, block_q, block_k,
                                                 bwd_block)
    if not (_tile_friendly(s, d, blk_q, blk_k)
            and _tile_friendly(s, d, bwd_q, bwd_k)):
        from ..attention import multi_head_attention
        m4 = None
        if mask is not None:
            m4 = mask if mask.ndim == 4 else mask[:, None, None, :]
        return multi_head_attention(q, k, v, mask=m4, causal=causal,
                                    impl="xla")
    bwd_variant = effective_bwd_variant(s, d, bwd_variant)

    if mask is not None and mask.ndim == 4:
        mask = mask[:, 0, 0, :]

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    fn = _make_flash(h, blk_q, blk_k, bwd_q, bwd_k, bwd_variant, causal,
                     mask is not None)
    mask2 = (mask.astype(jnp.int32) if mask is not None
             else jnp.ones((b, s), jnp.int32))
    o3 = fn(fold(q), fold(k), fold(v), mask2)
    return o3.reshape(b, h, s, d).transpose(0, 2, 1, 3)
