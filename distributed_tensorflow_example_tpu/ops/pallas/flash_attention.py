"""Blocked flash attention (forward + backward) as Pallas TPU kernels.

Memory-efficient attention: never materializes the [S, S] score matrix.
The forward kernel streams K/V blocks through VMEM with the online-softmax
recurrence (running max ``m`` / normalizer ``l``) and saves only the
per-row logsumexp ``L`` for the backward; the backward recomputes
probabilities blockwise (dq kernel loops K-blocks, dk/dv kernel loops
Q-blocks) — the standard flash-attention-2 decomposition.

Layout: inputs [B, S, H, D] (the framework's BSHD convention) are folded to
[B*H, S, D] so the grid is (batch·head, block index) and every program's
matmuls are [block, D] x [D, block] MXU tiles.

Scope/fallbacks: S must divide by the block size and D should be MXU-lane
friendly (64/128); `flash_attention` falls back to the XLA path otherwise.
On non-TPU backends kernels run in Pallas interpret mode (tests on the
virtual CPU mesh exercise the same code path).

Shares mask semantics with ops/attention.py (NEG_INF, 1 = attend).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..attention import NEG_INF

DEFAULT_BLOCK = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, l_ref, *,
                blk_q: int, blk_k: int, seq_len: int, causal: bool,
                sm_scale: float):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale          # [blk_q, D]
    d = q.shape[-1]

    m0 = jnp.full((blk_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((blk_q, 1), jnp.float32)
    acc0 = jnp.zeros((blk_q, d), jnp.float32)

    nk = seq_len // blk_k
    if causal:
        # blocks strictly above the diagonal contribute nothing
        nk = jnp.minimum(nk, (qi + 1) * blk_q // blk_k
                         + (1 if blk_q % blk_k else 0))

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if mask_ref is not None:
            mrow = mask_ref[0, 0, pl.ds(i * blk_k, blk_k)]
            s = jnp.where(mrow[None, :] != 0, s, NEG_INF)
        if causal:
            qpos = lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0) \
                + qi * blk_q
            kpos = lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1) \
                + i * blk_k
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new) * (s > NEG_INF / 2)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.dot(p, v,
                                       preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, nk, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)
    # logsumexp per row, saved for the backward recompute; kept [blk_q, 1]
    # (Mosaic tiling: 2D blocks need sublane%8, a trailing singleton dim
    # sidesteps it by matching the array dim)
    l_ref[0] = m + jnp.log(jnp.maximum(l, 1e-20))


def _fwd(q3, k3, v3, mask2, *, heads: int, blk_q: int, blk_k: int,
         causal: bool):
    """q3,k3,v3: [BH, S, D]; mask2: [B, S] or None. Returns (o, L)."""
    bh, s, d = q3.shape
    sm_scale = 1.0 / math.sqrt(d)
    grid = (bh, s // blk_q)

    qspec = pl.BlockSpec((1, blk_q, d), lambda b, i: (b, i, 0))
    kvspec = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0))
    in_specs = [qspec, kvspec, kvspec]
    args = [q3, k3, v3]
    if mask2 is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, s), lambda b, i: (b // heads, 0, 0)))
        args.append(mask2[:, None, :])
        kernel = functools.partial(
            _fwd_kernel, blk_q=blk_q, blk_k=blk_k, seq_len=s,
            causal=causal, sm_scale=sm_scale)
    else:
        kernel = functools.partial(
            lambda qr, kr, vr, o, lr, **kw: _fwd_kernel(
                qr, kr, vr, None, o, lr, **kw),
            blk_q=blk_q, blk_k=blk_k, seq_len=s, causal=causal,
            sm_scale=sm_scale)

    o, L = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, blk_q, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, blk_q, 1), lambda b, i: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                   jax.ShapeDtypeStruct((bh, s, 1), jnp.float32)],
        interpret=_interpret(),
    )(*args)
    return o, L


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, L_ref, D_ref, mask_ref,
                   dq_ref, *, blk_q: int, blk_k: int, seq_len: int,
                   causal: bool, sm_scale: float):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale
    do = do_ref[0].astype(jnp.float32)                   # [blk_q, D]
    Lrow = L_ref[0]                                      # [blk_q, 1]
    Drow = D_ref[0]
    d = q.shape[-1]

    nk = seq_len // blk_k
    if causal:
        nk = jnp.minimum(nk, (qi + 1) * blk_q // blk_k
                         + (1 if blk_q % blk_k else 0))

    def body(i, dq):
        k = k_ref[0, pl.ds(i * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if mask_ref is not None:
            mrow = mask_ref[0, 0, pl.ds(i * blk_k, blk_k)]
            s = jnp.where(mrow[None, :] != 0, s, NEG_INF)
        if causal:
            qpos = lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0) \
                + qi * blk_q
            kpos = lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1) \
                + i * blk_k
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp(s - Lrow) * (s > NEG_INF / 2)        # [blk_q, blk_k]
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - Drow) * sm_scale
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, nk, body, jnp.zeros((blk_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, L_ref, D_ref, mask_ref,
                    dk_ref, dv_ref, *, blk_q: int, blk_k: int, seq_len: int,
                    causal: bool, sm_scale: float):
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                     # [blk_k, D]
    v = v_ref[0].astype(jnp.float32)
    d = k.shape[-1]
    if mask_ref is not None:
        mrow = mask_ref[0, 0][None, :]                   # [1, blk_k]
    nq = seq_len // blk_q
    start_q = 0
    if causal:
        start_q = ki * blk_k // blk_q                    # skip above-diagonal

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * blk_q, blk_q), :].astype(jnp.float32) \
            * sm_scale
        do = do_ref[0, pl.ds(i * blk_q, blk_q), :].astype(jnp.float32)
        Lrow = L_ref[0, pl.ds(i * blk_q, blk_q), :]
        Drow = D_ref[0, pl.ds(i * blk_q, blk_q), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if mask_ref is not None:
            s = jnp.where(mrow != 0, s, NEG_INF)
        if causal:
            qpos = lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0) \
                + i * blk_q
            kpos = lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1) \
                + ki * blk_k
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp(s - Lrow) * (s > NEG_INF / 2)
        dv_new = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - Drow) * sm_scale
        dk_new = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros((blk_k, d), jnp.float32)
    dv0 = jnp.zeros((blk_k, d), jnp.float32)
    dk, dv = lax.fori_loop(start_q, nq, body, (dk0, dv0))
    # dk accumulated against q*sm_scale: one sm_scale already applied in ds;
    # q here is pre-scaled, so divide the double-applied scale back out
    dk_ref[0] = (dk / sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(q3, k3, v3, o3, do3, L, mask2, *, heads: int, blk_q: int,
         blk_k: int, causal: bool):
    bh, s, d = q3.shape
    sm_scale = 1.0 / math.sqrt(d)
    Dsum = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                   axis=-1, keepdims=True)                # [BH, S, 1]

    common = dict(blk_k=blk_k, blk_q=blk_q, seq_len=s, causal=causal,
                  sm_scale=sm_scale)

    def specs(blocked_q: bool):
        big = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0))
        row = pl.BlockSpec((1, s, 1), lambda b, i: (b, 0, 0))
        if blocked_q:
            qs = pl.BlockSpec((1, blk_q, d), lambda b, i: (b, i, 0))
            ls = pl.BlockSpec((1, blk_q, 1), lambda b, i: (b, i, 0))
            return [qs, big, big, qs, ls, ls]
        ks = pl.BlockSpec((1, blk_k, d), lambda b, i: (b, i, 0))
        return [big, ks, ks, big, row, row]

    mask_spec = pl.BlockSpec((1, 1, s), lambda b, i: (b // heads, 0, 0))
    kmask_spec = pl.BlockSpec((1, 1, blk_k),
                              lambda b, i: (b // heads, 0, i))

    # dq: grid over q blocks
    in_specs = specs(blocked_q=True)
    args = [q3, k3, v3, do3, L, Dsum]
    if mask2 is not None:
        in_specs.append(mask_spec)
        args.append(mask2[:, None, :])
        dq_kernel = functools.partial(_bwd_dq_kernel, **common)
    else:
        dq_kernel = functools.partial(
            lambda qr, kr, vr, dor, lr, dr, dq, **kw: _bwd_dq_kernel(
                qr, kr, vr, dor, lr, dr, None, dq, **kw), **common)
    dq = pl.pallas_call(
        dq_kernel, grid=(bh, s // blk_q), in_specs=in_specs,
        out_specs=pl.BlockSpec((1, blk_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        interpret=_interpret(),
    )(*args)

    # dk/dv: grid over k blocks
    in_specs = specs(blocked_q=False)
    args = [q3, k3, v3, do3, L, Dsum]
    if mask2 is not None:
        in_specs.append(kmask_spec)
        args.append(mask2[:, None, :])
        dkv_kernel = functools.partial(_bwd_dkv_kernel, **common)
    else:
        dkv_kernel = functools.partial(
            lambda qr, kr, vr, dor, lr, dr, dk, dv, **kw: _bwd_dkv_kernel(
                qr, kr, vr, dor, lr, dr, None, dk, dv, **kw), **common)
    dk, dv = pl.pallas_call(
        dkv_kernel, grid=(bh, s // blk_k), in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, blk_k, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, blk_k, d), lambda b, i: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct(k3.shape, k3.dtype),
                   jax.ShapeDtypeStruct(v3.shape, v3.dtype)],
        interpret=_interpret(),
    )(*args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrappers
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _make_flash(heads: int, blk_q: int, blk_k: int, causal: bool,
                has_mask: bool):
    kw = dict(heads=heads, blk_q=blk_q, blk_k=blk_k, causal=causal)

    @jax.custom_vjp
    def fn(q3, k3, v3, mask2):
        o, _ = _fwd(q3, k3, v3, mask2 if has_mask else None, **kw)
        return o

    def fwd(q3, k3, v3, mask2):
        o, L = _fwd(q3, k3, v3, mask2 if has_mask else None, **kw)
        return o, (q3, k3, v3, o, L, mask2)

    def bwd(res, do3):
        q3, k3, v3, o3, L, mask2 = res
        dq, dk, dv = _bwd(q3, k3, v3, o3, do3, L,
                          mask2 if has_mask else None, **kw)
        dmask = jnp.zeros_like(mask2) if mask2 is not None else None
        return dq, dk, dv, dmask

    fn.defvjp(fwd, bwd)
    return fn


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    mask: jax.Array | None = None, causal: bool = False,
                    block_q: int = DEFAULT_BLOCK,
                    block_k: int = DEFAULT_BLOCK) -> jax.Array:
    """Drop-in for ``multi_head_attention(impl="xla")``: [B,S,H,D] in/out.

    ``mask``: [B,S] key-validity (1 = attend) or broadcastable [B,1,1,S].
    Falls back to the XLA path when S doesn't divide the block size.
    """
    b, s, h, d = q.shape
    blk_q = min(block_q, s)
    blk_k = min(block_k, s)
    if s % blk_q or s % blk_k:
        from ..attention import multi_head_attention
        m4 = None
        if mask is not None:
            m4 = mask if mask.ndim == 4 else mask[:, None, None, :]
        return multi_head_attention(q, k, v, mask=m4, causal=causal,
                                    impl="xla")

    if mask is not None and mask.ndim == 4:
        mask = mask[:, 0, 0, :]

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    fn = _make_flash(h, blk_q, blk_k, causal, mask is not None)
    mask2 = (mask.astype(jnp.int32) if mask is not None
             else jnp.ones((b, s), jnp.int32))
    o3 = fn(fold(q), fold(k), fold(v), mask2)
    return o3.reshape(b, h, s, d).transpose(0, 2, 1, 3)
