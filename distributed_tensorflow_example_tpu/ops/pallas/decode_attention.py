"""Single-query decode attention over the KV-cache slab as one Pallas kernel.

The decode step's attention is the per-op latency floor's biggest owner
after layernorm (PROFILE_r05_decode: ~36 attention fusions at ~15 µs per
token-step — mask build, score, softmax, context as separate small XLA
fusions on [B, 1, H, D]-sized tensors). This kernel collapses that chain
into ONE program per (batch row, head): it reads K/V straight from the
[B, T, H, D] cache slab (no transpose, no repacking — the BlockSpec
index map picks the head plane), builds the ragged ``pad``/``pos``
validity mask from scalars in SMEM with an in-kernel iota, and runs the
f32 softmax + context matmul in VMEM. One kernel per layer per token
step instead of ~4-6.

Numerics mirror ``ops.attention.multi_head_attention(impl="xla")``:
scores in f32 scaled by 1/sqrt(D), NEG_INF masking (exp underflows to
exactly 0 — slot ``pos`` is always valid, so no fully-masked rows
exist), probabilities cast to the value dtype before the context matmul
with f32 accumulation.

Scope: the kernel path needs Mosaic-friendly tiles — the score row's
lane dim is the cache length T (T % 128 == 0) and the head dim must be
MXU-aligned (D == 64 or D % 128 == 0). Anything else falls back to the
XLA path (which the ``"loop"`` decode impl uses anyway). On non-TPU
backends the kernel runs in Pallas interpret mode so CPU tests exercise
the same code path (same recipe as flash_attention).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..attention import NEG_INF, multi_head_attention

# jax renamed TPUCompilerParams -> CompilerParams across the versions this
# repo meets (sandbox 0.4.x vs the chip runtime); take whichever exists
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def tile_friendly(total: int, head_dim: int) -> bool:
    """Kernel-path tile constraints: the [1, T] score row puts T in the
    lane dim (128-multiples) and the context matmul wants an MXU-aligned
    head dim — the same D rule as flash_attention."""
    return total % 128 == 0 and (head_dim == 64 or head_dim % 128 == 0)


def _kernel(pos_ref, pad_ref, q_ref, k_ref, v_ref, o_ref, *,
            total: int, sm_scale: float):
    b = pl.program_id(0)
    q = q_ref[0].astype(jnp.float32)                    # [1, D]
    k = k_ref[0].astype(jnp.float32)                    # [T, D]
    v = v_ref[0]                                        # [T, D]
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * sm_scale
    # ragged pad/pos mask fused in: slot j of row b is live iff
    # pad_b <= j <= pos_b (pos_b = the slot row b's current token sits
    # at — per-row since the continuous-batching engine, where slots
    # admitted at different times sit at different depths)
    kpos = lax.broadcasted_iota(jnp.int32, (1, total), 1)
    live = (kpos <= pos_ref[b]) & (kpos >= pad_ref[b])
    s = jnp.where(live, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)                                  # masked -> exact 0
    probs = (p / jnp.sum(p, axis=-1, keepdims=True)).astype(v.dtype)
    o_ref[0] = lax.dot_general(
        probs, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _dispatch(q, k, v, pos, pad):
    """Grid (B, H); per program ONE [T, D] K/V plane of the cache slab.

    Mosaic tiling note: the per-head plane is carved out of the
    [B, T, H·D] *view* of the slab (a free, contiguous reshape), so
    every block's trailing 2-D tile is [T, D] (sublane T — a 128-
    multiple — by lane D) or a [1, D] row whose singleton matches its
    array dim. Blocking the 4-D [B, T, H, D] layout directly would put
    a size-1 tile against the H dim (neither 8-divisible nor the array
    dim) — the interpret-passes-but-Mosaic-fails shape documented in
    the verify notes."""
    b, t, h, d = k.shape
    q3 = q.reshape(b * h, 1, d)
    k3 = k.reshape(b, t, h * d)
    v3 = v.reshape(b, t, h * d)
    out = pl.pallas_call(
        functools.partial(_kernel, total=t, sm_scale=1.0 / math.sqrt(d)),
        grid=(b, h),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),               # pos [B]
            pl.BlockSpec(memory_space=pltpu.SMEM),               # pad [B]
            pl.BlockSpec((1, 1, d), lambda bb, hh: (bb * h + hh, 0, 0)),
            pl.BlockSpec((1, t, d), lambda bb, hh: (bb, 0, hh)),
            pl.BlockSpec((1, t, d), lambda bb, hh: (bb, 0, hh)),
        ],
        out_specs=pl.BlockSpec((1, 1, d),
                               lambda bb, hh: (bb * h + hh, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, 1, d), v.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=_interpret(),
    )(pos, pad, q3, k3, v3)
    return out.reshape(b, h, d)


def xla_decode_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         pos, pad) -> jax.Array:
    """Reference path: the exact ``multi_head_attention(impl="xla")``
    call the ``"loop"`` decode step makes — the kernel's parity oracle
    and the fallback for tile-unfriendly shapes."""
    total = k.shape[1]
    slots = jnp.arange(total, dtype=jnp.int32)
    pos = jnp.asarray(pos, jnp.int32)
    pos_b = pos[:, None] if pos.ndim == 1 else pos
    live = (slots[None, :] <= pos_b) & (slots[None, :] >= pad[:, None])
    ctx = multi_head_attention(q[:, None], k, v,
                               mask=live[:, None, None, :], impl="xla")
    return ctx[:, 0]


# ---------------------------------------------------------------------------
# block-paged decode attention (round 10): K/V live in a shared block pool
# [N, block_size, H, D] instead of per-slot slabs; each row's logical cache
# is the run of physical blocks its block-table row names. Both impls gather
# THROUGH the table: the XLA fallback with one advanced-indexing gather (then
# the exact slab reference math), the kernel with scalar-prefetch index maps
# (the block id is read from SMEM before each K/V block's DMA is issued — no
# gathered [B, T, H, D] tensor ever exists).
#
# K-query speculative verify (round 16): the verify program presents BOTH
# impls with row-expanded queries — K lanes of one slot become K rows at
# consecutive `pos` values sharing one block-table row (repeated in
# `block_tables`). Neither impl needs a special case: rows are independent
# by construction, which is exactly the property the engine's exact-accept
# rule rides; kernel-vs-gather parity on the expanded shape is pinned in
# tests/test_paged_serving.py.
# ---------------------------------------------------------------------------

def paged_tile_friendly(block_size: int, head_dim: int) -> bool:
    """Paged-kernel tile constraints: each score row is [1, block_size]
    (block_size in the lane dim — 128-multiples) and the context matmul
    wants the same MXU-aligned head dim as the slab kernel."""
    return block_size % 128 == 0 and (head_dim == 64
                                      or head_dim % 128 == 0)


def xla_paged_decode_attention(q: jax.Array, k_pool: jax.Array,
                               v_pool: jax.Array, *, block_tables,
                               pos, pad, k_scale=None,
                               v_scale=None) -> jax.Array:
    """Reference path: gather each row's block run out of the pool (one
    advanced-indexing gather -> the row's [T, H, D] logical cache, with
    T = blocks_per_row * block_size) and run the exact slab reference.
    Bitwise equal to the slab path on equal logical contents — the
    paged byte-parity oracle.

    int8 pools (``k_scale``/``v_scale`` [N, Bs] f32 per-row scales):
    the gather additionally dequantizes each row — f32 multiply, cast
    to the query dtype — before the slab reference math (the kernel
    path's parity oracle for the quantized cache)."""
    n, bs, h, d = k_pool.shape
    bt = jnp.asarray(block_tables, jnp.int32)
    b, nb = bt.shape

    def gather(pool, scale):
        g = pool[bt]                                # [B, NB, Bs, H, D]
        if scale is not None:
            g = (g.astype(jnp.float32)
                 * scale[bt][..., None, None]).astype(q.dtype)
        return g.reshape(b, nb * bs, h, d)

    return xla_decode_attention(q, gather(k_pool, k_scale),
                                gather(v_pool, v_scale), pos=pos,
                                pad=pad)


def _paged_kernel(bt_ref, pos_ref, pad_ref, q_ref, k_ref, v_ref, *rest,
                  block_size: int, sm_scale: float, quant: bool):
    """Grid (B, H, NB): one [block_size, D] K/V block per step, gathered
    through the block table by the index maps (scalar prefetch). The
    softmax runs online over the NB dimension (m/l/acc scratch persists
    across the revisited output block); masked slots are zeroed
    explicitly so never-written pool blocks (incl. the engine's null
    block) contribute exact 0 regardless of their bytes.

    ``quant=True`` (int8 pools): two extra [1, 1, Bs] scale-row inputs
    follow v. The dequant is fused ALGEBRAICALLY — K's per-row scale
    multiplies the score COLUMNS (q·(k·s)ᵀ = (q·kᵀ)·s, broadcast along
    the [1, Bs] score row) and V's scale folds into the probabilities
    before the context matmul (p·(v·s) = (p·s)·v) — so no dequantized
    [Bs, D] tile is ever materialized and no transpose of the scale
    row is needed."""
    if quant:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[0, 0] = NEG_INF
        l_ref[0, 0] = 0.0
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)                    # [1, D]
    k = k_ref[0].astype(jnp.float32)                    # [Bs, D]
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * sm_scale
    if quant:
        s = s * ks_ref[0]                               # [1, Bs] scales
    kpos = j * block_size + lax.broadcasted_iota(
        jnp.int32, (1, block_size), 1)
    live = (kpos <= pos_ref[b]) & (kpos >= pad_ref[b])
    s = jnp.where(live, s, NEG_INF)
    m_prev = m_ref[0, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s))
    # explicit zeroing (not exp underflow): with the finite NEG_INF fill
    # an all-masked block would otherwise see exp(NEG_INF - NEG_INF) = 1
    p = jnp.where(live, jnp.exp(s - m_new), 0.0)        # [1, Bs]
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_ref[0, 0] * alpha + jnp.sum(p)
    if quant:
        pv = p * vs_ref[0]                              # fold V scales
        vblk = v_ref[0].astype(jnp.float32)
    else:
        pv = p.astype(v_ref.dtype)
        vblk = v_ref[0]
    acc_ref[...] = (acc_ref[...] * alpha
                    + lax.dot_general(
                        pv, vblk,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32))
    m_ref[0, 0] = m_new
    l_ref[0, 0] = l_new

    @pl.when(j == pl.num_programs(2) - 1)
    def _finalize():
        # slot `pos` is always live, so l >= exp(0) > 0
        o_ref[0] = (acc_ref[...] / l_ref[0, 0]).astype(o_ref.dtype)


def _paged_dispatch(q, k_pool, v_pool, block_tables, pos, pad,
                    k_scale=None, v_scale=None):
    """Grid (B, H, NB); per program ONE [Bs, D] K/V plane of the pool,
    selected by the block table via scalar-prefetch index maps. Same
    [N, Bs, H·D]-view trick as the slab kernel so every tile is
    Mosaic-friendly. int8 pools additionally stream the matching
    [1, Bs] scale row per block ([N, 1, Bs] view so the singleton tile
    dim matches its array dim — the Mosaic tiling rule the slab
    kernel's docstring records)."""
    n, bs, h, d = k_pool.shape
    b, nb = block_tables.shape
    quant = k_scale is not None
    q3 = q.reshape(b * h, 1, d)
    k3 = k_pool.reshape(n, bs, h * d)
    v3 = v_pool.reshape(n, bs, h * d)

    def kv_map(bb, hh, jj, bt, pos_s, pad_s):
        return (bt[bb, jj], 0, hh)

    def scale_map(bb, hh, jj, bt, pos_s, pad_s):
        return (bt[bb, jj], 0, 0)

    def q_map(bb, hh, jj, bt, pos_s, pad_s):
        return (bb * h + hh, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, d), q_map),
        pl.BlockSpec((1, bs, d), kv_map),
        pl.BlockSpec((1, bs, d), kv_map),
    ]
    operands = [q3, k3, v3]
    if quant:
        in_specs += [pl.BlockSpec((1, 1, bs), scale_map)] * 2
        operands += [k_scale.reshape(n, 1, bs).astype(jnp.float32),
                     v_scale.reshape(n, 1, bs).astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,          # block_tables, pos, pad
        grid=(b, h, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, d), q_map),
        scratch_shapes=[
            pltpu.SMEM((1, 1), jnp.float32),            # running max
            pltpu.SMEM((1, 1), jnp.float32),            # running sum
            pltpu.VMEM((1, d), jnp.float32),            # context acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, block_size=bs,
                          sm_scale=1.0 / math.sqrt(d), quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (b * h, 1, d), q.dtype if quant else v_pool.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(block_tables, pos, pad, *operands)
    return out.reshape(b, h, d)


def paged_decode_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, *, block_tables, pos, pad,
                           k_scale=None, v_scale=None,
                           impl: str = "auto") -> jax.Array:
    """One-query attention against the block-paged cache pool.

    ``q``: [B, H, D]; ``k_pool``/``v_pool``: [N, block_size, H, D]
    shared physical blocks; ``block_tables``: [B, NB] int32 — row b's
    logical slot j lives in ``pool[block_tables[b, j // Bs], j % Bs]``;
    ``pos``/``pad``: [B] int32, the same live-window semantics as the
    slab path (row b attends to logical slots ``pad_b <= j <= pos_b``).
    Returns [B, H, D] context.

    int8 KV cache: pass the pools as int8 plus ``k_scale``/``v_scale``
    ([N, Bs] f32 per-token-row scales) — BOTH impls fuse the dequant
    into the gather (the kernel algebraically, the XLA path on the
    gathered rows); the context dtype is then the QUERY's dtype. The
    scales and the int8 pools travel together: one without the other
    is a loud error, never a silent garbage read.

    ``impl`` as in :func:`decode_attention`; the kernel path needs
    :func:`paged_tile_friendly` shapes, anything else falls back to the
    gather + slab-reference XLA path.
    """
    n, bs, h, d = k_pool.shape
    b = q.shape[0]
    if q.shape != (b, h, d):
        raise ValueError(f"q shape {q.shape} != {(b, h, d)} from pool "
                         f"{k_pool.shape}")
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"unknown decode attention impl {impl!r}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together "
                         "(int8 pools carry one scale row per cached "
                         "token for BOTH k and v)")
    if k_scale is not None:
        if k_pool.dtype != jnp.int8 or v_pool.dtype != jnp.int8:
            raise ValueError(
                f"k_scale/v_scale describe int8 pools, got pool dtype "
                f"{k_pool.dtype}/{v_pool.dtype}")
        if tuple(k_scale.shape) != (n, bs) \
                or tuple(v_scale.shape) != (n, bs):
            raise ValueError(
                f"scale shape {tuple(k_scale.shape)}/"
                f"{tuple(v_scale.shape)} != per-row ({n}, {bs}) from "
                f"pool {k_pool.shape}")
    elif k_pool.dtype == jnp.int8:
        raise ValueError("int8 pools need k_scale/v_scale — attending "
                         "over raw int8 bytes would silently produce "
                         "garbage context")
    bt = jnp.asarray(block_tables, jnp.int32)
    if bt.ndim != 2 or bt.shape[0] != b:
        raise ValueError(f"block_tables shape {bt.shape} != ({b}, NB)")
    use_kernel = (impl == "pallas"
                  or (impl == "auto" and jax.default_backend() == "tpu"
                      and paged_tile_friendly(bs, d)))
    if use_kernel and not paged_tile_friendly(bs, d):
        raise ValueError(
            f"paged decode_attention kernel needs block_size % 128 == 0 "
            f"and an MXU-aligned head dim, got block_size={bs} D={d} "
            "(use impl='auto' for the XLA fallback)")
    posb = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    padb = jnp.broadcast_to(jnp.asarray(pad, jnp.int32).reshape(-1), (b,))
    if not use_kernel:
        return xla_paged_decode_attention(q, k_pool, v_pool,
                                          block_tables=bt, pos=posb,
                                          pad=padb, k_scale=k_scale,
                                          v_scale=v_scale)
    return _paged_dispatch(q, k_pool, v_pool, bt, posb, padb,
                           k_scale=k_scale, v_scale=v_scale)


def decode_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                     pos, pad, impl: str = "auto") -> jax.Array:
    """One-query attention against the cache slab.

    ``q``: [B, H, D] (the current token's heads); ``k``/``v``:
    [B, T, H, D] cache slabs (slot ``pos`` already written); ``pos``:
    int32 cache slot of the current token — a scalar (one shared decode
    depth, the ``generate`` loop) or a [B] vector (per-row depths, the
    continuous-batching engine where slots join mid-flight); ``pad``:
    [B] int32 per-row dead-slot count (ragged prompts). Returns
    [B, H, D] context.

    ``impl``: ``"auto"`` takes the kernel on TPU when
    :func:`tile_friendly` holds and the XLA path otherwise; ``"pallas"``
    forces the kernel (interpret mode off-TPU — the CPU test path);
    ``"xla"`` forces the reference.
    """
    b, t, h, d = k.shape
    if q.shape != (b, h, d):
        raise ValueError(f"q shape {q.shape} != {(b, h, d)} from cache "
                         f"{k.shape}")
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"unknown decode attention impl {impl!r}")
    use_kernel = (impl == "pallas"
                  or (impl == "auto" and jax.default_backend() == "tpu"
                      and tile_friendly(t, d)))
    if use_kernel and not tile_friendly(t, d):
        raise ValueError(
            f"decode_attention kernel needs T % 128 == 0 and an "
            f"MXU-aligned head dim, got T={t} D={d} (use impl='auto' "
            "for the XLA fallback)")
    if not use_kernel:
        return xla_decode_attention(q, k, v, pos=pos, pad=pad)
    # kernel reads one pos per row from SMEM; broadcast a scalar pos
    posb = jnp.broadcast_to(
        jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    return _dispatch(q, k, v, posb, pad.astype(jnp.int32))
