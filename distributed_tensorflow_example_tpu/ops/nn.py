"""Functional NN primitives with explicit parameter pytrees.

Initializers return plain dicts; apply functions are pure. Convention:
matmul-bearing ops accept a ``dtype`` compute dtype (bfloat16 on TPU keeps
the MXU fed at full rate) while params stay in ``param_dtype`` (float32 by
default) — the standard mixed-precision recipe.

Reference parity: the MLP used ``tf.Variable`` weight/bias pairs with
truncated-normal init and ``tf.matmul`` (SURVEY.md §2.1 'Model' row).
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# initializer helpers
# ---------------------------------------------------------------------------

def _truncated_normal(rng, shape, stddev, dtype):
    # match the classic tf.truncated_normal(stddev=1/sqrt(fan_in)) init of
    # the reference MLP: resample beyond 2 sigma ≈ truncate
    u = jax.random.truncated_normal(rng, -2.0, 2.0, shape, jnp.float32)
    return (u * stddev).astype(dtype)


def glorot_uniform(rng, shape, dtype, fan_in, fan_out):
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(rng, shape, jnp.float32, -limit, limit).astype(dtype)


def he_normal(rng, shape, dtype, fan_in):
    std = math.sqrt(2.0 / fan_in)
    return (jax.random.normal(rng, shape, jnp.float32) * std).astype(dtype)


# ---------------------------------------------------------------------------
# dense
# ---------------------------------------------------------------------------

def dense_init(rng, in_dim: int, out_dim: int, *,
               init: str = "truncated_normal",
               param_dtype=jnp.float32) -> Params:
    krng, _ = jax.random.split(rng)
    if init == "truncated_normal":
        kernel = _truncated_normal(krng, (in_dim, out_dim),
                                   1.0 / math.sqrt(in_dim), param_dtype)
    elif init == "glorot":
        kernel = glorot_uniform(krng, (in_dim, out_dim), param_dtype,
                                in_dim, out_dim)
    elif init == "he":
        kernel = he_normal(krng, (in_dim, out_dim), param_dtype, in_dim)
    else:
        raise ValueError(f"unknown init {init!r}")
    return {"kernel": kernel, "bias": jnp.zeros((out_dim,), param_dtype)}


def dense(params: Params, x: jax.Array, *, dtype=None) -> jax.Array:
    """y = x @ W + b. With ``dtype=bfloat16`` the matmul runs on the MXU in
    bf16 with f32 accumulation (preferred_element_type), and the OUTPUT is
    rounded back to bf16 in the dot's epilogue — the f32 accumulator never
    reaches HBM, so downstream activations move at half the bytes (the
    pre-round-3 f32 outputs made every transformer layer HBM-bound).
    Callers that need f32 results (final logits feeding a softmax loss)
    cast up afterwards."""
    kernel, bias = params["kernel"], params["bias"]
    if dtype is not None:
        x = x.astype(dtype)
        kernel = kernel.astype(dtype)
    y = jax.lax.dot_general(x, kernel,
                            (((x.ndim - 1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if dtype is not None:
        y = y.astype(dtype)
    return y + bias.astype(y.dtype)


# ---------------------------------------------------------------------------
# conv / pooling
# ---------------------------------------------------------------------------

def conv2d_init(rng, kh: int, kw: int, in_ch: int, out_ch: int, *,
                use_bias: bool = True,
                param_dtype=jnp.float32) -> Params:
    kernel = he_normal(rng, (kh, kw, in_ch, out_ch), param_dtype,
                       fan_in=kh * kw * in_ch)
    p: Params = {"kernel": kernel}
    if use_bias:
        p["bias"] = jnp.zeros((out_ch,), param_dtype)
    return p


def conv2d(params: Params, x: jax.Array, *, stride: int = 1,
           padding: str = "SAME", dtype=None) -> jax.Array:
    """NHWC conv, HWIO kernel — XLA's native TPU conv layout."""
    kernel = params["kernel"]
    if dtype is not None:
        x = x.astype(dtype)
        kernel = kernel.astype(dtype)
    # no preferred_element_type here: the conv VJP transposes with the f32
    # cotangent against bf16 operands and lax.conv rejects mixed dtypes
    # (dot_general's VJP handles it, so dense() accumulates in f32); conv
    # outputs stay bf16 and batchnorm normalizes in that dtype (its
    # statistics are taken in f32 internally)
    y = lax.conv_general_dilated(
        x, kernel, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if "bias" in params:
        y = y + params["bias"].astype(y.dtype)
    return y


def max_pool(x: jax.Array, window: int = 2, stride: int = 2,
             padding: str = "VALID") -> jax.Array:
    return lax.reduce_window(x, -jnp.inf, lax.max,
                             (1, window, window, 1), (1, stride, stride, 1),
                             padding)


def avg_pool(x: jax.Array, window: int = 2, stride: int = 2,
             padding: str = "VALID") -> jax.Array:
    s = lax.reduce_window(x, 0.0, lax.add,
                          (1, window, window, 1), (1, stride, stride, 1),
                          padding)
    return s / (window * window)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def layernorm_init(dim: int, *, param_dtype=jnp.float32) -> Params:
    return {"scale": jnp.ones((dim,), param_dtype),
            "bias": jnp.zeros((dim,), param_dtype)}


def layernorm(params: Params, x: jax.Array, *, eps: float = 1e-6) -> jax.Array:
    """Per-token statistics in f32 (upcast fuses into the reduction — no
    f32 HBM round-trip), output in ``x.dtype`` so a bf16 residual stream
    stays bf16."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    y = (y * params["scale"].astype(jnp.float32)
         + params["bias"].astype(jnp.float32))
    return y.astype(x.dtype)


def batchnorm_init(dim: int, *, param_dtype=jnp.float32
                   ) -> tuple[Params, Params]:
    """Returns (params, extras): scale/bias are trained; running mean/var
    live in TrainState.extras (non-trained state, SURVEY.md parity with
    non-trainable PS Variables)."""
    params = {"scale": jnp.ones((dim,), param_dtype),
              "bias": jnp.zeros((dim,), param_dtype)}
    extras = {"mean": jnp.zeros((dim,), jnp.float32),
              "var": jnp.ones((dim,), jnp.float32)}
    return params, extras


def batchnorm(params: Params, extras: Params, x: jax.Array, *,
              train: bool, momentum: float = 0.9, eps: float = 1e-5,
              stats_dtype=jnp.float32) -> tuple[jax.Array, Params]:
    """BatchNorm over N,H,W (all but last). In the auto sync mode the batch
    dim is globally sharded, so these are global-batch statistics (sync-BN).
    Under ``sync.mode='shard_map'`` the mean/var here are taken over the
    *local* per-replica batch instead (running stats are pmean'd after the
    step, but the forward normalization differs from auto mode) — BN models
    are excluded from the auto==shard_map equivalence claim; see
    ``parallel.sync_replicas``. Returns (y, new_extras).

    Mixed precision: batch statistics are taken in ``stats_dtype``
    (f32 default; the ``--bn_stats_dtype bfloat16`` experiment trades
    reduction precision for bytes — measured on-chip in BASELINE.md's
    ResNet roofline table), running stats are ALWAYS accumulated f32
    (they integrate across thousands of steps), and the normalization is
    applied in ``x.dtype`` via a folded per-channel scale/offset — bf16
    activations stay bf16 end to end, halving the HBM bytes of the
    BN/relu/residual chain (the ResNet bottleneck on TPU is bandwidth,
    not MXU flops)."""
    if train:
        axes = tuple(range(x.ndim - 1))
        xf = x.astype(stats_dtype)
        mean = jnp.mean(xf, axis=axes).astype(jnp.float32)
        # clamp: E[x^2] - mean^2 can round NEGATIVE (catastrophically so
        # in bf16 stats when |mean| >> std), and rsqrt(negative + eps)
        # would poison the step AND the persistent running var with NaN
        var = jnp.maximum(
            jnp.mean(jnp.square(xf), axis=axes).astype(jnp.float32)
            - jnp.square(mean), 0.0)
        new_extras = {
            "mean": momentum * extras["mean"] + (1 - momentum) * mean,
            "var": momentum * extras["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = extras["mean"], extras["var"]
        new_extras = extras
    # fold (mean, var, scale, bias) into y = x*a + b in f32, then apply in
    # the activation dtype
    inv = lax.rsqrt(var + eps) * params["scale"].astype(jnp.float32)
    off = params["bias"].astype(jnp.float32) - mean * inv
    return x * inv.astype(x.dtype) + off.astype(x.dtype), new_extras


# ---------------------------------------------------------------------------
# embedding / dropout
# ---------------------------------------------------------------------------

def embedding_init(rng, vocab: int, dim: int, *,
                   param_dtype=jnp.float32) -> Params:
    table = (jax.random.normal(rng, (vocab, dim), jnp.float32)
             * 0.02).astype(param_dtype)
    return {"table": table}


def embedding(params: Params, ids: jax.Array) -> jax.Array:
    return jnp.take(params["table"], ids, axis=0)


def dropout(rng: jax.Array, x: jax.Array, rate: float,
            *, train: bool) -> jax.Array:
    if not train or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)
