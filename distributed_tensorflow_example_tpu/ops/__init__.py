"""Core neural-net ops: pure-JAX functional primitives.

Replaces the reference's op layer (``tf.matmul`` / ``tf.nn.*`` under graph
mode, SURVEY.md §2.1): every op here is a pure function over explicit param
pytrees, traced once under jit and fused by XLA onto the MXU. Hot-path
kernels that benefit from manual scheduling live in :mod:`.pallas`.
"""

from .nn import (
    conv2d,
    conv2d_init,
    dense,
    dense_init,
    dropout,
    embedding,
    embedding_init,
    layernorm,
    layernorm_init,
    batchnorm,
    batchnorm_init,
    max_pool,
    avg_pool,
)
from .losses import (
    l2_regularization,
    sigmoid_xent,
    softmax_xent,
    softmax_xent_int_labels,
)

__all__ = [
    "dense", "dense_init", "conv2d", "conv2d_init", "dropout",
    "embedding", "embedding_init", "layernorm", "layernorm_init",
    "batchnorm", "batchnorm_init", "max_pool", "avg_pool",
    "softmax_xent", "softmax_xent_int_labels", "sigmoid_xent",
    "l2_regularization",
]
