"""Pipeline-parallel MNIST MLP — the PP demonstration model.

The reference has no pipeline parallelism (SURVEY.md §2.5: PP absent, not
required for parity); this model exists so the ``pipe`` mesh axis is a
delivered capability rather than a reserved name. Architecture: the
reference-parity MLP's input/output projections (784→H, H→10) wrapped
around a stack of L identical residual blocks ``h + relu(h·W + b)`` —
homogeneous blocks are what make GPipe stages SPMD-able
(:mod:`~distributed_tensorflow_example_tpu.parallel.pipeline`).

Unbound (no mesh, or ``pipe == 1``) the stack runs as a plain ``lax.scan``
on one device — bit-identical math to the pipelined run, which is exactly
the parity claim tests assert (pipelined == sequential for outputs, loss,
and gradients).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..config import TrainConfig
from ..ops import losses, nn
from ..parallel.mesh import AxisNames
from ..parallel.pipeline import make_pipeline
from ..parallel.sharding import ShardingRules
from .base import (cast_floating, classification_eval_metrics,
                   register_model, resolve_dtype)


@dataclasses.dataclass
class PipeMlpConfig:
    in_dim: int = 784
    hidden: int = 128
    blocks: int = 4            # total residual blocks, split over pipe
    num_classes: int = 10
    microbatches: int = 4      # GPipe M (per data shard)


def _block_scan(stacked, x, dtype):
    """Apply stacked residual blocks in order: the pipeline stage_fn (on a
    [L/P]-leaf shard) and the sequential oracle (on the full [L] stack)."""
    def body(h, blk):
        y = jax.lax.dot_general(
            h.astype(dtype), blk["kernel"].astype(dtype),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        # cast back to the carry dtype: with dtype=bf16 the f32-accumulated
        # dot would otherwise promote the residual and break the scan carry
        r = jax.nn.relu(y + blk["bias"].astype(jnp.float32)).astype(h.dtype)
        return h + r, None
    out, _ = jax.lax.scan(body, x, stacked)
    return out


class PipeMlp:
    name = "pipe_mlp"

    def __init__(self, cfg: PipeMlpConfig | None = None, dtype=jnp.float32,
                 param_dtype=jnp.float32):
        self.cfg = cfg or PipeMlpConfig()
        self.dtype = dtype
        self.param_dtype = param_dtype
        self._pipelined = None     # bound by bind_mesh when pipe > 1

    # ------------------------------------------------------------------
    def bind_mesh(self, mesh) -> None:
        """Attach a mesh; a pipe axis > 1 activates GPipe execution.

        The Trainer calls this for any model that defines it (mirroring how
        ring attention binds a mesh via ``attention_fn``)."""
        if mesh is not None and mesh.shape[AxisNames.PIPE] > 1:
            if self.cfg.blocks % mesh.shape[AxisNames.PIPE]:
                raise ValueError(
                    f"blocks={self.cfg.blocks} not divisible by pipe axis "
                    f"size {mesh.shape[AxisNames.PIPE]}")
            self._pipelined = make_pipeline(
                mesh, lambda p, x, mb_idx: _block_scan(p, x, self.dtype),
                num_microbatches=self.cfg.microbatches)
        else:
            self._pipelined = None

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array):
        c = self.cfg
        r_in, r_blk, r_out = jax.random.split(rng, 3)
        blk_keys = jax.random.split(r_blk, c.blocks)
        kernels = jnp.stack([
            nn.glorot_uniform(k, (c.hidden, c.hidden), jnp.float32,
                              c.hidden, c.hidden) for k in blk_keys])
        return cast_floating({
            "in_proj": nn.dense_init(r_in, c.in_dim, c.hidden),
            "blocks": {"kernel": kernels,
                       "bias": jnp.zeros((c.blocks, c.hidden), jnp.float32)},
            "out_proj": nn.dense_init(r_out, c.hidden, c.num_classes),
        }, self.param_dtype)

    def apply(self, params, extras, batch, rng=None, train: bool = False):
        x = batch["x"].reshape((batch["x"].shape[0], -1))
        h = jax.nn.relu(nn.dense(params["in_proj"], x, dtype=self.dtype))
        if self._pipelined is not None:
            h = self._pipelined(params["blocks"], h)
        else:
            h = _block_scan(params["blocks"], h, self.dtype)
        logits = nn.dense(params["out_proj"], h, dtype=self.dtype)
        return logits.astype(jnp.float32), extras

    def loss(self, params, extras, batch, rng):
        logits, new_extras = self.apply(params, extras, batch, rng,
                                        train=True)
        loss = losses.softmax_xent_int_labels(logits, batch["y"])
        aux = {"accuracy": losses.accuracy(logits, batch["y"])}
        return loss, (aux, new_extras)

    def eval_metrics(self, params, extras, batch) -> dict:
        logits, _ = self.apply(params, extras, batch, train=False)
        return classification_eval_metrics(logits, batch)

    # ------------------------------------------------------------------
    def sharding_rules(self, mesh_shape) -> ShardingRules:
        """Block stack sharded over pipe (stage placement); everything
        else replicated/fsdp per the default policy."""
        fsdp = getattr(mesh_shape, "fsdp", 1) if mesh_shape else 1
        pipe = getattr(mesh_shape, "pipe", 1) if mesh_shape else 1
        if pipe <= 1:
            return ShardingRules(fsdp_axis_size=fsdp)
        return ShardingRules(rules=[
            (r"blocks/(kernel|bias)", P(AxisNames.PIPE)),
        ], fsdp_axis_size=fsdp)

    def dummy_batch(self, batch_size: int):
        rs = np.random.RandomState(0)
        return {
            "x": rs.rand(batch_size, self.cfg.in_dim).astype(np.float32),
            "y": rs.randint(0, self.cfg.num_classes, size=(batch_size,),
                            dtype=np.int32),
        }


@register_model("pipe_mlp")
def _make_pipe_mlp(config: TrainConfig) -> PipeMlp:
    return PipeMlp(dtype=resolve_dtype(config.dtype),
                   param_dtype=resolve_dtype(config.param_dtype))
