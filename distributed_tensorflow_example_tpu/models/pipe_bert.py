"""Pipeline-parallel BERT: GPipe over the encoder stack.

The reference has no pipeline parallelism (SURVEY.md §2.5: PP absent,
not required for parity); round 3 delivered the mechanism on an MLP
(:mod:`.pipe_mlp`). This model applies it to the transformer family:
the L encoder layers live STACKED (one pytree with leading dim L),
sharded over the ``pipe`` mesh axis — each stage holds ``L/P``
consecutive layers — while the embedding front-end and MLM head stay
replicated outside the pipeline. Microbatches flow through the stages
via the shared :func:`~..parallel.pipeline.pipeline_spmd` ring
(``ppermute`` neighbor hops over ICI).

Correctness contract (asserted in tests/test_pipe_bert.py): bound to a
``pipe > 1`` mesh, outputs/loss/grads equal the unbound single-device
model — including dropout, because BOTH paths split the batch into
``microbatches`` and fold the per-(microbatch, layer) key the same way
(the pipeline hands each stage the microbatch index it is processing;
the sequential oracle maps over microbatches with the same indices).

Composes with data parallelism exactly like PipeMlp: on a
``{data, pipe}`` mesh each data shard runs its own P-stage pipeline and
XLA inserts the gradient all-reduce over ``data``.

Composes with tensor parallelism (PP×TP, the Megatron large-model combo)
on a ``{data, pipe, model}`` mesh using the *sequence-parallel* Megatron
layout (Korthikanti et al., "Reducing Activation Recomputation"): between
blocks the residual stream is sharded over ``model`` along the SEQUENCE
dim (layernorm is per-token, so seq-sharded LN is exact and no compute is
duplicated across TP peers); each block does
``all_gather(seq) → column-parallel QKV/FFN-in → row-parallel O/FFN-out →
reduce_scatter(seq)``. This is the formulation that keeps every
parameter's gradient correct under ``shard_map`` transposition: no
activation or parameter is used redundantly across ``model`` members, so
the implicit cross-``model`` psum of unmentioned-axis cotangents sums
genuinely partial contributions. The ``ppermute`` stage hop moves the
seq-shard each TP peer already holds — pipeline traffic shrinks by the
TP degree.
"""

from __future__ import annotations

import dataclasses
import functools
import re

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..config import TrainConfig
from ..ops.attention import multi_head_attention
from ..parallel.collectives import axis_size
from ..parallel.mesh import AxisNames
from ..parallel.pipeline import make_pipeline, sequential_blocks
from ..parallel.sharding import ShardingRules
from ..ops import nn
from ..utils.pytree import path_str as _path_str
from .base import cast_floating, register_model, resolve_dtype
from .bert import Bert, BertConfig, _make


def _row_dense_scatter(p, x, axis: str, *, dtype):
    """Row-parallel dense + reduce-scatter: ``x`` is ``[b, s, in/t]`` (this
    member's contraction shard), kernel ``[in/t, out]``; partial products
    are summed over ``model`` AND scattered along the sequence dim in one
    ``psum_scatter`` (the Megatron-SP output collective), returning
    ``[b, s/t, out]``. Bias is added once, after the reduction, on the
    seq-shard (so its gradient contributions stay partial per member)."""
    kernel, bias = p["kernel"], p["bias"]
    if dtype is not None:
        x = x.astype(dtype)
        kernel = kernel.astype(dtype)
    y = lax.dot_general(x, kernel, (((x.ndim - 1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    y = lax.psum_scatter(y, axis, scatter_dimension=1, tiled=True)
    if dtype is not None:
        y = y.astype(dtype)
    return y + bias.astype(y.dtype)


@dataclasses.dataclass
class PipeBertConfig(BertConfig):
    microbatches: int = 4       # GPipe M (per data shard)


class PipeBert(Bert):
    """BERT with the encoder stack stacked+pipelined over ``pipe``."""

    name = "pipe_bert"

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._pipe_mesh = None

    # ------------------------------------------------------------------
    def bind_mesh(self, mesh) -> None:
        if mesh is not None and mesh.shape[AxisNames.PIPE] > 1:
            if self.cfg.layers % mesh.shape[AxisNames.PIPE]:
                raise ValueError(
                    f"layers={self.cfg.layers} not divisible by pipe "
                    f"axis size {mesh.shape[AxisNames.PIPE]}")
            tp = mesh.shape[AxisNames.MODEL]
            if tp > 1:
                if self.cfg.heads % tp:
                    raise ValueError(
                        f"heads={self.cfg.heads} not divisible by model "
                        f"axis size {tp} (PP×TP shards attention by head)")
                if self.cfg.intermediate % tp:
                    raise ValueError(
                        f"intermediate={self.cfg.intermediate} not "
                        f"divisible by model axis size {tp}")
                if self.attention_fn is not None:
                    raise ValueError(
                        "attention_fn (ring attention / seq parallelism) "
                        "does not compose with PP×TP: the TP layer body "
                        "computes attention over its local heads with the "
                        "full sequence")
            self._pipe_mesh = mesh
        else:
            self._pipe_mesh = None

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array):
        flat = super().init(rng)
        c = self.cfg
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs),
            *[flat.pop(f"layer_{i}") for i in range(c.layers)])
        flat["layers"] = stacked
        return flat

    # ------------------------------------------------------------------
    def _dropout_tp(self, rng, x_local, tp_axis: str):
        """Dropout on a seq-sharded ``[b, s/t, h]`` tensor that is
        POSITIONALLY identical to ``nn.dropout`` on the full ``[b, s, h]``
        tensor: every TP member draws the full mask from the shared key
        and slices its own seq chunk (mask generation is cheap replicated
        compute; the values stream stays sharded)."""
        t = axis_size(tp_axis)
        m = lax.axis_index(tp_axis)
        b, sl, hd = x_local.shape
        keep = 1.0 - self.cfg.dropout
        full = jax.random.bernoulli(rng, keep, (b, sl * t, hd))
        shard = lax.dynamic_slice_in_dim(full, m * sl, sl, 1)
        return jnp.where(shard, x_local / keep, 0.0)

    def _layer_tp(self, lp, x, mask, lrng, *, train: bool,
                  use_dropout: bool, tp_axis: str):
        """One encoder layer in the Megatron sequence-parallel TP layout.

        ``x`` is the residual stream seq-sharded over ``model``
        (``[b, s/t, hidden]``); ``lp`` leaves are this member's kernel
        shards (QKV/FFN-in column-split, O/FFN-out row-split; LN params
        and row-dense biases full). Numerically equal to :meth:`_layer`
        up to reduction order (contractions split over ``model``)."""
        ap = lp["attn"]
        d_local = ap["q"]["kernel"].shape[-1]
        heads_local = d_local // self.head_dim

        h_full = lax.all_gather(x, tp_axis, axis=1, tiled=True)  # [b,s,h]
        b, s, _ = h_full.shape

        def split(y):
            return y.reshape(b, s, heads_local, self.head_dim)

        q = split(nn.dense(ap["q"], h_full, dtype=self.dtype))
        k = split(nn.dense(ap["k"], h_full, dtype=self.dtype))
        v = split(nn.dense(ap["v"], h_full, dtype=self.dtype))
        ctx = multi_head_attention(q, k, v, mask=mask[:, None, None, :],
                                   impl=self.attention_impl,
                                   flash_kwargs=self.attention_kwargs
                                   or None)
        ctx = ctx.reshape(b, s, d_local)
        a = _row_dense_scatter(ap["o"], ctx, tp_axis, dtype=self.dtype)
        if use_dropout:
            a = self._dropout_tp(jax.random.fold_in(lrng, 1), a, tp_axis)
        h1 = nn.layernorm(lp["attn_ln"], x + a.astype(x.dtype))
        g = lax.all_gather(h1, tp_axis, axis=1, tiled=True)
        f = nn.dense(lp["ffn"]["in"], g, dtype=self.dtype)
        f = jax.nn.gelu(f.astype(jnp.float32)).astype(self.dtype)
        f = _row_dense_scatter(lp["ffn"]["out"], f, tp_axis,
                               dtype=self.dtype)
        if use_dropout:
            f = self._dropout_tp(jax.random.fold_in(lrng, 2), f, tp_axis)
        return nn.layernorm(lp["ffn_ln"], h1 + f.astype(h1.dtype))

    def _stage_fn(self, *, offset_fn, train: bool, use_dropout: bool,
                  rng, tp_axis: str | None = None):
        """(local_stack, {h, mask}, mb_idx) -> same-structure pytree:
        applies this stage's layers in order. ``offset_fn(n_local)``
        yields the stage's first GLOBAL layer index — per-layer dropout
        keys fold (global layer, microbatch), so pipelined and
        sequential paths derive identical randomness. With ``tp_axis``
        the per-layer body is the sequence-parallel TP variant."""
        if tp_axis is None:
            base = functools.partial(self._layer, train=train,
                                     use_dropout=use_dropout)
        else:
            base = functools.partial(self._layer_tp, train=train,
                                     use_dropout=use_dropout,
                                     tp_axis=tp_axis)
        layer = self._maybe_remat(base)

        def stage(stack, x, mb_idx):
            n_local = jax.tree_util.tree_leaves(stack)[0].shape[0]
            offset = offset_fn(n_local)

            def body(h, xs):
                lp, j = xs
                lrng = None
                if use_dropout:
                    lrng = jax.random.fold_in(
                        jax.random.fold_in(rng, offset + j), mb_idx)
                return layer(lp, h, x["mask"], lrng), None

            h, _ = lax.scan(body, x["h"],
                            (stack, jnp.arange(n_local)))
            return {"h": h, "mask": x["mask"]}

        return stage

    def encode(self, params, batch, rng=None, train: bool = False):
        c = self.cfg
        h, mask, use_dropout = self._embed(params, batch, rng, train)
        x = {"h": h, "mask": mask}
        if self._pipe_mesh is not None:
            mesh = self._pipe_mesh
            tp = mesh.shape[AxisNames.MODEL]
            tp_axis = AxisNames.MODEL if tp > 1 else None
            if tp > 1 and h.shape[1] % tp:
                raise ValueError(
                    f"sequence length {h.shape[1]} not divisible by model "
                    f"axis size {tp} (activations are seq-sharded over TP)")
            stage = self._stage_fn(
                offset_fn=lambda n_local:
                    lax.axis_index(AxisNames.PIPE) * n_local,
                train=train, use_dropout=use_dropout, rng=rng,
                tp_axis=tp_axis)
            param_specs = x_specs = None
            if tp > 1:
                param_specs = self._stacked_specs(params["layers"])
                # residual stream seq-sharded over model between blocks
                # (Megatron-SP); mask stays full — attention masks keys
                # over the whole sequence
                x_specs = {"h": P(AxisNames.BATCH, AxisNames.MODEL),
                           "mask": P(AxisNames.BATCH)}
            piped = make_pipeline(mesh, stage,
                                  num_microbatches=c.microbatches,
                                  param_specs=param_specs,
                                  x_specs=x_specs)
            out = piped(params["layers"], x)
        else:
            stage = self._stage_fn(offset_fn=lambda n_local: 0,
                                   train=train, use_dropout=use_dropout,
                                   rng=rng)
            # dropout keys are per-microbatch: the oracle must split the
            # same way; without dropout one "microbatch" is exact and
            # cheapest
            m = c.microbatches if use_dropout else 1
            out = sequential_blocks(stage, params["layers"], x,
                                    num_microbatches=m)
        return out["h"]

    # ------------------------------------------------------------------
    #: (pattern, trailing spec) for the stacked encoder's TP layout —
    #: ONE source of truth for both the GSPMD placement rules
    #: (sharding_rules) and the shard_map in_specs (_stacked_specs).
    #: Patterns match the path below ``layers/``; the leading (stage)
    #: dim always carries ``pipe``.
    _TP_STACK = (
        (r"attn/(q|k|v)/kernel|ffn/in/kernel",
         (None, AxisNames.MODEL)),               # column-parallel
        (r"attn/(q|k|v)/bias|ffn/in/bias", (AxisNames.MODEL,)),
        (r"(attn/o|ffn/out)/kernel",
         (AxisNames.MODEL, None)),               # row-parallel
    )

    def _stacked_specs(self, stacked):
        """shard_map PartitionSpecs for the stacked encoder params under
        PP×TP: leading dim over pipe, kernel dims per ``_TP_STACK``
        (LN params and row-dense biases replicated over model)."""
        def spec(path, _):
            p = _path_str(path)
            for pattern, tail in self._TP_STACK:
                if re.search(pattern, p):
                    return P(AxisNames.PIPE, *tail)
            return P(AxisNames.PIPE)
        return jax.tree_util.tree_map_with_path(spec, stacked)

    def sharding_rules(self, mesh_shape) -> ShardingRules:
        """Stacked encoder sharded over pipe (stage placement); with a
        ``model`` axis > 1 the kernels additionally shard Megatron-style
        and the embedding/MLM head reuse Bert's TP rules. All four
        combinations of {pipe, model} > 1 are covered — on a pure-TP mesh
        (pipe=1) the stacked kernels still model-shard and GSPMD
        parallelizes the sequential path."""
        fsdp = getattr(mesh_shape, "fsdp", 1) if mesh_shape else 1
        pipe = getattr(mesh_shape, "pipe", 1) if mesh_shape else 1
        tp = getattr(mesh_shape, "model", 1) if mesh_shape else 1
        if pipe <= 1 and tp <= 1:
            return ShardingRules(fsdp_axis_size=fsdp)
        # \b, not ^: rule paths come prefixed (params/layers/... in a
        # TrainState) — an anchored rule silently never matches and the
        # stack would fall back to replicated placement. Each _TP_STACK
        # pattern is wrapped (?:...) so its alternation stays under the
        # \blayers/ anchor.
        lead = AxisNames.PIPE if pipe > 1 else None
        rules = []
        if tp > 1:
            rules += [(r"\blayers/(?:" + pattern + ")", P(lead, *tail))
                      for pattern, tail in self._TP_STACK]
            rules += list(self.TP_EMBED_RULES)
        if pipe > 1:
            rules.append((r"\blayers/", P(AxisNames.PIPE)))
        return ShardingRules(rules=rules, fsdp_axis_size=fsdp)


@register_model("pipe_bert")
def _make_pipe_bert(config: TrainConfig) -> PipeBert:
    cfg = PipeBertConfig()
    return _make(config, cfg, cls=PipeBert)


@register_model("pipe_bert_tiny")
def _make_pipe_bert_tiny(config: TrainConfig) -> PipeBert:
    t = BertConfig.tiny()
    cfg = PipeBertConfig(**dataclasses.asdict(t))
    cfg.layers = 4              # 2 stages x 2 layers on the test mesh
    return _make(config, cfg, config_vocab=False, cls=PipeBert)
