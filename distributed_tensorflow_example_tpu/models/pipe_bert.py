"""Pipeline-parallel BERT: GPipe over the encoder stack.

The reference has no pipeline parallelism (SURVEY.md §2.5: PP absent,
not required for parity); round 3 delivered the mechanism on an MLP
(:mod:`.pipe_mlp`). This model applies it to the transformer family:
the L encoder layers live STACKED (one pytree with leading dim L),
sharded over the ``pipe`` mesh axis — each stage holds ``L/P``
consecutive layers — while the embedding front-end and MLM head stay
replicated outside the pipeline. Microbatches flow through the stages
via the shared :func:`~..parallel.pipeline.pipeline_spmd` ring
(``ppermute`` neighbor hops over ICI).

Correctness contract (asserted in tests/test_pipe_bert.py): bound to a
``pipe > 1`` mesh, outputs/loss/grads equal the unbound single-device
model — including dropout, because BOTH paths split the batch into
``microbatches`` and fold the per-(microbatch, layer) key the same way
(the pipeline hands each stage the microbatch index it is processing;
the sequential oracle maps over microbatches with the same indices).

Composes with data parallelism exactly like PipeMlp: on a
``{data, pipe}`` mesh each data shard runs its own P-stage pipeline and
XLA inserts the gradient all-reduce over ``data``.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..config import TrainConfig
from ..parallel.mesh import AxisNames
from ..parallel.pipeline import make_pipeline, sequential_blocks
from ..parallel.sharding import ShardingRules
from ..ops import nn
from .base import cast_floating, register_model, resolve_dtype
from .bert import Bert, BertConfig, _make


@dataclasses.dataclass
class PipeBertConfig(BertConfig):
    microbatches: int = 4       # GPipe M (per data shard)


class PipeBert(Bert):
    """BERT with the encoder stack stacked+pipelined over ``pipe``."""

    name = "pipe_bert"

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._pipe_mesh = None

    # ------------------------------------------------------------------
    def bind_mesh(self, mesh) -> None:
        if mesh is not None and mesh.shape[AxisNames.PIPE] > 1:
            if self.cfg.layers % mesh.shape[AxisNames.PIPE]:
                raise ValueError(
                    f"layers={self.cfg.layers} not divisible by pipe "
                    f"axis size {mesh.shape[AxisNames.PIPE]}")
            self._pipe_mesh = mesh
        else:
            self._pipe_mesh = None

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array):
        flat = super().init(rng)
        c = self.cfg
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs),
            *[flat.pop(f"layer_{i}") for i in range(c.layers)])
        flat["layers"] = stacked
        return flat

    # ------------------------------------------------------------------
    def _stage_fn(self, *, offset_fn, train: bool, use_dropout: bool,
                  rng):
        """(local_stack, {h, mask}, mb_idx) -> same-structure pytree:
        applies this stage's layers in order. ``offset_fn(n_local)``
        yields the stage's first GLOBAL layer index — per-layer dropout
        keys fold (global layer, microbatch), so pipelined and
        sequential paths derive identical randomness."""
        layer = self._maybe_remat(
            functools.partial(self._layer, train=train,
                              use_dropout=use_dropout))

        def stage(stack, x, mb_idx):
            n_local = jax.tree_util.tree_leaves(stack)[0].shape[0]
            offset = offset_fn(n_local)

            def body(h, xs):
                lp, j = xs
                lrng = None
                if use_dropout:
                    lrng = jax.random.fold_in(
                        jax.random.fold_in(rng, offset + j), mb_idx)
                return layer(lp, h, x["mask"], lrng), None

            h, _ = lax.scan(body, x["h"],
                            (stack, jnp.arange(n_local)))
            return {"h": h, "mask": x["mask"]}

        return stage

    def encode(self, params, batch, rng=None, train: bool = False):
        c = self.cfg
        h, mask, use_dropout = self._embed(params, batch, rng, train)
        x = {"h": h, "mask": mask}
        if self._pipe_mesh is not None:
            mesh = self._pipe_mesh
            stage = self._stage_fn(
                offset_fn=lambda n_local:
                    lax.axis_index(AxisNames.PIPE) * n_local,
                train=train, use_dropout=use_dropout, rng=rng)
            piped = make_pipeline(mesh, stage,
                                  num_microbatches=c.microbatches)
            out = piped(params["layers"], x)
        else:
            stage = self._stage_fn(offset_fn=lambda n_local: 0,
                                   train=train, use_dropout=use_dropout,
                                   rng=rng)
            # dropout keys are per-microbatch: the oracle must split the
            # same way; without dropout one "microbatch" is exact and
            # cheapest
            m = c.microbatches if use_dropout else 1
            out = sequential_blocks(stage, params["layers"], x,
                                    num_microbatches=m)
        return out["h"]

    # ------------------------------------------------------------------
    def sharding_rules(self, mesh_shape) -> ShardingRules:
        """Stacked encoder sharded over pipe (stage placement); TP rules
        are not combined with PP here — embeddings/head follow the
        default replicated/fsdp policy."""
        fsdp = getattr(mesh_shape, "fsdp", 1) if mesh_shape else 1
        pipe = getattr(mesh_shape, "pipe", 1) if mesh_shape else 1
        if pipe <= 1:
            return ShardingRules(fsdp_axis_size=fsdp)
        # \b, not ^: rule paths come prefixed (params/layers/... in a
        # TrainState) — an anchored rule silently never matches and the
        # stack would fall back to replicated placement
        return ShardingRules(rules=[
            (r"\blayers/", P(AxisNames.PIPE)),
        ], fsdp_axis_size=fsdp)


@register_model("pipe_bert")
def _make_pipe_bert(config: TrainConfig) -> PipeBert:
    cfg = PipeBertConfig()
    return _make(config, cfg, cls=PipeBert)


@register_model("pipe_bert_tiny")
def _make_pipe_bert_tiny(config: TrainConfig) -> PipeBert:
    t = BertConfig.tiny()
    cfg = PipeBertConfig(**dataclasses.asdict(t))
    cfg.layers = 4              # 2 stages x 2 layers on the test mesh
    return _make(config, cfg, config_vocab=False, cls=PipeBert)
