"""MoE-BERT: the BERT MLM encoder with Mixture-of-Experts FFN layers —
the framework's expert-parallel model family (EP over the ``expert`` mesh
axis; no MoE exists in the reference, SURVEY.md §2.5, so this is a
capability extension, not parity).

Every other FFN is replaced by a Switch-style MoE block (alternating
dense/MoE, the GLaM/ST-MoE layout); the router's load-balancing aux loss
is added to the MLM loss with weight ``aux_weight``. Expert weights are
stacked [E, ...] and sharded over ``expert`` by ``sharding_rules``, so
under jit the token dispatch/combine einsums become GSPMD-inserted
collectives over the expert axis — the dense-dispatch analogue of the
hand-written ``all_to_all`` EP path (ops/moe.py; equivalence asserted in
tests/test_moe.py).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..config import TrainConfig
from ..ops import moe, nn
from ..parallel.mesh import AxisNames
from ..parallel.sharding import ShardingRules
from .base import register_model, resolve_dtype
from .bert import Bert, BertConfig


@dataclasses.dataclass
class MoeBertConfig(BertConfig):
    n_experts: int = 8
    top_k: int = 1
    capacity_factor: float = 1.25
    moe_every: int = 2            # MoE FFN every k-th layer (offset 1)
    aux_weight: float = 0.01      # load-balancing loss weight
    router_z_weight: float = 0.0  # ST-MoE router z-loss weight (1e-3 typ.)
    jitter: float = 0.0           # router input noise U[1-j, 1+j], train only

    @classmethod
    def tiny(cls) -> "MoeBertConfig":
        return cls(vocab_size=1000, hidden=128, layers=2, heads=4,
                   intermediate=256, max_len=128, max_predictions=8,
                   n_experts=4, capacity_factor=2.0)


class MoeBert(Bert):
    name = "moe_bert"

    def __init__(self, cfg: MoeBertConfig, dtype=jnp.float32,
                 attention_impl: str = "xla", attention_fn=None,
                 param_dtype=jnp.float32, remat: str = "none",
                 attention_kwargs: dict | None = None):
        super().__init__(cfg, dtype=dtype, attention_impl=attention_impl,
                         attention_fn=attention_fn, param_dtype=param_dtype,
                         remat=remat, attention_kwargs=attention_kwargs)
        self.cfg: MoeBertConfig = cfg

    def _is_moe_layer(self, i: int) -> bool:
        return (i % self.cfg.moe_every) == (self.cfg.moe_every - 1)

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array):
        params = super().init(rng)
        c = self.cfg
        keys = jax.random.split(jax.random.fold_in(rng, 7777), c.layers)
        for i in range(c.layers):
            if self._is_moe_layer(i):
                lp = params[f"layer_{i}"]
                del lp["ffn"]
                lp["moe"] = moe.moe_ffn_init(keys[i], c.n_experts, c.hidden,
                                             c.intermediate,
                                             param_dtype=self.param_dtype)
        return params

    # ------------------------------------------------------------------
    def encode(self, params, batch, rng=None, train: bool = False):
        h, _ = self.encode_with_aux(params, batch, rng, train)
        return h

    def _moe_layer(self, lp, h, mask, lrng, *, train: bool,
                   use_dropout: bool):
        """One MoE encoder layer: MHA -> add&LN -> MoE FFN -> add&LN.
        Returns ``(h, aux dict)`` — pure in its array args so it can be
        jax.checkpoint-wrapped like Bert._layer. The attention half and
        the FFN tail are shared with Bert (``_attn_block``/``_ffn_block``);
        only the FFN body differs. Router jitter engages only in training
        WITH randomness (lrng comes from the step rng; eval passes
        rng=None, so jittered eval is impossible by construction)."""
        c = self.cfg
        h = self._attn_block(lp, h, mask, lrng, train=train,
                             use_dropout=use_dropout)
        jrng = (jax.random.fold_in(lrng, 3)
                if train and c.jitter > 0 and lrng is not None else None)
        f, aux = moe.moe_ffn(lp["moe"], h,
                             n_experts=c.n_experts, top_k=c.top_k,
                             capacity_factor=c.capacity_factor,
                             dtype=self.dtype, rng=jrng, jitter=c.jitter)
        return self._ffn_block(lp, h, f, lrng, use_dropout=use_dropout), aux

    def encode_with_aux(self, params, batch, rng=None, train: bool = False):
        """Same block structure as Bert.encode with MoE FFNs swapped in;
        returns ``(seq_out, aux_total)`` — the summed per-layer router
        load-balancing losses ride the return path (never stored on
        ``self``: a tracer on a long-lived object leaks across traces)."""
        c = self.cfg
        h, mask, use_dropout = self._embed(params, batch, rng, train)
        dense_layer = self._maybe_remat(
            functools.partial(self._layer, train=train,
                              use_dropout=use_dropout))
        moe_layer = self._maybe_remat(
            functools.partial(self._moe_layer, train=train,
                              use_dropout=use_dropout))

        aux_total = {
            "lb_loss": jnp.zeros((), jnp.float32),
            "z_loss": jnp.zeros((), jnp.float32),
            "dropped_fraction": jnp.zeros((), jnp.float32),
            "expert_load": jnp.zeros((c.n_experts,), jnp.float32),
        }
        n_moe = 0
        for i in range(c.layers):
            lp = params[f"layer_{i}"]
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            if self._is_moe_layer(i):
                h, aux = moe_layer(lp, h, mask, lrng)
                aux_total = jax.tree_util.tree_map(jnp.add, aux_total, aux)
                n_moe += 1
            else:
                h = dense_layer(lp, h, mask, lrng)
        # loss terms stay SUMS over layers (each layer's router is its
        # own regularization target); visibility stats become means
        for k in ("dropped_fraction", "expert_load"):
            aux_total[k] = aux_total[k] / max(1, n_moe)
        return h, aux_total

    # ------------------------------------------------------------------
    def loss(self, params, extras, batch, rng):
        seq_out, aux = self.encode_with_aux(params, batch, rng, train=True)
        new_extras = extras
        w = batch["masked_weights"].astype(jnp.float32)
        # the MLM head loss is Bert's shared implementation (full or
        # fused blockwise core per cfg.lm_loss_impl — ops/losses.py)
        mlm, acc = self._mlm_loss_and_acc(params, seq_out, batch, w)
        total = (mlm + self.cfg.aux_weight * aux["lb_loss"]
                 + self.cfg.router_z_weight * aux["z_loss"])
        load = aux["expert_load"]
        # the metrics stream is self-describing about routing health:
        # dropped_token_fraction > 0 means capacity overflow is silently
        # zeroing expert outputs; expert_load is the full [E] utilization
        # vector (vector metrics flow to the JSONL, scalar hooks skip it)
        metrics = {"mlm_accuracy": acc, "mlm_loss": mlm,
                   "aux_loss": aux["lb_loss"],
                   "router_z_loss": aux["z_loss"],
                   "dropped_token_fraction": aux["dropped_fraction"],
                   "expert_load": load,
                   "expert_load_min": jnp.min(load),
                   "expert_load_max": jnp.max(load)}
        return total, (metrics, new_extras)

    # ------------------------------------------------------------------
    def sharding_rules(self, mesh_shape) -> ShardingRules:
        """Bert's Megatron TP rules + expert-sharded MoE weights.

        EP × TP (VERDICT r4 task #7): with BOTH ``expert`` and ``model``
        axes > 1, each expert's FFN kernels are additionally
        Megatron-split over ``model`` — w_in [E, H, I/tp] column-wise,
        w_out [E, I/tp, H] row-wise — so the dense dispatch/combine
        einsums run with the token exchange over ``expert`` AND the
        per-expert matmul reduction over ``model`` in one GSPMD program
        (a model-axis psum closes each expert FFN, exactly the dense-FFN
        Megatron pattern). Either axis alone degrades to plain EP or
        plain per-expert TP."""
        E = AxisNames.EXPERT
        M = AxisNames.MODEL
        base = super().sharding_rules(mesh_shape)
        ep = getattr(mesh_shape, "expert", 1) if mesh_shape else 1
        tp = getattr(mesh_shape, "model", 1) if mesh_shape else 1
        e = E if ep > 1 else None
        m = M if tp > 1 else None
        if e is None and m is None:
            return base
        rules = [
            (r"moe/w_in", P(e, None, m)),
            (r"moe/b_in", P(e, m)),
            (r"moe/w_out", P(e, m, None)),
            (r"moe/b_out", P(e, None)),
        ] + list(base.rules)
        return ShardingRules(rules=rules,
                             fsdp_axis_size=base.fsdp_axis_size)


def _apply_moe_overrides(cfg: MoeBertConfig,
                         config: TrainConfig) -> MoeBertConfig:
    """CLI-reachable routing + training-quality knobs (--moe_experts/
    --moe_top_k/--moe_capacity_factor/--moe_every/--moe_aux_weight/
    --moe_router_z_weight/--moe_jitter); None keeps the model default."""
    if config.moe_experts is not None:
        if config.moe_experts < 1:
            raise ValueError(
                f"moe_experts={config.moe_experts} must be >= 1")
        cfg.n_experts = config.moe_experts
    if config.moe_top_k is not None:
        cfg.top_k = config.moe_top_k
    if not 1 <= cfg.top_k <= cfg.n_experts:
        # validate the COMBINED result: --moe_experts alone can push
        # n_experts below the model's default top_k
        raise ValueError(
            f"moe_top_k={cfg.top_k} must be in "
            f"[1, n_experts={cfg.n_experts}]")
    if config.moe_capacity_factor is not None:
        if config.moe_capacity_factor <= 0:
            raise ValueError(
                f"moe_capacity_factor={config.moe_capacity_factor} "
                "must be > 0 (capacity would clamp to 1 slot and drop "
                "nearly every token)")
        cfg.capacity_factor = config.moe_capacity_factor
    if config.moe_every is not None:
        if not 1 <= config.moe_every <= cfg.layers:
            raise ValueError(
                f"moe_every={config.moe_every} must be in [1, layers="
                f"{cfg.layers}] (larger would yield zero MoE layers)")
        cfg.moe_every = config.moe_every
    if config.moe_aux_weight is not None:
        if config.moe_aux_weight < 0:
            raise ValueError(
                f"moe_aux_weight={config.moe_aux_weight} must be >= 0")
        cfg.aux_weight = config.moe_aux_weight
    if config.moe_router_z_weight is not None:
        if config.moe_router_z_weight < 0:
            raise ValueError(f"moe_router_z_weight="
                             f"{config.moe_router_z_weight} must be >= 0")
        cfg.router_z_weight = config.moe_router_z_weight
    if config.moe_jitter is not None:
        if not 0 <= config.moe_jitter < 1:
            raise ValueError(
                f"moe_jitter={config.moe_jitter} must be in [0, 1) "
                "(multiplicative noise amplitude)")
        cfg.jitter = config.moe_jitter
    return cfg


@register_model("moe_bert")
def _make_moe_bert(config: TrainConfig) -> MoeBert:
    from .bert import _make
    return _make(config, _apply_moe_overrides(MoeBertConfig(), config),
                 cls=MoeBert)


@register_model("moe_bert_tiny")
def _make_moe_bert_tiny(config: TrainConfig) -> MoeBert:
    from .bert import _make
    return _make(config, _apply_moe_overrides(MoeBertConfig.tiny(),
                                              config),
                 config_vocab=False, cls=MoeBert)
