"""GPT-style causal language model + KV-cache autoregressive decoding.

No decoder-only model exists in the reference (MLP/CNN era — SURVEY.md
§2.5/§5.7); this family exists because a framework claiming transformer
coverage needs the *causal* half of the design space: causal attention
masks, next-token training, and the TPU-native autoregressive inference
pattern (static-shape KV cache advanced by ``lax.scan`` +
``dynamic_update_slice`` — the decode loop that cannot be expressed as
"just call the trainer again").

Architecture (GPT-2 layout): learned token + position embeddings, pre-LN
blocks (``h += attn(ln1(h)); h += ffn(ln2(h))``), final layernorm, LM
head weight-tied to the token embedding. Causal masking rides the shared
:func:`~..ops.attention.multi_head_attention` ``causal=True`` path (xla
and flash impls both support it).

TPU-first notes:

- bf16 matmuls / f32 softmax+LN, static shapes (same recipe as Bert).
- Megatron TP via ``sharding_rules`` (QKV/FFN-in column-split, O/FFN-out
  row-split, vocab-sharded tied embedding) — the same rule shapes as
  Bert, so TP/fsdp/data compose identically.
- ``generate``: prefill runs ONE full causal forward over the prompt
  (MXU-dense), then the decode loop is a single compiled ``lax.scan``
  whose carry is the static-shape [B, T, H, D] per-layer KV cache —
  no per-token retrace, no dynamic shapes, one dispatch for the whole
  generation.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..config import TrainConfig, flash_attention_kwargs, lm_loss_settings
from ..ops import losses, nn
from ..ops.attention import multi_head_attention
from ..parallel.mesh import AxisNames
from ..parallel.sharding import ShardingRules
from .base import cast_floating, register_model, resolve_dtype
from .bert import REMAT_POLICIES


def quantize_kv_rows(x):
    """Symmetric per-row int8 quantization of K/V entries: ``x``
    [..., H, D] -> ``(q int8 [..., H, D], scale f32 [...])`` with
    ``scale = max|row| / 127`` over each trailing [H, D] plane (eps
    floor so an all-zero row dequantizes to exact zeros instead of
    NaN). Deterministic in the row values alone — the property the
    prefix cache's byte-identity contract rides: the same token prefix
    always produces the same int8 block bytes, whether written by
    prefill or by a teacher-forced decode step."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=(-2, -1)),
                        1e-8) / 127.0
    q = jnp.round(xf / scale[..., None, None]).astype(jnp.int8)
    return q, scale


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 30522       # framework default vocab (BERT wordpiece)
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    intermediate: int = 3072
    max_len: int = 1024
    dropout: float = 0.1
    #: LM-loss execution strategy (ops/losses.py lm_head_xent). The
    #: [B, S, vocab] logits tensor is the memory wall of causal-LM
    #: training (b64 s512 at the 30k vocab is ~4 GB of f32 logits —
    #: measured OOM on the v5e chip) AND ~21 ms of the 170 ms gpt_small
    #: step (BASELINE.md "Vocab chain"): "full" materializes it (the
    #: parity oracle and kill switch), "chunked" bounds residency at
    #: [B, loss_chunk, vocab] via jax.checkpoint recompute (the legacy
    #: escape hatch, now the fallback), "fused" never builds it in
    #: either direction (blockwise vocab scan + custom VJP) and gets
    #: token_accuracy from the same pass.
    loss_impl: str = "full"
    #: seq chunk for loss_impl="chunked" (must divide seq_len; > 0
    #: with loss_impl="full" is accepted as the legacy spelling of
    #: "chunked" — the pre-round-7 --lm_loss_chunk contract).
    loss_chunk: int = 0
    #: vocab tile for loss_impl="fused" (0 = losses.DEFAULT_VOCAB_BLOCK;
    #: swept by experiments/vocab_chain_sweep.py).
    loss_vocab_block: int = 0

    @classmethod
    def small(cls) -> "GPTConfig":
        """GPT-2-small shape (124M at its native 50k vocab)."""
        return cls()

    @classmethod
    def tiny(cls) -> "GPTConfig":
        return cls(vocab_size=1000, hidden=128, layers=2, heads=4,
                   intermediate=256, max_len=128)


class GPT:
    name = "gpt"

    def __init__(self, cfg: GPTConfig, dtype=jnp.float32,
                 attention_impl: str = "xla", attention_fn=None,
                 param_dtype=jnp.float32, remat: str = "none",
                 decode_attention_impl: str = "auto",
                 attention_kwargs: dict | None = None,
                 accuracy_every_n: int = 1):
        assert cfg.hidden % cfg.heads == 0
        if remat != "none" and remat not in REMAT_POLICIES:
            raise ValueError(f"remat must be one of "
                             f"{['none', *REMAT_POLICIES]}, got {remat!r}")
        if decode_attention_impl not in ("auto", "pallas", "xla"):
            raise ValueError(f"decode_attention_impl must be auto/pallas/"
                             f"xla, got {decode_attention_impl!r}")
        # LM-loss lever validation, loud at model build (config-built
        # models are additionally validated by config.lm_loss_settings
        # before any trace):
        if cfg.loss_impl not in losses.LM_LOSS_IMPLS:
            raise ValueError(
                f"lm_loss_impl must be one of {losses.LM_LOSS_IMPLS}, "
                f"got {cfg.loss_impl!r}")
        if cfg.loss_chunk < 0:
            raise ValueError(
                f"lm_loss_chunk={cfg.loss_chunk} must be >= 0")
        if cfg.loss_vocab_block < 0:
            raise ValueError(f"lm_loss_vocab_block={cfg.loss_vocab_block} "
                             "must be >= 0")
        if cfg.loss_chunk and cfg.loss_impl == "full":
            # legacy spelling: loss_chunk alone meant "chunked" before
            # the impl knob existed — honor it rather than silently
            # ignoring the chunk (the knob's whole point is not OOMing)
            cfg.loss_impl = "chunked"
        if cfg.loss_impl == "chunked" and not cfg.loss_chunk:
            raise ValueError("lm_loss_impl='chunked' needs lm_loss_chunk "
                             "> 0 (the chunk size)")
        if cfg.loss_impl == "fused" and cfg.loss_chunk:
            raise ValueError(
                "lm_loss_chunk conflicts with lm_loss_impl='fused': the "
                "fused vocab scan never materializes full logits, so "
                "there is nothing for the seq-chunk recompute to bound "
                "— drop --lm_loss_chunk (or pick impl='chunked')")
        if cfg.loss_vocab_block and cfg.loss_impl != "fused":
            raise ValueError(
                f"lm_loss_vocab_block={cfg.loss_vocab_block} tunes the "
                f"fused vocab scan and requires lm_loss_impl='fused', "
                f"got {cfg.loss_impl!r}")
        if accuracy_every_n < 1:
            raise ValueError(f"token_accuracy_every_n={accuracy_every_n} "
                             "must be >= 1")
        if accuracy_every_n != 1 and cfg.loss_impl == "fused":
            # same loud contract as config.lm_loss_settings, for direct
            # (non-config) construction: fused's accuracy is free, so
            # the cadence knob would be silently inert
            raise ValueError(
                f"token_accuracy_every_n={accuracy_every_n} skips the "
                "full/chunked paths' per-step argmax; lm_loss_impl="
                "'fused' computes accuracy inside the same vocab scan "
                "at no extra cost — drop the knob")
        #: cadence of the per-step token_accuracy argmax on the
        #: full/chunked paths (1 = every step; the fused path's argmax
        #: is free and ignores this). n > 1 keeps a step counter in
        #: TrainState.extras and skips the argmax on non-multiple steps
        #: (token_accuracy then reads -1.0 — the skipped-metric
        #: sentinel). Does NOT compose with microbatch accumulation
        #: (the loss runs per microbatch and the metric mean would
        #: blend real accuracies with the sentinel) —
        #: config.lm_loss_settings rejects that combination.
        self.accuracy_every_n = accuracy_every_n
        self.cfg = cfg
        self.dtype = dtype
        self.param_dtype = param_dtype
        self.attention_impl = attention_impl
        # flash-kernel tuning levers (block sizes / bwd variant), already
        # validated by config.flash_attention_kwargs when built from a
        # TrainConfig; {} = kernel defaults
        self.attention_kwargs = dict(attention_kwargs or {})
        # decode fast path: single-query Pallas attention over the cache
        # slab ("auto" = kernel on TPU at tile-friendly shapes, XLA
        # otherwise; see ops/pallas/decode_attention.py)
        self.decode_attention_impl = decode_attention_impl
        # sequence parallelism: pass make_ring_attention(mesh, causal=True)
        # — the ring schedule's causal block masking (global q/k offsets
        # per hop) makes the sharded result equal the single-device
        # causal attention; asserted in tests/test_gpt.py
        self.attention_fn = attention_fn
        self.remat = remat
        self.head_dim = cfg.hidden // cfg.heads

    def _maybe_remat(self, fn):
        if self.remat == "none":
            return fn
        return jax.checkpoint(fn, policy=REMAT_POLICIES[self.remat])

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array):
        c = self.cfg
        keys = iter(jax.random.split(rng, 2 + c.layers * 6))
        params: dict = {
            "wte": nn.embedding_init(next(keys), c.vocab_size, c.hidden),
            "wpe": nn.embedding_init(next(keys), c.max_len, c.hidden),
        }
        for i in range(c.layers):
            params[f"layer_{i}"] = {
                "ln1": nn.layernorm_init(c.hidden),
                "attn": {
                    "q": nn.dense_init(next(keys), c.hidden, c.hidden,
                                       init="glorot"),
                    "k": nn.dense_init(next(keys), c.hidden, c.hidden,
                                       init="glorot"),
                    "v": nn.dense_init(next(keys), c.hidden, c.hidden,
                                       init="glorot"),
                    "o": nn.dense_init(next(keys), c.hidden, c.hidden,
                                       init="glorot"),
                },
                "ln2": nn.layernorm_init(c.hidden),
                "ffn": {
                    "in": nn.dense_init(next(keys), c.hidden,
                                        c.intermediate, init="glorot"),
                    "out": nn.dense_init(next(keys), c.intermediate,
                                         c.hidden, init="glorot"),
                },
            }
        params["ln_f"] = nn.layernorm_init(c.hidden)
        params = cast_floating(params, self.param_dtype)
        if self.accuracy_every_n != 1:
            # the every-n accuracy cadence needs a step counter the loss
            # can read; extras is the framework slot for exactly this
            # kind of non-trained state (f32 so shard_map's extras
            # pmean is exact — equal values on every replica). NOTE the
            # counter is part of the checkpoint layout: flipping the
            # knob ON over an existing run's ckpt_dir fails loudly at
            # restore ("checkpoint missing leaf extras/lm_step") —
            # set it from the first step of a run, not mid-flight
            return params, {"lm_step": jnp.zeros((), jnp.float32)}
        return params

    # ------------------------------------------------------------------
    def _qkv(self, ap, h):
        b, s, _ = h.shape

        def split(x):
            return x.reshape(b, s, self.cfg.heads, self.head_dim)

        return (split(nn.dense(ap["q"], h, dtype=self.dtype)),
                split(nn.dense(ap["k"], h, dtype=self.dtype)),
                split(nn.dense(ap["v"], h, dtype=self.dtype)))

    def _ffn(self, lp, x):
        f = nn.dense(lp["ffn"]["in"], x, dtype=self.dtype)
        f = jax.nn.gelu(f.astype(jnp.float32)).astype(self.dtype)
        return nn.dense(lp["ffn"]["out"], f, dtype=self.dtype)

    def _layer(self, lp, h, mask, lrng, *, train: bool,
               use_dropout: bool, return_kv: bool = False):
        """Pre-LN decoder block (full-sequence causal path). ONE body for
        training and prefill: ``return_kv`` additionally yields this
        layer's (k, v) so the decode cache is filled by the exact same
        computation the oracle runs — an architecture tweak here cannot
        diverge the cached path."""
        c = self.cfg
        b, s, _ = h.shape
        q, k, v = self._qkv(lp["attn"], nn.layernorm(lp["ln1"], h))
        if self.attention_fn is not None:
            ctx = self.attention_fn(q, k, v, mask=mask, causal=True)
        else:
            ctx = multi_head_attention(
                q, k, v, mask=mask[:, None, None, :], causal=True,
                impl=self.attention_impl,
                flash_kwargs=self.attention_kwargs or None)
        a = nn.dense(lp["attn"]["o"], ctx.reshape(b, s, c.hidden),
                     dtype=self.dtype)
        if use_dropout:
            a = nn.dropout(jax.random.fold_in(lrng, 1), a, c.dropout,
                           train=True)
        h = h + a.astype(h.dtype)
        f = self._ffn(lp, nn.layernorm(lp["ln2"], h))
        if use_dropout:
            f = nn.dropout(jax.random.fold_in(lrng, 2), f, c.dropout,
                           train=True)
        h = h + f.astype(h.dtype)
        return (h, (k, v)) if return_kv else h

    def _embed(self, params, ids, pos_ids, rng, train):
        c = self.cfg
        h = (nn.embedding(params["wte"], ids)
             + nn.embedding(params["wpe"], pos_ids))
        h = h.astype(self.dtype)
        use_dropout = train and c.dropout > 0 and rng is not None
        if use_dropout:
            h = nn.dropout(jax.random.fold_in(rng, 1000), h, c.dropout,
                           train=True)
        return h, use_dropout

    def encode(self, params, batch, rng=None, train: bool = False):
        c = self.cfg
        ids = batch["input_ids"]
        _, s = ids.shape
        mask = batch.get("attention_mask", jnp.ones_like(ids))
        h, use_dropout = self._embed(
            params, ids, jnp.arange(s, dtype=jnp.int32)[None], rng, train)
        layer = self._maybe_remat(
            functools.partial(self._layer, train=train,
                              use_dropout=use_dropout))
        for i in range(c.layers):
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            h = layer(params[f"layer_{i}"], h, mask, lrng)
        return nn.layernorm(params["ln_f"], h)

    def lm_logits(self, params, h):
        """Weight-tied LM head: [B,S,hid] -> [B,S,V] f32 logits."""
        table = params["wte"]["table"]
        logits = jnp.einsum("bsh,vh->bsv", h.astype(self.dtype),
                            table.astype(self.dtype),
                            preferred_element_type=jnp.float32)
        return logits

    def apply(self, params, extras, batch, rng=None, train: bool = False):
        return self.lm_logits(
            params, self.encode(params, batch, rng, train)), extras

    # ------------------------------------------------------------------
    def _lm_loss(self, params, h, targets, w, *, accuracy: bool = True):
        """Next-token loss + accuracy over encoded ``h`` [B, S, hid].
        ``targets``/``w`` are the S-1 shifted labels/weights; ONE setup
        pads them with a weight-0 dummy at position S-1 so every impl
        (full / chunked / fused — ops/losses.py lm_head_xent, the shared
        blockwise core) sees the same aligned [B, S] arrays. Returns
        (loss, accuracy) as weighted token means."""
        c = self.cfg
        targets = jnp.concatenate(
            [targets, jnp.zeros_like(targets[:, :1])], axis=1)
        w = jnp.concatenate([w, jnp.zeros_like(w[:, :1])], axis=1)
        return losses.lm_head_xent(
            h, params["wte"]["table"], targets, w, impl=c.loss_impl,
            seq_chunk=c.loss_chunk, vocab_block=c.loss_vocab_block,
            dtype=self.dtype, accuracy=accuracy)

    def loss(self, params, extras, batch, rng):
        # next-token prediction: position t predicts token t+1; padding
        # (attention_mask == 0) carries no loss
        targets = batch["input_ids"][:, 1:]
        mask = batch.get("attention_mask",
                         jnp.ones_like(batch["input_ids"]))
        w = mask[:, 1:].astype(jnp.float32)
        h = self.encode(params, batch, rng, train=True)
        every = self.accuracy_every_n
        step = (extras.get("lm_step")
                if every != 1 and isinstance(extras, dict) else None)
        if every == 1 or self.cfg.loss_impl == "fused" or step is None:
            # fused gets the argmax free from the same vocab scan; step
            # is None for direct callers that never initialized the
            # counter (init() emits it only when the knob is set)
            loss, acc = self._lm_loss(params, h, targets, w)
            return loss, ({"token_accuracy": acc}, extras)
        # every-n cadence: one branch runs per step (lax.cond), so the
        # full-vocab argmax is genuinely skipped on non-multiple steps
        loss, acc = lax.cond(
            jnp.mod(step, float(every)) == 0,
            lambda: self._lm_loss(params, h, targets, w, accuracy=True),
            lambda: self._lm_loss(params, h, targets, w, accuracy=False))
        new_extras = dict(extras)
        new_extras["lm_step"] = step + 1.0
        return loss, ({"token_accuracy": acc}, new_extras)

    def eval_metrics(self, params, extras, batch) -> dict:
        targets = batch["input_ids"][:, 1:]
        mask = batch.get("attention_mask",
                         jnp.ones_like(batch["input_ids"]))
        w = mask[:, 1:].astype(jnp.float32)
        valid = batch.get("__valid__")
        if valid is not None:
            w = w * valid.astype(jnp.float32)[:, None]
        # eval rides the configured impl too: the final eval of a
        # chunked/fused run must not re-materialize the [B, S, vocab]
        # tensor the lever exists to avoid — and always reports
        # accuracy (the every-n knob is a per-train-step economy)
        h = self.encode(params, batch, train=False)
        loss, acc = self._lm_loss(params, h, targets, w)
        return {
            "loss": loss,
            # the classic LM headline number; exp of the masked mean xent
            "perplexity": jnp.exp(loss),
            "token_accuracy": acc,
        }

    # ------------------------------------------------------------------
    # autoregressive decoding (static-shape KV cache, one compiled scan)
    # ------------------------------------------------------------------
    def _prefill_full(self, params, ids, total_len: int, *, mask=None,
                      pos_ids=None):
        """Full causal forward over the (possibly padded) prompt,
        additionally returning per-layer K/V padded to ``total_len``
        slots. ``mask``/``pos_ids`` serve the ragged-prompt path: pad
        slots are attention-masked out and real tokens carry their own
        positions. Returns (hidden [B,S,hid] post-ln_f, caches
        {layer_i: {k, v}: [B,T,H,D]})."""
        c = self.cfg
        _, s = ids.shape
        if mask is None:
            mask = jnp.ones_like(ids)
        if pos_ids is None:
            pos_ids = jnp.arange(s, dtype=jnp.int32)[None]
        h, _ = self._embed(params, ids, pos_ids, rng=None, train=False)
        caches = {}
        pad = [(0, 0), (0, total_len - s), (0, 0), (0, 0)]
        for i in range(c.layers):
            h, (k, v) = self._layer(params[f"layer_{i}"], h, mask, None,
                                    train=False, use_dropout=False,
                                    return_kv=True)
            caches[f"layer_{i}"] = {"k": jnp.pad(k, pad),
                                    "v": jnp.pad(v, pad)}
        h = nn.layernorm(params["ln_f"], h)
        return h, caches

    def _prefill(self, params, ids, total_len: int, *, mask=None,
                 pos_ids=None):
        """:meth:`_prefill_full` sliced to the LAST slot's hidden state
        — the right-packed contract (every row's prompt ends at slot
        S0-1) the monolithic ``generate`` path runs on."""
        h, caches = self._prefill_full(params, ids, total_len, mask=mask,
                                       pos_ids=pos_ids)
        return h[:, -1], caches

    def _decode_step(self, params, caches, tok, pos, pad=None):
        """One-token forward against the cache. ``tok`` [B] int32,
        ``pos`` scalar (the CACHE SLOT tok sits at). ``pad`` [B] is the
        per-row left-pad count of a ragged prompt: row b's token at slot
        j holds position j - pad_b, and slots below pad_b are dead.
        Returns (logits [B,V], updated caches)."""
        c = self.cfg
        b = tok.shape[0]
        total = jax.tree_util.tree_leaves(caches)[0].shape[1]
        if pad is None:
            pad = jnp.zeros((b,), jnp.int32)
        h, _ = self._embed(params, tok[:, None], (pos - pad)[:, None],
                           rng=None, train=False)
        slots = jnp.arange(total, dtype=jnp.int32)
        kmask = (slots[None, :] <= pos) & (slots[None, :] >= pad[:, None])
        new_caches = {}
        for i in range(c.layers):
            lp = params[f"layer_{i}"]
            cache = caches[f"layer_{i}"]
            q, k, v = self._qkv(lp["attn"], nn.layernorm(lp["ln1"], h))
            ck = lax.dynamic_update_slice_in_dim(cache["k"],
                                                 k.astype(cache["k"].dtype),
                                                 pos, axis=1)
            cv = lax.dynamic_update_slice_in_dim(cache["v"],
                                                 v.astype(cache["v"].dtype),
                                                 pos, axis=1)
            new_caches[f"layer_{i}"] = {"k": ck, "v": cv}
            ctx = multi_head_attention(
                q, ck, cv, mask=kmask[:, None, None, :],
                impl="xla")     # 1-query attention: tiles never pay off
            a = nn.dense(lp["attn"]["o"], ctx.reshape(b, 1, c.hidden),
                         dtype=self.dtype)
            h = h + a.astype(h.dtype)
            f = self._ffn(lp, nn.layernorm(lp["ln2"], h))
            h = h + f.astype(h.dtype)
        h = nn.layernorm(params["ln_f"], h)
        return self.lm_logits(params, h)[:, 0], new_caches

    # ------------------------------------------------------------------
    # decode fast path: stacked layer axis + lax.scan + fused QKV
    # ------------------------------------------------------------------
    def stack_decode_params(self, params, *, weight_quant: str | None = None):
        """Restack the per-layer param dicts into ONE pytree with a
        leading layer axis, with the Q/K/V projections fused into a
        single [hid, 3*hid] kernel per layer. The decode layer loop then
        runs as ``lax.scan`` over this stack: one traced layer body
        instead of ``layers`` unrolled copies, and one fat QKV matmul
        per layer instead of three skinny ones — the kernel-count floor
        attack PROFILE_r05_decode motivates.

        ``weight_quant="int8"`` additionally stores the four matmul
        kernels as symmetric per-output-channel int8 (scale = f32 row
        max / 127), halving decode weight traffic for the stacked
        layers. LOSSY: greedy parity with the bf16 path is NOT
        guaranteed — it exists as the decode lever table's int8
        comparison row. Embeddings / LM head / layernorms stay in
        ``param_dtype``.

        Cost note: ``generate`` restacks INSIDE the compiled program,
        once per generation (``params`` is a runtime argument to the
        caller's jit, so XLA cannot constant-fold it) — one extra
        param read+write against the ``max_new`` weight re-reads of
        the decode loop, <2% of a 128-token generation's traffic and
        paid identically by every lever row except ``loop``. Exported
        artifacts bake params as constants, so there the restack
        folds away at trace time.
        """
        if weight_quant not in (None, "int8"):
            raise ValueError(f"weight_quant must be None or 'int8', got "
                             f"{weight_quant!r}")
        c = self.cfg
        lps = [params[f"layer_{i}"] for i in range(c.layers)]

        def stk(fn):
            return jnp.stack([fn(lp) for lp in lps])

        def dense_stack(fn):
            d = {"kernel": stk(lambda lp: fn(lp)["kernel"]),
                 "bias": stk(lambda lp: fn(lp)["bias"])}
            if weight_quant == "int8":
                w = d.pop("kernel").astype(jnp.float32)
                scale = jnp.maximum(
                    jnp.max(jnp.abs(w), axis=1, keepdims=True), 1e-8) / 127.0
                d["kernel_q"] = jnp.round(w / scale).astype(jnp.int8)
                d["scale"] = scale
            return d

        return {
            "ln1": {"scale": stk(lambda lp: lp["ln1"]["scale"]),
                    "bias": stk(lambda lp: lp["ln1"]["bias"])},
            "qkv": dense_stack(lambda lp: {
                "kernel": jnp.concatenate(
                    [lp["attn"][n]["kernel"] for n in ("q", "k", "v")],
                    axis=1),
                "bias": jnp.concatenate(
                    [lp["attn"][n]["bias"] for n in ("q", "k", "v")])}),
            "o": dense_stack(lambda lp: lp["attn"]["o"]),
            "ln2": {"scale": stk(lambda lp: lp["ln2"]["scale"]),
                    "bias": stk(lambda lp: lp["ln2"]["bias"])},
            "ffn_in": dense_stack(lambda lp: lp["ffn"]["in"]),
            "ffn_out": dense_stack(lambda lp: lp["ffn"]["out"]),
        }

    def _dequant(self, dp):
        """int8-stacked dense params -> plain {kernel, bias} (no-op for
        unquantized stacks). Runs INSIDE the layer scan body, so the
        int8 tensors are what crosses HBM per layer step."""
        if "kernel_q" not in dp:
            return dp
        w = (dp["kernel_q"].astype(jnp.float32) * dp["scale"])
        return {"kernel": w.astype(self.dtype), "bias": dp["bias"]}

    def _decode_step_stacked(self, params, stacked, caches, tok, pos,
                             pad=None, decode_attention: str | None = None):
        """One-token forward as ONE ``lax.scan`` over the stacked layer
        axis. Same contract as :meth:`_decode_step` (exact greedy
        parity is tier-1-tested), but the per-token program is the
        compact fast path: fused QKV, 2-D [B, hid] residual stream (no
        singleton seq axis to re-tile), and the cache-slab attention as
        either the single-query Pallas kernel or the XLA reference.

        ``caches``: ``{"k": [L, B, T, H, D], "v": [L, B, T, H, D]}`` —
        the per-layer slabs stacked along the scan axis.
        """
        from ..ops.pallas.decode_attention import (decode_attention as
                                                   decode_attn)
        c = self.cfg
        b = tok.shape[0]
        impl = decode_attention or self.decode_attention_impl
        if pad is None:
            pad = jnp.zeros((b,), jnp.int32)
        h, _ = self._embed(params, tok[:, None], (pos - pad)[:, None],
                           rng=None, train=False)
        h = h[:, 0]                                       # [B, hid]

        def body(h, xs):
            lp, ck, cv = xs
            qkv = nn.dense(self._dequant(lp["qkv"]),
                           nn.layernorm(lp["ln1"], h), dtype=self.dtype)
            q, k, v = [x.reshape(b, c.heads, self.head_dim)
                       for x in jnp.split(qkv, 3, axis=-1)]
            ck = lax.dynamic_update_slice(
                ck, k[:, None].astype(ck.dtype), (0, pos, 0, 0))
            cv = lax.dynamic_update_slice(
                cv, v[:, None].astype(cv.dtype), (0, pos, 0, 0))
            ctx = decode_attn(q, ck, cv, pos=pos, pad=pad, impl=impl)
            a = nn.dense(self._dequant(lp["o"]), ctx.reshape(b, c.hidden),
                         dtype=self.dtype)
            h = h + a.astype(h.dtype)
            f = nn.dense(self._dequant(lp["ffn_in"]),
                         nn.layernorm(lp["ln2"], h), dtype=self.dtype)
            f = jax.nn.gelu(f.astype(jnp.float32)).astype(self.dtype)
            f = nn.dense(self._dequant(lp["ffn_out"]), f, dtype=self.dtype)
            h = h + f.astype(h.dtype)
            return h, (ck, cv)

        h, (ks, vs) = lax.scan(body, h,
                               (stacked, caches["k"], caches["v"]))
        h = nn.layernorm(params["ln_f"], h)
        return (self.lm_logits(params, h[:, None])[:, 0],
                {"k": ks, "v": vs})

    def ragged_prefill(self, params, input_ids, prompt_mask,
                       total_len: int):
        """Ragged-prompt prefill: right-pack every row's real tokens
        against slot S0-1 (stable argsort — order preserving), build
        per-row positions/attention from the pad count, and run
        :meth:`_prefill` padded to ``total_len`` cache slots. Returns
        ``(last_hidden [B, hid], caches, pad [B])``. ONE body for
        :meth:`generate`'s ragged branch and the stepwise serving
        export (serving.export_generator ``stepwise=True``) — the
        continuous-batching engine's admission prefill is the exact
        computation the monolithic path runs."""
        b, s0 = input_ids.shape
        # normalize to 0/1 first: the docstring contract is "nonzero
        # = real token", and a 2 in the mask would otherwise corrupt
        # the pad count below (and disagree with the HTTP server's
        # `!= 0` validation)
        pm = (jnp.asarray(prompt_mask) != 0).astype(jnp.int32)
        # stable argsort keys pads (0) first, real tokens (1) after
        # IN ORDER: one gather right-packs every row
        order = jnp.argsort(pm, axis=1, stable=True)
        ids = jnp.take_along_axis(jnp.asarray(input_ids), order, axis=1)
        pad = (s0 - jnp.sum(pm, axis=1)).astype(jnp.int32)
        valid = jnp.arange(s0, dtype=jnp.int32)[None, :] >= pad[:, None]
        ids = jnp.where(valid, ids, 0)
        pos_ids = jnp.maximum(
            jnp.arange(s0, dtype=jnp.int32)[None, :] - pad[:, None], 0)
        last_h, caches = self._prefill(params, ids, total_len,
                                       mask=valid.astype(jnp.int32),
                                       pos_ids=pos_ids)
        return last_h, caches, pad

    def decode_step_batched(self, params, stacked, caches, tok, pos,
                            pad, alive=None,
                            decode_attention: str | None = None):
        """One-token forward with PER-ROW cache depths — the decode
        step of the continuous-batching serving engine, where slots
        were admitted at different times and therefore sit at
        different positions in their own sequences.

        Same fast-path body as :meth:`_decode_step_stacked` (one
        ``lax.scan`` over the stacked layer axis, fused QKV, 2-D
        residual stream) with two generalizations:

        - ``pos`` is [B] int32 (row b's token writes cache slot
          ``pos[b]`` and carries position id ``pos[b] - pad[b]``)
          instead of one shared scalar;
        - ``alive`` [B] (bool / 0-1) gates the cache write: a retired
          slot's slab keeps its old bytes (its lane still computes —
          wasted work the shared step accepts — but cannot mutate the
          pool; admission prefill overwrites the whole slab anyway).

        Rows are independent: row b's logits depend only on row b's
        token/pos/pad/cache, which is what makes the engine's shared
        step produce the same token stream per request as a
        single-request run (tier-1 tested). ``caches``:
        ``{"k": [L, B, T, H, D], "v": [L, B, T, H, D]}``.
        """
        from ..ops.pallas.decode_attention import (decode_attention as
                                                   decode_attn)
        c = self.cfg
        b = tok.shape[0]
        total = caches["k"].shape[2]
        impl = decode_attention or self.decode_attention_impl
        pos = jnp.clip(jnp.asarray(pos, jnp.int32), 0, total - 1)
        pad = jnp.asarray(pad, jnp.int32)
        if alive is None:
            alive = jnp.ones((b,), bool)
        alive = jnp.asarray(alive) != 0
        # dead rows may carry stale pos/pad; clamp the position id so
        # the wpe lookup stays in range (live rows are unaffected —
        # their pos - pad is a real position by construction)
        pos_ids = jnp.clip(pos - pad, 0, c.max_len - 1)
        h, _ = self._embed(params, tok[:, None], pos_ids[:, None],
                           rng=None, train=False)
        h = h[:, 0]                                       # [B, hid]
        rows = jnp.arange(b)

        def body(h, xs):
            lp, ck, cv = xs
            qkv = nn.dense(self._dequant(lp["qkv"]),
                           nn.layernorm(lp["ln1"], h), dtype=self.dtype)
            q, k, v = [x.reshape(b, c.heads, self.head_dim)
                       for x in jnp.split(qkv, 3, axis=-1)]
            # per-row scatter at each row's own depth; dead rows
            # rewrite their old bytes (no-op write keeps the pool
            # stable for retired slots)
            k_w = jnp.where(alive[:, None, None],
                            k.astype(ck.dtype), ck[rows, pos])
            v_w = jnp.where(alive[:, None, None],
                            v.astype(cv.dtype), cv[rows, pos])
            ck = ck.at[rows, pos].set(k_w)
            cv = cv.at[rows, pos].set(v_w)
            ctx = decode_attn(q, ck, cv, pos=pos, pad=pad, impl=impl)
            a = nn.dense(self._dequant(lp["o"]), ctx.reshape(b, c.hidden),
                         dtype=self.dtype)
            h = h + a.astype(h.dtype)
            f = nn.dense(self._dequant(lp["ffn_in"]),
                         nn.layernorm(lp["ln2"], h), dtype=self.dtype)
            f = jax.nn.gelu(f.astype(jnp.float32)).astype(self.dtype)
            f = nn.dense(self._dequant(lp["ffn_out"]), f, dtype=self.dtype)
            h = h + f.astype(h.dtype)
            return h, (ck, cv)

        h, (ks, vs) = lax.scan(body, h,
                               (stacked, caches["k"], caches["v"]))
        h = nn.layernorm(params["ln_f"], h)
        return (self.lm_logits(params, h[:, None])[:, 0],
                {"k": ks, "v": vs})

    # ------------------------------------------------------------------
    # block-paged serving path (round 10): the KV pool is shared
    # [L, N, block_size, H, D] physical blocks + per-slot block tables
    # ------------------------------------------------------------------
    def paged_prefill(self, params, input_ids, prompt_mask, k_pool,
                      v_pool, table_row, *, k_scale=None, v_scale=None):
        """LEFT-ALIGNED prompt prefill writing WHOLE blocks through a
        block-table row — the paged serving engine's admission program.

        Unlike :meth:`ragged_prefill` (which right-packs so the
        monolithic loop can advance one shared scalar slot), the paged
        layout keeps token i at logical slot i: a shared token prefix
        then occupies the same leading blocks for every request
        regardless of total prompt length, which is what makes
        block-granularity prefix reuse possible at all (right-packing
        shifts the prefix by the per-request pad count). The engine's
        decode step has per-row ``pos`` anyway, so nothing needed the
        shared-scalar trick here.

        ``input_ids``/``prompt_mask``: [1, S0] (mask 1 = real token,
        left-aligned); ``k_pool``/``v_pool``: [L, N, Bs, H, D];
        ``table_row``: [ceil(S0 / Bs)] int32 physical block ids (the
        engine points unused trailing entries at the reserved null
        block 0 — whole-block writes land there and are never read).
        Returns ``(logits [1, V] of the last real token, k_pool',
        v_pool')`` with every prompt-capacity block of this row
        overwritten.

        ``k_scale``/``v_scale`` ([L, N, Bs] f32 parallel pools) switch
        on QUANTIZE-ON-WRITE for an int8 pool: each token row's [H, D]
        K/V plane is stored symmetric int8 with its per-row scale
        (:func:`quantize_kv_rows` — deterministic in the bytes, so
        prefix-cache sharing mounts byte-identical blocks) and the
        return grows to ``(logits, k_pool', v_pool', k_scale',
        v_scale')``."""
        c = self.cfg
        _, s0 = input_ids.shape
        bs = k_pool.shape[2]
        nb_p = table_row.shape[0]
        total = nb_p * bs
        pm = (jnp.asarray(prompt_mask) != 0)
        ids = jnp.where(pm, jnp.asarray(input_ids), 0)
        h_full, caches = self._prefill_full(
            params, ids, total, mask=pm.astype(jnp.int32),
            pos_ids=jnp.arange(s0, dtype=jnp.int32)[None])
        p = jnp.sum(pm.astype(jnp.int32))
        last_h = jnp.take_along_axis(
            h_full, jnp.maximum(p - 1, 0)[None, None, None], axis=1)[:, 0]
        kv = self._stack_caches(caches)         # {"k"/"v": [L,1,T,H,D]}
        l = c.layers

        def scatter(pool, stacked):
            blocks = stacked[:, 0].reshape(l, nb_p, bs, *stacked.shape[3:])
            return pool.at[:, table_row].set(blocks.astype(pool.dtype))

        logits = self.lm_logits(params, last_h[:, None])[:, 0]
        if k_scale is None:
            return logits, scatter(k_pool, kv["k"]), scatter(v_pool,
                                                             kv["v"])
        # int8 pool: quantize each token row before the block scatter;
        # the scale rows ride a parallel [L, N, Bs] pool through the
        # same table indices
        def scatter_q(pool, spool, stacked):
            q, s = quantize_kv_rows(stacked[:, 0])      # [L,T,H,D]/[L,T]
            qb = q.reshape(l, nb_p, bs, *q.shape[2:])
            sb = s.reshape(l, nb_p, bs)
            return (pool.at[:, table_row].set(qb),
                    spool.at[:, table_row].set(sb))
        kq, ks = scatter_q(k_pool, k_scale, kv["k"])
        vq, vs = scatter_q(v_pool, v_scale, kv["v"])
        return logits, kq, vq, ks, vs

    def paged_prefill_chunk(self, params, input_ids, chunk_mask, start,
                            k_pool, v_pool, table_row, chunk_blocks, *,
                            k_scale=None, v_scale=None):
        """ONE ``chunk_tokens``-wide slice of a left-aligned paged
        prefill — the SLO scheduler's bounded-stall admission program
        (round 18). A long prompt's monolithic prefill stalls every
        live decode slot for the whole forward; this program processes
        only the tokens at logical slots ``start .. start+C-1``,
        reading the PRIOR chunks' K/V back from the pool through
        ``table_row``, so the engine can interleave shared decode steps
        between chunks and bound the worst-case decode stall at one
        chunk's dispatch time.

        ``input_ids``/``chunk_mask``: [1, C] (mask 1 = real token,
        left-aligned — only the final chunk of a prompt is ragged);
        ``start``: scalar int32, the chunk's first logical slot (the
        engine keeps it block-aligned); ``table_row``: [NB_p] int32,
        the slot's WHOLE prompt-capacity block run (the attention
        gather's context window); ``chunk_blocks``: [C / Bs] int32,
        the physical blocks this chunk writes (entries past the
        prompt's allocated run point at the reserved null block 0,
        whose bytes are never read). Returns ``(logits [1, V] of the
        chunk's last real token, k_pool', v_pool')`` — the logits only
        matter on the FINAL chunk, where they are the request's first
        sample point, exactly like :meth:`paged_prefill`'s return.

        Parity contract: per-token math is row-independent (embedding,
        layernorm, dense) and the attention softmax over the gathered
        pool window differs from the monolithic prefill's only by
        exactly-zero masked terms, so with a float pool (storage dtype
        == compute dtype) the chunked byte stream — K/V block bytes
        AND the final-chunk logits — is bit-identical to one
        :meth:`paged_prefill` dispatch (tier-1 tested). An int8 pool
        re-reads prior chunks through the quantize/dequant pair the
        monolithic prefill never pays, so int8 composition rides the
        repo's token-agreement drift gate instead (DESIGN.md §15).

        ``k_scale``/``v_scale`` switch on quantize-on-write exactly as
        in :meth:`paged_prefill`; the return grows the same way."""
        c = self.cfg
        _, cw = input_ids.shape
        bs = k_pool.shape[2]
        nb_c = chunk_blocks.shape[0]
        nb_p = table_row.shape[0]
        total = nb_p * bs
        start = jnp.asarray(start, jnp.int32)
        cm = (jnp.asarray(chunk_mask) != 0)
        ids = jnp.where(cm, jnp.asarray(input_ids), 0)
        # global positions; masked lanes clamp so the wpe gather stays
        # in range (their rows are garbage nothing reads)
        pos_ids = jnp.clip(start + jnp.arange(cw, dtype=jnp.int32),
                           0, c.max_len - 1)[None]
        h, _ = self._embed(params, ids, pos_ids, rng=None, train=False)
        # key validity over the gathered context window: every slot
        # before this chunk holds a real prior-chunk token (chunks tile
        # block-aligned), slots inside the chunk follow its mask, and
        # slots at or past the chunk end were never written
        slots = jnp.arange(total, dtype=jnp.int32)
        in_chunk = (slots >= start) & (slots < start + cw)
        chunk_valid = jnp.take(
            cm[0], jnp.clip(slots - start, 0, cw - 1))
        kv_valid = (slots < start) | (in_chunk & chunk_valid)
        # causal: query lane j (global slot start + j) sees slot s
        # iff s <= start + j
        qpos = start + jnp.arange(cw, dtype=jnp.int32)
        mask4 = (kv_valid[None, :]
                 & (slots[None, :] <= qpos[:, None]))[None, None]
        quant = k_scale is not None

        def write(pool, fresh):
            # [1, C, H, D] fresh K/V -> the chunk's whole blocks (same
            # scatter shape as paged_prefill, through chunk_blocks)
            blocks = fresh[0].reshape(nb_c, bs, *fresh.shape[2:])
            return pool.at[chunk_blocks].set(blocks.astype(pool.dtype))

        def write_q(pool, spool, fresh):
            q, s = quantize_kv_rows(fresh[0])          # [C,H,D] / [C]
            return (pool.at[chunk_blocks].set(
                        q.reshape(nb_c, bs, *q.shape[1:])),
                    spool.at[chunk_blocks].set(s.reshape(nb_c, bs)))

        new_k, new_v = [], []
        new_ks, new_vs = [], []
        for i in range(c.layers):
            lp = params[f"layer_{i}"]
            q, k, v = self._qkv(lp["attn"], nn.layernorm(lp["ln1"], h))
            # write THIS chunk's K/V first (verify-style: the gather
            # below must already see lanes 0..j-1's keys), then gather
            # the whole context window back through the table
            if quant:
                kp, ksp = write_q(k_pool[i], k_scale[i], k)
                vp, vsp = write_q(v_pool[i], v_scale[i], v)
                ctx_k = (kp[table_row].astype(jnp.float32)
                         * ksp[table_row][..., None, None])
                ctx_v = (vp[table_row].astype(jnp.float32)
                         * vsp[table_row][..., None, None])
                new_ks.append(ksp)
                new_vs.append(vsp)
            else:
                kp = write(k_pool[i], k)
                vp = write(v_pool[i], v)
                ctx_k, ctx_v = kp[table_row], vp[table_row]
            new_k.append(kp)
            new_v.append(vp)
            ctx_k = ctx_k.reshape(1, total, c.heads, self.head_dim) \
                .astype(self.dtype)
            ctx_v = ctx_v.reshape(1, total, c.heads, self.head_dim) \
                .astype(self.dtype)
            ctx = multi_head_attention(q, ctx_k, ctx_v, mask=mask4,
                                       impl="xla")
            a = nn.dense(lp["attn"]["o"],
                         ctx.reshape(1, cw, c.hidden), dtype=self.dtype)
            h = h + a.astype(h.dtype)
            f = self._ffn(lp, nn.layernorm(lp["ln2"], h))
            h = h + f.astype(h.dtype)
        h = nn.layernorm(params["ln_f"], h)
        p_chunk = jnp.sum(cm.astype(jnp.int32))
        last_h = jnp.take_along_axis(
            h, jnp.maximum(p_chunk - 1, 0)[None, None, None],
            axis=1)[:, 0]
        logits = self.lm_logits(params, last_h[:, None])[:, 0]
        out = (logits, jnp.stack(new_k), jnp.stack(new_v))
        if quant:
            out += (jnp.stack(new_ks), jnp.stack(new_vs))
        return out

    def decode_step_batched_paged(self, params, stacked, pools,
                                  block_tables, tok, pos, pad,
                                  alive=None,
                                  decode_attention: str | None = None):
        """:meth:`decode_step_batched` with the cache read/written
        THROUGH per-slot block tables: row b's token writes physical
        block ``block_tables[b, pos_b // Bs]`` at offset ``pos_b % Bs``,
        and attention gathers K/V through the same table (both decode-
        attention impls). ``pools``: ``{"k"/"v": [L, N, Bs, H, D]}``;
        ``block_tables``: [B, NB] int32. Rows stay independent — the
        engine guarantees a written block is uniquely owned (copy-on-
        write happens host-side before the step), and a dead row's
        table points at the null block, where its gated write rewrites
        old bytes.

        int8 KV cache: when ``pools`` additionally carries
        ``"k_scale"``/``"v_scale"`` ([L, N, Bs] f32), the K/V pools
        are int8 — the step QUANTIZES its new row on write
        (:func:`quantize_kv_rows`, same per-row symmetric scheme as
        :meth:`paged_prefill`, so forced-suffix bytes match what a
        cold prefill of the same tokens writes up to the drift-gate
        contract) and both decode-attention impls fuse the dequant
        into the gather (no dequantized pool tensor ever exists)."""
        from ..ops.pallas.decode_attention import paged_decode_attention
        c = self.cfg
        b = tok.shape[0]
        bs = pools["k"].shape[2]
        nb = block_tables.shape[1]
        impl = decode_attention or self.decode_attention_impl
        pos = jnp.clip(jnp.asarray(pos, jnp.int32), 0, nb * bs - 1)
        pad = jnp.asarray(pad, jnp.int32)
        bt = jnp.asarray(block_tables, jnp.int32)
        if alive is None:
            alive = jnp.ones((b,), bool)
        alive = jnp.asarray(alive) != 0
        pos_ids = jnp.clip(pos - pad, 0, c.max_len - 1)
        h, _ = self._embed(params, tok[:, None], pos_ids[:, None],
                           rng=None, train=False)
        h = h[:, 0]                                       # [B, hid]
        rows = jnp.arange(b)
        pbid = bt[rows, pos // bs]                        # [B] physical
        off = pos % bs
        quant = "k_scale" in pools

        def body(h, xs):
            if quant:
                lp, ck, cv, cks, cvs = xs
            else:
                lp, ck, cv = xs
            qkv = nn.dense(self._dequant(lp["qkv"]),
                           nn.layernorm(lp["ln1"], h), dtype=self.dtype)
            q, k, v = [x.reshape(b, c.heads, self.head_dim)
                       for x in jnp.split(qkv, 3, axis=-1)]
            if quant:
                # quantize-on-write: the new row's int8 bytes + scale,
                # gated like the float write (dead rows rewrite old)
                kq, ksc = quantize_kv_rows(k)
                vq, vsc = quantize_kv_rows(v)
                ck = ck.at[pbid, off].set(jnp.where(
                    alive[:, None, None], kq, ck[pbid, off]))
                cv = cv.at[pbid, off].set(jnp.where(
                    alive[:, None, None], vq, cv[pbid, off]))
                cks = cks.at[pbid, off].set(jnp.where(
                    alive, ksc, cks[pbid, off]))
                cvs = cvs.at[pbid, off].set(jnp.where(
                    alive, vsc, cvs[pbid, off]))
                ctx = paged_decode_attention(
                    q, ck, cv, block_tables=bt, pos=pos, pad=pad,
                    k_scale=cks, v_scale=cvs, impl=impl)
            else:
                k_w = jnp.where(alive[:, None, None],
                                k.astype(ck.dtype), ck[pbid, off])
                v_w = jnp.where(alive[:, None, None],
                                v.astype(cv.dtype), cv[pbid, off])
                ck = ck.at[pbid, off].set(k_w)
                cv = cv.at[pbid, off].set(v_w)
                ctx = paged_decode_attention(q, ck, cv, block_tables=bt,
                                             pos=pos, pad=pad, impl=impl)
            a = nn.dense(self._dequant(lp["o"]), ctx.reshape(b, c.hidden),
                         dtype=self.dtype)
            h = h + a.astype(h.dtype)
            f = nn.dense(self._dequant(lp["ffn_in"]),
                         nn.layernorm(lp["ln2"], h), dtype=self.dtype)
            f = jax.nn.gelu(f.astype(jnp.float32)).astype(self.dtype)
            f = nn.dense(self._dequant(lp["ffn_out"]), f, dtype=self.dtype)
            h = h + f.astype(h.dtype)
            return h, (ck, cv, cks, cvs) if quant else (ck, cv)

        if quant:
            h, (ks, vs, kss, vss) = lax.scan(
                body, h, (stacked, pools["k"], pools["v"],
                          pools["k_scale"], pools["v_scale"]))
            out_pools = {"k": ks, "v": vs, "k_scale": kss,
                         "v_scale": vss}
        else:
            h, (ks, vs) = lax.scan(body, h,
                                   (stacked, pools["k"], pools["v"]))
            out_pools = {"k": ks, "v": vs}
        h = nn.layernorm(params["ln_f"], h)
        return self.lm_logits(params, h[:, None])[:, 0], out_pools

    def decode_verify_batched_paged(self, params, stacked, pools,
                                    block_tables, tok, pos, pad,
                                    alive, n_tok,
                                    decode_attention: str | None = None):
        """K-token VERIFY step for speculative decoding: row b carries
        ``tok[b] = [anchor, draft_1, ..., draft_{K-1}]`` — the anchor is
        the token a normal decode step would dispatch (its KV is not in
        the pool yet), the drafts are the self-drafter's proposals.
        Lane j writes its K/V at logical slot ``pos[b] + j`` through the
        block table and its logits predict the token at ``pos[b]+j+1``,
        so the host can accept the longest draft prefix that matches the
        greedy argmax chain and rewind ``pos`` past the rest.

        Implemented as :meth:`decode_step_batched_paged` over ROW-
        EXPANDED inputs: lane (b, j) becomes an independent row at
        ``pos[b] + j`` sharing row b's block table. Within one layer the
        scan body writes every row's K/V into the pool BEFORE the
        attention gather, so lane j's window (``slots <= pos[b]+j``)
        already contains lanes 0..j-1's keys — exactly the state a
        sequential dispatch of the same tokens would have produced. The
        verify step therefore inherits the batched step's byte-parity
        contract (rows are computationally independent) AND its whole
        quantization surface: int8 stacked weights and the int8 paged
        pool (quantize-on-write + fused-dequant gathers) run unchanged.

        ``tok``: [B, K] int32; ``pos``/``pad``/``alive``: [B];
        ``n_tok``: [B] int32 in [1, K] — lanes >= ``n_tok[b]`` are
        write-gated like dead rows (they rewrite old bytes; their
        logits are computed but the host ignores them), which is how
        draftless/sampled slots ride the same dispatch at width 1.
        Distinct lanes of one row write distinct (block, offset) pairs
        (positions ``pos..pos+K-1`` are consecutive), so the expanded
        scatter has no intra-row write collision. Returns
        (``logits [B, K, V]``, updated pools)."""
        b, kk = tok.shape
        lanes = jnp.arange(kk, dtype=jnp.int32)
        pos = jnp.asarray(pos, jnp.int32)
        n_tok = jnp.asarray(n_tok, jnp.int32)
        alive = (jnp.asarray(alive) != 0)
        pos_e = (pos[:, None] + lanes[None, :]).reshape(-1)
        pad_e = jnp.repeat(jnp.asarray(pad, jnp.int32), kk)
        alive_e = (alive[:, None]
                   & (lanes[None, :] < n_tok[:, None])).reshape(-1)
        bt_e = jnp.repeat(jnp.asarray(block_tables, jnp.int32), kk,
                          axis=0)
        logits, new = self.decode_step_batched_paged(
            params, stacked, pools, bt_e, tok.reshape(-1), pos_e,
            pad_e, alive_e, decode_attention=decode_attention)
        return logits.reshape(b, kk, -1), new

    def _stack_caches(self, caches):
        """Per-layer {layer_i: {k, v}} prefill caches -> the stacked
        {"k": [L, ...], "v": [L, ...]} slabs the scan step consumes."""
        c = self.cfg
        return {n: jnp.stack([caches[f"layer_{i}"][n]
                              for i in range(c.layers)])
                for n in ("k", "v")}

    def _filter_logits(self, logits, top_k: int, top_p: float):
        """Nucleus/top-k filtering of [B, V] (temperature-scaled)
        logits: everything outside the kept set drops to the shared
        NEG_INF fill (exp underflows to exactly 0 under categorical).
        top-p keeps the smallest prefix of the descending-probability
        order whose EXCLUSIVE cumulative mass is < top_p — the top token
        always survives.

        Tie behavior (deliberate, ``>=``-threshold semantics): only
        logits STRICTLY below the kth-largest / nucleus-threshold value
        are dropped, so every token exactly TIED with the boundary
        survives — top_k can keep more than k tokens and top-p more
        than the nucleus mass on exact ties. Ties at the boundary are
        measure-zero in f32 practice; when they do occur, keeping both
        is the symmetric choice (dropping would need an arbitrary
        vocab-order preference). Covered by the tied-logits unit tests
        in tests/test_gpt.py."""
        from ..ops.attention import NEG_INF
        if top_k:
            kth = lax.top_k(logits, top_k)[0][:, -1:]
            logits = jnp.where(logits < kth, NEG_INF, logits)
        if top_p > 0.0:
            sl = jnp.sort(logits, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(sl, axis=-1)
            keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
            thresh = jnp.min(jnp.where(keep, sl, jnp.inf), axis=-1,
                             keepdims=True)
            logits = jnp.where(logits < thresh, NEG_INF, logits)
        return logits

    def generate(self, params, input_ids, max_new_tokens: int, *,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, eos_id: int | None = None,
                 pad_id: int = 0, prompt_mask=None,
                 rng: jax.Array | None = None,
                 decode_impl: str = "stacked",
                 decode_attention: str | None = None,
                 tokens_per_dispatch: int = 1,
                 weight_quant: str | None = None):
        """Autoregressive generation — one compiled program (prefill +
        KV-cache decode loop), greedy (``temperature=0``) or sampled
        with optional ``top_k``/``top_p`` (nucleus) filtering.

        ``decode_impl`` picks the decode-step body: ``"stacked"`` (the
        default fast path — layer loop as ``lax.scan`` over restacked
        leading-axis params with fused QKV; greedy output is exactly
        the ``"loop"`` path's, tier-1-tested) or ``"loop"`` (the
        reference per-layer Python loop). ``decode_attention``
        overrides the model's ``decode_attention_impl`` for the stacked
        path (``"auto"``/``"pallas"``/``"xla"``).

        ``tokens_per_dispatch=K`` emits K tokens per decode-loop body
        (``lax.scan``'s unroll) so fixed per-iteration overhead
        amortizes across K token steps; output is exactly the K=1
        token stream. Requires ``eos_id=None`` (the early-stop
        ``while_loop`` has a dynamic trip count — nothing to unroll).

        ``weight_quant="int8"`` decodes against int8-quantized stacked
        layer weights (see :meth:`stack_decode_params`) — LOSSY, the
        lever-table comparison row, stacked path only.

        ``prompt_mask`` [B, S0] (1 = real token) admits RAGGED prompt
        batches: real tokens (left-aligned by convention; any layout is
        compacted order-preserving) are repacked against the RIGHT edge
        internally, so every row's prompt ends at slot S0-1 and the
        decode loop advances one shared scalar cache slot — no per-row
        scatter. Positions/attention account for the per-row pad count;
        each row must contain at least one real token.

        ``eos_id`` switches the fixed-trip ``lax.scan`` decode loop to a
        ``lax.while_loop`` that STOPS once every row has emitted EOS
        (the EOS itself is emitted; later slots hold ``pad_id``) — the
        early exit is device-side, still one dispatch.

        Returns [B, max_new_tokens] int32. Jit-compatible:
        ``jax.jit(partial(model.generate, max_new_tokens=K))``.
        """
        c = self.cfg
        b, s0 = input_ids.shape
        if max_new_tokens < 0:
            raise ValueError(f"max_new_tokens must be >= 0, got "
                             f"{max_new_tokens}")
        if max_new_tokens == 0:
            return jnp.zeros((b, 0), jnp.int32)
        total = s0 + max_new_tokens
        if total > c.max_len:
            raise ValueError(
                f"prompt {s0} + max_new_tokens {max_new_tokens} exceeds "
                f"max_len {c.max_len}")
        if temperature > 0.0 and rng is None:
            raise ValueError("sampling (temperature > 0) needs rng")
        if (top_k or top_p) and temperature <= 0.0:
            raise ValueError("top_k/top_p shape the SAMPLING "
                             "distribution; greedy decoding "
                             "(temperature=0) would silently ignore "
                             "them — set temperature > 0")
        if not 0 <= top_p <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got {top_p}")
        if top_k < 0 or top_k > c.vocab_size:
            raise ValueError(f"top_k must be in [0, vocab_size="
                             f"{c.vocab_size}], got {top_k}")
        if decode_impl not in ("stacked", "loop"):
            raise ValueError(f"decode_impl must be 'stacked' or 'loop', "
                             f"got {decode_impl!r}")
        if tokens_per_dispatch < 1:
            raise ValueError(f"tokens_per_dispatch must be >= 1, got "
                             f"{tokens_per_dispatch}")
        if tokens_per_dispatch > 1 and eos_id is not None:
            raise ValueError(
                "tokens_per_dispatch > 1 needs eos_id=None: the EOS "
                "early-stop while_loop has a dynamic trip count, so "
                "there is no fixed K-step body to unroll")
        if weight_quant is not None and decode_impl != "stacked":
            raise ValueError("weight_quant needs decode_impl='stacked' "
                             "(only the stacked scan consumes the "
                             "quantized layer stack)")
        if decode_attention is not None and decode_impl != "stacked":
            raise ValueError(
                "decode_attention picks the stacked path's cache-slab "
                "attention; decode_impl='loop' always uses the XLA "
                "reference — silently ignoring the override would "
                "mislabel a benchmark")

        if prompt_mask is not None:
            if tuple(prompt_mask.shape) != (b, s0):
                raise ValueError(
                    f"prompt_mask shape {tuple(prompt_mask.shape)} != "
                    f"input_ids shape {(b, s0)}")
            last_h, caches, pad = self.ragged_prefill(
                params, input_ids, prompt_mask, total)
        else:
            pad = jnp.zeros((b,), jnp.int32)
            last_h, caches = self._prefill(params, input_ids, total)
        first_logits = self.lm_logits(params, last_h[:, None])[:, 0]

        if decode_impl == "stacked":
            stacked = self.stack_decode_params(params,
                                               weight_quant=weight_quant)
            caches = self._stack_caches(caches)

            def step(caches, tok, pos):
                return self._decode_step_stacked(
                    params, stacked, caches, tok, pos, pad,
                    decode_attention=decode_attention)
        else:
            def step(caches, tok, pos):
                return self._decode_step(params, caches, tok, pos, pad)

        def pick(logits, step_rng):
            if temperature <= 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            scaled = self._filter_logits(logits / temperature, top_k,
                                         top_p)
            return jax.random.categorical(
                step_rng, scaled, axis=-1).astype(jnp.int32)

        def step_rng(step):
            return (jax.random.fold_in(rng, step)
                    if rng is not None else None)

        tok0 = pick(first_logits, step_rng(0))

        if eos_id is None:
            def body(carry, i):
                caches, tok, pos = carry
                logits, caches = step(caches, tok, pos)
                nxt = pick(logits, step_rng(i + 1))
                return (caches, nxt, pos + 1), tok

            # tokens_per_dispatch=K unrolls K token steps into each
            # loop body: ~1/K the loop-bookkeeping overhead per token,
            # and XLA schedules across the K steps' kernels
            (_, last_tok, _), toks = lax.scan(
                body, (caches, tok0, jnp.int32(s0)),
                jnp.arange(max_new_tokens - 1, dtype=jnp.int32),
                unroll=max(1, min(tokens_per_dispatch,
                                  max_new_tokens - 1)))
            # toks carries tokens 0..max_new-2 (each body emits its
            # INPUT token); the final pick is appended explicitly
            return jnp.concatenate([toks.transpose(1, 0),
                                    last_tok[:, None]], axis=1)

        # EOS early-stop: while_loop emits into a preallocated buffer
        # and exits as soon as every row is done — a batch whose rows
        # all finish by step k pays k+1 decode steps, not max_new
        out0 = jnp.full((b, max_new_tokens), pad_id, jnp.int32)

        def cond(carry):
            _, _, _, done, _, t = carry
            return (t < max_new_tokens) & jnp.logical_not(jnp.all(done))

        def wbody(carry):
            caches, tok, pos, done, out, t = carry
            emit = jnp.where(done, pad_id, tok)
            out = lax.dynamic_update_slice_in_dim(out, emit[:, None], t,
                                                  axis=1)
            done = done | (tok == eos_id)

            # the decode step computes the NEXT token — skip it when no
            # next slot will be emitted (last iteration, or every row
            # just finished), matching the scan path's
            # one-decode-per-emitted-token cost
            def dec(caches, tok, pos):
                logits, caches = step(caches, tok, pos)
                return pick(logits, step_rng(t + 1)), caches

            nxt, caches = lax.cond(
                (t + 1 < max_new_tokens) & jnp.logical_not(jnp.all(done)),
                dec, lambda caches, tok, pos: (tok, caches),
                caches, tok, pos)
            return (caches, nxt, pos + 1, done, out, t + 1)

        carry = (caches, tok0, jnp.int32(s0),
                 jnp.zeros((b,), bool), out0, jnp.int32(0))
        _, _, _, _, out, _ = lax.while_loop(cond, wbody, carry)
        return out

    # ------------------------------------------------------------------
    def sharding_rules(self, mesh_shape) -> ShardingRules:
        """Megatron TP, same shapes as Bert; vocab-sharded tied head."""
        M = AxisNames.MODEL
        fsdp = getattr(mesh_shape, "fsdp", 1) if mesh_shape else 1
        tp = getattr(mesh_shape, "model", 1) if mesh_shape else 1
        if tp <= 1:
            return ShardingRules(fsdp_axis_size=fsdp)
        return ShardingRules(rules=[
            (r"attn/(q|k|v)/kernel", P(None, M)),
            (r"attn/(q|k|v)/bias", P(M)),
            (r"attn/o/kernel", P(M, None)),
            (r"ffn/in/kernel", P(None, M)),
            (r"ffn/in/bias", P(M)),
            (r"ffn/out/kernel", P(M, None)),
            (r"\bwte/table", P(M, None)),       # vocab-sharded tied head
        ], fsdp_axis_size=fsdp)

    def dummy_batch(self, batch_size: int):
        c = self.cfg
        rs = np.random.RandomState(0)
        s = min(128, c.max_len)
        return {
            "input_ids": rs.randint(0, c.vocab_size, (batch_size, s),
                                    dtype=np.int32),
            "attention_mask": np.ones((batch_size, s), np.int32),
        }


def _make(config: TrainConfig, cfg: GPTConfig, *,
          config_vocab: bool = True) -> GPT:
    if config_vocab:
        cfg.vocab_size = config.data.vocab_size
    cfg.max_len = max(cfg.max_len, config.data.seq_len)
    # loud config-time validation of the LM-loss lever surface (impl /
    # chunk / vocab block / accuracy cadence), before any trace
    ls = lm_loss_settings(config)
    cfg.loss_impl = ls["impl"]
    cfg.loss_chunk = ls["chunk"]
    cfg.loss_vocab_block = ls["vocab_block"]
    return GPT(cfg, dtype=resolve_dtype(config.dtype),
               attention_impl=config.attention_impl,
               param_dtype=resolve_dtype(config.param_dtype),
               remat=config.remat,
               attention_kwargs=flash_attention_kwargs(config),
               accuracy_every_n=ls["accuracy_every_n"])


@register_model("gpt")
def _make_gpt(config: TrainConfig) -> GPT:
    return _make(config, GPTConfig.small())


@register_model("gpt_tiny")
def _make_gpt_tiny(config: TrainConfig) -> GPT:
    return _make(config, GPTConfig.tiny(), config_vocab=False)
