"""EP × PP: Mixture-of-Experts encoder layers inside GPipe stages.

Neither exists in the reference (SURVEY.md §2.5 marks PP and EP absent);
this model closes the composition-matrix cell VERDICT r4 missing #4
named: the ``pipe`` and ``expert`` axes live in ONE program. Every
encoder layer is an MoE layer (homogeneous blocks are what make a
stacked pipeline SPMD-able — a dense/MoE alternation cannot stack), the
layer stack is sharded over ``pipe`` exactly like
:class:`~.pipe_bert.PipeBert`, and inside each stage tick the FFN runs
the SAME explicit expert-parallel dataflow as
:func:`~..ops.moe.moe_ffn_ep_body`: tokens sharded over ``expert``,
``lax.all_to_all`` token exchange, local expert compute, exchange back.

Gradient correctness under ``shard_map`` follows the PP×TP design rule
(pipe_bert.py module docstring): nothing is computed redundantly across
``expert`` members — the batch is sharded over ``(data, fsdp, expert)``
inside the pipeline, so attention runs on each member's own token shard
and the router routes each member's own tokens; every unmentioned-axis
cotangent psum therefore sums genuinely partial contributions.

Aux (load-balancing + router-z) losses ride the pipeline as extra
microbatch-shaped accumulator leaves in the activation pytree: each
stage adds its layers' aux for the microbatch it is processing, and the
final values are batch means. Two semantics notes that make the parity
tests precise (tests/test_pipe_moe.py):

- Routing DECISIONS are per token (grouping-independent), so at a
  capacity where nothing drops, outputs/loss/grads on the aux-free path
  match the sequential model tightly. The aux STATS are per-(microbatch
  group) and the lb formula is nonlinear in them, so aux values depend
  on which examples share a group — a layout-defined property (member-
  major across the expert shards). The aux oracle reorders the batch to
  form the same groups and then matches at 1e-5. Capacity caveat as in
  test_moe.py: the explicit path's capacity is per token shard, so
  parity asserts use a generous capacity_factor.
- Dropout masks are drawn per token shard (independent across expert
  members — operationally sound), so bit-parity with the unsharded
  oracle under dropout is a pipe-only property, as in PipeBert.
"""

from __future__ import annotations

import dataclasses
import re

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..config import TrainConfig
from ..ops import moe
from ..parallel.collectives import axis_size
from ..parallel.mesh import AxisNames
from ..parallel.pipeline import make_pipeline, sequential_blocks
from ..parallel.sharding import ShardingRules
from ..utils.pytree import path_str as _path_str
from .base import register_model
from .bert import BertConfig, _make
from .pipe_bert import PipeBert, PipeBertConfig


@dataclasses.dataclass
class PipeMoeBertConfig(PipeBertConfig):
    n_experts: int = 8
    top_k: int = 1
    capacity_factor: float = 1.25
    aux_weight: float = 0.01
    router_z_weight: float = 0.0

    @classmethod
    def tiny(cls) -> "PipeMoeBertConfig":
        t = BertConfig.tiny()
        cfg = cls(**dataclasses.asdict(t))
        cfg.layers = 4            # 2 stages x 2 layers on the test mesh
        cfg.n_experts = 4
        cfg.capacity_factor = 2.0
        return cfg


class PipeMoeBert(PipeBert):
    """Pipelined BERT whose every encoder FFN is an expert-parallel MoE."""

    name = "pipe_moe_bert"

    # ------------------------------------------------------------------
    def bind_mesh(self, mesh) -> None:
        if mesh is not None and mesh.shape[AxisNames.MODEL] > 1:
            raise ValueError(
                "pipe_moe_bert composes pipe x expert; a model axis > 1 "
                "(EP x TP x PP) is not supported — use moe_bert for "
                "EP x TP or pipe_bert for PP x TP")
        if mesh is not None and mesh.shape[AxisNames.EXPERT] > 1:
            ep = mesh.shape[AxisNames.EXPERT]
            if self.cfg.n_experts % ep:
                raise ValueError(
                    f"n_experts={self.cfg.n_experts} not divisible by "
                    f"expert axis size {ep}")
        super().bind_mesh(mesh)
        # the EP dataflow needs the mesh even when pipe == 1 (pure EP
        # under a pipeline-of-one); PipeBert only records pipe > 1 meshes
        if (mesh is not None and self._pipe_mesh is None
                and mesh.shape[AxisNames.EXPERT] > 1):
            self._pipe_mesh = mesh

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array):
        # Bert layer structure with the FFN swapped for MoE weights,
        # then stacked [L, ...] like PipeBert (homogeneous blocks)
        flat = super(PipeBert, self).init(rng)
        c = self.cfg
        for i in range(c.layers):
            lp = flat[f"layer_{i}"]
            del lp["ffn"]
            lp["moe"] = moe.moe_ffn_init(
                jax.random.fold_in(rng, 10_000 + i), c.n_experts,
                c.hidden, c.intermediate,
                param_dtype=self.param_dtype)
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs),
            *[flat.pop(f"layer_{i}") for i in range(c.layers)])
        flat["layers"] = stacked
        return flat

    # ------------------------------------------------------------------
    def _moe_ffn_in_stage(self, lp_moe, h, ep_axis, stat_axes):
        """FFN body for one layer inside the pipeline shard_map: the
        explicit EP dataflow when the expert axis is real, the dense
        dispatch otherwise (pipe-only meshes and the sequential
        oracle)."""
        c = self.cfg
        if ep_axis is not None:
            return moe.moe_ffn_ep_body(
                lp_moe, h, n_experts=c.n_experts,
                n_ranks=axis_size(ep_axis), top_k=c.top_k,
                capacity_factor=c.capacity_factor, dtype=self.dtype,
                axis_name=ep_axis, stat_axes=stat_axes)
        return moe.moe_ffn(lp_moe, h, n_experts=c.n_experts,
                           top_k=c.top_k,
                           capacity_factor=c.capacity_factor,
                           dtype=self.dtype)

    def _moe_stage_fn(self, *, offset_fn, train: bool, use_dropout: bool,
                      rng, ep_axis: str | None, stat_axes):
        """(local_stack, {h, mask, lb, z, dropped}, mb_idx) -> same
        structure: this stage's MoE layers in order, aux accumulated
        onto the microbatch-shaped leaves."""
        def one_layer(lp, h, mask, lrng):
            h = self._attn_block(lp, h, mask, lrng, train=train,
                                 use_dropout=use_dropout)
            f, aux = self._moe_ffn_in_stage(lp["moe"], h, ep_axis,
                                            stat_axes)
            h = self._ffn_block(lp, h, f, lrng, use_dropout=use_dropout)
            return h, aux

        layer = self._maybe_remat(one_layer)

        def stage(stack, x, mb_idx):
            n_local = jax.tree_util.tree_leaves(stack)[0].shape[0]
            offset = offset_fn(n_local)

            def body(carry, xs):
                h, lb, z, dropped = carry
                lp, j = xs
                lrng = None
                if use_dropout:
                    lrng = jax.random.fold_in(
                        jax.random.fold_in(rng, offset + j), mb_idx)
                h, aux = layer(lp, h, x["mask"], lrng)
                return (h, lb + aux["lb_loss"], z + aux["z_loss"],
                        dropped + aux["dropped_fraction"]), None

            (h, lb, z, dropped), _ = lax.scan(
                body, (x["h"], jnp.zeros((), jnp.float32),
                       jnp.zeros((), jnp.float32),
                       jnp.zeros((), jnp.float32)),
                (stack, jnp.arange(n_local)))
            # aux rides the activation pytree: broadcast this stage's
            # contribution onto the per-example accumulator leaves (every
            # example of the microbatch carries the same value, so the
            # final batch mean is the per-microbatch mean)
            b = x["lb"].shape[0]
            return {"h": h, "mask": x["mask"],
                    "lb": x["lb"] + jnp.broadcast_to(lb, (b,)),
                    "z": x["z"] + jnp.broadcast_to(z, (b,)),
                    "dropped": x["dropped"]
                    + jnp.broadcast_to(dropped, (b,))}

        return stage

    # ------------------------------------------------------------------
    def encode_with_aux(self, params, batch, rng=None,
                        train: bool = False):
        c = self.cfg
        h, mask, use_dropout = self._embed(params, batch, rng, train)
        b = h.shape[0]
        zero = jnp.zeros((b,), jnp.float32)
        x = {"h": h, "mask": mask, "lb": zero, "z": zero, "dropped": zero}
        mesh = self._pipe_mesh
        if mesh is not None:
            ep = mesh.shape[AxisNames.EXPERT]
            ep_axis = AxisNames.EXPERT if ep > 1 else None
            batch_axes = tuple(AxisNames.BATCH) + (
                (AxisNames.EXPERT,) if ep > 1 else ())
            stat_axes = batch_axes
            stage = self._moe_stage_fn(
                offset_fn=lambda n_local:
                    lax.axis_index(AxisNames.PIPE) * n_local,
                train=train, use_dropout=use_dropout, rng=rng,
                ep_axis=ep_axis, stat_axes=stat_axes)
            piped = make_pipeline(
                mesh, stage, num_microbatches=c.microbatches,
                param_specs=self._stacked_specs(params["layers"]),
                x_specs=jax.tree_util.tree_map(
                    lambda _: P(batch_axes), x))
            out = piped(params["layers"], x)
        else:
            stage = self._moe_stage_fn(
                offset_fn=lambda n_local: 0, train=train,
                use_dropout=use_dropout, rng=rng, ep_axis=None,
                stat_axes=())
            # ALWAYS the pipeline's microbatch split: MoE routing
            # (capacity, stats) is per-microbatch, so unlike the dense
            # PipeBert the no-dropout oracle cannot collapse to m=1
            out = sequential_blocks(stage, params["layers"], x,
                                    num_microbatches=c.microbatches)
        n_layers = jnp.float32(c.layers)
        return out["h"], {
            "lb_loss": jnp.mean(out["lb"]),
            "z_loss": jnp.mean(out["z"]),
            # visibility: mean over layers (loss terms stay sums — each
            # router is its own regularization target, as in MoeBert)
            "dropped_fraction": jnp.mean(out["dropped"]) / n_layers,
        }

    def encode(self, params, batch, rng=None, train: bool = False):
        return self.encode_with_aux(params, batch, rng, train)[0]

    # ------------------------------------------------------------------
    def loss(self, params, extras, batch, rng):
        seq_out, aux = self.encode_with_aux(params, batch, rng,
                                            train=True)
        w = batch["masked_weights"].astype(jnp.float32)
        # the MLM head loss is Bert's shared implementation (full or
        # fused blockwise core per cfg.lm_loss_impl — ops/losses.py)
        mlm, acc = self._mlm_loss_and_acc(params, seq_out, batch, w)
        total = (mlm + self.cfg.aux_weight * aux["lb_loss"]
                 + self.cfg.router_z_weight * aux["z_loss"])
        metrics = {"mlm_accuracy": acc, "mlm_loss": mlm,
                   "aux_loss": aux["lb_loss"],
                   "router_z_loss": aux["z_loss"],
                   "dropped_token_fraction": aux["dropped_fraction"]}
        return total, (metrics, extras)

    # ------------------------------------------------------------------
    #: stacked-MoE placement: leading dim pipe, expert dim expert
    _EP_STACK = (
        (r"moe/w_(in|out)", (AxisNames.EXPERT, None, None)),
        (r"moe/b_(in|out)", (AxisNames.EXPERT, None)),
    )

    def _stacked_specs(self, stacked):
        """shard_map specs: pipe on the stage dim, expert on the expert
        dim of the MoE arrays, router/LN/attention replicated across
        expert (their COMPUTE is per-token-shard, never redundant)."""
        def spec(path, _):
            p = _path_str(path)
            for pattern, tail in self._EP_STACK:
                if re.search(pattern, p):
                    return P(AxisNames.PIPE, *tail)
            return P(AxisNames.PIPE)
        return jax.tree_util.tree_map_with_path(spec, stacked)

    def sharding_rules(self, mesh_shape) -> ShardingRules:
        fsdp = getattr(mesh_shape, "fsdp", 1) if mesh_shape else 1
        pipe = getattr(mesh_shape, "pipe", 1) if mesh_shape else 1
        ep = getattr(mesh_shape, "expert", 1) if mesh_shape else 1
        if pipe <= 1 and ep <= 1:
            return ShardingRules(fsdp_axis_size=fsdp)
        lead = AxisNames.PIPE if pipe > 1 else None
        rules = [(r"\blayers/(?:" + pattern + ")", P(lead, *tail))
                 for pattern, tail in self._EP_STACK]
        if pipe > 1:
            rules.append((r"\blayers/", P(AxisNames.PIPE)))
        return ShardingRules(rules=rules, fsdp_axis_size=fsdp)


def _apply_overrides(cfg: PipeMoeBertConfig,
                     config: TrainConfig) -> PipeMoeBertConfig:
    """The shared --moe_* CLI knobs, minus the two that do not apply
    here: every pipelined layer is MoE (homogeneous stacking), so
    --moe_every has no meaning, and router jitter is not wired into the
    pipelined path — both hard-error instead of silently ignoring."""
    if config.moe_experts is not None:
        if config.moe_experts < 1:
            raise ValueError(
                f"moe_experts={config.moe_experts} must be >= 1")
        cfg.n_experts = config.moe_experts
    if config.moe_top_k is not None:
        cfg.top_k = config.moe_top_k
    if not 1 <= cfg.top_k <= cfg.n_experts:
        raise ValueError(f"moe_top_k={cfg.top_k} must be in "
                         f"[1, n_experts={cfg.n_experts}]")
    if config.moe_capacity_factor is not None:
        if config.moe_capacity_factor <= 0:
            raise ValueError("moe_capacity_factor must be > 0")
        cfg.capacity_factor = config.moe_capacity_factor
    if config.moe_aux_weight is not None:
        cfg.aux_weight = config.moe_aux_weight
    if config.moe_router_z_weight is not None:
        cfg.router_z_weight = config.moe_router_z_weight
    if config.moe_every is not None:
        raise ValueError(
            "moe_every does not apply to pipe_moe_bert: every pipelined "
            "layer is MoE (homogeneous blocks stack over pipe)")
    if config.moe_jitter is not None:
        raise ValueError(
            "moe_jitter is not wired into the pipelined MoE path — use "
            "moe_bert for jittered routing")
    return cfg


@register_model("pipe_moe_bert")
def _make_pipe_moe_bert(config: TrainConfig) -> PipeMoeBert:
    cfg = _apply_overrides(PipeMoeBertConfig(), config)
    return _make(config, cfg, cls=PipeMoeBert)


@register_model("pipe_moe_bert_tiny")
def _make_pipe_moe_bert_tiny(config: TrainConfig) -> PipeMoeBert:
    cfg = _apply_overrides(PipeMoeBertConfig.tiny(), config)
    return _make(config, cfg, config_vocab=False, cls=PipeMoeBert)
