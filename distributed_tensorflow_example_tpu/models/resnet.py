"""ResNet family: ResNet-20 (CIFAR-10) and ResNet-50 (ImageNet).

Reference workloads 3 and 4 (BASELINE.json:9-10: 'CIFAR-10 ResNet-20 sync
SGD on v4-8', 'ImageNet ResNet-50, multi-host TPUStrategy on v4-32').

TPU-first choices: NHWC layout (XLA:TPU native), bf16 compute with f32
BatchNorm statistics, BatchNorm running stats in ``TrainState.extras``
(sync-BN semantics fall out of global-batch sharding in the auto step,
see parallel/sync_replicas.py).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import TrainConfig
from ..ops import losses, nn
from .base import (DefaultRulesMixin, cast_floating,
                   classification_eval_metrics, register_model,
                   resolve_dtype)


def _bn_apply(params, extras, x, *, train, momentum=0.9,
              stats_dtype=jnp.float32):
    # x keeps its compute dtype (bf16): nn.batchnorm takes statistics in
    # stats_dtype internally (f32 default) and normalizes in x.dtype, so
    # activations never round-trip HBM as f32 (the pre-round-3 upcast
    # here cost ~40% of step time)
    return nn.batchnorm(params, extras, x, train=train, momentum=momentum,
                        stats_dtype=stats_dtype)


class _BasicBlock:
    """3x3 + 3x3 with identity/projection shortcut (ResNet-20)."""

    expansion = 1

    @staticmethod
    def init(rng, in_ch: int, width: int, stride: int):
        r = jax.random.split(rng, 3)
        out_ch = width
        params = {
            "conv1": nn.conv2d_init(r[0], 3, 3, in_ch, width, use_bias=False),
            "conv2": nn.conv2d_init(r[1], 3, 3, width, out_ch, use_bias=False),
        }
        extras = {}
        params["bn1"], extras["bn1"] = nn.batchnorm_init(width)
        params["bn2"], extras["bn2"] = nn.batchnorm_init(out_ch)
        if stride != 1 or in_ch != out_ch:
            params["proj"] = nn.conv2d_init(r[2], 1, 1, in_ch, out_ch,
                                            use_bias=False)
            params["proj_bn"], extras["proj_bn"] = nn.batchnorm_init(out_ch)
        return params, extras, out_ch

    @staticmethod
    def apply(params, extras, x, *, stride, train, dtype,
              bn_stats_dtype=jnp.float32):
        new = {}
        h = nn.conv2d(params["conv1"], x, stride=stride, dtype=dtype)
        h, new["bn1"] = _bn_apply(params["bn1"], extras["bn1"], h, train=train,
                                  stats_dtype=bn_stats_dtype)
        h = jax.nn.relu(h)
        h = nn.conv2d(params["conv2"], h, dtype=dtype)
        h, new["bn2"] = _bn_apply(params["bn2"], extras["bn2"], h, train=train,
                                  stats_dtype=bn_stats_dtype)
        if "proj" in params:
            s = nn.conv2d(params["proj"], x, stride=stride, dtype=dtype)
            s, new["proj_bn"] = _bn_apply(params["proj_bn"],
                                          extras["proj_bn"], s, train=train,
                                          stats_dtype=bn_stats_dtype)
        else:
            s = x.astype(h.dtype)
        return jax.nn.relu(h + s), new


class _BottleneckBlock:
    """1x1 → 3x3 → 1x1(×4) with projection shortcut (ResNet-50)."""

    expansion = 4

    @staticmethod
    def init(rng, in_ch: int, width: int, stride: int):
        r = jax.random.split(rng, 4)
        out_ch = width * 4
        params = {
            "conv1": nn.conv2d_init(r[0], 1, 1, in_ch, width, use_bias=False),
            "conv2": nn.conv2d_init(r[1], 3, 3, width, width, use_bias=False),
            "conv3": nn.conv2d_init(r[2], 1, 1, width, out_ch, use_bias=False),
        }
        extras = {}
        params["bn1"], extras["bn1"] = nn.batchnorm_init(width)
        params["bn2"], extras["bn2"] = nn.batchnorm_init(width)
        params["bn3"], extras["bn3"] = nn.batchnorm_init(out_ch)
        if stride != 1 or in_ch != out_ch:
            params["proj"] = nn.conv2d_init(r[3], 1, 1, in_ch, out_ch,
                                            use_bias=False)
            params["proj_bn"], extras["proj_bn"] = nn.batchnorm_init(out_ch)
        return params, extras, out_ch

    @staticmethod
    def apply(params, extras, x, *, stride, train, dtype,
              bn_stats_dtype=jnp.float32):
        new = {}
        h = nn.conv2d(params["conv1"], x, dtype=dtype)
        h, new["bn1"] = _bn_apply(params["bn1"], extras["bn1"], h, train=train,
                                  stats_dtype=bn_stats_dtype)
        h = jax.nn.relu(h)
        h = nn.conv2d(params["conv2"], h, stride=stride, dtype=dtype)
        h, new["bn2"] = _bn_apply(params["bn2"], extras["bn2"], h, train=train,
                                  stats_dtype=bn_stats_dtype)
        h = jax.nn.relu(h)
        h = nn.conv2d(params["conv3"], h, dtype=dtype)
        h, new["bn3"] = _bn_apply(params["bn3"], extras["bn3"], h, train=train,
                                  stats_dtype=bn_stats_dtype)
        if "proj" in params:
            s = nn.conv2d(params["proj"], x, stride=stride, dtype=dtype)
            s, new["proj_bn"] = _bn_apply(params["proj_bn"],
                                          extras["proj_bn"], s, train=train,
                                          stats_dtype=bn_stats_dtype)
        else:
            s = x.astype(h.dtype)
        return jax.nn.relu(h + s), new


class ResNet(DefaultRulesMixin):
    """Configurable ResNet. Two presets registered below:

    - ``resnet20``: CIFAR stem (3x3/16, no maxpool), basic blocks [3,3,3],
      widths [16,32,64] — the canonical CIFAR-10 ResNet-20.
    - ``resnet50``: ImageNet stem (7x7/64 s2 + maxpool), bottlenecks
      [3,4,6,3], widths [64,128,256,512].
    """

    def __init__(self, name: str, block, stage_sizes: Sequence[int],
                 widths: Sequence[int], num_classes: int,
                 input_hw: int, imagenet_stem: bool, dtype=jnp.float32,
                 param_dtype=jnp.float32, label_smoothing: float = 0.0,
                 bn_stats_dtype=jnp.float32):
        self.name = name
        self.block = block
        self.stage_sizes = list(stage_sizes)
        self.widths = list(widths)
        self.num_classes = num_classes
        self.input_hw = input_hw
        self.imagenet_stem = imagenet_stem
        self.dtype = dtype
        self.param_dtype = param_dtype
        # the standard ImageNet recipe smooths training targets (eval
        # metrics stay unsmoothed — comparable across smoothing settings)
        self.label_smoothing = label_smoothing
        # --bn_stats_dtype experiment knob: batch-statistic reduction
        # dtype (running stats stay f32 regardless — they accumulate)
        self.bn_stats_dtype = bn_stats_dtype

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array):
        n_blocks = sum(self.stage_sizes)
        keys = jax.random.split(rng, n_blocks + 2)
        ki = iter(range(n_blocks + 2))

        params: dict = {}
        extras: dict = {}
        if self.imagenet_stem:
            params["stem"] = nn.conv2d_init(keys[next(ki)], 7, 7, 3, 64,
                                            use_bias=False)
            ch = 64
        else:
            params["stem"] = nn.conv2d_init(keys[next(ki)], 3, 3, 3, 16,
                                            use_bias=False)
            ch = 16
        params["stem_bn"], extras["stem_bn"] = nn.batchnorm_init(ch)

        for si, (n, w) in enumerate(zip(self.stage_sizes, self.widths)):
            for bi in range(n):
                stride = 2 if (bi == 0 and si > 0) else 1
                p, e, ch = self.block.init(keys[next(ki)], ch, w, stride)
                params[f"s{si}b{bi}"] = p
                extras[f"s{si}b{bi}"] = e

        params["fc"] = nn.dense_init(keys[next(ki)], ch, self.num_classes,
                                     init="truncated_normal")
        # extras (BN running stats) stay f32: they accumulate across steps
        return cast_floating(params, self.param_dtype), extras

    # ------------------------------------------------------------------
    def apply(self, params, extras, batch, rng=None, train: bool = False):
        x = batch["x"]
        new: dict = {}
        h = nn.conv2d(params["stem"], x,
                      stride=2 if self.imagenet_stem else 1,
                      dtype=self.dtype)
        h, new["stem_bn"] = _bn_apply(params["stem_bn"], extras["stem_bn"],
                                      h, train=train,
                                      stats_dtype=self.bn_stats_dtype)
        h = jax.nn.relu(h)
        if self.imagenet_stem:
            h = nn.max_pool(h, 3, 2, padding="SAME")

        for si, n in enumerate(self.stage_sizes):
            for bi in range(n):
                stride = 2 if (bi == 0 and si > 0) else 1
                key = f"s{si}b{bi}"
                h, new[key] = self.block.apply(
                    params[key], extras[key], h, stride=stride,
                    train=train, dtype=self.dtype,
                    bn_stats_dtype=self.bn_stats_dtype)

        h = jnp.mean(h.astype(jnp.float32), axis=(1, 2))   # global avg pool
        logits = nn.dense(params["fc"], h, dtype=self.dtype)
        return logits.astype(jnp.float32), (new if train else extras)

    # ------------------------------------------------------------------
    def loss(self, params, extras, batch, rng):
        logits, new_extras = self.apply(params, extras, batch, rng, train=True)
        loss = losses.softmax_xent_int_labels(
            logits, batch["y"], label_smoothing=self.label_smoothing)
        aux = {"accuracy": losses.accuracy(logits, batch["y"])}
        return loss, (aux, new_extras)

    def eval_metrics(self, params, extras, batch) -> dict:
        logits, _ = self.apply(params, extras, batch, train=False)
        # top-5 is the ImageNet recipes' second headline number; only
        # meaningful when there are >5 classes (resnet50's 1000)
        return classification_eval_metrics(
            logits, batch, top5=self.num_classes > 5)

    def dummy_batch(self, batch_size: int):
        rs = np.random.RandomState(0)
        hw = self.input_hw
        return {
            "x": rs.rand(batch_size, hw, hw, 3).astype(np.float32),
            "y": rs.randint(0, self.num_classes, size=(batch_size,),
                            dtype=np.int32),
        }


def _bn_stats_dtype(config: TrainConfig):
    if config.bn_stats_dtype not in ("float32", "bfloat16"):
        raise ValueError(
            f"bn_stats_dtype={config.bn_stats_dtype!r} must be float32 "
            "or bfloat16")
    return resolve_dtype(config.bn_stats_dtype)


@register_model("resnet20")
def _make_resnet20(config: TrainConfig) -> ResNet:
    return ResNet("resnet20", _BasicBlock, [3, 3, 3], [16, 32, 64],
                  num_classes=10, input_hw=32, imagenet_stem=False,
                  dtype=resolve_dtype(config.dtype),
                  param_dtype=resolve_dtype(config.param_dtype),
                  label_smoothing=config.label_smoothing,
                  bn_stats_dtype=_bn_stats_dtype(config))


@register_model("resnet50")
def _make_resnet50(config: TrainConfig) -> ResNet:
    return ResNet("resnet50", _BottleneckBlock, [3, 4, 6, 3],
                  [64, 128, 256, 512], num_classes=1000, input_hw=224,
                  imagenet_stem=True, dtype=resolve_dtype(config.dtype),
                  param_dtype=resolve_dtype(config.param_dtype),
                  label_smoothing=config.label_smoothing,
                  bn_stats_dtype=_bn_stats_dtype(config))
