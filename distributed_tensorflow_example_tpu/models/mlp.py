"""MNIST 2-layer MLP — the reference's parity model.

Reference: 784→hidden→10 with truncated-normal init, softmax cross-entropy,
plain SGD under SyncReplicasOptimizer (SURVEY.md §2.1 'Model: MNIST 2-layer
MLP'; BASELINE.json:7 'MNIST 2-layer MLP, 1 PS + 1 worker'). The classic
script used hidden=100 and lr=0.5-ish; both are config knobs here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..config import TrainConfig
from ..ops import losses, nn
from .base import (DefaultRulesMixin, cast_floating,
                   classification_eval_metrics, register_model,
                   resolve_dtype)


class MLP(DefaultRulesMixin):
    name = "mlp"

    def __init__(self, in_dim: int = 784, hidden: int = 100,
                 num_classes: int = 10, dtype=jnp.float32,
                 param_dtype=jnp.float32):
        self.in_dim, self.hidden, self.num_classes = in_dim, hidden, num_classes
        self.dtype = dtype
        self.param_dtype = param_dtype

    def init(self, rng: jax.Array):
        r1, r2 = jax.random.split(rng)
        return cast_floating({
            "fc1": nn.dense_init(r1, self.in_dim, self.hidden),
            "fc2": nn.dense_init(r2, self.hidden, self.num_classes),
        }, self.param_dtype)

    def apply(self, params, extras, batch, rng=None, train: bool = False):
        x = batch["x"].reshape((batch["x"].shape[0], -1))
        h = jax.nn.relu(nn.dense(params["fc1"], x, dtype=self.dtype))
        # logits in f32: softmax losses need the headroom (dense outputs
        # the compute dtype since the bf16-activation change)
        logits = nn.dense(params["fc2"], h, dtype=self.dtype)
        return logits.astype(jnp.float32), extras

    def loss(self, params, extras, batch, rng):
        logits, new_extras = self.apply(params, extras, batch, rng, train=True)
        loss = losses.softmax_xent_int_labels(logits, batch["y"])
        aux = {"accuracy": losses.accuracy(logits, batch["y"])}
        return loss, (aux, new_extras)

    def eval_metrics(self, params, extras, batch) -> dict:
        logits, _ = self.apply(params, extras, batch, train=False)
        return classification_eval_metrics(logits, batch)

    def dummy_batch(self, batch_size: int):
        rs = np.random.RandomState(0)
        return {
            "x": rs.rand(batch_size, self.in_dim).astype(np.float32),
            "y": rs.randint(0, self.num_classes, size=(batch_size,),
                            dtype=np.int32),
        }


@register_model("mlp")
def _make_mlp(config: TrainConfig) -> MLP:
    return MLP(dtype=resolve_dtype(config.dtype),
               param_dtype=resolve_dtype(config.param_dtype))
