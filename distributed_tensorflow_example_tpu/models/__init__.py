"""Model zoo, in the order of the reference's workload configs
(BASELINE.json:7-11): MNIST MLP, MNIST LeNet CNN, CIFAR ResNet-20,
ImageNet ResNet-50, BERT-base MLM.
"""

from .base import Model, get_model, list_models, register_model
from . import mlp as mlp          # registers "mlp"
from . import lenet as lenet      # registers "lenet"
from . import resnet as resnet    # registers "resnet20", "resnet50"
from . import bert as bert        # registers "bert", "bert_tiny"
from . import moe as moe          # registers "moe_bert", "moe_bert_tiny"
from . import pipe_mlp as pipe_mlp  # registers "pipe_mlp"
from . import pipe_bert as pipe_bert  # registers "pipe_bert"(+_tiny)
from . import pipe_moe as pipe_moe  # registers "pipe_moe_bert"(+_tiny)
from . import gpt as gpt          # registers "gpt", "gpt_tiny"

__all__ = ["Model", "get_model", "list_models", "register_model"]
