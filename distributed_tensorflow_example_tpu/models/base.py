"""Model protocol + registry.

A model is a small object exposing:

- ``init(rng) -> params`` or ``(params, extras)``
- ``apply(params, extras, batch, rng, train) -> (logits, new_extras)``
- ``loss(params, extras, batch, rng) -> (loss, (aux, new_extras))`` — the
  framework-canonical training loss (see
  :mod:`~distributed_tensorflow_example_tpu.parallel.sync_replicas`)
- ``eval_metrics(params, extras, batch) -> dict`` — forward-only metrics
- ``sharding_rules(mesh_shape) -> ShardingRules`` — per-model placement
  (tensor-parallel specs etc.); the default replicates/fsdp-shards.
- ``dummy_batch(batch_size) -> batch`` — shape-correct synthetic batch for
  compile checks and benchmarks.

The registry replaces the reference's implicit "one script per model"
arrangement with ``--model`` selection from a single CLI (SURVEY.md §2.1).
"""

from __future__ import annotations

from typing import Any, Callable, Protocol

import jax
import jax.numpy as jnp

from ..config import TrainConfig
from ..parallel.sharding import ShardingRules


def resolve_dtype(name: str):
    """Config dtype string -> jnp dtype (the framework's two-dtype policy:
    bf16 feeds the MXU, f32 everywhere precision matters)."""
    return jnp.bfloat16 if name == "bfloat16" else jnp.float32


def cast_floating(tree, dtype):
    """Cast floating-point leaves to ``dtype`` (int/bool leaves untouched).

    Models apply this to their freshly-initialized params so
    ``TrainConfig.param_dtype`` governs parameter storage dtype uniformly;
    initializers compute in f32 first, so this matches passing
    ``param_dtype`` into every ``ops.nn.*_init`` call."""
    def c(x):
        return (x.astype(dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x)
    return jax.tree_util.tree_map(c, tree)


def classification_eval_metrics(logits, batch, *, top5: bool = False
                                ) -> dict:
    """Shared eval_metrics body for integer-label classifiers.

    Honors an optional ``batch["__valid__"]`` example mask (1.0 = real
    example, 0.0 = padding) so the Trainer can pad the eval tail batch to a
    static shape — one compiled executable for the whole eval pass instead
    of a recompile per distinct tail size (SURVEY.md §2.3 static-shape
    discipline). ``top5`` adds the ImageNet recipes' second headline
    number."""
    from ..ops import losses
    w = batch.get("__valid__")
    out = {
        "loss": losses.softmax_xent_int_labels(logits, batch["y"], where=w),
        "accuracy": losses.accuracy(logits, batch["y"], where=w),
    }
    if top5:
        out["top5_accuracy"] = losses.topk_accuracy(
            logits, batch["y"], 5, where=w)
    return out


class Model(Protocol):
    name: str

    def init(self, rng: jax.Array): ...
    def apply(self, params, extras, batch, rng, train: bool): ...
    def loss(self, params, extras, batch, rng): ...
    def eval_metrics(self, params, extras, batch) -> dict: ...
    def sharding_rules(self, mesh_shape) -> ShardingRules: ...
    def dummy_batch(self, batch_size: int): ...


_REGISTRY: dict[str, Callable[[TrainConfig], Any]] = {}


def register_model(name: str):
    def deco(factory):
        _REGISTRY[name] = factory
        return factory
    return deco


def get_model(name: str, config: TrainConfig | None = None):
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](config or TrainConfig(model=name))


def list_models() -> list[str]:
    return sorted(_REGISTRY)


class DefaultRulesMixin:
    """Default placement: replicate, fsdp-shard big params when fsdp>1."""

    def sharding_rules(self, mesh_shape) -> ShardingRules:
        fsdp = getattr(mesh_shape, "fsdp", 1) if mesh_shape else 1
        return ShardingRules(fsdp_axis_size=fsdp)
